"""paddle_tpu.ir — pattern rewriting over jaxprs.

Capability slot: the reference's PIR pattern-rewrite infrastructure
(``paddle/pir/include/pattern_rewrite/``, declarative DRR in
``fluid/pir/drr/``) and its pass manager. On TPU the IR *is* the jaxpr
(SURVEY §7 design stance: jax.jit/XLA replace PIR+executors), so the
user-visible rewrite surface operates on jaxprs:

- `RewritePattern`: match one equation (or a single-use CHAIN of
  equations) and emit replacement computation with ordinary jnp ops.
- `PatternRewriter.rewrite(fn)`: returns a new function whose jaxpr has
  every match replaced — implemented by re-tracing an interpreter over
  the original jaxpr (no manual Var surgery, so it composes with any
  primitive, including scan/pjit), with optional dead-code elimination.

The rewritten function is a normal traceable callable: jit it, grad it,
inspect it with jax.make_jaxpr — exactly how PIR passes feed the rest of
the reference stack.
"""
from __future__ import annotations

import jax
from jax.extend import core as jex_core
from jax import tree_util

__all__ = ["RewritePattern", "ChainPattern", "PatternRewriter",
           "TransposePairPattern", "CastChainPattern", "AddZeroPattern",
           "dead_code_elimination"]


class RewritePattern:
    """Single-equation pattern. Subclass and implement:

    - ``matches(eqn) -> bool`` — inspect primitive/params.
    - ``rewrite(*invals) -> outputs`` — replacement computation in jnp
      ops (tuple matching the eqn's outputs, or a single value).
    """

    def matches(self, eqn) -> bool:  # pragma: no cover - abstract
        raise NotImplementedError

    def rewrite(self, *invals):  # pragma: no cover - abstract
        raise NotImplementedError


class ChainPattern(RewritePattern):
    """Match a linear chain of primitives ``[p0, p1, ...]`` where each
    intermediate value has exactly ONE use (the next link). Subclasses
    implement ``rewrite_chain(eqns, *invals)`` receiving the matched
    equations (first-to-last) and the FIRST eqn's inputs."""

    prims: tuple = ()

    def matches(self, eqn) -> bool:
        return bool(self.prims) and eqn.primitive.name == self.prims[0]

    def rewrite_chain(self, eqns, *invals):  # pragma: no cover - abstract
        raise NotImplementedError


def _iter_eqn_invals(eqn):
    return [v for v in eqn.invars if not isinstance(v, jex_core.Literal)]


def _plan_chains(jaxpr, patterns):
    """Find chain matches: eqn index -> (pattern, [eqn indices])."""
    use_count = {}
    producers = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in _iter_eqn_invals(eqn):
            use_count[v] = use_count.get(v, 0) + 1
        for v in eqn.outvars:
            producers[v] = i
    for v in jaxpr.outvars:
        if not isinstance(v, jex_core.Literal):
            use_count[v] = use_count.get(v, 0) + 1

    consumers = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in _iter_eqn_invals(eqn):
            consumers.setdefault(v, []).append(i)

    matches = {}
    claimed = set()
    for i, eqn in enumerate(jaxpr.eqns):
        if i in claimed:
            continue
        for pat in patterns:
            if not isinstance(pat, ChainPattern) or not pat.matches(eqn):
                continue
            idxs, cur = [i], eqn
            ok = True
            for want in pat.prims[1:]:
                if len(cur.outvars) != 1:
                    ok = False
                    break
                out = cur.outvars[0]
                if use_count.get(out, 0) != 1 or out not in consumers:
                    ok = False
                    break
                nxt = consumers[out][0]
                if jaxpr.eqns[nxt].primitive.name != want:
                    ok = False
                    break
                idxs.append(nxt)
                cur = jaxpr.eqns[nxt]
            if ok and not (set(idxs) & claimed):
                matches[i] = (pat, idxs)
                claimed.update(idxs)
                break
    return matches


def dead_code_elimination(jaxpr):
    """Indices of live equations (transitively reaching the outputs)."""
    live_vars = {v for v in jaxpr.outvars
                 if not isinstance(v, jex_core.Literal)}
    live_eqns = set()
    for i in range(len(jaxpr.eqns) - 1, -1, -1):
        eqn = jaxpr.eqns[i]
        # effects (io_callback, debug prints) pin an eqn live
        if any(v in live_vars for v in eqn.outvars) or eqn.effects:
            live_eqns.add(i)
            live_vars.update(_iter_eqn_invals(eqn))
    return live_eqns


class PatternRewriter:
    """Apply patterns greedily until fixpoint (bounded), then DCE.

    parity: pir::PassManager + pattern_rewrite's greedy driver
    (ApplyPatternsGreedily).
    """

    def __init__(self, patterns, dce=True, max_iterations=8):
        self.patterns = list(patterns)
        self.dce = dce
        self.max_iterations = max_iterations

    # -- single pass over one closed jaxpr --------------------------------
    def _rewrite_once(self, closed, args_flat):
        jaxpr = closed.jaxpr
        chain_matches = _plan_chains(jaxpr, self.patterns)
        changed = [False]
        live = (dead_code_elimination(jaxpr) if self.dce
                else set(range(len(jaxpr.eqns))))
        if len(live) != len(jaxpr.eqns):
            changed[0] = True

        def interp(*flat_args):
            env = {}

            def read(v):
                if isinstance(v, jex_core.Literal):
                    return v.val
                return env[v]

            def write(v, val):
                env[v] = val

            for cv, cval in zip(jaxpr.constvars, closed.consts):
                write(cv, cval)
            for iv, aval in zip(jaxpr.invars, flat_args):
                write(iv, aval)

            skip = set()
            i = 0
            while i < len(jaxpr.eqns):
                eqn = jaxpr.eqns[i]
                if i in skip or i not in live:
                    i += 1
                    continue
                if i in chain_matches:
                    pat, idxs = chain_matches[i]
                    first, last = jaxpr.eqns[idxs[0]], jaxpr.eqns[idxs[-1]]
                    invals = [read(v) for v in first.invars
                              if not isinstance(v, jex_core.Literal)]
                    out = pat.rewrite_chain([jaxpr.eqns[j] for j in idxs],
                                            *invals)
                    outs = out if isinstance(out, (tuple, list)) else (out,)
                    for v, val in zip(last.outvars, outs):
                        write(v, val)
                    skip.update(idxs)
                    changed[0] = True
                    i += 1
                    continue
                pat = next((p for p in self.patterns
                            if not isinstance(p, ChainPattern)
                            and p.matches(eqn)), None)
                if pat is not None:
                    invals = [read(v) for v in eqn.invars
                              if not isinstance(v, jex_core.Literal)]
                    out = pat.rewrite(*invals)
                    outs = out if isinstance(out, (tuple, list)) else (out,)
                    for v, val in zip(eqn.outvars, outs):
                        write(v, val)
                    changed[0] = True
                    i += 1
                    continue
                # default: evaluate the eqn unchanged (the canonical
                # eval_jaxpr binding dance, incl. call-like primitives)
                subfuns, bind_params = eqn.primitive.get_bind_params(
                    eqn.params)
                invals = [read(v) for v in eqn.invars]
                outs = eqn.primitive.bind(*subfuns, *invals, **bind_params)
                if not eqn.primitive.multiple_results:
                    outs = (outs,)
                for v, val in zip(eqn.outvars, outs):
                    write(v, val)
                i += 1
            return [read(v) for v in jaxpr.outvars]

        new_closed = jax.make_jaxpr(interp)(*args_flat)
        return new_closed, changed[0]

    def rewrite(self, fn):
        """fn -> rewritten callable (same signature, pytree in/out).

        The rewritten jaxpr is CACHED per input signature (treedef +
        avals): repeated calls pay only jaxpr evaluation, not retracing
        + the rewrite fixpoint."""
        rewriter = self
        cache = {}

        def wrapped(*args, **kwargs):
            flat, in_tree = tree_util.tree_flatten((args, kwargs))
            sig = (in_tree, tuple(
                (tuple(getattr(a, "shape", ())),
                 str(getattr(a, "dtype", type(a)))) for a in flat))
            entry = cache.get(sig)
            if entry is None:
                def flat_fn(*flat_args):
                    a, k = tree_util.tree_unflatten(in_tree, flat_args)
                    out = fn(*a, **k)
                    leaves, out_tree = tree_util.tree_flatten(out)
                    flat_fn.out_tree = out_tree
                    return leaves

                closed = jax.make_jaxpr(flat_fn)(*flat)
                for _ in range(rewriter.max_iterations):
                    closed, changed = rewriter._rewrite_once(closed, flat)
                    if not changed:
                        break
                entry = (closed, flat_fn.out_tree)
                cache[sig] = entry
            closed, out_tree = entry
            out_flat = jax.core.eval_jaxpr(
                closed.jaxpr, closed.consts, *flat)
            return tree_util.tree_unflatten(out_tree, out_flat)

        wrapped.__name__ = getattr(fn, "__name__", "rewritten")
        return wrapped

    def jaxpr_of(self, fn, *example_args):
        """The post-rewrite jaxpr (inspection surface, paddle.pir-style)."""
        flat, in_tree = tree_util.tree_flatten((example_args, {}))

        def flat_fn(*flat_args):
            a, k = tree_util.tree_unflatten(in_tree, flat_args)
            return tree_util.tree_leaves(fn(*a, **k))

        closed = jax.make_jaxpr(flat_fn)(*flat)
        for _ in range(self.max_iterations):
            closed, changed = self._rewrite_once(closed, flat)
            if not changed:
                break
        return closed


# ---------------------------------------------------------------------------
# built-in patterns (the reference ships a library of canonicalisations)
# ---------------------------------------------------------------------------
class TransposePairPattern(ChainPattern):
    """transpose(transpose(x, p), p') == x when p' inverts p."""

    prims = ("transpose", "transpose")

    def rewrite_chain(self, eqns, x):
        import numpy as np

        p0 = eqns[0].params["permutation"]
        p1 = eqns[1].params["permutation"]
        perm = tuple(np.asarray(p0)[list(p1)])
        if perm == tuple(range(len(perm))):
            return x
        import jax.numpy as jnp

        return jnp.transpose(x, perm)  # still fuses the pair into one


class CastChainPattern(ChainPattern):
    """convert(convert(x, a), b) -> convert(x, b) (lossy-mid casts are
    NOT collapsed: f32->bf16->f32 must keep the rounding)."""

    prims = ("convert_element_type", "convert_element_type")

    def rewrite_chain(self, eqns, x):
        import jax.numpy as jnp

        mid = eqns[0].params["new_dtype"]
        final = eqns[1].params["new_dtype"]
        src = x.dtype
        # collapse ONLY provably-lossless intermediates: float -> wider
        # (or equal) float. Anything else (narrowing floats, any integer
        # hop — int wrap-around, float->int truncation) changes values,
        # so both casts stay.
        if (jnp.issubdtype(src, jnp.floating)
                and jnp.issubdtype(mid, jnp.floating)
                and jnp.finfo(mid).bits >= jnp.finfo(src).bits):
            return x.astype(final)
        return x.astype(mid).astype(final)


class AddZeroPattern(RewritePattern):
    """x + 0 (literal) -> x."""

    def matches(self, eqn):
        if eqn.primitive.name != "add":
            return False
        return any(isinstance(v, jex_core.Literal)
                   and getattr(v.val, "shape", None) in ((), None)
                   and v.val == 0 for v in eqn.invars)

    def rewrite(self, *invals):
        return invals[0]
