"""Compatibility shims for older jax releases.

The framework targets the current jax API (``jax.shard_map`` with
``check_vma``/``axis_names``); on older runtimes (0.4.x) that surface
lives in ``jax.experimental.shard_map`` with different keyword names.
``install()`` runs once at package import and patches the missing
attributes onto the ``jax`` module so every call site (framework and
tests alike) can use the modern spelling unconditionally.

Mapping for the legacy signature
``shard_map(f, mesh, in_specs, out_specs, check_rep=True, auto=frozenset())``:

- ``check_vma=X``    -> ``check_rep=X`` (same meaning, renamed)
- ``axis_names={a}`` -> ``auto = mesh.axis_names - {a}`` (modern jax lists
  the MANUAL axes; legacy jax lists the AUTO complement)
"""
from __future__ import annotations

import functools


def install():
    import jax

    _install_enable_x64(jax)
    _install_pallas_names(jax)
    _install_abstract_mesh(jax)
    _install_pcast(jax)
    if hasattr(jax, "shard_map"):
        return

    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def shard_map(f=None, *, mesh=None, in_specs=None, out_specs=None,
                  check_vma=None, check_rep=None, axis_names=None):
        if f is None:  # partial application: shard_map(mesh=..., ...)(f)
            return functools.partial(
                shard_map, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_vma=check_vma, check_rep=check_rep,
                axis_names=axis_names)
        if check_rep is None:
            # default OFF: call sites written for the modern vma checker
            # trip false positives in the stricter legacy rep checker
            # (e.g. cond branches with mismatched replication types)
            check_rep = bool(check_vma) if check_vma is not None else False
        auto = frozenset()
        if axis_names is not None and mesh is not None:
            auto = frozenset(mesh.axis_names) - frozenset(axis_names)
        return _legacy_shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=check_rep,
                                 auto=auto)

    jax.shard_map = shard_map


def _install_enable_x64(jax):
    """``jax.enable_x64(bool)`` context manager.

    On legacy jax this is deliberately a NO-OP rather than
    jax.experimental.enable_x64/disable_x64: flipping x64 in the middle
    of an outer trace is buggy there (literals staged at lowering time
    revert to the global setting, producing mixed-width MLIR that the
    verifier rejects). The framework only uses ``enable_x64(False)`` to
    keep int64 literals away from Mosaic, and Mosaic never runs where
    this shim is active (legacy jax drives the pallas INTERPRET path,
    which tolerates 64-bit types)."""
    if hasattr(jax, "enable_x64"):
        return

    import contextlib

    jax.enable_x64 = lambda enabled=True: contextlib.nullcontext()


def _install_pcast(jax):
    """``jax.lax.pcast`` adjusts the varying/invariant manual-axis type
    annotation consumed by the modern vma checker. Legacy jax has no vma
    tracking (we always pass check_rep=False through the shard_map shim),
    so the cast is semantically an identity."""
    if hasattr(jax.lax, "pcast"):
        return
    jax.lax.pcast = lambda x, axis_name=None, to=None: x


def _install_abstract_mesh(jax):
    """``jax.sharding.get_abstract_mesh()`` — legacy jax has no
    abstract-mesh tracking, so report a permanently EMPTY mesh: callers
    branch to their no-manual-axes path, which matches legacy shard_map
    semantics (fully manual regions never reach with_sharding_constraint
    with hybrid specs there)."""
    if hasattr(jax.sharding, "get_abstract_mesh"):
        return

    class _EmptyAbstractMesh:
        empty = True
        axis_names = ()
        axis_types = ()

    _singleton = _EmptyAbstractMesh()
    jax.sharding.get_abstract_mesh = lambda: _singleton


def _install_pallas_names(jax):
    """``pltpu.CompilerParams`` was called ``TPUCompilerParams`` on 0.4.x."""
    try:
        from jax.experimental.pallas import tpu as pltpu
    except Exception:  # pallas not importable on this backend: nothing to do
        return
    if not hasattr(pltpu, "CompilerParams") and hasattr(
            pltpu, "TPUCompilerParams"):
        pltpu.CompilerParams = pltpu.TPUCompilerParams
