"""Queue-based reverse-mode autograd engine over the GradNode tape.

TPU-native re-design of the reference's ``egr::RunBackward``
(``paddle/fluid/eager/backward.cc:106``): build dependency counts over the
reachable node graph, seed output cotangents, then pop-run nodes whose
consumers have all contributed, accumulating into ``GradTensorHolder``-style
buffers.  ``paddle.grad``-style subgraph capture (the reference's
``GeneralGrad``) is implemented via capture keys on (node, out_index) / leaf.

When ``create_graph=True`` the per-node backward computation is re-recorded
through :func:`apply_op`, so higher-order derivatives compose naturally.
"""
from __future__ import annotations

import collections

import jax.numpy as jnp
import numpy as np
from jax import tree_util

from .dispatch import GradNode, apply_op, run_vjp, zero_cotangent


def _raw(x):
    from .tensor import Tensor

    return x._data if isinstance(x, Tensor) else x


def _accum(a, b):
    if a is None:
        return b
    from .tensor import Tensor

    if isinstance(a, Tensor) or isinstance(b, Tensor):
        return apply_op(jnp.add, a, b, _op_name="grad_accumulate")
    return jnp.add(a, b)


def _capture_key(t):
    if t._grad_node is not None:
        return ("node", id(t._grad_node), t._out_index)
    return ("leaf", id(t))


def run_backward(
    tensors,
    grad_tensors=None,
    retain_graph=False,
    create_graph=False,
    inputs=None,
    allow_unused=False,
    accumulate_grad=True,
):
    """Core engine. If `inputs` is given, returns their grads (capture mode)."""
    from .tensor import Tensor

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    tensors = list(tensors)
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]
    grad_tensors = list(grad_tensors)
    if len(grad_tensors) != len(tensors):
        raise ValueError("grad_tensors must match outputs in length")

    capture = None
    if inputs is not None:
        capture = {}
        for t in inputs:
            capture.setdefault(_capture_key(t), None)

    retain = retain_graph or create_graph

    # ---- seed cotangents --------------------------------------------------
    holders = {}  # id(node) -> list per out_idx of accumulated ct
    node_by_id = {}

    def _seed_value(t, g):
        if g is None:
            ones = jnp.ones(t._data.shape, t._data.dtype)
            return Tensor(ones) if create_graph else ones
        if not create_graph:
            g = _raw(g)
        elif not isinstance(g, Tensor):
            g = Tensor(jnp.asarray(g))
        return g

    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient and t._grad_node is None:
            raise RuntimeError(
                "Tensor passed to backward() has stop_gradient=True and no "
                "grad graph; nothing to differentiate."
            )
        g = _seed_value(t, g)
        node = t._grad_node
        if node is None:
            _deliver_leaf(t, g, capture, accumulate_grad, create_graph)
            continue
        node_by_id[id(node)] = node
        h = holders.setdefault(id(node), [None] * len(node.out_avals))
        h[t._out_index] = _accum(h[t._out_index], g)
        if capture is not None:
            k = ("node", id(node), t._out_index)
            if k in capture:
                capture[k] = _accum(capture[k], g)

    # ---- discover reachable graph + dependency counts ---------------------
    reachable = {}
    stack = list(node_by_id.values())
    while stack:
        n = stack.pop()
        if id(n) in reachable:
            continue
        reachable[id(n)] = n
        for e in n.edges:
            if e[0] == "node":
                stack.append(e[1])
    dep = collections.Counter()
    for n in reachable.values():
        for e in n.edges:
            if e[0] == "node" and id(e[1]) in reachable:
                dep[id(e[1])] += 1

    queue = collections.deque(
        n for nid, n in reachable.items() if dep[nid] == 0 and nid in holders
    )
    # nodes with dep 0 but no seed can exist only if unreachable from outputs;
    # they are simply never processed.

    processed = set()
    while queue:
        node = queue.popleft()
        if id(node) in processed:
            continue
        processed.add(id(node))
        h = holders.get(id(node), [None] * len(node.out_avals))
        cts = []
        for idx, aval in enumerate(node.out_avals):
            ct = h[idx]
            if ct is None:
                z = zero_cotangent(aval)
                ct = Tensor(z) if (create_graph and np.issubdtype(aval[1], np.inexact)) else z
            for hook in node.hooks.get(idx, ()):
                ct = hook(ct if isinstance(ct, Tensor) else Tensor(ct))
                if not create_graph:
                    ct = _raw(ct)
            cts.append(ct)

        gin = _node_backward(node, cts, create_graph)

        for g, edge in zip(gin, node.edges):
            if g is None:
                continue
            if edge[0] == "leaf":
                _deliver_leaf(edge[1], g, capture, accumulate_grad, create_graph)
            else:
                _, target, idx = edge
                if id(target) in reachable:
                    th = holders.setdefault(id(target), [None] * len(target.out_avals))
                    th[idx] = _accum(th[idx], g)
                    if capture is not None:
                        k = ("node", id(target), idx)
                        if k in capture:
                            capture[k] = _accum(capture[k], g)
                    dep[id(target)] -= 1
                    if dep[id(target)] == 0:
                        queue.append(target)
        holders.pop(id(node), None)
        if not retain:
            node.release()

    # ---- collect captured input grads ------------------------------------
    if capture is None:
        return None
    results = []
    for t in inputs:
        g = capture[_capture_key(t)]
        if g is None:
            if not allow_unused:
                raise RuntimeError(
                    "One of the differentiated tensors appears to not have "
                    "been used in the graph; set allow_unused=True to return "
                    "None for it."
                )
            results.append(None)
        else:
            if not isinstance(g, Tensor):
                g = Tensor(g, stop_gradient=True)
            results.append(g)
    return results


def _deliver_leaf(leaf, g, capture, accumulate_grad, create_graph):
    from .tensor import Tensor

    if capture is not None:
        k = ("leaf", id(leaf))
        if k in capture:
            capture[k] = _accum(capture[k], g)
        return  # only_inputs=True semantics: don't touch other leaves' .grad
    if leaf.stop_gradient or not accumulate_grad:
        return
    for hook in leaf._hooks:
        out = hook(g if isinstance(g, Tensor) else Tensor(g))
        if out is not None:
            g = out if create_graph else _raw(out)
    if not isinstance(g, Tensor):
        g = Tensor(g, stop_gradient=True)
    if leaf._grad is None:
        leaf._grad = g
    else:
        new = apply_op(jnp.add, leaf._grad, g, _op_name="grad_accumulate")
        if not create_graph:
            new.stop_gradient = True
        leaf._grad = new


PYLAYER_BACKWARD = None  # wired by paddle_tpu.autograd (PyLayer support)


def _node_backward(node: GradNode, cts, create_graph):
    from .tensor import Tensor

    if PYLAYER_BACKWARD is not None and type(node).__name__ == "_PyLayerGradNode":
        return PYLAYER_BACKWARD(node, cts, create_graph)

    if not create_graph:
        return run_vjp(node, cts)

    import jax

    def bw(cts_leaves, ins):
        c = tree_util.tree_unflatten(node.out_treedef, cts_leaves)
        _, pull = jax.vjp(node.pure_fn, list(ins))
        return pull(c)[0]

    if node.released:
        raise RuntimeError(
            f"GradNode {node.name} has been freed; use retain_graph=True."
        )
    return apply_op(bw, cts, node.in_tensors, _op_name=f"{node.name}_grad")
