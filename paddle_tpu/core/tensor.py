"""The eager Tensor: a python handle over a ``jax.Array`` payload.

TPU-native analogue of the reference's eager tensor
(``paddle/phi/api/include/tensor.h:82`` + ``paddle/fluid/pybind/eager.cc``):
holds the device buffer, the autograd meta (grad node + output index,
cf. ``AutogradMeta``), the ``stop_gradient`` flag (default True like the
reference — Parameters flip it to False), and the accumulated ``.grad``.

Most operator methods (``matmul``, ``__add__``, ``reshape``...) are patched on
by ``paddle_tpu.ops`` at import time, mirroring how the reference monkey-patches
``eager_math_op_patch.cc`` methods onto the pybind tensor type.

In-place ops (``add_``, ``__setitem__``) follow functional-rebind semantics:
the new value is computed out-of-place (XLA is functional) and this handle is
re-pointed at it, keeping autograd exact.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .. import dtypes as _dtype_mod

_tensor_counter = [0]


class Tensor:
    __slots__ = (
        "_data",
        "stop_gradient",
        "_grad",
        "_grad_node",
        "_out_index",
        "_hooks",
        "name",
        "persistable",
        "trainable",
        "_dist_attr",
        "__weakref__",
        "__dict__",
    )

    def __init__(self, data, stop_gradient=True, name=None):
        # `data` must already be a jax array (or tracer); user-facing creation
        # goes through paddle_tpu.to_tensor.
        self._data = data
        self.stop_gradient = stop_gradient
        self._grad = None
        self._grad_node = None
        self._out_index = 0
        self._hooks = []
        if name is None:
            _tensor_counter[0] += 1
            name = f"generated_tensor_{_tensor_counter[0]}"
        self.name = name
        self.persistable = False
        self.trainable = not stop_gradient
        self._dist_attr = None

    # ------------------------------------------------------------------
    # structure / metadata
    # ------------------------------------------------------------------
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    dim = ndim

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def dtype(self):
        return _dtype_mod.dtype_from_array(self._data)

    @property
    def place(self):
        from .. import device as _device

        try:
            dev = self._data.devices()
            plat = next(iter(dev)).platform
        except Exception:
            plat = "cpu"
        if plat == "cpu":
            return _device.CPUPlace(0)
        return _device.TPUPlace(0)

    @property
    def is_leaf(self):
        return self._grad_node is None

    def numel(self):
        return self.size

    def element_size(self):
        return self.dtype.itemsize

    # ------------------------------------------------------------------
    # host interop
    # ------------------------------------------------------------------
    def numpy(self):
        return np.asarray(self._data)

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def item(self, *args):
        a = self.numpy()
        if args:
            return a.item(*args)
        return a.item()

    def tolist(self):
        return self.numpy().tolist()

    def __float__(self):
        return float(self.item())

    def __int__(self):
        return int(self.item())

    def __bool__(self):
        if self.size != 1:
            raise ValueError(
                "The truth value of a Tensor with more than one element is "
                "ambiguous."
            )
        return bool(self.item())

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __repr__(self):
        grad_info = f", stop_gradient={self.stop_gradient}"
        try:
            vals = np.array2string(
                self.numpy(), precision=6, separator=", ", threshold=64
            )
        except Exception:
            vals = f"<traced {self._data}>"
        return (
            f"Tensor(shape={self.shape}, dtype={self.dtype.name}"
            f"{grad_info},\n       {vals})"
        )

    # ------------------------------------------------------------------
    # autograd
    # ------------------------------------------------------------------
    @property
    def grad(self):
        return self._grad

    @grad.setter
    def grad(self, value):
        self._grad = value

    def backward(self, grad_tensor=None, retain_graph=False):
        from . import autograd_engine

        autograd_engine.run_backward(
            [self], [grad_tensor], retain_graph=retain_graph
        )

    def clear_grad(self):
        self._grad = None

    def clear_gradient(self, set_to_zero=False):
        if set_to_zero and self._grad is not None:
            self._grad = Tensor(jnp.zeros_like(self._grad._data))
        else:
            self._grad = None

    def zero_grad(self):
        self.clear_grad()

    def register_hook(self, hook):
        """Hook on this tensor's gradient. Returns a removable handle."""
        if self._grad_node is not None:
            hooks = self._grad_node.hooks.setdefault(self._out_index, [])
            hooks.append(hook)
            container = hooks
        else:
            self._hooks.append(hook)
            container = self._hooks

        class RemovableHandle:
            def remove(self_inner):
                try:
                    container.remove(hook)
                except ValueError:
                    pass

        return RemovableHandle()

    def detach(self):
        t = Tensor(self._data, stop_gradient=True, name=self.name + ".detach")
        return t

    def detach_(self):
        self._grad_node = None
        self._out_index = 0
        self.stop_gradient = True
        return self

    def clone(self):
        from .dispatch import apply_op

        return apply_op(lambda x: x + 0, self, _op_name="clone")

    # ------------------------------------------------------------------
    # in-place rebind machinery
    # ------------------------------------------------------------------
    def _assign_result_(self, result: "Tensor"):
        """Re-point this handle at `result` (functional in-place)."""
        self._data = result._data
        self._grad_node = result._grad_node
        self._out_index = result._out_index
        self.stop_gradient = result.stop_gradient
        return self

    def set_value(self, value):
        if isinstance(value, Tensor):
            arr = value._data
        else:
            arr = jnp.asarray(np.asarray(value), dtype=self._data.dtype)
        arr = jnp.asarray(arr, dtype=self._data.dtype)
        if tuple(arr.shape) != tuple(self._data.shape):
            arr = arr.reshape(self._data.shape)
        # preserve device/sharding of the existing payload where possible
        try:
            arr = jax.device_put(arr, self._data.sharding)
        except Exception:
            pass
        self._data = arr
        return self

    def copy_(self, other, blocking=True):
        return self.set_value(other)

    # value/device helpers
    def cpu(self):
        return Tensor(jax.device_put(self._data, jax.devices("cpu")[0]))

    def to(self, *args, **kwargs):
        # supports .to(dtype), .to(device), .to(device, dtype)
        t = self
        for a in list(args) + list(kwargs.values()):
            try:
                d = _dtype_mod.convert_dtype(a)
            except (TypeError, ValueError, KeyError):
                continue
            t = t.astype(d)
        return t

    def pin_memory(self):
        return self

    def cuda(self, *a, **k):  # compat: "cuda" = the accelerator
        return self

    def contiguous(self):
        return self

    def is_contiguous(self):
        return True

    @property
    def T(self):
        from .dispatch import apply_op

        return apply_op(
            lambda x: jnp.transpose(x), self, _op_name="transpose"
        )

    # `astype` is defined here (needed before ops patching) -----------------
    def astype(self, dtype):
        from .dispatch import apply_op

        npd = _dtype_mod.to_np(dtype)
        return apply_op(
            lambda x: x.astype(npd), self, _op_name="cast"
        )

    cast = astype

    def _md5sum(self):
        import hashlib

        return hashlib.md5(np.ascontiguousarray(self.numpy()).tobytes()).hexdigest()


class Parameter(Tensor):
    """A trainable Tensor (stop_gradient=False by default)."""

    def __init__(self, data, trainable=True, name=None):
        super().__init__(data, stop_gradient=not trainable, name=name)
        self.persistable = True
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.do_model_average = None
        self.need_clip = True

    @property
    def trainable(self):
        return not self.stop_gradient

    @trainable.setter
    def trainable(self, v):
        self.stop_gradient = not v

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


# -- introspection surface (parity: tensor.prototype.pyi long tail) ---------
def _add_introspection():
    import jax.numpy as jnp

    Tensor.is_dense = lambda self: True
    Tensor.is_dist = lambda self: getattr(self, "_dist_attr", None) is not None
    Tensor.is_sparse = lambda self: False
    Tensor.is_sparse_coo = lambda self: False
    Tensor.is_sparse_csr = lambda self: False
    Tensor.is_selected_rows = lambda self: False
    Tensor.is_coalesced = lambda self: False
    Tensor.is_same_shape = lambda self, other: tuple(self.shape) == tuple(other.shape)
    Tensor.sparse_dim = lambda self: 0
    Tensor.dense_dim = lambda self: self._data.ndim
    Tensor.nnz = lambda self: int(jnp.count_nonzero(self._data))
    Tensor.get_tensor = lambda self: self
    Tensor.get_map_tensor = lambda self: self
    Tensor.get_selected_rows = lambda self: self
    Tensor.rows = lambda self: []
    Tensor.cols = lambda self: []
    Tensor.crows = lambda self: []
    Tensor.layout = property(lambda self: "NCHW")
    Tensor.type = lambda self: "DenseTensor"
    Tensor.offset = lambda self: 0
    Tensor.num_shard = lambda self: 1
    Tensor.data_ptr = lambda self: id(self._data)
    Tensor.get_strides = lambda self: list(self._data.strides) if hasattr(self._data, "strides") else []
    Tensor.strides = property(lambda self: self.get_strides())
    Tensor.grad_ = property(lambda self: self.grad)
    Tensor.grad_fn = property(lambda self: self._grad_node)
    Tensor._grad_ivar = lambda self: self.grad
    Tensor.data = property(lambda self: self,
                           lambda self, v: setattr(self, "_data",
                                                   v._data if isinstance(v, Tensor) else v))
    Tensor.process_mesh = property(
        lambda self: self._dist_attr.process_mesh if self._dist_attr else None)
    Tensor.placements = property(
        lambda self: self._dist_attr.placements if self._dist_attr else None)
    Tensor.set_vocab = lambda self, v: None
    Tensor.set_string_list = lambda self, v: None


_add_introspection()
