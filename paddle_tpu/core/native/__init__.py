"""Native (C++) runtime components, built on first import.

The reference implements its runtime substrate in C++ (store: N12
`tcp_store.h:121`; host tracer: N34 `host_tracer.cc`). These are the
TPU-native equivalents, compiled from the sources in this directory with
g++ into one shared library and bound via ctypes (the environment has no
pybind11 — ctypes is the sanctioned binding path).

Falls back cleanly (``LIB is None``) if no toolchain is available;
pure-Python equivalents in distributed/store.py and profiler keep the
API working.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_NAME = "libpaddle_tpu_native.so"

LIB = None


def _sources():
    return [os.path.join(_DIR, f) for f in sorted(os.listdir(_DIR))
            if f.endswith(".cc")]


def _build(lib_path):
    srcs = _sources()
    cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", "-pthread",
           "-o", lib_path] + srcs
    subprocess.run(cmd, check=True, capture_output=True, timeout=240)


def _load():
    global LIB
    lib_path = os.path.join(_DIR, _LIB_NAME)
    srcs = _sources()
    if not srcs:
        return None
    newest_src = max(os.path.getmtime(s) for s in srcs)
    if not os.path.exists(lib_path) or os.path.getmtime(lib_path) < newest_src:
        try:
            # build into a temp file then atomically rename, so concurrent
            # importers never load a half-written library
            fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
            os.close(fd)
            _build(tmp)
            os.replace(tmp, lib_path)
        except Exception:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
    try:
        lib = ctypes.CDLL(lib_path)
    except OSError:
        return None

    lib.pt_store_server_start.restype = ctypes.c_void_p
    lib.pt_store_server_start.argtypes = [ctypes.c_int,
                                          ctypes.POINTER(ctypes.c_int)]
    lib.pt_store_server_stop.argtypes = [ctypes.c_void_p]

    lib.pt_tracer_enable.argtypes = [ctypes.c_int]
    lib.pt_tracer_enabled.restype = ctypes.c_int
    lib.pt_tracer_now_ns.restype = ctypes.c_int64
    lib.pt_tracer_record.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int32, ctypes.c_int32,
    ]
    lib.pt_tracer_count.restype = ctypes.c_size_t
    lib.pt_tracer_drain.restype = ctypes.c_size_t
    lib.pt_tracer_drain.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.c_size_t,
    ]
    return lib


LIB = _load()


def available() -> bool:
    return LIB is not None


# ----------------------------------------------------------------- tracer

def tracer_enable(on=True):
    if LIB is not None:
        LIB.pt_tracer_enable(1 if on else 0)


def tracer_record(name: str, start_ns: int, end_ns: int, tid: int = 0,
                  kind: int = 0):
    if LIB is not None:
        LIB.pt_tracer_record(name.encode()[:63], start_ns, end_ns, tid, kind)


def tracer_now_ns() -> int:
    if LIB is not None:
        return LIB.pt_tracer_now_ns()
    import time

    return time.monotonic_ns()


def tracer_drain(cap=1 << 20):
    """Drain recorded events -> list of (name, start_ns, end_ns, tid, kind)."""
    if LIB is None:
        return []
    n = LIB.pt_tracer_count()
    if n == 0:
        return []
    cap = min(int(n), cap)
    names = ctypes.create_string_buffer(cap * 64)
    starts = (ctypes.c_int64 * cap)()
    ends = (ctypes.c_int64 * cap)()
    tids = (ctypes.c_int32 * cap)()
    kinds = (ctypes.c_int32 * cap)()
    got = LIB.pt_tracer_drain(names, starts, ends, tids, kinds, cap)
    out = []
    for i in range(got):
        raw = names.raw[i * 64:(i + 1) * 64]
        nm = raw.split(b"\0", 1)[0].decode(errors="replace")
        out.append((nm, starts[i], ends[i], tids[i], kinds[i]))
    return out
