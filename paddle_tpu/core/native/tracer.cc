// Host tracer: low-overhead RecordEvent buffer with chrome-trace export.
//
// Capability parity: the reference's native profiler host side
// (paddle/fluid/platform/profiler/host_tracer.cc RecordEvent +
// chrometracing_logger.cc). Device-side timing comes from jax.profiler
// (XPlane); this buffer captures framework host events (op dispatch,
// dataloader, collective launches) with ns timestamps and near-zero
// per-event cost, then Python renders chrome trace JSON.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <mutex>
#include <vector>

namespace {

struct Event {
  char name[64];
  int64_t start_ns;
  int64_t end_ns;
  int32_t tid;
  int32_t kind;  // 0 = duration, 1 = instant, 2 = counter(value=end_ns)
};

std::mutex g_mu;
std::vector<Event> g_events;
std::atomic<bool> g_enabled{false};

int64_t now_ns() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<int64_t>(ts.tv_sec) * 1000000000LL + ts.tv_nsec;
}

}  // namespace

extern "C" {

void pt_tracer_enable(int on) { g_enabled.store(on != 0); }

int pt_tracer_enabled() { return g_enabled.load() ? 1 : 0; }

int64_t pt_tracer_now_ns() { return now_ns(); }

// Record a completed duration event.
void pt_tracer_record(const char* name, int64_t start_ns, int64_t end_ns,
                      int32_t tid, int32_t kind) {
  if (!g_enabled.load()) return;
  Event e;
  std::strncpy(e.name, name ? name : "", sizeof(e.name) - 1);
  e.name[sizeof(e.name) - 1] = 0;
  e.start_ns = start_ns;
  e.end_ns = end_ns;
  e.tid = tid;
  e.kind = kind;
  std::lock_guard<std::mutex> lk(g_mu);
  g_events.push_back(e);
}

size_t pt_tracer_count() {
  std::lock_guard<std::mutex> lk(g_mu);
  return g_events.size();
}

// Copy up to `cap` events into caller-provided parallel arrays; returns n.
// names buffer must be cap*64 bytes.
size_t pt_tracer_drain(char* names, int64_t* starts, int64_t* ends,
                       int32_t* tids, int32_t* kinds, size_t cap) {
  std::lock_guard<std::mutex> lk(g_mu);
  size_t n = g_events.size() < cap ? g_events.size() : cap;
  for (size_t i = 0; i < n; ++i) {
    std::memcpy(names + i * 64, g_events[i].name, 64);
    starts[i] = g_events[i].start_ns;
    ends[i] = g_events[i].end_ns;
    tids[i] = g_events[i].tid;
    kinds[i] = g_events[i].kind;
  }
  g_events.erase(g_events.begin(), g_events.begin() + n);
  return n;
}

void pt_tracer_clear() {
  std::lock_guard<std::mutex> lk(g_mu);
  g_events.clear();
}

}  // extern "C"
