// TCPStore: key-value rendezvous for multi-controller bootstrap.
//
// Capability parity: the reference's C++ TCPStore
// (paddle/phi/core/distributed/store/tcp_store.h:121, socket.cpp) used by
// init_parallel_env for rank rendezvous and barriers. Same role here: the
// store carries coordinator discovery and small control-plane values; all
// tensor traffic rides XLA collectives, never the store.
//
// Protocol (length-prefixed binary, little-endian):
//   request:  u8 cmd | u32 klen | key bytes | u64 vlen | value bytes
//   response: u64 vlen | value bytes            (GET/WAIT/ADD)
//             u64 0xFFFFFFFFFFFFFFFF            (GET miss)
// cmds: 0=SET 1=GET 2=ADD(value=i64 delta -> new value as i64) 3=WAIT
//       4=DELETE 5=COMPARE_SET(unused) 6=PING
//
// Single-threaded poll() loop; WAIT parks the connection until the key
// appears (the reference parks the socket the same way).

#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <map>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string>
#include <sys/socket.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Conn {
  int fd;
  std::string inbuf;
  bool waiting = false;
  std::string wait_key;
};

struct Server {
  int listen_fd = -1;
  int port = 0;
  std::thread thread;
  bool stop = false;
  std::map<std::string, std::string> kv;
  std::vector<Conn*> conns;
};

bool send_all(int fd, const char* p, size_t n) {
  while (n > 0) {
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
    if (w <= 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

void reply_value(int fd, const std::string& v) {
  uint64_t vlen = v.size();
  std::string out(reinterpret_cast<char*>(&vlen), 8);
  out += v;
  send_all(fd, out.data(), out.size());
}

void reply_miss(int fd) {
  uint64_t vlen = ~0ULL;
  send_all(fd, reinterpret_cast<char*>(&vlen), 8);
}

// Returns bytes consumed (0 if incomplete).
size_t handle_one(Server* srv, Conn* c) {
  const std::string& b = c->inbuf;
  if (b.size() < 1 + 4) return 0;
  uint8_t cmd = static_cast<uint8_t>(b[0]);
  uint32_t klen;
  std::memcpy(&klen, b.data() + 1, 4);
  if (b.size() < 1 + 4 + klen + 8) return 0;
  std::string key = b.substr(5, klen);
  uint64_t vlen;
  std::memcpy(&vlen, b.data() + 5 + klen, 8);
  size_t total = 1 + 4 + klen + 8 + vlen;
  if (b.size() < total) return 0;
  std::string val = b.substr(5 + klen + 8, vlen);

  switch (cmd) {
    case 0:  // SET
      srv->kv[key] = val;
      reply_value(c->fd, "");
      break;
    case 1: {  // GET
      auto it = srv->kv.find(key);
      if (it == srv->kv.end()) reply_miss(c->fd);
      else reply_value(c->fd, it->second);
      break;
    }
    case 2: {  // ADD
      int64_t delta = 0;
      if (val.size() == 8) std::memcpy(&delta, val.data(), 8);
      int64_t cur = 0;
      auto it = srv->kv.find(key);
      if (it != srv->kv.end() && it->second.size() == 8)
        std::memcpy(&cur, it->second.data(), 8);
      cur += delta;
      std::string nv(reinterpret_cast<char*>(&cur), 8);
      srv->kv[key] = nv;
      reply_value(c->fd, nv);
      break;
    }
    case 3: {  // WAIT
      auto it = srv->kv.find(key);
      if (it != srv->kv.end()) {
        reply_value(c->fd, it->second);
      } else {
        c->waiting = true;
        c->wait_key = key;
      }
      break;
    }
    case 4:  // DELETE
      srv->kv.erase(key);
      reply_value(c->fd, "");
      break;
    case 6:  // PING
      reply_value(c->fd, "pong");
      break;
    default:
      reply_miss(c->fd);
  }
  return total;
}

void serve(Server* srv) {
  while (!srv->stop) {
    std::vector<pollfd> fds;
    fds.push_back({srv->listen_fd, POLLIN, 0});
    for (Conn* c : srv->conns) fds.push_back({c->fd, POLLIN, 0});
    int r = ::poll(fds.data(), fds.size(), 100 /*ms*/);
    if (r <= 0) continue;

    // conns polled THIS round: an accept below grows srv->conns past the
    // fds snapshot, and indexing fds[i+1] for the new conn would read out
    // of bounds — garbage revents can fake a POLLIN on the idle socket and
    // wedge the whole single-threaded loop in a blocking recv.
    const size_t n_polled = fds.size() - 1;
    if (fds[0].revents & POLLIN) {
      int fd = ::accept(srv->listen_fd, nullptr, nullptr);
      if (fd >= 0) {
        int one = 1;
        setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        srv->conns.push_back(new Conn{fd});
      }
    }
    std::vector<Conn*> alive;
    for (size_t i = 0; i < srv->conns.size(); ++i) {
      if (i >= n_polled) {  // accepted this round; poll it next iteration
        alive.push_back(srv->conns[i]);
        continue;
      }
      Conn* c = srv->conns[i];
      bool dead = false;
      if (fds[i + 1].revents & (POLLIN | POLLHUP | POLLERR)) {
        char buf[65536];
        ssize_t n = ::recv(c->fd, buf, sizeof(buf), 0);
        if (n <= 0) {
          dead = true;
        } else {
          c->inbuf.append(buf, static_cast<size_t>(n));
          size_t used;
          while ((used = handle_one(srv, c)) > 0) {
            c->inbuf.erase(0, used);
          }
          // a SET/ADD may satisfy parked WAITs
          for (Conn* w : srv->conns) {
            if (w->waiting && srv->kv.count(w->wait_key)) {
              w->waiting = false;
              reply_value(w->fd, srv->kv[w->wait_key]);
            }
          }
        }
      }
      if (dead) {
        ::close(c->fd);
        delete c;
      } else {
        alive.push_back(c);
      }
    }
    srv->conns.swap(alive);
  }
  for (Conn* c : srv->conns) {
    ::close(c->fd);
    delete c;
  }
  srv->conns.clear();
  ::close(srv->listen_fd);
}

}  // namespace

extern "C" {

// Returns an opaque handle (>0) or 0 on failure; *out_port gets the port.
void* pt_store_server_start(int port, int* out_port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    ::close(fd);
    return nullptr;
  }
  socklen_t len = sizeof(addr);
  getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len);
  if (::listen(fd, 128) != 0) {
    ::close(fd);
    return nullptr;
  }
  Server* srv = new Server();
  srv->listen_fd = fd;
  srv->port = ntohs(addr.sin_port);
  if (out_port) *out_port = srv->port;
  srv->thread = std::thread(serve, srv);
  return srv;
}

void pt_store_server_stop(void* handle) {
  Server* srv = static_cast<Server*>(handle);
  if (!srv) return;
  srv->stop = true;
  if (srv->thread.joinable()) srv->thread.join();
  delete srv;
}

}  // extern "C"
