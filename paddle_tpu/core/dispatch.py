"""Eager op dispatch with tape-based autograd recording.

This is the TPU-native analogue of the reference's generated ``*_ad_func``
eager wrappers (``paddle/fluid/eager/auto_code_generator/generator/eager_gen.py``)
plus ``GradNodeBase`` recording (``paddle/fluid/eager/grad_node_info.h:197``):
every framework op is a *pure jax function*; :func:`apply_op` executes it on the
unwrapped ``jax.Array`` payloads and, when gradients are required, records a
:class:`GradNode` holding the pure function and its differentiable inputs.

Backward (see ``autograd_engine.py``) recomputes the op under ``jax.vjp`` —
i.e. eager mode rematerializes forward activations during backward (cheap on
accelerators, memory-friendly, and makes higher-order autograd fall out
naturally because the backward computation can itself be re-recorded).

The jit/to_static path does NOT use this tape: whole training steps are traced
functionally and differentiated with ``jax.grad`` (see paddle_tpu/jit).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import tree_util

from .. import framework
from .. import telemetry as _telemetry

# module-level handles: the disabled path must cost one attribute check,
# not a registry lookup per op
_TELEMETRY_REG = _telemetry.get_registry()
_OP_DISPATCH = _telemetry.counter(
    "op_dispatch_total", "eager ops dispatched through apply_op",
    labelnames=("op",), max_series=2048)


def _is_tensor(x):
    from .tensor import Tensor

    return isinstance(x, Tensor)


class GradNode:
    """One recorded op on the tape.

    ``pure_fn`` maps a list of differentiable input arrays to the op's output
    pytree; non-differentiable inputs are captured in its closure (the
    analogue of the reference's ``TensorWrapper`` input capture).
    """

    __slots__ = (
        "name",
        "pure_fn",
        "in_arrays",
        "in_tensors",
        "edges",
        "out_avals",
        "out_treedef",
        "hooks",
        "released",
        "__weakref__",
    )

    def __init__(
        self, name, pure_fn, in_arrays, in_tensors, edges, out_avals, out_treedef
    ):
        self.name = name
        self.pure_fn = pure_fn
        self.in_arrays = in_arrays
        self.in_tensors = in_tensors  # differentiable input Tensors (captured)
        self.edges = edges  # list of ("node", node, out_idx) | ("leaf", tensor)
        self.out_avals = out_avals  # [(shape, np_dtype)] per output leaf
        self.out_treedef = out_treedef
        self.hooks = {}  # out_idx -> [fn]
        self.released = False

    def release(self):
        self.pure_fn = None
        self.in_arrays = None
        self.in_tensors = None
        self.released = True

    def __repr__(self):
        return f"<GradNode {self.name} n_out={len(self.out_avals)}>"


def _cast_leaf(a, target):
    """AMP leaf-cast rule, shared by eager autocast and segment capture
    (jit/lazy._amp_cast_wrap): cast float arrays to ``target``; pass
    through non-arrays, non-floats and float64."""
    if hasattr(a, "dtype") and hasattr(a, "astype") and jnp.issubdtype(
        getattr(a, "dtype", None), jnp.floating
    ) and a.dtype != target and a.dtype != np.float64:
        return a.astype(target)
    return a


def _maybe_autocast(op_name, arrays):
    from .. import amp as _amp

    state = _amp.amp_state()
    if not state.enabled:
        return arrays
    low = state.dtype.np_dtype
    if op_name in _amp.WHITE_LIST:
        target = low
    elif op_name in _amp.BLACK_LIST:
        target = np.float32
    else:
        return arrays
    return [_cast_leaf(a, target) for a in arrays]


def _differentiable(leaf):
    if not _is_tensor(leaf) or leaf.stop_gradient:
        return False
    return jnp.issubdtype(leaf._data.dtype, jnp.inexact)


def _record_static(fn, leaves, arrays, treedef, out_tree, op_name=None):
    """Append a replayable closure to the active static Program (the
    analogue of op-desc insertion, see paddle_tpu/static). The op name
    resolves registry metadata (ops/registry.py) onto the record — the
    program-level view of the reference's per-op YAML attrs."""
    from ..static import _active_program

    prog = _active_program()
    if prog is None:
        return
    tensor_pos = [i for i, l in enumerate(leaves) if _is_tensor(l)]

    def replay(tensor_arrays, _arrays=list(arrays), _pos=tuple(tensor_pos),
               _treedef=treedef):
        buf = list(_arrays)
        for p, a in zip(_pos, tensor_arrays):
            buf[p] = a
        a2, k2 = tree_util.tree_unflatten(_treedef, buf)
        return fn(*a2, **k2)

    out_leaves = [t for t in tree_util.tree_flatten(
        out_tree, is_leaf=_is_tensor)[0] if _is_tensor(t)]
    prog._record(replay, [leaves[i] for i in tensor_pos], out_leaves,
                 op_name=op_name)


def _check_nan_inf(op_name, out):
    """FLAGS_check_nan_inf eager hook: after every op, sync and verify all
    float outputs are finite, raising with the op's name (reference:
    nan_inf_utils per-kernel check, enabled by the same flag). Off by
    default — the flag read is the only cost."""
    from ..utils.flags import get_flags

    if not get_flags("check_nan_inf")["check_nan_inf"]:
        return
    for leaf in tree_util.tree_leaves(out):
        if isinstance(leaf, jax.core.Tracer):
            return  # inside a trace: the checkify-instrumented step covers it
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating):
            if not bool(jnp.all(jnp.isfinite(leaf))):
                raise FloatingPointError(
                    f"FLAGS_check_nan_inf: op '{op_name}' produced nan/inf "
                    f"(shape {tuple(leaf.shape)}, dtype {leaf.dtype})")


def apply_op(fn, *args, _op_name=None, **kwargs):
    """Run pure jax function `fn` over (args, kwargs) that may contain Tensors.

    Returns outputs wrapped as Tensors, recording a GradNode if needed.
    """
    from .tensor import Tensor

    leaves, treedef = tree_util.tree_flatten(
        (args, kwargs), is_leaf=lambda x: _is_tensor(x)
    )
    arrays = [l._data if _is_tensor(l) else l for l in leaves]

    name_for_amp = _op_name or getattr(fn, "__name__", "op")

    if _TELEMETRY_REG.enabled:
        _OP_DISPATCH.inc(labels=(name_for_amp,))

    # Segment capture (jit/lazy.py): record the op into the current
    # segment instead of dispatching — graph-broken to_static calls
    # compile op RUNS, not single ops. AMP casts are folded INTO the
    # recorded op (amp_target) so a captured segment under auto_cast
    # computes in the same dtypes as the per-op eager fallback. In
    # grad_mode traces (training fallback), each flushed segment becomes
    # ONE GradNode (lazy._attach_grad) — staged autograd.
    from ..jit.lazy import current_trace

    _trace = current_trace()
    if _trace is not None and (
            _trace.grad_mode or not framework.is_grad_enabled()):
        from .. import amp as _amp

        state = _amp.amp_state()
        amp_target = None
        if state.enabled:
            if name_for_amp in _amp.WHITE_LIST:
                amp_target = state.dtype.np_dtype
            elif name_for_amp in _amp.BLACK_LIST:
                amp_target = np.float32
        grad_on = _trace.grad_mode and framework.is_grad_enabled()
        out = _trace.record(fn, arrays, treedef, name_for_amp,
                            amp_target=amp_target,
                            leaves=leaves if grad_on else None)
        wrapped = _wrap_outputs(out, node=None)
        if grad_on:
            _trace.note_out_tensors(tree_util.tree_flatten(
                wrapped, is_leaf=_is_tensor)[0])
        return wrapped

    # AMP autocast: per-op white/black list casting (reference analogue:
    # AMP logic injected per-op by eager codegen, eager_gen.py:1996-2055).
    arrays = _maybe_autocast(name_for_amp, arrays)

    record = framework.is_grad_enabled()
    diff_pos = [i for i, l in enumerate(leaves) if _differentiable(l)] if record else []

    if not diff_pos:
        a2, k2 = tree_util.tree_unflatten(treedef, arrays)
        out = fn(*a2, **k2)
        _check_nan_inf(name_for_amp, out)
        wrapped = _wrap_outputs(out, node=None)
        _record_static(fn, leaves, arrays, treedef, wrapped,
                       op_name=name_for_amp)
        return wrapped

    def pure(diff_arrays):
        buf = list(arrays)
        for pos, arr in zip(diff_pos, diff_arrays):
            buf[pos] = arr
        a2, k2 = tree_util.tree_unflatten(treedef, buf)
        return fn(*a2, **k2)

    in_arrays = [arrays[i] for i in diff_pos]
    out = pure(in_arrays)
    _check_nan_inf(name_for_amp, out)

    edges = []
    for i in diff_pos:
        t = leaves[i]
        if t._grad_node is not None:
            edges.append(("node", t._grad_node, t._out_index))
        else:
            edges.append(("leaf", t))

    out_leaves, out_treedef = tree_util.tree_flatten(out)
    out_avals = [(tuple(o.shape), np.dtype(o.dtype)) for o in out_leaves]
    in_tensors = [leaves[i] for i in diff_pos]
    node = GradNode(name_for_amp, pure, in_arrays, in_tensors, edges,
                    out_avals, out_treedef)

    wrapped = []
    for idx, o in enumerate(out_leaves):
        t = Tensor(o, stop_gradient=not jnp.issubdtype(o.dtype, jnp.inexact))
        if not t.stop_gradient:
            t._grad_node = node
            t._out_index = idx
        wrapped.append(t)
    out_tree = tree_util.tree_unflatten(out_treedef, wrapped)
    _record_static(fn, leaves, arrays, treedef, out_tree,
                   op_name=name_for_amp)
    return out_tree


def _wrap_outputs(out, node):
    from .tensor import Tensor

    out_leaves, out_treedef = tree_util.tree_flatten(out)
    wrapped = [Tensor(o, stop_gradient=True) for o in out_leaves]
    return tree_util.tree_unflatten(out_treedef, wrapped)


def run_vjp(node: GradNode, cotangents):
    """Compute input gradients for `node` given per-output cotangent arrays."""
    if node.released:
        raise RuntimeError(
            f"GradNode {node.name} has been freed; pass retain_graph=True "
            "if you need to backward through the graph a second time."
        )
    cts = tree_util.tree_unflatten(node.out_treedef, cotangents)
    _, pull = jax.vjp(node.pure_fn, node.in_arrays)
    (gin,) = pull(cts)
    return gin


def zero_cotangent(aval):
    shape, dt = aval
    if np.issubdtype(dt, np.inexact):
        return jnp.zeros(shape, dt)
    return np.zeros(shape, jax.dtypes.float0)
