from .tensor import Tensor  # noqa: F401
from .dispatch import apply_op  # noqa: F401
from . import autograd_engine  # noqa: F401
