"""Op surface assembly + Tensor method patching.

Re-exports the functional op library and monkey-patches operator methods onto
:class:`~paddle_tpu.core.tensor.Tensor`, mirroring how the reference attaches
math methods to its pybind eager tensor
(``paddle/fluid/pybind/eager_math_op_patch.cc``).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.dispatch import apply_op
from ..core.tensor import Tensor

from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403

from . import creation, math, manipulation, logic, linalg, random  # noqa: F401


# ---------------------------------------------------------------------------
# indexing
# ---------------------------------------------------------------------------
def _convert_index(idx):
    if isinstance(idx, Tensor):
        return idx._data
    if isinstance(idx, tuple):
        return tuple(_convert_index(i) for i in idx)
    if isinstance(idx, list):
        return jnp.asarray(np.asarray(idx)) if idx and not isinstance(idx[0], (slice, type(None))) else [
            _convert_index(i) for i in idx
        ]
    return idx


def _tensor_getitem(self, idx):
    jidx = _convert_index(idx)
    return apply_op(lambda a: a[jidx], self, _op_name="getitem")


def _tensor_setitem(self, idx, value):
    jidx = _convert_index(idx)
    if isinstance(value, Tensor):
        out = apply_op(
            lambda a, v: a.at[jidx].set(v.astype(a.dtype)),
            self,
            value,
            _op_name="setitem",
        )
    else:
        out = apply_op(
            lambda a: a.at[jidx].set(jnp.asarray(value, a.dtype)),
            self,
            _op_name="setitem",
        )
    self._assign_result_(out)


def _tensor_iter(self):
    for i in range(self.shape[0]):
        yield self[i]


# ---------------------------------------------------------------------------
# method patching
# ---------------------------------------------------------------------------
_BINARY_DUNDERS = {
    "__add__": math.add,
    "__radd__": lambda x, y: math.add(y, x) if isinstance(y, Tensor) else apply_op(lambda a: jnp.add(y, a), x),
    "__sub__": math.subtract,
    "__rsub__": lambda x, y: apply_op(lambda a: jnp.subtract(y, a), x) if not isinstance(y, Tensor) else math.subtract(y, x),
    "__mul__": math.multiply,
    "__rmul__": lambda x, y: apply_op(lambda a: jnp.multiply(y, a), x) if not isinstance(y, Tensor) else math.multiply(y, x),
    "__truediv__": math.divide,
    "__rtruediv__": lambda x, y: apply_op(lambda a: jnp.true_divide(y, a), x) if not isinstance(y, Tensor) else math.divide(y, x),
    "__floordiv__": math.floor_divide,
    "__rfloordiv__": lambda x, y: apply_op(lambda a: jnp.floor_divide(y, a), x),
    "__mod__": math.mod,
    "__rmod__": lambda x, y: apply_op(lambda a: jnp.mod(y, a), x),
    "__pow__": math.pow,
    "__rpow__": lambda x, y: apply_op(lambda a: jnp.power(y, a), x),
    "__matmul__": linalg.matmul,
    "__rmatmul__": lambda x, y: linalg.matmul(y, x) if isinstance(y, Tensor) else apply_op(lambda a: jnp.matmul(y, a), x),
    "__eq__": logic.equal,
    "__ne__": logic.not_equal,
    "__lt__": logic.less_than,
    "__le__": logic.less_equal,
    "__gt__": logic.greater_than,
    "__ge__": logic.greater_equal,
    "__and__": logic.bitwise_and,
    "__or__": logic.bitwise_or,
    "__xor__": logic.bitwise_xor,
    "__lshift__": logic.bitwise_left_shift,
    "__rshift__": logic.bitwise_right_shift,
}

_UNARY_DUNDERS = {
    "__neg__": math.neg,
    "__abs__": math.abs,
    "__invert__": logic.bitwise_not,
}

_METHODS = dict(
    # math
    add=math.add, subtract=math.subtract, multiply=math.multiply,
    divide=math.divide, floor_divide=math.floor_divide, mod=math.mod,
    remainder=math.remainder, pow=math.pow, maximum=math.maximum,
    minimum=math.minimum, fmax=math.fmax, fmin=math.fmin,
    exp=math.exp, expm1=math.expm1, log=math.log, log2=math.log2,
    log10=math.log10, log1p=math.log1p, sqrt=math.sqrt, rsqrt=math.rsqrt,
    abs=math.abs, neg=math.neg, sign=math.sign, sin=math.sin, cos=math.cos,
    tan=math.tan, asin=math.asin, acos=math.acos, atan=math.atan,
    sinh=math.sinh, cosh=math.cosh, tanh=math.tanh, asinh=math.asinh,
    acosh=math.acosh, atanh=math.atanh, floor=math.floor, ceil=math.ceil,
    round=math.round, trunc=math.trunc, frac=math.frac,
    reciprocal=math.reciprocal, square=math.square, erf=math.erf,
    erfinv=math.erfinv, sigmoid=math.sigmoid, digamma=math.digamma,
    lgamma=math.lgamma, logit=math.logit, scale=math.scale, clip=math.clip,
    lerp=math.lerp, nan_to_num=math.nan_to_num, atan2=math.atan2,
    angle=math.angle, conj=math.conj, real=math.real, imag=math.imag,
    # reductions
    sum=math.sum, mean=math.mean, prod=math.prod, max=math.max, min=math.min,
    amax=math.amax, amin=math.amin, logsumexp=math.logsumexp, all=math.all,
    any=math.any, std=math.std, var=math.var, median=math.median,
    nanmean=math.nanmean, nansum=math.nansum, quantile=math.quantile,
    count_nonzero=math.count_nonzero,
    argmax=math.argmax, argmin=math.argmin, cumsum=math.cumsum,
    cumprod=math.cumprod, cummax=math.cummax, cummin=math.cummin,
    logcumsumexp=math.logcumsumexp, trace=math.trace, diff=math.diff,
    isnan=math.isnan, isinf=math.isinf, isfinite=math.isfinite, isin=math.isin,
    inner=math.inner, outer=math.outer, kron=math.kron,
    heaviside=math.heaviside, hypot=math.hypot,
    # manipulation
    reshape=manipulation.reshape, reshape_=manipulation.reshape_,
    transpose=manipulation.transpose, flatten=manipulation.flatten,
    flatten_=manipulation.flatten_, squeeze=manipulation.squeeze,
    squeeze_=manipulation.squeeze_, unsqueeze=manipulation.unsqueeze,
    unsqueeze_=manipulation.unsqueeze_, tile=manipulation.tile,
    expand=manipulation.expand, expand_as=manipulation.expand_as,
    broadcast_to=manipulation.broadcast_to, flip=manipulation.flip,
    roll=manipulation.roll, rot90=manipulation.rot90, split=manipulation.split,
    chunk=manipulation.chunk, unbind=manipulation.unbind,
    gather=manipulation.gather, gather_nd=manipulation.gather_nd,
    scatter=manipulation.scatter, scatter_=manipulation.scatter_,
    scatter_nd_add=manipulation.scatter_nd_add,
    index_select=manipulation.index_select, index_sample=manipulation.index_sample,
    index_add=manipulation.index_add, index_put=manipulation.index_put,
    take_along_axis=manipulation.take_along_axis,
    put_along_axis=manipulation.put_along_axis, take=manipulation.take,
    masked_select=manipulation.masked_select, masked_fill=manipulation.masked_fill,
    masked_fill_=manipulation.masked_fill_, where=manipulation.where,
    nonzero=manipulation.nonzero, repeat_interleave=manipulation.repeat_interleave,
    pad=manipulation.pad, topk=manipulation.topk, sort=manipulation.sort,
    argsort=manipulation.argsort, unique=manipulation.unique,
    unique_consecutive=manipulation.unique_consecutive,
    moveaxis=manipulation.moveaxis, swapaxes=manipulation.swapaxes,
    kthvalue=manipulation.kthvalue, mode=manipulation.mode,
    as_strided=manipulation.as_strided, unfold=manipulation.unfold,
    tensor_split=manipulation.tensor_split, bucketize=manipulation.bucketize,
    view=manipulation.view,
    fill_diagonal_tensor=manipulation.fill_diagonal_tensor,
    fill_diagonal_tensor_=manipulation.fill_diagonal_tensor_,
    top_p_sampling=manipulation.top_p_sampling,
    # logic
    equal=logic.equal, not_equal=logic.not_equal, less_than=logic.less_than,
    less_equal=logic.less_equal, greater_than=logic.greater_than,
    greater_equal=logic.greater_equal, logical_and=logic.logical_and,
    logical_or=logic.logical_or, logical_xor=logic.logical_xor,
    logical_not=logic.logical_not, bitwise_and=logic.bitwise_and,
    bitwise_or=logic.bitwise_or, bitwise_xor=logic.bitwise_xor,
    bitwise_not=logic.bitwise_not, isclose=logic.isclose,
    allclose=logic.allclose, equal_all=logic.equal_all,
    # linalg
    matmul=linalg.matmul, mm=linalg.mm, bmm=linalg.bmm, dot=linalg.dot,
    mv=linalg.mv, norm=linalg.norm, dist=linalg.dist, cross=linalg.cross,
    cholesky=linalg.cholesky, inverse=linalg.inverse, t=manipulation.t,
    cast=manipulation.cast, cast_=manipulation.cast_,
    # creation-ish
    tril=creation.tril, triu=creation.triu, diag=creation.diag,
    diag_embed=creation.diag_embed,
    # random in-place
    uniform_=random.uniform_, normal_=random.normal_,
    exponential_=random.exponential_, bernoulli_=random.bernoulli_,
    multinomial=random.multinomial, bernoulli=random.bernoulli,
)

# autogenerated in-place arithmetic variants (functional rebind)
_INPLACE_FROM = dict(
    add_=math.add, subtract_=math.subtract, multiply_=math.multiply,
    divide_=math.divide, scale_=math.scale, clip_=math.clip, pow_=math.pow,
    exp_=math.exp, sqrt_=math.sqrt, rsqrt_=math.rsqrt, abs_=math.abs,
    floor_=math.floor, ceil_=math.ceil, round_=math.round, neg_=math.neg,
    reciprocal_=math.reciprocal, tanh_=math.tanh, sigmoid_=math.sigmoid,
    erfinv_=math.erfinv, remainder_=math.remainder, mod_=math.mod,
    lerp_=math.lerp, where_=manipulation.where,
)


def _make_inplace(fn):
    def method(self, *args, **kwargs):
        out = fn(self, *args, **kwargs)
        return self._assign_result_(out)

    return method


def _make_method(fn):
    def method(self, *args, **kwargs):
        return fn(self, *args, **kwargs)

    return method


def _fill_(self, value):
    out = apply_op(
        lambda a: jnp.full_like(a, value), self, _op_name="fill_"
    )
    return self._assign_result_(out)


def _zero_(self):
    return _fill_(self, 0)


def _fill_diagonal_(self, value, offset=0, wrap=False):
    def _fd(a):
        n = min(a.shape[-2], a.shape[-1])
        idx = jnp.arange(n - (offset if offset > 0 else 0))
        rows = idx + max(-offset, 0)
        cols = idx + max(offset, 0)
        return a.at[..., rows, cols].set(value)

    return self._assign_result_(apply_op(_fd, self, _op_name="fill_diagonal_"))


def patch_tensor_methods():
    for dunder, fn in _BINARY_DUNDERS.items():
        setattr(Tensor, dunder, _make_method(fn))
    for dunder, fn in _UNARY_DUNDERS.items():
        setattr(Tensor, dunder, _make_method(fn))
    for name, fn in _METHODS.items():
        setattr(Tensor, name, _make_method(fn))
    for name, fn in _INPLACE_FROM.items():
        setattr(Tensor, name, _make_inplace(fn))
    Tensor.__getitem__ = _tensor_getitem
    Tensor.__setitem__ = _tensor_setitem
    Tensor.__iter__ = _tensor_iter
    Tensor.__hash__ = object.__hash__
    Tensor.fill_ = _fill_
    Tensor.zero_ = _zero_
    Tensor.fill_diagonal_ = _fill_diagonal_
    # numpy priority so np_scalar * Tensor defers to Tensor.__rmul__
    Tensor.__array_priority__ = 1000


patch_tensor_methods()
from .compat import *  # noqa: F401,F403


# -- inplace `_` variants (parity: paddle's trailing-underscore API) --------
# Functional-core emulation: compute out-of-place, then rebind the payload
# (the reference mutates the buffer; with XLA's immutable arrays, rebinding
# is observationally equivalent for the python surface).
def _make_inplace(base_name):
    def inplace(x, *args, **kwargs):
        base = getattr(x, base_name, None)
        if base is None:
            import paddle_tpu as _p

            fn = getattr(_p, base_name)
            out = fn(x, *args, **kwargs)
        else:
            out = base(*args, **kwargs)
        x._data = out._data.astype(x._data.dtype) if out._data.dtype != x._data.dtype else out._data
        x._grad_node = out._grad_node
        x._out_index = getattr(out, "_out_index", 0)
        return x

    inplace.__name__ = base_name + "_"
    return inplace


_INPLACE_BASES = [
    "abs", "acos", "asin", "atan", "cos", "sin", "tan", "sinh", "cosh",
    "tanh", "exp", "expm1", "log", "log2", "log10", "log1p", "sqrt",
    "rsqrt", "square", "floor", "ceil", "round", "trunc", "sigmoid",
    "reciprocal", "neg", "erf", "erfinv", "digamma", "lgamma", "frac",
    "cumsum", "cumprod", "clip", "scale", "pow", "remainder", "mod",
    "floor_divide", "floor_mod", "divide", "multiply", "subtract", "add",
    "equal", "greater_equal", "greater_than", "less_equal", "less_than",
    "not_equal", "logical_and", "logical_or", "logical_not", "logical_xor",
    "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
    "gcd", "lcm", "hypot", "copysign", "nan_to_num", "logit", "i0",
    "index_add", "index_put", "index_fill", "tril", "triu", "gammaln",
    "gammainc", "gammaincc", "multigammaln", "polygamma", "sinc", "ldexp",
    "renorm", "lerp", "fill_diagonal", "masked_scatter", "t", "less",
    "addmm",
    "bitwise_invert", "bitwise_left_shift", "bitwise_right_shift",
]

import sys as _sys

_mod = _sys.modules[__name__]
for _b in _INPLACE_BASES:
    if not hasattr(Tensor, _b) and not hasattr(_mod, _b):
        continue
    _ip = _make_inplace(_b)
    setattr(_mod, _b + "_", _ip)
    setattr(Tensor, _b + "_", _ip)


def _random_inplace(name, sampler):
    def fn(x, *args, **kwargs):
        from .. import framework

        x._data = sampler(framework.next_rng_key(), x._data, *args)
        return x

    fn.__name__ = name
    setattr(_mod, name, fn)
    setattr(Tensor, name, fn)


import jax as _jax

_random_inplace("cauchy_", lambda k, a, loc=0.0, scale=1.0:
                (loc + scale * _jax.random.cauchy(k, a.shape)).astype(a.dtype))
_random_inplace("geometric_", lambda k, a, probs=0.5:
                jnp.floor(jnp.log(_jax.random.uniform(k, a.shape, minval=1e-7))
                          / jnp.log1p(-probs)).astype(a.dtype))
_random_inplace("log_normal_", lambda k, a, mean=1.0, std=2.0:
                jnp.exp(mean + std * _jax.random.normal(k, a.shape)).astype(a.dtype))
_random_inplace("exponential_", lambda k, a, lam=1.0:
                (_jax.random.exponential(k, a.shape) / lam).astype(a.dtype))
