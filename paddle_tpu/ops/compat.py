"""Long-tail tensor API surface (parity: the remaining python/paddle
top-level exports — special functions, split/stack helpers, scatter
variants, reductions). Each op is a pure jnp function through apply_op.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import apply_op
from ..core.tensor import Tensor


def _op(name, jfn):
    def f(x, *args, **kwargs):
        kwargs.pop("name", None)
        return apply_op(lambda a, *r: jfn(a, *r), x, *args, _op_name=name)

    f.__name__ = name
    return f


# -- special functions ------------------------------------------------------
gammaln = _op("gammaln", lambda a: jax.scipy.special.gammaln(a))
digamma_fn = lambda a: jax.scipy.special.digamma(a)
gammainc = _op("gammainc", lambda a, x: jax.scipy.special.gammainc(a, x))
gammaincc = _op("gammaincc", lambda a, x: jax.scipy.special.gammaincc(a, x))
i0e = _op("i0e", lambda a: jax.scipy.special.i0e(a))
i1e = _op("i1e", lambda a: jax.scipy.special.i1e(a))
sinc = _op("sinc", lambda a: jnp.sinc(a))
signbit = _op("signbit", lambda a: jnp.signbit(a))
sgn = _op("sgn", lambda a: jnp.sign(a))
positive = _op("positive", lambda a: +a)
bitwise_invert = _op("bitwise_invert", lambda a: jnp.invert(a))


def polygamma(x, n, name=None):
    return apply_op(
        lambda a: jax.scipy.special.polygamma(int(n), a), x,
        _op_name="polygamma")


def multigammaln(x, p, name=None):
    def _mg(a):
        out = 0.25 * p * (p - 1) * math.log(math.pi)
        for i in range(p):
            out = out + jax.scipy.special.gammaln(a - i / 2.0)
        return out

    return apply_op(_mg, x, _op_name="multigammaln")


def frexp(x, name=None):
    return apply_op(lambda a: jnp.frexp(a), x, _op_name="frexp")


def ldexp(x, y, name=None):
    # x * 2**y (reference math.py ldexp uses pow: fractional exponents scale
    # fractionally). Integer exponents ride jnp.ldexp (exact, no overflow at
    # large y in float64); the working dtype is the promoted float of (x, y).
    def _ldexp(a, b):
        out_dt = jnp.promote_types(jnp.promote_types(a.dtype, b.dtype),
                                   jnp.float32)
        if jnp.issubdtype(b.dtype, jnp.integer):
            return jnp.ldexp(a.astype(out_dt), b)
        return a.astype(out_dt) * jnp.power(jnp.asarray(2.0, out_dt),
                                            b.astype(out_dt))

    return apply_op(_ldexp, x, y, _op_name="ldexp")


# -- reductions -------------------------------------------------------------
def nanmedian(x, axis=None, keepdim=False, mode="avg", name=None):
    return apply_op(lambda a: jnp.nanmedian(a, axis=axis, keepdims=keepdim),
                    x, _op_name="nanmedian")


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    return apply_op(
        lambda a: jnp.nanquantile(a, q, axis=axis, keepdims=keepdim), x,
        _op_name="nanquantile")


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    def _tz(ya, xa):
        if xa is not None:
            return jax.scipy.integrate.trapezoid(ya, xa, axis=axis)
        return jax.scipy.integrate.trapezoid(ya, dx=dx or 1.0, axis=axis)

    return apply_op(_tz, y, x, _op_name="trapezoid")


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    def _ctz(ya, xa):
        ya = jnp.moveaxis(ya, axis, -1)
        if xa is not None:
            xs = jnp.moveaxis(xa, axis, -1) if xa.ndim == ya.ndim else xa
            d = jnp.diff(xs, axis=-1)
        else:
            d = dx or 1.0
        avg = (ya[..., 1:] + ya[..., :-1]) / 2.0
        return jnp.moveaxis(jnp.cumsum(avg * d, axis=-1), -1, axis)

    return apply_op(_ctz, y, x, _op_name="cumulative_trapezoid")


def reduce_as(x, target, name=None):
    def _ra(a, t):
        extra = a.ndim - t.ndim
        axes = tuple(range(extra)) + tuple(
            i + extra for i, s in enumerate(t.shape) if s == 1 and a.shape[i + extra] != 1
        )
        out = jnp.sum(a, axis=axes, keepdims=False)
        return out.reshape(t.shape)

    return apply_op(_ra, x, target, _op_name="reduce_as")


def histogram_bin_edges(input, bins=100, min=0, max=0, name=None):
    def _hbe(a):
        lo, hi = (min, max) if (min or max) else (jnp.min(a), jnp.max(a))
        return jnp.linspace(lo, hi, bins + 1)

    return apply_op(_hbe, input, _op_name="histogram_bin_edges")


def pdist(x, p=2.0, name=None):
    def _pd(a):
        n = a.shape[0]
        diffs = a[:, None, :] - a[None, :, :]
        d = jnp.linalg.norm(diffs, ord=p, axis=-1)
        iu = jnp.triu_indices(n, 1)
        return d[iu]

    return apply_op(_pd, x, _op_name="pdist")


def hypot(x, y, name=None):
    return apply_op(lambda a, b: jnp.hypot(a, b), x, y, _op_name="hypot")


# -- construction / reshaping ----------------------------------------------
def vander(x, n=None, increasing=False, name=None):
    return apply_op(
        lambda a: jnp.vander(a, N=n, increasing=increasing), x,
        _op_name="vander")


def block_diag(inputs, name=None):
    return apply_op(
        lambda *xs: jax.scipy.linalg.block_diag(*xs), *inputs,
        _op_name="block_diag")


def column_stack(x, name=None):
    return apply_op(lambda *xs: jnp.column_stack(xs), *x,
                    _op_name="column_stack")


def row_stack(x, name=None):
    return apply_op(lambda *xs: jnp.vstack(xs), *x, _op_name="row_stack")


def hsplit(x, num_or_indices, name=None):
    return apply_op(lambda a: jnp.hsplit(a, num_or_indices), x,
                    _op_name="hsplit")


def vsplit(x, num_or_indices, name=None):
    return apply_op(lambda a: jnp.vsplit(a, num_or_indices), x,
                    _op_name="vsplit")


def dsplit(x, num_or_indices, name=None):
    return apply_op(lambda a: jnp.dsplit(a, num_or_indices), x,
                    _op_name="dsplit")


def unflatten(x, axis, shape, name=None):
    def _uf(a):
        ax = axis % a.ndim
        new = list(a.shape[:ax]) + list(shape) + list(a.shape[ax + 1:])
        return a.reshape(new)

    return apply_op(_uf, x, _op_name="unflatten")


def unstack(x, axis=0, num=None, name=None):
    def _us(a):
        return tuple(jnp.moveaxis(a, axis, 0))

    return list(apply_op(_us, x, _op_name="unstack"))


def matrix_transpose(x, name=None):
    return apply_op(lambda a: jnp.swapaxes(a, -1, -2), x,
                    _op_name="matrix_transpose")


def vecdot(x, y, axis=-1, name=None):
    return apply_op(lambda a, b: jnp.sum(a * b, axis=axis), x, y,
                    _op_name="vecdot")


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op(
        lambda a: jnp.diagonal(a, offset=offset, axis1=axis1, axis2=axis2),
        x, _op_name="diagonal")


def combinations(x, r=2, with_replacement=False, name=None):
    import itertools

    def _cb(a):
        n = a.shape[0]
        it = (itertools.combinations_with_replacement(range(n), r)
              if with_replacement else itertools.combinations(range(n), r))
        idx = jnp.asarray(list(it))
        return a[idx]

    return apply_op(_cb, x, _op_name="combinations")


def cartesian_prod(x, name=None):
    def _cp(*xs):
        grids = jnp.meshgrid(*xs, indexing="ij")
        return jnp.stack([g.reshape(-1) for g in grids], axis=-1)

    return apply_op(_cp, *x, _op_name="cartesian_prod")


def renorm(x, p, axis, max_norm, name=None):
    def _rn(a):
        moved = jnp.moveaxis(a, axis, 0)
        flat = moved.reshape(moved.shape[0], -1)
        norms = jnp.linalg.norm(flat, ord=p, axis=1, keepdims=True)
        scale = jnp.where(norms > max_norm, max_norm / jnp.maximum(norms, 1e-12), 1.0)
        out = flat * scale
        return jnp.moveaxis(out.reshape(moved.shape), 0, axis)

    return apply_op(_rn, x, _op_name="renorm")


# -- scatter family ---------------------------------------------------------
def select_scatter(x, values, axis, index, name=None):
    def _ss(a, v):
        idx = [slice(None)] * a.ndim
        idx[axis] = index
        return a.at[tuple(idx)].set(v)

    return apply_op(_ss, x, values, _op_name="select_scatter")


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    def _sls(a, v):
        idx = [slice(None)] * a.ndim
        for ax, st, en, sr in zip(axes, starts, ends, strides):
            idx[ax] = slice(st, en, sr)
        return a.at[tuple(idx)].set(v)

    return apply_op(_sls, x, value, _op_name="slice_scatter")


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    def _ds(a, v):
        n = min(a.shape[axis1], a.shape[axis2])
        rows = jnp.arange(max(0, -offset), max(0, -offset) + v.shape[-1])
        cols = jnp.arange(max(0, offset), max(0, offset) + v.shape[-1])
        idx = [slice(None)] * a.ndim
        out = a
        # build advanced index along the two axes
        index = [slice(None)] * a.ndim
        index[axis1] = rows
        index[axis2] = cols
        return out.at[tuple(index)].set(v)

    return apply_op(_ds, x, y, _op_name="diagonal_scatter")


def index_fill(x, index, axis, fill_value, name=None):
    def _if(a, idx):
        sl = [slice(None)] * a.ndim
        sl[axis] = idx
        return a.at[tuple(sl)].set(fill_value)

    return apply_op(_if, x, index, _op_name="index_fill")


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1,
                name=None):
    def _si(a):
        size = index_num // nshards
        lo, hi = shard_id * size, (shard_id + 1) * size
        inside = (a >= lo) & (a < hi)
        return jnp.where(inside, a - lo, ignore_value)

    return apply_op(_si, input, _op_name="shard_index")


def increment(x, value=1.0, name=None):
    out = apply_op(lambda a: a + value, x, _op_name="increment")
    x._data = out._data
    return x


def reverse(x, axis, name=None):
    ax = axis if isinstance(axis, (list, tuple)) else [axis]
    return apply_op(lambda a: jnp.flip(a, ax), x, _op_name="reverse")


def view_as(x, other, name=None):
    return apply_op(lambda a, b: a.reshape(b.shape), x, other,
                    _op_name="view_as")


def as_complex(x, name=None):
    return apply_op(lambda a: jax.lax.complex(a[..., 0], a[..., 1]), x,
                    _op_name="as_complex")


def as_real(x, name=None):
    return apply_op(lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], -1), x,
                    _op_name="as_real")


# -- random fills -----------------------------------------------------------
def log_normal(mean=1.0, std=2.0, shape=None, dtype=None, name=None):
    from .. import framework

    key = framework.next_rng_key()
    z = jax.random.normal(key, tuple(shape or [1]))
    return Tensor(jnp.exp(mean + std * z))


def standard_gamma(x, name=None):
    from .. import framework

    def _sg(a):
        return jax.random.gamma(framework.next_rng_key(), a, a.shape)

    return apply_op(_sg, x, _op_name="standard_gamma")


# -- dlpack -----------------------------------------------------------------
def to_dlpack(x):
    """Return the jax array itself — it carries __dlpack__/__dlpack_device__
    (the modern dlpack protocol passes the exporter object, not a capsule)."""
    return x._data if isinstance(x, Tensor) else x


def from_dlpack(ext):
    if hasattr(ext, "__dlpack__"):
        return Tensor(jnp.from_dlpack(ext))
    # legacy capsule path
    from jax import dlpack as jdl

    return Tensor(jdl.from_dlpack(ext))
