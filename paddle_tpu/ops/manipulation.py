"""Shape / layout manipulation ops (parity: python/paddle/tensor/manipulation.py)."""
from __future__ import annotations

import builtins

import numpy as np
import jax
import jax.numpy as jnp

from .. import dtypes as _dt
from ..core.dispatch import apply_op
from ..core.tensor import Tensor


def _static_ints(v):
    """Resolve a shape-like argument (may contain Tensors) to python ints.

    XLA needs static shapes: under jit tracing, a traced element raises the
    standard jax concretization error (which to_static catches to fall back
    to eager) instead of silently mis-resolving.
    """
    if isinstance(v, Tensor):
        v = v._data
    if hasattr(v, "ndim") and getattr(v, "ndim", 0) >= 1:
        return [int(i) for i in np.asarray(v)]  # one host sync, not per-element
    if isinstance(v, (list, tuple)):
        return [int(i.item()) if isinstance(i, Tensor) else int(i) for i in v]
    return int(v)


def reshape(x, shape, name=None):
    shape = _static_ints(shape)
    return apply_op(lambda a: jnp.reshape(a, shape), x, _op_name="reshape")


def reshape_(x, shape, name=None):
    return x._assign_result_(reshape(x, shape))


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return x.astype(shape_or_dtype)


def transpose(x, perm=None, name=None):
    if perm is not None:
        perm = _static_ints(perm)
    return apply_op(lambda a: jnp.transpose(a, perm), x, _op_name="transpose")


def t(x, name=None):
    def _t(a):
        if a.ndim < 2:
            return a
        return jnp.swapaxes(a, -2, -1)

    return apply_op(_t, x, _op_name="t")


def moveaxis(x, source, destination, name=None):
    return apply_op(
        lambda a: jnp.moveaxis(a, source, destination), x, _op_name="moveaxis"
    )


def swapaxes(x, axis0, axis1, name=None):
    return apply_op(
        lambda a: jnp.swapaxes(a, axis0, axis1), x, _op_name="swapaxes"
    )


transpose_ = lambda x, perm, name=None: x._assign_result_(transpose(x, perm))


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def _flatten(a):
        nd = a.ndim
        if nd == 0:
            return a.reshape([1])
        s = start_axis % nd
        e = stop_axis % nd
        new_shape = list(a.shape[:s]) + [-1] + list(a.shape[e + 1 :])
        return a.reshape(new_shape)

    return apply_op(_flatten, x, _op_name="flatten")


def squeeze(x, axis=None, name=None):
    def _squeeze(a):
        if axis is None:
            return jnp.squeeze(a)
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        axes = tuple(ax % a.ndim for ax in axes if a.shape[ax % a.ndim] == 1)
        return jnp.squeeze(a, axis=axes) if axes else a

    return apply_op(_squeeze, x, _op_name="squeeze")


def squeeze_(x, axis=None, name=None):
    return x._assign_result_(squeeze(x, axis))


def unsqueeze(x, axis, name=None):
    ax = _static_ints(axis)

    def _unsqueeze(a):
        axes = ax if isinstance(ax, list) else [ax]
        out = a
        for i in axes:
            out = jnp.expand_dims(out, i)
        return out

    return apply_op(_unsqueeze, x, _op_name="unsqueeze")


def unsqueeze_(x, axis, name=None):
    return x._assign_result_(unsqueeze(x, axis))


def concat(x, axis=0, name=None):
    axis = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    return apply_op(
        lambda xs: jnp.concatenate(xs, axis=axis), list(x), _op_name="concat"
    )


def stack(x, axis=0, name=None):
    return apply_op(lambda xs: jnp.stack(xs, axis=axis), list(x), _op_name="stack")


def hstack(x, name=None):
    return apply_op(lambda xs: jnp.hstack(xs), list(x), _op_name="hstack")


def vstack(x, name=None):
    return apply_op(lambda xs: jnp.vstack(xs), list(x), _op_name="vstack")


def dstack(x, name=None):
    return apply_op(lambda xs: jnp.dstack(xs), list(x), _op_name="dstack")


def split(x, num_or_sections, axis=0, name=None):
    axis = int(axis.item()) if isinstance(axis, Tensor) else int(axis)

    def _split(a):
        n = num_or_sections
        if isinstance(n, int):
            return list(jnp.split(a, n, axis=axis))
        sections = _static_ints(n)
        total = a.shape[axis]
        if -1 in sections:
            known = builtins_sum(s for s in sections if s != -1)
            sections = [total - known if s == -1 else s for s in sections]
        offsets = np.cumsum(sections)[:-1].tolist()
        return list(jnp.split(a, offsets, axis=axis))

    return apply_op(_split, x, _op_name="split")


def builtins_sum(it):
    import builtins

    return builtins.sum(it)


def tensor_split(x, num_or_indices, axis=0, name=None):
    return apply_op(
        lambda a: list(jnp.array_split(a, num_or_indices, axis=axis)),
        x,
        _op_name="tensor_split",
    )


def chunk(x, chunks, axis=0, name=None):
    return apply_op(
        lambda a: list(jnp.array_split(a, chunks, axis=axis)), x, _op_name="chunk"
    )


def unbind(input, axis=0, name=None):
    def _unbind(a):
        n = a.shape[axis]
        return [jnp.squeeze(s, axis) for s in jnp.split(a, n, axis=axis)]

    return apply_op(_unbind, input, _op_name="unbind")


def tile(x, repeat_times, name=None):
    reps = _static_ints(repeat_times)
    if isinstance(reps, int):
        reps = [reps]
    return apply_op(lambda a: jnp.tile(a, reps), x, _op_name="tile")


def expand(x, shape, name=None):
    shape = _static_ints(shape)

    def _expand(a):
        tgt = list(shape)
        # -1 means keep the original dim
        nd_off = len(tgt) - a.ndim
        for i in range(len(tgt)):
            if tgt[i] == -1:
                tgt[i] = a.shape[i - nd_off]
        return jnp.broadcast_to(a, tgt)

    return apply_op(_expand, x, _op_name="expand")


def expand_as(x, y, name=None):
    return apply_op(
        lambda a, b: jnp.broadcast_to(a, b.shape), x, y, _op_name="expand_as"
    )


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(inputs, name=None):
    return apply_op(
        lambda xs: list(jnp.broadcast_arrays(*xs)), list(inputs), _op_name="broadcast_tensors"
    )


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def flip(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    return apply_op(lambda a: jnp.flip(a, axis=tuple(axes)), x, _op_name="flip")


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply_op(lambda a: jnp.rot90(a, k, axes), x, _op_name="rot90")


def roll(x, shifts, axis=None, name=None):
    return apply_op(lambda a: jnp.roll(a, shifts, axis), x, _op_name="roll")


# -- gather / scatter family ------------------------------------------------
def gather(x, index, axis=0, name=None):
    axis = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    return apply_op(
        lambda a, i: jnp.take(a, i.reshape(-1) if i.ndim > 1 else i, axis=axis),
        x,
        index,
        _op_name="gather",
    )


def gather_nd(x, index, name=None):
    def _gather_nd(a, idx):
        tup = tuple(jnp.moveaxis(idx, -1, 0))
        return a[tup]

    return apply_op(_gather_nd, x, index, _op_name="gather_nd")


def scatter(x, index, updates, overwrite=True, name=None):
    def _scatter(a, i, u):
        i = i.reshape(-1)
        if overwrite:
            return a.at[i].set(u)
        # paddle semantics: when not overwrite, zero target rows then add
        zeroed = a.at[i].set(jnp.zeros_like(u))
        return zeroed.at[i].add(u)

    return apply_op(_scatter, x, index, updates, _op_name="scatter")


def scatter_(x, index, updates, overwrite=True, name=None):
    return x._assign_result_(scatter(x, index, updates, overwrite))


def scatter_nd_add(x, index, updates, name=None):
    def _snd(a, i, u):
        tup = tuple(jnp.moveaxis(i, -1, 0))
        return a.at[tup].add(u)

    return apply_op(_snd, x, index, updates, _op_name="scatter_nd_add")


def scatter_nd(index, updates, shape, name=None):
    shape = _static_ints(shape)

    def _snd(i, u):
        zeros = jnp.zeros(shape, u.dtype)
        tup = tuple(jnp.moveaxis(i, -1, 0))
        return zeros.at[tup].add(u)

    return apply_op(_snd, index, updates, _op_name="scatter_nd")


def index_select(x, index, axis=0, name=None):
    return apply_op(
        lambda a, i: jnp.take(a, i, axis=axis), x, index, _op_name="index_select"
    )


def index_sample(x, index, name=None):
    return apply_op(
        lambda a, i: jnp.take_along_axis(a, i, axis=1), x, index, _op_name="index_sample"
    )


def index_add(x, index, axis, value, name=None):
    def _index_add(a, i, v):
        # builtins.slice: the module-level paddle `slice` op shadows it here
        idx = [builtins.slice(None)] * a.ndim
        idx[axis] = i
        return a.at[tuple(idx)].add(v)

    return apply_op(_index_add, x, index, value, _op_name="index_add")


def index_put(x, indices, value, accumulate=False, name=None):
    def _index_put(a, idxs, v):
        tup = tuple(idxs)
        if accumulate:
            return a.at[tup].add(v)
        return a.at[tup].set(v)

    return apply_op(_index_put, x, list(indices), value, _op_name="index_put")


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    return apply_op(
        lambda a, i: jnp.take_along_axis(a, i, axis=axis),
        arr,
        indices,
        _op_name="take_along_axis",
    )


def put_along_axis(arr, indices, values, axis, reduce="assign", include_self=True, broadcast=True, name=None):
    def _put(a, i, v):
        v = jnp.broadcast_to(jnp.asarray(v, a.dtype), i.shape)
        if reduce == "assign":
            return jnp.put_along_axis(a, i, v, axis=axis, inplace=False)
        mode = {"add": "add", "multiply": "multiply", "mul": "multiply",
                "amax": "max", "amin": "min"}[reduce]
        grids = jnp.meshgrid(*[jnp.arange(s) for s in i.shape], indexing="ij")
        full_idx = list(grids)
        full_idx[axis % a.ndim] = i
        return getattr(a.at[tuple(full_idx)], mode)(v)

    return apply_op(_put, arr, indices, values, _op_name="put_along_axis")


def take(x, index, mode="raise", name=None):
    return apply_op(
        lambda a, i: jnp.take(a.reshape(-1), i.reshape(-1) if i.ndim == 0 else i, mode="clip" if mode == "clip" else "wrap" if mode == "wrap" else None),
        x,
        index,
        _op_name="take",
    )


def masked_select(x, mask, name=None):
    # dynamic output shape: eager-only (like the reference's masked_select)
    return apply_op(lambda a, m: a[m], x, mask, _op_name="masked_select")


def masked_fill(x, mask, value, name=None):
    return apply_op(
        lambda a, m, v: jnp.where(m, jnp.asarray(v, a.dtype), a),
        x,
        mask,
        value,
        _op_name="masked_fill",
    )


def masked_fill_(x, mask, value, name=None):
    return x._assign_result_(masked_fill(x, mask, value))


def masked_scatter(x, mask, value, name=None):
    def _ms(a, m, v):
        flat_m = m.reshape(-1)
        nsel = int(np.asarray(flat_m).sum())
        src = v.reshape(-1)[:nsel]
        out = a.reshape(-1).at[jnp.where(flat_m)[0]].set(src)
        return out.reshape(a.shape)

    return apply_op(_ms, x, mask, value, _op_name="masked_scatter")


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return apply_op(
        lambda c, a, b: jnp.where(c, a, b), condition, x, y, _op_name="where"
    )


def where_(condition, x, y, name=None):
    return x._assign_result_(where(condition, x, y))


def nonzero(x, as_tuple=False):
    arr = x._data
    res = jnp.nonzero(arr)  # eager-only (dynamic shape)
    if as_tuple:
        return tuple(Tensor(r) for r in res)
    return Tensor(jnp.stack(res, axis=1).astype(np.int64))


def repeat_interleave(x, repeats, axis=None, name=None):
    def _ri(a, r):
        return jnp.repeat(a, r, axis=axis)

    return apply_op(_ri, x, repeats, _op_name="repeat_interleave")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", pad_from_left_axis=True, name=None):
    pad_list = _static_ints(pad)

    def _pad(a):
        nd = a.ndim
        if len(pad_list) == 2 * nd:
            # full-rank paddle format: [d0_l, d0_r, d1_l, d1_r, ...]
            width = [(pad_list[2 * i], pad_list[2 * i + 1]) for i in range(nd)]
        else:
            # torch-style trailing-dims format applied to last len(pad)//2 dims
            k = len(pad_list) // 2
            width = [(0, 0)] * (nd - k)
            # NCHW conv-style: pad applies to spatial dims (last k), reversed order
            for i in range(k):
                width.append((pad_list[2 * (k - 1 - i)], pad_list[2 * (k - 1 - i) + 1]))
        jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge",
                 "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(a, width, mode=jmode, constant_values=value)
        return jnp.pad(a, width, mode=jmode)

    return apply_op(_pad, x, _op_name="pad")


def slice(input, axes, starts, ends, name=None):
    import builtins

    axes = _static_ints(axes)
    starts = _static_ints(starts)
    ends = _static_ints(ends)

    def _slice(a):
        idx = [builtins.slice(None)] * a.ndim
        for ax, s, e in zip(axes, starts, ends):
            idx[ax] = builtins.slice(s, e)
        return a[tuple(idx)]

    return apply_op(_slice, input, _op_name="slice")


def strided_slice(x, axes, starts, ends, strides, name=None):
    import builtins

    axes = _static_ints(axes)
    starts = _static_ints(starts)
    ends = _static_ints(ends)
    strides = _static_ints(strides)

    def _ss(a):
        idx = [builtins.slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[ax] = builtins.slice(s, e, st)
        return a[tuple(idx)]

    return apply_op(_ss, x, _op_name="strided_slice")


def crop(x, shape=None, offsets=None, name=None):
    import builtins

    shape = _static_ints(shape)
    offsets = _static_ints(offsets) if offsets is not None else [0] * len(shape)

    def _crop(a):
        idx = tuple(
            builtins.slice(o, o + (s if s != -1 else a.shape[i] - o))
            for i, (o, s) in enumerate(zip(offsets, shape))
        )
        return a[idx]

    return apply_op(_crop, x, _op_name="crop")


# -- search / sort ----------------------------------------------------------
def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    k = int(k.item()) if isinstance(k, Tensor) else int(k)

    def _topk(a):
        ax = axis % a.ndim
        arr = jnp.moveaxis(a, ax, -1)
        if largest:
            vals, inds = jax.lax.top_k(arr, k)
        else:
            vals, inds = jax.lax.top_k(-arr, k)
            vals = -vals
        return (
            jnp.moveaxis(vals, -1, ax),
            jnp.moveaxis(inds.astype(np.int64), -1, ax),
        )

    return apply_op(_topk, x, _op_name="topk")


def sort(x, axis=-1, descending=False, stable=False, name=None):
    def _sort(a):
        out = jnp.sort(a, axis=axis, stable=stable)
        return jnp.flip(out, axis=axis) if descending else out

    return apply_op(_sort, x, _op_name="sort")


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    def _argsort(a):
        out = jnp.argsort(a, axis=axis, stable=stable, descending=descending).astype(np.int64)
        return out

    return apply_op(_argsort, x, _op_name="argsort")


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    def _ss(seq, v):
        side = "right" if right else "left"
        if seq.ndim == 1:
            out = jnp.searchsorted(seq, v, side=side)
        else:
            out = jax.vmap(lambda s, vv: jnp.searchsorted(s, vv, side=side))(
                seq.reshape(-1, seq.shape[-1]), v.reshape(-1, v.shape[-1])
            ).reshape(v.shape)
        return out.astype(np.int32 if out_int32 else np.int64)

    return apply_op(_ss, sorted_sequence, values, _op_name="searchsorted")


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def _kth(a):
        vals = jnp.sort(a, axis=axis)
        inds = jnp.argsort(a, axis=axis).astype(np.int64)
        taken_v = jnp.take(vals, k - 1, axis=axis)
        taken_i = jnp.take(inds, k - 1, axis=axis)
        if keepdim:
            taken_v = jnp.expand_dims(taken_v, axis)
            taken_i = jnp.expand_dims(taken_i, axis)
        return taken_v, taken_i

    return apply_op(_kth, x, _op_name="kthvalue")


def mode(x, axis=-1, keepdim=False, name=None):
    def _mode(a):
        sorted_a = jnp.sort(a, axis=axis)
        n = a.shape[axis]
        # count runs via comparisons
        vals, counts = jax.vmap(
            lambda row: _mode_1d(row)
        )(jnp.moveaxis(sorted_a, axis, -1).reshape(-1, n))
        shp = list(a.shape)
        del shp[axis % a.ndim]
        vals = vals.reshape(shp)
        if keepdim:
            vals = jnp.expand_dims(vals, axis)
        idx = jnp.argmax(
            (a == (jnp.expand_dims(vals, axis) if not keepdim else vals)).astype(np.int32), axis=axis
        ).astype(np.int64)
        if keepdim:
            idx = jnp.expand_dims(idx, axis)
        return vals, idx

    def _mode_1d(row):
        uniq_mask = jnp.concatenate([jnp.array([True]), row[1:] != row[:-1]])
        run_id = jnp.cumsum(uniq_mask) - 1
        counts = jnp.zeros(row.shape[0], np.int32).at[run_id].add(1)
        best = jnp.argmax(counts)
        val = row[jnp.argmax(run_id == best)]
        return val, counts

    return apply_op(_mode, x, _op_name="mode")


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    # dynamic shape: eager-only
    arr = np.asarray(x._data)
    res = np.unique(
        arr,
        return_index=return_index,
        return_inverse=return_inverse,
        return_counts=return_counts,
        axis=axis,
    )
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    outs = [Tensor(jnp.asarray(r if i == 0 else r.astype(_dt.to_np(dtype)))) for i, r in enumerate(res)]
    return tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    arr = np.asarray(x._data)
    if axis is None:
        arr = arr.reshape(-1)
    mask = np.concatenate([[True], arr[1:] != arr[:-1]]) if arr.ndim == 1 else None
    vals = arr[mask] if mask is not None else arr
    outs = [Tensor(jnp.asarray(vals))]
    if return_inverse:
        inv = np.cumsum(mask) - 1
        outs.append(Tensor(jnp.asarray(inv.astype(_dt.to_np(dtype)))))
    if return_counts:
        idx = np.where(mask)[0]
        counts = np.diff(np.append(idx, arr.shape[0]))
        outs.append(Tensor(jnp.asarray(counts.astype(_dt.to_np(dtype)))))
    return outs[0] if len(outs) == 1 else tuple(outs)


def one_hot(x, num_classes, name=None):
    return apply_op(
        lambda a: jax.nn.one_hot(a, num_classes, dtype=np.float32),
        x,
        _op_name="one_hot",
    )


def numel(x, name=None):
    return Tensor(jnp.asarray(x.size, dtype=np.int64))


def rank(x):
    return Tensor(jnp.asarray(x.ndim, dtype=np.int32))


def shape(x):
    return Tensor(jnp.asarray(x.shape, dtype=np.int64))


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)


def is_floating_point(x):
    return x.dtype.is_floating_point


def is_integer(x):
    return x.dtype.is_integer


def is_complex(x):
    return x.dtype.is_complex


def cast(x, dtype):
    return x.astype(dtype)


def cast_(x, dtype):
    return x._assign_result_(x.astype(dtype))


def as_strided(x, shape, stride, offset=0, name=None):
    def _as_strided(a):
        flat = a.reshape(-1)
        idx = np.zeros(tuple(shape), dtype=np.int64) + offset
        for dim, (s, st) in enumerate(zip(shape, stride)):
            r = np.arange(s) * st
            sh = [1] * len(shape)
            sh[dim] = s
            idx = idx + r.reshape(sh)
        return flat[jnp.asarray(idx)]

    return apply_op(_as_strided, x, _op_name="as_strided")


def unfold(x, axis, size, step, name=None):
    def _unfold(a):
        n = a.shape[axis]
        starts = np.arange(0, n - size + 1, step)
        slices = [jax.lax.slice_in_dim(a, int(s), int(s) + size, axis=axis) for s in starts]
        return jnp.stack(slices, axis=axis if axis >= 0 else a.ndim + axis)

    return apply_op(_unfold, x, _op_name="unfold")


def atleast_1d(*inputs, name=None):
    outs = [apply_op(jnp.atleast_1d, x, _op_name="atleast_1d") for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [apply_op(jnp.atleast_2d, x, _op_name="atleast_2d") for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [apply_op(jnp.atleast_3d, x, _op_name="atleast_3d") for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    return x._assign_result_(flatten(x, start_axis, stop_axis))


def tolist(x):
    return x.tolist()


def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1, name=None):
    """Fill the (dim1, dim2) diagonal band of x with tensor y (parity:
    tensor/manipulation.py fill_diagonal_tensor). y's shape is x's shape
    with dim1/dim2 removed and the diagonal length appended."""
    def _fd(a, b):
        a2 = jnp.moveaxis(a, (dim1, dim2), (-2, -1))
        n, m = a2.shape[-2:]
        i0, j0 = (0, offset) if offset >= 0 else (-offset, 0)
        ln = min(n - i0, m - j0)
        if ln <= 0:
            raise ValueError(f"offset {offset} leaves no diagonal "
                             f"for dims ({n}, {m})")
        ii = jnp.arange(ln) + i0
        jj = jnp.arange(ln) + j0
        a2 = a2.at[..., ii, jj].set(b.astype(a.dtype))
        return jnp.moveaxis(a2, (-2, -1), (dim1, dim2))

    return apply_op(_fd, x, y, _op_name="fill_diagonal_tensor")


def fill_diagonal_tensor_(x, y, offset=0, dim1=0, dim2=1, name=None):
    return x._assign_result_(fill_diagonal_tensor(x, y, offset, dim1, dim2))


def top_p_sampling(x, ps, threshold=None, topp_seed=None, seed=-1, k=0,
                   mode="truncated", return_top=False, name=None):
    """Nucleus sampling over probability rows (parity: tensor/search.py
    top_p_sampling). x [b, vocab] probabilities, ps [b] per-row top-p.
    Returns (scores [b, 1], ids [b, 1]) of the sampled token."""
    from .. import framework

    def _tps(probs, p_row, thr):
        srt = jnp.sort(probs, axis=-1)[:, ::-1]
        idx = jnp.argsort(probs, axis=-1)[:, ::-1]
        csum = jnp.cumsum(srt, axis=-1)
        # keep the smallest prefix with mass >= p (first token always kept)
        keep = (csum - srt) < p_row[:, None]
        if thr is not None:
            keep = keep & (srt >= thr[:, None])
        keep = keep.at[:, 0].set(True)  # prefix guarantee: top-1 always
        if mode == "non-truncated":
            # no truncation: sample the full (threshold-filtered)
            # distribution; top_p only gates which rows get truncated in
            # the reference kernel's two-pass scheme
            masked = srt if thr is None else jnp.where(
                srt >= thr[:, None], srt, 0.0)
        else:
            masked = jnp.where(keep, srt, 0.0)
        norm = masked / jnp.maximum(
            jnp.sum(masked, axis=-1, keepdims=True), 1e-20)
        # explicit seed must not consume the global RNG stream
        key = (jax.random.PRNGKey(seed) if seed >= 0
               else framework.next_rng_key())
        choice = jax.random.categorical(key, jnp.log(norm + 1e-20), axis=-1)
        rows = jnp.arange(probs.shape[0])
        out_ids = idx[rows, choice]
        out_scores = probs[rows, out_ids]
        return out_scores[:, None], out_ids[:, None].astype(jnp.int64)

    scores, ids = apply_op(
        _tps, x, ps, threshold, _op_name="top_p_sampling")
    if return_top and k:
        tk_scores, tk_ids = topk(x, k, axis=-1)
        return scores, ids, tk_scores, tk_ids
    return scores, ids
