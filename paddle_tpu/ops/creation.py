"""Tensor creation ops (parity: python/paddle/tensor/creation.py)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .. import dtypes as _dt
from .. import framework, device as _device
from ..core.dispatch import apply_op
from ..core.tensor import Tensor


def _resolve_dtype(dtype, default=None):
    if dtype is None:
        return default
    return _dt.to_np(dtype)


def _put(arr):
    """Host array → default device (lazy placement; no backend query)."""
    return arr


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor"""
    if isinstance(data, Tensor):
        out = data.astype(dtype) if dtype is not None else data.clone()
        out.stop_gradient = stop_gradient
        return out
    if isinstance(data, (jnp.ndarray, jax.Array)) and not isinstance(data, np.ndarray):
        arr = data
        if dtype is not None:
            arr = arr.astype(_dt.to_np(dtype))
        t = Tensor(arr, stop_gradient=stop_gradient)
        return t
    a = np.asarray(data)
    if dtype is not None:
        a = a.astype(_dt.to_np(dtype))
    elif a.dtype == np.float64:
        # python floats / float lists default to the framework default dtype
        a = a.astype(framework.get_default_dtype().np_dtype)
    elif a.dtype == np.int32 and isinstance(data, (int, list, tuple)):
        a = a.astype(np.int64)
    if place is not None:
        dev = _device.jax_device_for(place)
        t = Tensor(jax.device_put(a, dev), stop_gradient=stop_gradient)
    else:
        t = Tensor(jnp.asarray(a), stop_gradient=stop_gradient)
    return t


def _shape_list(shape):
    if isinstance(shape, Tensor):
        return [int(s) for s in shape.numpy().tolist()]
    if isinstance(shape, (int, np.integer)):
        return [int(shape)]
    out = []
    for s in shape:
        if isinstance(s, Tensor):
            out.append(int(s.item()))
        else:
            out.append(int(s))
    return out


def zeros(shape, dtype=None, name=None):
    d = _resolve_dtype(dtype, framework.get_default_dtype().np_dtype)
    return Tensor(_put(jnp.zeros(_shape_list(shape), d)))


def ones(shape, dtype=None, name=None):
    d = _resolve_dtype(dtype, framework.get_default_dtype().np_dtype)
    return Tensor(_put(jnp.ones(_shape_list(shape), d)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        if isinstance(fill_value, bool):
            d = np.bool_
        elif isinstance(fill_value, int):
            d = np.int64
        else:
            d = framework.get_default_dtype().np_dtype
    else:
        d = _dt.to_np(dtype)
    return Tensor(_put(jnp.full(_shape_list(shape), fill_value, d)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    d = _resolve_dtype(dtype, None)
    return Tensor(jnp.zeros(x._data.shape, d or x._data.dtype))


def ones_like(x, dtype=None, name=None):
    d = _resolve_dtype(dtype, None)
    return Tensor(jnp.ones(x._data.shape, d or x._data.dtype))


def full_like(x, fill_value, dtype=None, name=None):
    d = _resolve_dtype(dtype, None)
    return Tensor(jnp.full(x._data.shape, fill_value, d or x._data.dtype))


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x

    start, end, step = _v(start), _v(end), _v(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        if all(isinstance(v, (int, np.integer)) for v in (start, end, step)):
            d = np.int64
        else:
            d = framework.get_default_dtype().np_dtype
    else:
        d = _dt.to_np(dtype)
    return Tensor(_put(jnp.arange(start, end, step, dtype=d)))


def linspace(start, stop, num, dtype=None, name=None):
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x

    d = _resolve_dtype(dtype, framework.get_default_dtype().np_dtype)
    return Tensor(_put(jnp.linspace(_v(start), _v(stop), int(_v(num)), dtype=d)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    d = _resolve_dtype(dtype, framework.get_default_dtype().np_dtype)
    return Tensor(_put(jnp.logspace(start, stop, int(num), base=base, dtype=d)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    d = _resolve_dtype(dtype, framework.get_default_dtype().np_dtype)
    return Tensor(_put(jnp.eye(int(num_rows), num_columns and int(num_columns), dtype=d)))


def assign(x, output=None):
    src = to_tensor(x) if not isinstance(x, Tensor) else x
    out = apply_op(lambda a: a + 0 if jnp.issubdtype(a.dtype, jnp.inexact) else jnp.asarray(a), src, _op_name="assign")
    if output is not None:
        output._assign_result_(out)
        return output
    return out


def clone(x, name=None):
    return x.clone()


def tril(x, diagonal=0, name=None):
    return apply_op(lambda a: jnp.tril(a, diagonal), x, _op_name="tril")


def triu(x, diagonal=0, name=None):
    return apply_op(lambda a: jnp.triu(a, diagonal), x, _op_name="triu")


def diag(x, offset=0, padding_value=0, name=None):
    def _diag(a):
        if a.ndim == 1:
            out = jnp.diag(a, offset)
            if padding_value != 0:
                mask = jnp.eye(out.shape[0], dtype=bool)
                mask = jnp.roll(mask, offset, axis=1) if offset else mask
                out = jnp.where(mask, out, padding_value)
            return out
        return jnp.diag(a, offset)

    return apply_op(_diag, x, _op_name="diag")


def diagflat(x, offset=0, name=None):
    return apply_op(lambda a: jnp.diagflat(a, offset), x, _op_name="diagflat")


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    def _de(a):
        n = a.shape[-1]
        m = n + abs(offset)
        out = jnp.zeros(a.shape[:-1] + (m, m), a.dtype)
        idx = jnp.arange(n)
        rows = idx + max(-offset, 0)
        cols = idx + max(offset, 0)
        out = out.at[..., rows, cols].set(a)
        nd = out.ndim
        d1, d2 = dim1 % nd, dim2 % nd
        if (d1, d2) != (nd - 2, nd - 1):
            out = jnp.moveaxis(out, (nd - 2, nd - 1), (d1, d2))
        return out

    return apply_op(_de, x, _op_name="diag_embed")


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    return apply_op(lambda *xs: list(jnp.meshgrid(*xs, indexing="ij")), *args, _op_name="meshgrid")


def tril_indices(row, col, offset=0, dtype="int64"):
    r, c = np.tril_indices(row, offset, col)
    return Tensor(_put(jnp.asarray(np.stack([r, c]), dtype=_dt.to_np(dtype))))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    col = col if col is not None else row
    r, c = np.triu_indices(row, offset, col)
    return Tensor(_put(jnp.asarray(np.stack([r, c]), dtype=_dt.to_np(dtype))))


def complex(real, imag, name=None):
    return apply_op(lambda r, i: jax.lax.complex(r, i), real, imag, _op_name="complex")


def as_tensor(data, dtype=None):
    return to_tensor(data, dtype=dtype)


def clone_detached(x):
    return x.detach()


def polar(abs_t, angle, name=None):
    return apply_op(
        lambda a, th: jax.lax.complex(a * jnp.cos(th), a * jnp.sin(th)),
        abs_t,
        angle,
        _op_name="polar",
    )
