"""Linear algebra ops (parity: python/paddle/tensor/linalg.py).

The hot path — ``matmul`` — lowers directly to ``jnp.matmul`` so XLA maps it
onto the MXU (reference analogue: ``phi/kernels/gpu/matmul_kernel.cu`` over
cuBLAS; here the systolic array via a single HLO dot_general).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import apply_op
from ..core.tensor import Tensor


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def _matmul(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -2, -1) if a.ndim >= 2 else a
        if transpose_y:
            b = jnp.swapaxes(b, -2, -1) if b.ndim >= 2 else b
        return jnp.matmul(a, b)

    return apply_op(_matmul, x, y, _op_name="matmul")


mm = matmul


def bmm(x, y, name=None):
    return apply_op(jnp.matmul, x, y, _op_name="bmm")


def dot(x, y, name=None):
    def _dot(a, b):
        return jnp.sum(a * b, axis=-1)

    return apply_op(_dot, x, y, _op_name="dot")


def mv(x, vec, name=None):
    return apply_op(jnp.matmul, x, vec, _op_name="mv")


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply_op(
        lambda i, a, b: beta * i + alpha * jnp.matmul(a, b),
        input,
        x,
        y,
        _op_name="addmm",
    )


def einsum(equation, *operands):
    return apply_op(
        lambda ops: jnp.einsum(equation, *ops), list(operands), _op_name="einsum"
    )


def tensordot(x, y, axes=2, name=None):
    return apply_op(
        lambda a, b: jnp.tensordot(a, b, axes=axes), x, y, _op_name="tensordot"
    )


def cross(x, y, axis=9, name=None):
    def _cross(a, b):
        ax = axis
        if ax == 9:
            ax = next(i for i, s in enumerate(a.shape) if s == 3)
        return jnp.cross(a, b, axis=ax)

    return apply_op(_cross, x, y, _op_name="cross")


def norm(x, p=None, axis=None, keepdim=False, name=None):
    def _norm(a):
        if p is None or p == "fro":
            if axis is None:
                return jnp.sqrt(jnp.sum(jnp.square(jnp.abs(a))))
            return jnp.linalg.norm(a, ord=None, axis=_ax(axis), keepdims=keepdim)
        if p == "nuc":
            return jnp.linalg.norm(a, ord="nuc", axis=_ax(axis), keepdims=keepdim)
        if p == float("inf") or p == "inf":
            ordv = jnp.inf
        elif p == float("-inf"):
            ordv = -jnp.inf
        else:
            ordv = p
        if axis is None:
            return jnp.linalg.norm(a.reshape(-1), ord=ordv, keepdims=False)
        return jnp.linalg.norm(a, ord=ordv, axis=_ax(axis), keepdims=keepdim)

    def _ax(axis):
        if isinstance(axis, (list, tuple)):
            return tuple(axis)
        return axis

    return apply_op(_norm, x, _op_name="norm")


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    return apply_op(
        lambda a: jnp.linalg.vector_norm(
            a, ord=p, axis=tuple(axis) if isinstance(axis, (list, tuple)) else axis, keepdims=keepdim
        ),
        x,
        _op_name="vector_norm",
    )


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    return apply_op(
        lambda a: jnp.linalg.matrix_norm(a, ord=p, keepdims=keepdim),
        x,
        _op_name="matrix_norm",
    )


def dist(x, y, p=2, name=None):
    return apply_op(
        lambda a, b: jnp.linalg.norm((a - b).reshape(-1), ord=p), x, y, _op_name="dist"
    )


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary", name=None):
    def _cdist(a, b):
        diff = a[..., :, None, :] - b[..., None, :, :]
        if p == 2.0:
            return jnp.sqrt(jnp.sum(diff * diff, axis=-1) + 1e-30)
        return jnp.sum(jnp.abs(diff) ** p, axis=-1) ** (1.0 / p)

    return apply_op(_cdist, x, y, _op_name="cdist")


def t(input, name=None):
    from .manipulation import t as _t

    return _t(input)


def transpose(x, perm, name=None):
    from .manipulation import transpose as _transpose

    return _transpose(x, perm)


def cholesky(x, upper=False, name=None):
    def _chol(a):
        lower = jnp.linalg.cholesky(a)
        return jnp.swapaxes(lower, -2, -1) if upper else lower

    return apply_op(_chol, x, _op_name="cholesky")


def cholesky_solve(x, y, upper=False, name=None):
    return apply_op(
        lambda b, l: jax.scipy.linalg.cho_solve((l, not upper), b),
        x,
        y,
        _op_name="cholesky_solve",
    )


def inverse(x, name=None):
    return apply_op(jnp.linalg.inv, x, _op_name="inverse")


inv = inverse


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply_op(
        lambda a: jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian),
        x,
        _op_name="pinv",
    )


def solve(x, y, name=None):
    return apply_op(jnp.linalg.solve, x, y, _op_name="solve")


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    return apply_op(
        lambda a, b: jax.scipy.linalg.solve_triangular(
            a, b, trans=1 if transpose else 0, lower=not upper, unit_diagonal=unitriangular
        ),
        x,
        y,
        _op_name="triangular_solve",
    )


def lu(x, pivot=True, get_infos=False, name=None):
    def _lu(a):
        lu_mat, piv = jax.scipy.linalg.lu_factor(a)
        if get_infos:
            return lu_mat, piv.astype(np.int32) + 1, jnp.zeros((), np.int32)
        return lu_mat, piv.astype(np.int32) + 1

    return apply_op(_lu, x, _op_name="lu")


def svd(x, full_matrices=False, name=None):
    def _svd(a):
        u, s, vh = jnp.linalg.svd(a, full_matrices=full_matrices)
        return u, s, jnp.swapaxes(vh, -2, -1)  # paddle returns V not V^H

    return apply_op(_svd, x, _op_name="svd")


def qr(x, mode="reduced", name=None):
    def _qr(a):
        if mode == "r":
            return jnp.linalg.qr(a, mode="r")
        q, r = jnp.linalg.qr(a, mode=mode)
        return q, r

    return apply_op(_qr, x, _op_name="qr")


def eig(x, name=None):
    # XLA lacks general eig on TPU; compute on CPU host like the reference's
    # CPU-only kernels for eig.
    arr = np.asarray(x._data)
    w, v = np.linalg.eig(arr)
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))


def eigh(x, UPLO="L", name=None):
    return apply_op(
        lambda a: tuple(jnp.linalg.eigh(a, UPLO=UPLO)), x, _op_name="eigh"
    )


def eigvals(x, name=None):
    arr = np.asarray(x._data)
    return Tensor(jnp.asarray(np.linalg.eigvals(arr)))


def eigvalsh(x, UPLO="L", name=None):
    return apply_op(
        lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO), x, _op_name="eigvalsh"
    )


def det(x, name=None):
    return apply_op(jnp.linalg.det, x, _op_name="det")


def slogdet(x, name=None):
    def _slogdet(a):
        sign, logdet = jnp.linalg.slogdet(a)
        return jnp.stack([sign, logdet])

    return apply_op(_slogdet, x, _op_name="slogdet")


def matrix_power(x, n, name=None):
    return apply_op(
        lambda a: jnp.linalg.matrix_power(a, n), x, _op_name="matrix_power"
    )


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return apply_op(
        lambda a: jnp.linalg.matrix_rank(a, rtol=tol).astype(np.int64),
        x,
        _op_name="matrix_rank",
    )


def lstsq(x, y, rcond=None, driver=None, name=None):
    def _lstsq(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank.astype(np.int64), sv

    return apply_op(_lstsq, x, y, _op_name="lstsq")


def multi_dot(x, name=None):
    return apply_op(lambda xs: jnp.linalg.multi_dot(xs), list(x), _op_name="multi_dot")


def corrcoef(x, rowvar=True, name=None):
    return apply_op(
        lambda a: jnp.corrcoef(a, rowvar=rowvar), x, _op_name="corrcoef"
    )


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return apply_op(
        lambda a, fw, aw: jnp.cov(
            a, rowvar=rowvar, ddof=1 if ddof else 0, fweights=fw, aweights=aw
        ),
        x,
        fweights,
        aweights,
        _op_name="cov",
    )


def histogram(input, bins=100, min=0, max=0, weight=None, density=False, name=None):
    def _hist(a, w):
        lo, hi = (min, max) if (min != 0 or max != 0) else (None, None)
        rng = (lo, hi) if lo is not None else None
        h, _ = jnp.histogram(a.reshape(-1), bins=bins, range=rng, weights=w, density=density)
        return h if density or w is not None else h.astype(np.int64)

    return apply_op(_hist, input, weight, _op_name="histogram")


def histogramdd(x, bins=10, ranges=None, density=False, weights=None, name=None):
    def _histdd(a, w):
        h, edges = jnp.histogramdd(a, bins=bins, range=ranges, weights=w, density=density)
        return (h, list(edges))

    return apply_op(_histdd, x, weights, _op_name="histogramdd")


def bincount(x, weights=None, minlength=0, name=None):
    def _bincount(a, w):
        length = int(np.maximum(np.asarray(a).max(initial=-1) + 1, minlength))
        return jnp.bincount(a, weights=w, length=length)

    return apply_op(_bincount, x, weights, _op_name="bincount")


def householder_product(x, tau, name=None):
    def _hp(a, t):
        m, n = a.shape[-2], a.shape[-1]
        eye = jnp.eye(m, dtype=a.dtype)
        q = jnp.broadcast_to(eye, a.shape[:-2] + (m, m)).copy() if a.ndim > 2 else eye

        def body(i, q):
            v = jnp.where(jnp.arange(m) < i, 0.0, a[..., :, i])
            v = v.at[..., i].set(1.0)
            h = jnp.eye(m, dtype=a.dtype) - t[..., i] * jnp.outer(v, v)
            return q @ h

        for i in range(n):
            q = body(i, q)
        return q[..., :, :n]

    return apply_op(_hp, x, tau, _op_name="householder_product")
