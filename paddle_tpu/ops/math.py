"""Elementwise math + reductions (parity: python/paddle/tensor/math.py)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .. import dtypes as _dt, framework
from ..core.dispatch import apply_op
from ..core.tensor import Tensor


def _unary(jfn, name):
    def op(x, name=None):
        return apply_op(jfn, x, _op_name=name)

    op.__name__ = name
    return op


def _binary(jfn, name):
    def op(x, y, name=None):
        return apply_op(jfn, x, y, _op_name=name)

    op.__name__ = name
    return op


# -- elementwise unary ------------------------------------------------------
exp = _unary(jnp.exp, "exp")
expm1 = _unary(jnp.expm1, "expm1")
log = _unary(jnp.log, "log")
log2 = _unary(jnp.log2, "log2")
log10 = _unary(jnp.log10, "log10")
log1p = _unary(jnp.log1p, "log1p")
sqrt = _unary(jnp.sqrt, "sqrt")
rsqrt = _unary(jax.lax.rsqrt, "rsqrt")
abs = _unary(jnp.abs, "abs")
absolute = abs
neg = _unary(jnp.negative, "neg")
negative = neg
sign = _unary(jnp.sign, "sign")
sin = _unary(jnp.sin, "sin")
cos = _unary(jnp.cos, "cos")
tan = _unary(jnp.tan, "tan")
asin = _unary(jnp.arcsin, "asin")
acos = _unary(jnp.arccos, "acos")
atan = _unary(jnp.arctan, "atan")
arcsin, arccos, arctan = asin, acos, atan
sinh = _unary(jnp.sinh, "sinh")
cosh = _unary(jnp.cosh, "cosh")
tanh = _unary(jnp.tanh, "tanh")
asinh = _unary(jnp.arcsinh, "asinh")
acosh = _unary(jnp.arccosh, "acosh")
atanh = _unary(jnp.arctanh, "atanh")
floor = _unary(jnp.floor, "floor")
ceil = _unary(jnp.ceil, "ceil")
round = _unary(jnp.round, "round")
trunc = _unary(jnp.trunc, "trunc")
frac = _unary(lambda x: x - jnp.trunc(x), "frac")
reciprocal = _unary(jnp.reciprocal, "reciprocal")
square = _unary(jnp.square, "square")
erf = _unary(jax.scipy.special.erf, "erf")
erfinv = _unary(jax.scipy.special.erfinv, "erfinv")
sigmoid = _unary(jax.nn.sigmoid, "sigmoid")
digamma = _unary(jax.scipy.special.digamma, "digamma")
lgamma = _unary(jax.scipy.special.gammaln, "lgamma")
gamma = _unary(lambda x: jnp.exp(jax.scipy.special.gammaln(x)) * jnp.sign(x) ** 0, "gamma")
i0 = _unary(jax.scipy.special.i0, "i0")
i1 = _unary(jax.scipy.special.i1, "i1")
angle = _unary(jnp.angle, "angle")
conj = _unary(jnp.conj, "conj")
real = _unary(jnp.real, "real")
imag = _unary(jnp.imag, "imag")
deg2rad = _unary(jnp.deg2rad, "deg2rad")
rad2deg = _unary(jnp.rad2deg, "rad2deg")
exponent_bias = None  # placeholder


def logit(x, eps=None, name=None):
    def _logit(a):
        if eps is not None:
            a = jnp.clip(a, eps, 1.0 - eps)
        return jnp.log(a / (1.0 - a))

    return apply_op(_logit, x, _op_name="logit")


# -- elementwise binary -----------------------------------------------------
add = _binary(jnp.add, "add")
subtract = _binary(jnp.subtract, "subtract")
multiply = _binary(jnp.multiply, "multiply")
mul = multiply


def divide(x, y, name=None):
    def _div(a, b):
        out = jnp.true_divide(a, b)
        if not (
            jnp.issubdtype(jnp.result_type(a), jnp.inexact)
            or jnp.issubdtype(jnp.result_type(b), jnp.inexact)
        ):
            out = out.astype(framework.get_default_dtype().np_dtype)
        return out

    return apply_op(_div, x, y, _op_name="divide")


floor_divide = _binary(jnp.floor_divide, "floor_divide")
floor_mod = _binary(jnp.mod, "floor_mod")
mod = _binary(jnp.mod, "mod")
remainder = mod
pow = _binary(jnp.power, "pow")
maximum = _binary(jnp.maximum, "maximum")
minimum = _binary(jnp.minimum, "minimum")
fmax = _binary(jnp.fmax, "fmax")
fmin = _binary(jnp.fmin, "fmin")
atan2 = _binary(jnp.arctan2, "atan2")
heaviside = _binary(jnp.heaviside, "heaviside")
hypot = _binary(jnp.hypot, "hypot")
logaddexp = _binary(jnp.logaddexp, "logaddexp")
nextafter = _binary(jnp.nextafter, "nextafter")
copysign = _binary(jnp.copysign, "copysign")
gcd = _binary(jnp.gcd, "gcd")
lcm = _binary(jnp.lcm, "lcm")
kron = _binary(jnp.kron, "kron")
ldexp = _binary(lambda a, b: a * (2.0**b), "ldexp")
inner_alias = None


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    def _scale(a, s, b):
        s = jnp.asarray(s, a.dtype) if not np.isscalar(s) else s
        if bias_after_scale:
            return a * s + b
        return (a + b) * s

    return apply_op(_scale, x, scale, bias, _op_name="scale")


def clip(x, min=None, max=None, name=None):
    def _clip(a, lo, hi):
        return jnp.clip(a, lo, hi)

    lo = min.item() if isinstance(min, Tensor) else min
    hi = max.item() if isinstance(max, Tensor) else max
    return apply_op(_clip, x, lo, hi, _op_name="clip")


def lerp(x, y, weight, name=None):
    return apply_op(lambda a, b, w: a + w * (b - a), x, y, weight, _op_name="lerp")


def add_n(inputs, name=None):
    if isinstance(inputs, Tensor):
        return inputs
    return apply_op(
        lambda xs: sum(xs[1:], start=xs[0]) if len(xs) > 1 else xs[0],
        list(inputs),
        _op_name="add_n",
    )


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply_op(
        lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf),
        x,
        _op_name="nan_to_num",
    )


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply_op(lambda a: scale_b * jnp.tanh(scale_a * a), x, _op_name="stanh")


def multiplex(inputs, index, name=None):
    def _mpx(xs, idx):
        stacked = jnp.stack(xs, axis=0)
        return jnp.take_along_axis(
            stacked, idx.reshape(1, -1, *([1] * (stacked.ndim - 2))), axis=0
        )[0]

    return apply_op(_mpx, list(inputs), index, _op_name="multiplex")


# -- reductions -------------------------------------------------------------
def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        ax = axis.numpy().tolist()
        return tuple(ax) if isinstance(ax, list) else int(ax)
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    axis = _norm_axis(axis)
    d = _dt.to_np(dtype) if dtype is not None else None

    def _sum(a):
        out_dtype = d
        if out_dtype is None and jnp.issubdtype(a.dtype, jnp.bool_):
            out_dtype = np.int64
        return jnp.sum(a, axis=axis, keepdims=keepdim, dtype=out_dtype)

    return apply_op(_sum, x, _op_name="sum")


def mean(x, axis=None, keepdim=False, name=None):
    axis = _norm_axis(axis)
    return apply_op(
        lambda a: jnp.mean(a, axis=axis, keepdims=keepdim), x, _op_name="mean"
    )


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    axis = _norm_axis(axis)
    d = _dt.to_np(dtype) if dtype is not None else None
    return apply_op(
        lambda a: jnp.prod(a, axis=axis, keepdims=keepdim, dtype=d),
        x,
        _op_name="prod",
    )


def max(x, axis=None, keepdim=False, name=None):
    axis = _norm_axis(axis)
    return apply_op(
        lambda a: jnp.max(a, axis=axis, keepdims=keepdim), x, _op_name="max"
    )


def min(x, axis=None, keepdim=False, name=None):
    axis = _norm_axis(axis)
    return apply_op(
        lambda a: jnp.min(a, axis=axis, keepdims=keepdim), x, _op_name="min"
    )


amax = max
amin = min


def logsumexp(x, axis=None, keepdim=False, name=None):
    axis = _norm_axis(axis)
    return apply_op(
        lambda a: jax.scipy.special.logsumexp(a, axis=axis, keepdims=keepdim),
        x,
        _op_name="logsumexp",
    )


def all(x, axis=None, keepdim=False, name=None):
    axis = _norm_axis(axis)
    return apply_op(
        lambda a: jnp.all(a, axis=axis, keepdims=keepdim), x, _op_name="all"
    )


def any(x, axis=None, keepdim=False, name=None):
    axis = _norm_axis(axis)
    return apply_op(
        lambda a: jnp.any(a, axis=axis, keepdims=keepdim), x, _op_name="any"
    )


def count_nonzero(x, axis=None, keepdim=False, name=None):
    axis = _norm_axis(axis)
    return apply_op(
        lambda a: jnp.count_nonzero(a, axis=axis, keepdims=keepdim).astype(np.int64),
        x,
        _op_name="count_nonzero",
    )


def nanmean(x, axis=None, keepdim=False, name=None):
    axis = _norm_axis(axis)
    return apply_op(
        lambda a: jnp.nanmean(a, axis=axis, keepdims=keepdim), x, _op_name="nanmean"
    )


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    axis = _norm_axis(axis)
    d = _dt.to_np(dtype) if dtype is not None else None
    return apply_op(
        lambda a: jnp.nansum(a, axis=axis, keepdims=keepdim, dtype=d),
        x,
        _op_name="nansum",
    )


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    axis = _norm_axis(axis)
    return apply_op(
        lambda a: jnp.median(a, axis=axis, keepdims=keepdim), x, _op_name="median"
    )


def quantile(x, q, axis=None, keepdim=False, name=None):
    axis = _norm_axis(axis)
    return apply_op(
        lambda a, qq: jnp.quantile(a, jnp.asarray(qq), axis=axis, keepdims=keepdim),
        x,
        q,
        _op_name="quantile",
    )


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    axis = _norm_axis(axis)
    return apply_op(
        lambda a: jnp.std(a, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim),
        x,
        _op_name="std",
    )


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    axis = _norm_axis(axis)
    return apply_op(
        lambda a: jnp.var(a, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim),
        x,
        _op_name="var",
    )


# -- arg / index reductions -------------------------------------------------
def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    axis_n = _norm_axis(axis)
    d = _dt.to_np(dtype)

    def _argmax(a):
        out = jnp.argmax(a, axis=axis_n)
        if keepdim and axis_n is not None:
            out = jnp.expand_dims(out, axis_n)
        return out.astype(d)

    return apply_op(_argmax, x, _op_name="argmax")


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    axis_n = _norm_axis(axis)
    d = _dt.to_np(dtype)

    def _argmin(a):
        out = jnp.argmin(a, axis=axis_n)
        if keepdim and axis_n is not None:
            out = jnp.expand_dims(out, axis_n)
        return out.astype(d)

    return apply_op(_argmin, x, _op_name="argmin")


# -- cumulative -------------------------------------------------------------
def cumsum(x, axis=None, dtype=None, name=None):
    d = _dt.to_np(dtype) if dtype is not None else None

    def _cumsum(a):
        if axis is None:
            return jnp.cumsum(a.reshape(-1), dtype=d)
        return jnp.cumsum(a, axis=int(axis), dtype=d)

    return apply_op(_cumsum, x, _op_name="cumsum")


def cumprod(x, dim=None, dtype=None, name=None):
    d = _dt.to_np(dtype) if dtype is not None else None

    def _cumprod(a):
        if dim is None:
            return jnp.cumprod(a.reshape(-1), dtype=d)
        return jnp.cumprod(a, axis=int(dim), dtype=d)

    return apply_op(_cumprod, x, _op_name="cumprod")


def cummax(x, axis=None, dtype="int64", name=None):
    def _cummax(a):
        ax = 0 if axis is None else int(axis)
        arr = a.reshape(-1) if axis is None else a
        vals = jax.lax.associative_scan(jnp.maximum, arr, axis=ax)
        inds = _cummax_indices(arr, ax)
        return vals, inds.astype(_dt.to_np(dtype))

    return apply_op(_cummax, x, _op_name="cummax")


def _cummax_indices(arr, ax):
    n = arr.shape[ax]
    idx = jnp.arange(n)
    shape = [1] * arr.ndim
    shape[ax] = n
    idx = idx.reshape(shape)

    def combine(a, b):
        av, ai = a
        bv, bi = b
        take_b = bv >= av
        return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)

    _, inds = jax.lax.associative_scan(
        combine, (arr, jnp.broadcast_to(idx, arr.shape)), axis=ax
    )
    return inds


def cummin(x, axis=None, dtype="int64", name=None):
    def _cummin(a):
        ax = 0 if axis is None else int(axis)
        arr = a.reshape(-1) if axis is None else a
        vals = jax.lax.associative_scan(jnp.minimum, arr, axis=ax)
        neg_inds = _cummax_indices(-arr, ax)
        return vals, neg_inds.astype(_dt.to_np(dtype))

    return apply_op(_cummin, x, _op_name="cummin")


def logcumsumexp(x, axis=None, name=None):
    def _lcse(a):
        ax = 0 if axis is None else int(axis)
        arr = a.reshape(-1) if axis is None else a
        return jax.lax.associative_scan(jnp.logaddexp, arr, axis=ax)

    return apply_op(_lcse, x, _op_name="logcumsumexp")


# -- tests ------------------------------------------------------------------
isnan = _unary(jnp.isnan, "isnan")
isinf = _unary(jnp.isinf, "isinf")
isfinite = _unary(jnp.isfinite, "isfinite")
isneginf = _unary(jnp.isneginf, "isneginf")
isposinf = _unary(jnp.isposinf, "isposinf")
isreal = _unary(jnp.isreal, "isreal")


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    return apply_op(
        lambda a, t: jnp.isin(a, t, invert=invert), x, test_x, _op_name="isin"
    )


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    return apply_op(
        lambda a, p, ap: jnp.diff(a, n=n, axis=axis, prepend=p, append=ap),
        x,
        prepend,
        append,
        _op_name="diff",
    )


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op(
        lambda a: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2),
        x,
        _op_name="trace",
    )


def inner(x, y, name=None):
    return apply_op(jnp.inner, x, y, _op_name="inner")


def outer(x, y, name=None):
    return apply_op(
        lambda a, b: jnp.outer(a.reshape(-1), b.reshape(-1)), x, y, _op_name="outer"
    )
