"""Random ops over the global Generator key chain.

Parity: python/paddle/tensor/random.py over ``phi::Generator`` Philox states.
Each op consumes one subkey from the default generator; under
``framework.rng_key_scope`` (used by the jit path) keys come from the scoped
chain so traced programs receive per-step randomness as an argument.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .. import dtypes as _dt, framework, device as _device
from ..core.dispatch import apply_op
from ..core.tensor import Tensor
from .creation import _shape_list


def _key():
    return framework.next_rng_key()


def _default_float():
    return framework.get_default_dtype().np_dtype


def rand(shape, dtype=None, name=None):
    d = _dt.to_np(dtype) if dtype is not None else _default_float()
    return Tensor(jax.random.uniform(_key(), _shape_list(shape), dtype=d))


def randn(shape, dtype=None, name=None):
    d = _dt.to_np(dtype) if dtype is not None else _default_float()
    return Tensor(jax.random.normal(_key(), _shape_list(shape), dtype=d))


def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    d = _dt.to_np(dtype) if dtype is not None else _default_float()
    key = jax.random.PRNGKey(seed) if seed else _key()
    return Tensor(
        jax.random.uniform(key, _shape_list(shape), dtype=d, minval=min, maxval=max)
    )


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        key = _key()

        def _normal(m, s):
            shp = jnp.broadcast_shapes(
                jnp.shape(m) if not np.isscalar(m) else (),
                jnp.shape(s) if not np.isscalar(s) else (),
            )
            return m + s * jax.random.normal(key, shp, dtype=_default_float())

        return apply_op(_normal, mean, std, _op_name="normal")
    shp = _shape_list(shape) if shape is not None else []
    return Tensor(
        mean + std * jax.random.normal(_key(), shp, dtype=_default_float())
    )


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None):
    d = _dt.to_np(dtype) if dtype is not None else _default_float()
    key = jax.random.PRNGKey(seed) if seed else _key()
    return Tensor(mean + std * jax.random.normal(key, _shape_list(shape), dtype=d))


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    d = _dt.to_np(dtype)
    return Tensor(
        jax.random.randint(_key(), _shape_list(shape), low, high, dtype=d)
    )


def randint_like(x, low=0, high=None, dtype=None, name=None):
    if high is None:
        low, high = 0, low
    d = _dt.to_np(dtype) if dtype is not None else _dt.to_np(x.dtype)
    return Tensor(
        jax.random.randint(_key(), tuple(x.shape), low, high).astype(d)
    )


def randperm(n, dtype="int64", name=None):
    d = _dt.to_np(dtype)
    return Tensor(jax.random.permutation(_key(), n).astype(d))


def bernoulli(x, name=None):
    key = _key()
    return apply_op(
        lambda p: jax.random.bernoulli(key, p).astype(p.dtype),
        x,
        _op_name="bernoulli",
    )


def bernoulli_(x, p=0.5, name=None):
    key = _key()
    out = Tensor(jax.random.bernoulli(key, p, tuple(x.shape)).astype(x._data.dtype))
    return x._assign_result_(out)


def binomial(count, prob, name=None):
    key = _key()
    return apply_op(
        lambda n, p: jax.random.binomial(key, n.astype(np.float32), p).astype(np.int64),
        count,
        prob,
        _op_name="binomial",
    )


def poisson(x, name=None):
    key = _key()
    return apply_op(
        lambda lam: jax.random.poisson(key, lam).astype(lam.dtype),
        x,
        _op_name="poisson",
    )


def multinomial(x, num_samples=1, replacement=False, name=None):
    key = _key()

    def _multinomial(p):
        logits = jnp.log(jnp.maximum(p, 1e-30))
        if replacement:
            return jax.random.categorical(
                key, logits, axis=-1, shape=(num_samples,) + p.shape[:-1]
            ).T.astype(np.int64) if p.ndim > 1 else jax.random.categorical(
                key, logits, shape=(num_samples,)
            ).astype(np.int64)
        # without replacement: Gumbel top-k trick
        g = jax.random.gumbel(key, p.shape)
        _, idx = jax.lax.top_k(logits + g, num_samples)
        return idx.astype(np.int64)

    return apply_op(_multinomial, x, _op_name="multinomial")


def exponential_(x, lam=1.0, name=None):
    key = _key()
    out = Tensor(
        (jax.random.exponential(key, tuple(x.shape)) / lam).astype(x._data.dtype)
    )
    return x._assign_result_(out)


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.PRNGKey(seed) if seed else _key()
    out = Tensor(
        jax.random.uniform(
            key, tuple(x.shape), dtype=x._data.dtype, minval=min, maxval=max
        )
    )
    return x._assign_result_(out)


def normal_(x, mean=0.0, std=1.0, name=None):
    out = Tensor(
        (mean + std * jax.random.normal(_key(), tuple(x.shape))).astype(x._data.dtype)
    )
    return x._assign_result_(out)


def rand_like(x, dtype=None, name=None):
    d = _dt.to_np(dtype) if dtype is not None else x._data.dtype
    return Tensor(jax.random.uniform(_key(), tuple(x.shape), dtype=d))


def randn_like(x, dtype=None, name=None):
    d = _dt.to_np(dtype) if dtype is not None else x._data.dtype
    return Tensor(jax.random.normal(_key(), tuple(x.shape), dtype=d))


def shuffle(x, axis=0, name=None):
    key = _key()
    return apply_op(
        lambda a: jax.random.permutation(key, a, axis=axis), x, _op_name="shuffle"
    )
