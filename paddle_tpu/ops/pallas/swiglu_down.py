"""Fused swiglu + down-projection as a Pallas TPU megakernel.

The norm→ffn seam of a decoder block ends in
``(silu(gate) * up) @ wd`` — unfused, the ``[tokens, intermediate]``
swiglu product makes a full HBM round-trip between the elementwise pass
and the down matmul (~45MB per microbatch at 1.3B/b4, 2x that at
LLaMA-7B widths where intermediate=11008). This kernel streams
(gate, up, wd) blocks through VMEM, applies silu*mul on the VPU, and
feeds the MXU dot directly — the product never exists in HBM
(FlashFuser-style seam fusion; docs/SCAN.md).

Backward is a hand-written custom_vjp (residuals: gate, up, wd — gate/up
already carry the ``ffn_gate``/``ffn_up`` remat anchors at the call
site, so a save policy controls their lifetime, not this kernel): the
swiglu product is rebuilt in XLA-fused elementwise math for the wd
weight-grad contraction, mirroring the int8-FFN vjp discipline
(models/gpt.py::_ffn_i8_bwd) without the quantization round-trip.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: row/contraction block sizes: rows feed the MXU 128-wide; the K blocks
#: walk the intermediate dim so wd never needs more than [bk, h] VMEM
_BLOCK_ROWS = 256
_BLOCK_K = 512


def _rows_block(n):
    for b in (_BLOCK_ROWS, 128, 64, 32, 16, 8):
        if n % b == 0:
            return b
    return None


def _k_block(m):
    for b in (_BLOCK_K, 256, 128):
        if m % b == 0:
            return b
    return None


def swiglu_down_supported(gate_shape, wd_shape):
    """Mosaic-tileable shapes: rows divisible by a sublane block, the
    intermediate dim by a K block, and lane-aligned trailing dims."""
    rows = 1
    for s in gate_shape[:-1]:
        rows *= int(s)
    m, h = int(wd_shape[0]), int(wd_shape[1])
    return (int(gate_shape[-1]) == m
            and _rows_block(rows) is not None
            and _k_block(m) is not None
            and h % 128 == 0 and m % 128 == 0)


def _fwd_kernel(g_ref, u_ref, wd_ref, o_ref, acc_ref, *, nk):
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    g32 = g_ref[:].astype(jnp.float32)
    u32 = u_ref[:].astype(jnp.float32)
    ffn = (g32 * jax.lax.logistic(g32) * u32).astype(g_ref.dtype)
    acc_ref[:] += jnp.dot(ffn, wd_ref[:],
                          preferred_element_type=jnp.float32)

    @pl.when(k == nk - 1)
    def _done():
        o_ref[:] = acc_ref[:].astype(o_ref.dtype)


def _fwd(g2, u2, wd, interpret):
    rows, m = g2.shape
    h = wd.shape[1]
    br = _rows_block(rows)
    bk = _k_block(m)
    nk = m // bk
    with jax.enable_x64(False):
        out = pl.pallas_call(
            functools.partial(_fwd_kernel, nk=nk),
            grid=(rows // br, nk),
            in_specs=[
                pl.BlockSpec((br, bk), lambda i, k: (i, k)),
                pl.BlockSpec((br, bk), lambda i, k: (i, k)),
                pl.BlockSpec((bk, h), lambda i, k: (k, 0)),
            ],
            out_specs=pl.BlockSpec((br, h), lambda i, k: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((rows, h), g2.dtype),
            scratch_shapes=[pltpu.VMEM((br, h), jnp.float32)],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "arbitrary")),
            cost_estimate=pl.CostEstimate(
                flops=2 * rows * m * h + 4 * rows * m,
                bytes_accessed=(2 * rows * m + m * h + rows * h)
                * g2.dtype.itemsize,
                transcendentals=rows * m,
            ),
            interpret=interpret,
        )(g2, u2, wd)
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _swiglu_down(g2, u2, wd, interpret):
    return _fwd(g2, u2, wd, interpret)


def _swiglu_down_fwd(g2, u2, wd, interpret):
    return _fwd(g2, u2, wd, interpret), (g2, u2, wd)


def _swiglu_down_bwd(interpret, res, g):
    g2, u2, wd = res
    gate = g2.astype(jnp.float32)
    up = u2.astype(jnp.float32)
    sig = jax.nn.sigmoid(gate)
    silu = gate * sig
    dsilu = sig * (1.0 + gate * (1.0 - sig))
    ffn = (silu * up).astype(g2.dtype)
    dffn = g @ wd.T
    dwd = jnp.einsum("rm,rh->mh", ffn, g).astype(wd.dtype)
    gf = dffn.astype(jnp.float32)
    dgate = (gf * up * dsilu).astype(g2.dtype)
    dup = (gf * silu).astype(u2.dtype)
    return dgate, dup, dwd


_swiglu_down.defvjp(_swiglu_down_fwd, _swiglu_down_bwd)


def swiglu_down(gate, up, wd, interpret=None):
    """Fused ``(silu(gate) * up) @ wd``. gate/up [..., M], wd [M, H] ->
    [..., H]; the swiglu product never materializes in HBM. Callers gate
    on :func:`swiglu_down_supported` — unsupported shapes raise here
    (loud, per the kernel-dispatch discipline in models/gpt.py)."""
    from . import use_interpret

    if interpret is None:
        interpret = use_interpret()
    if not swiglu_down_supported(gate.shape, wd.shape):
        raise ValueError(
            f"swiglu_down: untileable shapes gate={tuple(gate.shape)} "
            f"wd={tuple(wd.shape)} — guard with swiglu_down_supported")
    shape = gate.shape
    g2 = gate.reshape(-1, shape[-1])
    u2 = up.reshape(-1, shape[-1])
    out = _swiglu_down(g2, u2, wd, bool(interpret))
    return out.reshape(shape[:-1] + (wd.shape[1],))
