"""Pallas TPU kernels — the capability slot the reference fills with
hand-written CUDA fusions (``phi/kernels/fusion/gpu``, ``phi/kernels/gpu/
flash_attn_kernel.cu``).

Design stance (TPU-first): only ops that XLA cannot already fuse optimally
get a Pallas kernel. Flash attention (tiled online-softmax over VMEM blocks)
and row-normalisation (rms/layer norm over long rows) qualify; elementwise
chains like rope/swiglu/bias-act do NOT — XLA fuses those into the
surrounding matmuls, and a Pallas kernel would break that fusion.

All kernels run in interpret mode on CPU (tests) and compiled on TPU.
"""
from __future__ import annotations

import jax


def use_interpret() -> bool:
    """Interpret-mode on non-TPU backends so the same kernel code is tested
    on the CPU mesh (SURVEY §4: fake-backend strategy)."""
    try:
        return jax.default_backend() != "tpu"
    except Exception:
        return True


from .flash_attention import flash_attention, flash_attention_fwd  # noqa: E402
from .rms_norm import rms_norm  # noqa: E402

__all__ = ["flash_attention", "flash_attention_fwd", "rms_norm", "use_interpret"]
