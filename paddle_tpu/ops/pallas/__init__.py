"""Pallas TPU kernels — the capability slot the reference fills with
hand-written CUDA fusions (``phi/kernels/fusion/gpu``, ``phi/kernels/gpu/
flash_attn_kernel.cu``).

Design stance (TPU-first): only ops that XLA cannot already fuse optimally
get a Pallas kernel. Flash attention (tiled online-softmax over VMEM blocks)
and row-normalisation (rms/layer norm over long rows) qualify; elementwise
chains like rope/swiglu/bias-act do NOT — XLA fuses those into the
surrounding matmuls, and a Pallas kernel would break that fusion.

All kernels run in interpret mode on CPU (tests) and compiled on TPU.
"""
from __future__ import annotations

import logging
import os

import jax

_log = logging.getLogger("paddle_tpu.pallas")
_tpu_cache = [None]


def on_tpu_device() -> bool:
    """True when the addressable devices can compile Mosaic kernels.

    Gate on the *device* platform (not ``jax.default_backend()`` alone) so
    experimental platform registrations that tunnel to a real chip (e.g. the
    axon remote-v5e plugin, whose devices report platform="tpu",
    device_kind="TPU v5 lite") take the compiled path. Override with
    PADDLE_TPU_FORCE_PALLAS=1/0.
    """
    force = os.environ.get("PADDLE_TPU_FORCE_PALLAS")
    if force is not None:
        return force not in ("0", "false", "")
    if _tpu_cache[0] is None:
        try:
            _tpu_cache[0] = jax.devices()[0].platform == "tpu"
        except Exception:
            _tpu_cache[0] = False
    return _tpu_cache[0]


def use_interpret() -> bool:
    """Interpret-mode on non-TPU backends so the same kernel code is tested
    on the CPU mesh (SURVEY §4: fake-backend strategy)."""
    return not on_tpu_device()


_path_logged = set()


def log_path_once(op: str, path: str) -> None:
    """One-line record of which implementation served an op (pallas vs xla),
    so benchmarks can prove the fast path engaged. Keyed on (op, path): a
    mid-run path switch (shape-dependent fallback) is logged too. INFO level
    — bench.py raises this logger to INFO to record the path."""
    if (op, path) not in _path_logged:
        _path_logged.add((op, path))
        _log.info("paddle_tpu dispatch path: %s -> %s", op, path)


from .flash_attention import flash_attention, flash_attention_fwd  # noqa: E402
from .rms_norm import rms_norm  # noqa: E402
from .swiglu_down import swiglu_down, swiglu_down_supported  # noqa: E402

__all__ = ["flash_attention", "flash_attention_fwd", "rms_norm",
           "swiglu_down", "swiglu_down_supported", "use_interpret"]
