"""Fused residual-add + RMS norm as a Pallas TPU kernel.

Capability parity: the reference's fused residual+norm CUDA kernels
(``phi/kernels/fusion/gpu/fused_layernorm_kernel.cu`` — residual_bias_add
+ norm in one pass). The transformer block computes ``y = x + attn_out``
followed by ``rms(y)``; unfused, ``y`` makes an HBM round-trip between
the add and the norm's read (plus a second read for the norm's variance
pass when XLA doesn't fuse across the reduce). This kernel streams row
blocks through VMEM once and emits BOTH tensors the block needs: the new
residual stream ``y`` and the normalised ``o``.

Backward reuses the forward's rstd residual (closed-form jnp, XLA-fused)
and returns the ONE shared cotangent for x and r — the caller adds the
downstream residual gradient itself.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _rows_block(n: int) -> int:
    for b in (256, 128, 64, 32, 16, 8, 4, 2, 1):
        if n % b == 0:
            return b
    return 1


def _fwd_kernel(x_ref, r_ref, w_ref, y_ref, o_ref, rstd_ref, *, eps):
    y32 = x_ref[:].astype(jnp.float32) + r_ref[:].astype(jnp.float32)
    y_ref[:] = y32.astype(y_ref.dtype)
    # norm reads the ROUNDED residual stream (bf16), matching the unfused
    # reference `rms(x + r)` where the add materialises in model dtype
    yn = y_ref[:].astype(jnp.float32)
    var = jnp.mean(jnp.square(yn), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    o_ref[:] = (yn * rstd * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)
    rstd_ref[:] = rstd


def _fwd(x2, r2, w, eps, interpret):
    n, h = x2.shape
    br = _rows_block(n)
    with jax.enable_x64(False):
        y, o, rstd = pl.pallas_call(
            functools.partial(_fwd_kernel, eps=eps),
            grid=(n // br,),
            in_specs=[
                pl.BlockSpec((br, h), lambda i: (i, 0)),
                pl.BlockSpec((br, h), lambda i: (i, 0)),
                pl.BlockSpec((h,), lambda i: (0,)),
            ],
            out_specs=[
                pl.BlockSpec((br, h), lambda i: (i, 0)),
                pl.BlockSpec((br, h), lambda i: (i, 0)),
                pl.BlockSpec((br, 1), lambda i: (i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((n, h), x2.dtype),
                jax.ShapeDtypeStruct((n, h), x2.dtype),
                jax.ShapeDtypeStruct((n, 1), jnp.float32),
            ],
            interpret=interpret,
        )(x2, r2, w)
    return y, o, rstd[:, 0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _add_rms(x2, r2, w, eps, interpret):
    y, o, _ = _fwd(x2, r2, w, eps, interpret)
    return y, o


def _add_rms_fwd(x2, r2, w, eps, interpret):
    y, o, rstd = _fwd(x2, r2, w, eps, interpret)
    # named residuals: under selective remat, policies saving
    # "addrms_y"/"rms_rstd" let the backward reuse them instead of
    # re-running this kernel
    from jax.ad_checkpoint import checkpoint_name

    y = checkpoint_name(y, "addrms_y")
    rstd = checkpoint_name(rstd, "rms_rstd")
    return (y, o), (y, w, rstd)


def _add_rms_bwd(eps, interpret, res, gs):
    y, w, rstd = res
    gy, go = gs
    yf = y.astype(jnp.float32)
    gf = go.astype(jnp.float32)
    wf = w.astype(jnp.float32)
    r = rstd[:, None]
    yhat = yf * r
    gw_ = gf * wf
    dnorm = r * (gw_ - yhat * jnp.mean(gw_ * yhat, axis=-1, keepdims=True))
    dy = gy.astype(jnp.float32) + dnorm
    dw = jnp.sum(gf * yhat, axis=0)
    dy = dy.astype(y.dtype)
    return dy, dy, dw.astype(w.dtype)


_add_rms.defvjp(_add_rms_fwd, _add_rms_bwd)


def add_rms_norm(x, residual, weight, epsilon=1e-6, interpret=None):
    """Fused ``y = x + residual; o = rms_norm(y) * weight``.

    Returns ``(y, o)`` — the updated residual stream and the normalised
    activations. Shapes: x/residual [..., H], weight [H].
    """
    from . import use_interpret

    if interpret is None:
        interpret = use_interpret()
    shape = x.shape
    x2 = x.reshape(-1, shape[-1])
    r2 = residual.reshape(-1, shape[-1])
    y, o = _add_rms(x2, r2, weight, float(epsilon), bool(interpret))
    return y.reshape(shape), o.reshape(shape)
