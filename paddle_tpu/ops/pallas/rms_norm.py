"""Fused RMS norm as a Pallas TPU kernel.

Capability parity: ``phi/kernels/fusion/gpu/fused_rms_norm*`` (reference's
hand-written CUDA fusion). Forward is a single VMEM pass over row blocks;
backward uses the closed-form jnp expression (XLA fuses it into one kernel,
and it reuses the forward's rstd residual instead of recomputing variance).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _rows_block(n: int) -> int:
    for b in (256, 128, 64, 32, 16, 8, 4, 2, 1):
        if n % b == 0:
            return b
    return 1


def _fwd_kernel(x_ref, w_ref, o_ref, rstd_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    o_ref[:] = (x * rstd * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)
    rstd_ref[:] = rstd  # [br, 1] — 2D so the last block dim is the full dim


def _fwd(x2, w, eps, interpret):
    n, h = x2.shape
    br = _rows_block(n)
    # keep Mosaic tracing in 32-bit mode (global x64 is on for API parity)
    with jax.enable_x64(False):
        o, rstd = _fwd_call(n, h, br, eps, interpret, x2, w)
    return o, rstd[:, 0]


def _fwd_call(n, h, br, eps, interpret, x2, w):
    return pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=(n // br,),
        in_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((h,), lambda i: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h), x2.dtype),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x2, w)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _rms(x2, w, eps, interpret):
    return _fwd(x2, w, eps, interpret)[0]


def _rms_fwd(x2, w, eps, interpret):
    o, rstd = _fwd(x2, w, eps, interpret)
    # named residual: selective-remat policies listing "rms_rstd" keep the
    # [rows] f32 sidecar so the backward reuses it instead of re-running
    # the forward kernel to regenerate the variance
    from jax.ad_checkpoint import checkpoint_name

    rstd = checkpoint_name(rstd, "rms_rstd")
    return o, (x2, w, rstd)


def _rms_bwd(eps, interpret, res, g):
    x2, w, rstd = res
    h = x2.shape[-1]
    xf = x2.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    wf = w.astype(jnp.float32)
    r = rstd[:, None]
    xhat = xf * r
    gw = gf * wf
    # d/dx of x * rstd(x): rstd * (gw - xhat * mean(gw * xhat))
    dx = r * (gw - xhat * jnp.mean(gw * xhat, axis=-1, keepdims=True))
    dw = jnp.sum(gf * xhat, axis=0)
    return dx.astype(x2.dtype), dw.astype(w.dtype)


_rms.defvjp(_rms_fwd, _rms_bwd)


def rms_norm(x, weight, epsilon=1e-6, interpret=None):
    """RMS-normalise the last axis of ``x`` and scale by ``weight``."""
    from . import use_interpret

    if interpret is None:
        interpret = use_interpret()
    shape = x.shape
    x2 = x.reshape(-1, shape[-1])
    o = _rms(x2, weight, float(epsilon), bool(interpret))
    return o.reshape(shape)
