"""Decode-serving attention as Pallas TPU kernels.

Capability parity: the reference's serving attention fusion kernels —
`phi/kernels/fusion/gpu/masked_multihead_attention_kernel.cu` (single-token
decode over a dense [B, H, MaxLen, D] cache) and
`phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu` (paged KV
cache addressed through block tables). TPU redesign: one online-softmax
kernel per cache layout, KV streamed through VMEM in blocks/pages, q heads
grouped by their shared kv head (GQA never materialises repeated KV), and
per-batch valid lengths arriving via scalar prefetch so block tables can
drive the BlockSpec index maps (the pages a sequence doesn't own are never
even fetched from HBM).

Decode is HBM-bandwidth-bound (the whole KV cache is read once per token),
so the kernels optimise for streaming: f32 accumulation scratch, last grid
dim sequential over KV, page/block granularity aligned to Mosaic tiling.

Layouts:
  decode_attention:  q [B, Hq, D], cache [B, Hkv, S, D], lengths [B]
  paged_attention:   q [B, Hq, D], pages [Hkv, NumPages, PageSize, D],
                     block_tables [B, PagesPerSeq], lengths [B]
`lengths[b]` counts the VALID kv positions (including the current token's
freshly-written slot).
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# f32-typed constants: weak python floats promote to f64 under x64 on
# old-jax interpret-mode lowering, which rejects the mixed-width where()
NEG_INF = np.float32(-1e30)
ONE_F32 = np.float32(1.0)


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, acc, m_scr, l_scr,
                   *, scale, bk, nk):
    b = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    length = len_ref[b]

    @pl.when(j * bk < length)          # skip fully-invalid kv blocks
    def _():
        q = q_ref[0, 0]                # [rep, d]
        k = k_ref[0, 0]                # [bk, d]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale                      # [rep, bk]
        pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + j * bk
        s = jnp.where(pos < length, s, NEG_INF)

        m_prev = m_scr[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:, 0:1] = alpha * l_scr[:, 0:1] + jnp.sum(p, -1, keepdims=True)
        acc[:] = acc[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:, 0:1] = m_new

    @pl.when(j == nk - 1)
    def _():
        l = l_scr[:, 0:1]
        o_ref[0, 0] = (acc[:] / jnp.where(l == 0.0, ONE_F32, l)).astype(o_ref.dtype)


def decode_attention(q, k_cache, v_cache, lengths, *, scale=None,
                     block_k=512, interpret=None):
    """Single-token decode attention over a dense KV cache.

    q [B, Hq, D] -> out [B, Hq, D]; cache [B, Hkv, S, D]; lengths [B].
    """
    from . import use_interpret

    if interpret is None:
        interpret = use_interpret()
    b, hq, d = q.shape
    _, hkv, s, _ = k_cache.shape
    rep = hq // hkv
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    bk = min(block_k, s)
    while s % bk:
        bk //= 2
    nk = s // bk

    qg = q.reshape(b, hkv, rep, d)
    kern = functools.partial(_decode_kernel, scale=scale, bk=bk, nk=nk)
    with jax.enable_x64(False):
        out = pl.pallas_call(
            kern,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(b, hkv, nk),
                in_specs=[
                    pl.BlockSpec((1, 1, rep, d), lambda bi, h, j, L: (bi, h, 0, 0)),
                    pl.BlockSpec((1, 1, bk, d), lambda bi, h, j, L: (bi, h, j, 0)),
                    pl.BlockSpec((1, 1, bk, d), lambda bi, h, j, L: (bi, h, j, 0)),
                ],
                out_specs=pl.BlockSpec(
                    (1, 1, rep, d), lambda bi, h, j, L: (bi, h, 0, 0)),
                scratch_shapes=[
                    pltpu.VMEM((rep, d), jnp.float32),
                    pltpu.VMEM((rep, 128), jnp.float32),
                    pltpu.VMEM((rep, 128), jnp.float32),
                ],
            ),
            out_shape=jax.ShapeDtypeStruct((b, hkv, rep, d), q.dtype),
            interpret=interpret,
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")),
            cost_estimate=pl.CostEstimate(
                flops=4 * b * hq * s * d,
                bytes_accessed=(b * hq * d + 2 * b * hkv * s * d)
                * q.dtype.itemsize,
                transcendentals=b * hq * s,
            ),
        )(lengths.astype(jnp.int32), qg, k_cache, v_cache)
    return out.reshape(b, hq, d)


# ------------------------------------------------------------------ paged

def _paged_kernel(tables_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                  acc, m_scr, l_scr, *, scale, page, npages):
    b = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    length = len_ref[b]

    @pl.when(j * page < length)
    def _():
        q = q_ref[0, 0]                # [rep, d]
        k = k_ref[0, 0]                # [page, d]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale                      # [rep, page]
        pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + j * page
        s = jnp.where(pos < length, s, NEG_INF)

        m_prev = m_scr[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:, 0:1] = alpha * l_scr[:, 0:1] + jnp.sum(p, -1, keepdims=True)
        acc[:] = acc[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:, 0:1] = m_new

    @pl.when(j == npages - 1)
    def _():
        l = l_scr[:, 0:1]
        o_ref[0, 0] = (acc[:] / jnp.where(l == 0.0, ONE_F32, l)).astype(o_ref.dtype)


def _paged_int8_kernel(tables_ref, len_ref, q_ref, kc_ref, ks_ref,
                       vc_ref, vs_ref, o_ref, acc, m_scr, l_scr,
                       *, scale, page, npages):
    """Paged decode over int8 KV pages: dequantize (codes, scales)
    INSIDE the kernel, so only ~1/4 of the exact cache's bytes cross
    HBM->VMEM per token (int8 codes + one f32 scale per head_dim row vs
    f32/bf16 rows) — the serving int8_kv mode's gather+dequantize-in-HBM
    path becomes a streaming read (docs/SERVING.md)."""
    b = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    length = len_ref[b]

    @pl.when(j * page < length)
    def _():
        q = q_ref[0, 0]                # [rep, d]
        # per-row dequant: codes [page, d] int8 * scale [page] f32 —
        # the quantize_rows_int8 grid (block = the head_dim row the
        # page table already addresses)
        k = kc_ref[0, 0].astype(jnp.float32) * ks_ref[0, 0, 0][:, None]
        v = vc_ref[0, 0].astype(jnp.float32) * vs_ref[0, 0, 0][:, None]
        s = jax.lax.dot_general(
            q.astype(jnp.float32), k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32
        ) * scale                      # [rep, page]
        pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + j * page
        s = jnp.where(pos < length, s, NEG_INF)

        m_prev = m_scr[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:, 0:1] = alpha * l_scr[:, 0:1] + jnp.sum(p, -1, keepdims=True)
        acc[:] = acc[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:, 0:1] = m_new

    @pl.when(j == npages - 1)
    def _():
        l = l_scr[:, 0:1]
        o_ref[0, 0] = (acc[:] / jnp.where(l == 0.0, ONE_F32, l)).astype(o_ref.dtype)


def paged_attention_int8(q, k_codes, k_scales, v_codes, v_scales,
                         block_tables, lengths, *, scale=None,
                         interpret=None):
    """Paged-KV decode attention over int8 pages (the serving
    ``int8_kv=True`` storage: ``memory.quantize_rows_int8`` codes
    ``[Hkv, NumPages, PageSize, D]`` int8 + scales
    ``[Hkv, NumPages, PageSize, 1]`` f32). Dequantization happens in
    VMEM per fetched page — numerically identical to gathering the
    owned pages and dequantizing in HBM (same codes * scales product),
    without ever materializing the dequantized cache.
    """
    from . import use_interpret

    if interpret is None:
        interpret = use_interpret()
    b, hq, d = q.shape
    hkv, num_pages, page, _ = k_codes.shape
    rep = hq // hkv
    pages_per_seq = block_tables.shape[1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)

    def _page_index(bi, h, j, tables, lens):
        t = tables[bi, j]
        return (h, jnp.clip(t, jnp.int32(0), jnp.int32(num_pages - 1)),
                0, 0)

    qg = q.reshape(b, hkv, rep, d)
    # scales ride sublane-padded [Hkv, P, 8, page] (the lse8 pattern:
    # Mosaic blocks need >= 8 sublanes) — a broadcast view, 32B/page-row
    ks8 = jnp.broadcast_to(k_scales.reshape(hkv, num_pages, 1, page),
                           (hkv, num_pages, 8, page))
    vs8 = jnp.broadcast_to(v_scales.reshape(hkv, num_pages, 1, page),
                           (hkv, num_pages, 8, page))
    kern = functools.partial(_paged_int8_kernel, scale=scale, page=page,
                             npages=pages_per_seq)
    with jax.enable_x64(False):
        out = pl.pallas_call(
            kern,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(b, hkv, pages_per_seq),
                in_specs=[
                    pl.BlockSpec((1, 1, rep, d),
                                 lambda bi, h, j, T, L: (bi, h, 0, 0)),
                    pl.BlockSpec((1, 1, page, d), _page_index),
                    pl.BlockSpec((1, 1, 8, page), _page_index),
                    pl.BlockSpec((1, 1, page, d), _page_index),
                    pl.BlockSpec((1, 1, 8, page), _page_index),
                ],
                out_specs=pl.BlockSpec(
                    (1, 1, rep, d), lambda bi, h, j, T, L: (bi, h, 0, 0)),
                scratch_shapes=[
                    pltpu.VMEM((rep, d), jnp.float32),
                    pltpu.VMEM((rep, 128), jnp.float32),
                    pltpu.VMEM((rep, 128), jnp.float32),
                ],
            ),
            out_shape=jax.ShapeDtypeStruct((b, hkv, rep, d), q.dtype),
            interpret=interpret,
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")),
            cost_estimate=pl.CostEstimate(
                flops=4 * b * hq * pages_per_seq * page * d,
                bytes_accessed=(b * hq * d * q.dtype.itemsize
                                + 2 * b * hkv * pages_per_seq * page
                                * (d + 4)),
                transcendentals=b * hq * pages_per_seq * page,
            ),
        )(block_tables.astype(jnp.int32), lengths.astype(jnp.int32),
          qg, k_codes, ks8, v_codes, vs8)
    return out.reshape(b, hq, d)


def paged_attention(q, k_pages, v_pages, block_tables, lengths, *,
                    scale=None, interpret=None):
    """Paged-KV decode attention (block_multi_head_attention slot).

    q [B, Hq, D]; pages [Hkv, NumPages, PageSize, D];
    block_tables [B, PagesPerSeq] (page ids per sequence, row-major);
    lengths [B] valid kv length. The BlockSpec index map reads the block
    table via scalar prefetch, so only the pages a sequence actually owns
    are fetched from HBM.
    """
    from . import use_interpret

    if interpret is None:
        interpret = use_interpret()
    b, hq, d = q.shape
    hkv, num_pages, page, _ = k_pages.shape
    rep = hq // hkv
    pages_per_seq = block_tables.shape[1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)

    def _page_index(bi, h, j, tables, lens):
        # clamp so garbage table entries past `lengths` stay in-bounds
        # (i32 bounds: python-int literals weak-type to i64 under x64 and
        # old-jax lowering rejects the mixed-width clip call)
        t = tables[bi, j]
        return (h, jnp.clip(t, jnp.int32(0), jnp.int32(num_pages - 1)),
                0, 0)

    qg = q.reshape(b, hkv, rep, d)
    kern = functools.partial(_paged_kernel, scale=scale, page=page,
                             npages=pages_per_seq)
    with jax.enable_x64(False):
        out = pl.pallas_call(
            kern,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(b, hkv, pages_per_seq),
                in_specs=[
                    pl.BlockSpec((1, 1, rep, d),
                                 lambda bi, h, j, T, L: (bi, h, 0, 0)),
                    pl.BlockSpec((1, 1, page, d), _page_index),
                    pl.BlockSpec((1, 1, page, d), _page_index),
                ],
                out_specs=pl.BlockSpec(
                    (1, 1, rep, d), lambda bi, h, j, T, L: (bi, h, 0, 0)),
                scratch_shapes=[
                    pltpu.VMEM((rep, d), jnp.float32),
                    pltpu.VMEM((rep, 128), jnp.float32),
                    pltpu.VMEM((rep, 128), jnp.float32),
                ],
            ),
            out_shape=jax.ShapeDtypeStruct((b, hkv, rep, d), q.dtype),
            interpret=interpret,
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")),
            cost_estimate=pl.CostEstimate(
                flops=4 * b * hq * pages_per_seq * page * d,
                bytes_accessed=(b * hq * d
                                + 2 * b * hkv * pages_per_seq * page * d)
                * q.dtype.itemsize,
                transcendentals=b * hq * pages_per_seq * page,
            ),
        )(block_tables.astype(jnp.int32), lengths.astype(jnp.int32),
          qg, k_pages, v_pages)
    return out.reshape(b, hq, d)
