"""Flash attention as a Pallas TPU kernel (fwd + bwd), with custom_vjp.

Capability parity: the reference binds the external CUDA flashattn library
(``phi/kernels/gpu/flash_attn_kernel.cu``); on TPU the same slot is a tiled
online-softmax kernel that keeps q/k/v blocks in VMEM and accumulates in
float32 — O(S) memory instead of the O(S^2) score matrix.

Layout: public entry takes paddle's [B, S, H, D]; kernels run on [BH, S, D].
GQA is handled in the BlockSpec index maps (q-head blocks read their shared
kv head directly) — kv is never materialised at q-head width.

Causal semantics match the XLA fallback (`_xla_sdpa`): when sq != sk the
queries align to the END of the key sequence (kv-cache decode convention),
i.e. query row i sees key cols <= i + (sk - sq).

Grid convention (TPU grids execute the LAST dimension innermost &
sequentially, so scratch accumulators carry across it):
  forward:  (B*Hq, Sq/bq, Sk/bk)   — k-blocks stream through a fixed q-block
  backward: dq   (B*Hq, Sq/bq, Sk/bk)
            dkdv (B*Hkv, Sk/bk, rep*Sq/bq) — the q sweep covers all rep
            q-heads sharing the kv head, keeping accumulation sequential.
"""
from __future__ import annotations

import functools
import math

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# f32-typed constants: weak python floats promote to f64 under x64 on
# old-jax interpret-mode lowering, which rejects the mixed-width where()
NEG_INF = np.float32(-1e30)  # large-negative instead of -inf: keeps exp()
                 # exact zero without nan from (-inf) - (-inf) in rescale
ONE_F32 = np.float32(1.0)


def _block_for(s: int, env="PTPU_FA_BLOCK", default=1024):
    """Pick a seq block size whose lse/delta blocks satisfy Mosaic's
    last-dim tiling (multiple of 128, or the full dimension).
    PTPU_FA_BLOCK / PTPU_FA_BWD_BLOCK override the preferred fwd/bwd sizes
    (perf knobs; measured on v5e at seq 2048 end-to-end 1.3B pretrain:
    fwd 1024 > 512 by 4.3%, 512 > 256/128 by 17%/40% — bigger q/k tiles
    amortise the VMEM streaming; the bwd kernels hold more live blocks so
    their sweet spot can differ)."""
    import os

    raw = os.environ.get(env)
    if raw is None:
        pref = default
    else:
        try:
            pref = int(raw)
        except ValueError:
            # a mistyped knob must not silently masquerade as a measured
            # configuration — the sweeps record these envs verbatim
            raise ValueError(
                f"{env}={raw!r}: expected an integer block size in "
                "tokens (a multiple of 128)") from None
        if pref % 128:
            import warnings

            warnings.warn(
                f"{env}={pref} is not a multiple of 128 — Mosaic block "
                f"tiling requires it; IGNORING the override and using "
                f"the default {default}. Fix the knob or the recorded "
                "perf numbers will not measure what the env claims.",
                RuntimeWarning, stacklevel=2)
            pref = default
    if s <= 512:
        return s  # full-dim block (always tileable at these sizes)
    for b in (pref, 1024, 512, 256, 128):
        if b % 128 == 0 and s % b == 0:
            return b
    return None


def _bwd_block_for(s: int):
    # 1024 measured best once causally-skipped blocks stopped being
    # fetched (the clamp halved bwd DMA volume; before it, 512 won)
    return _block_for(s, env="PTPU_FA_BWD_BLOCK", default=1024)


def supported_seq(s: int) -> bool:
    return _block_for(s) is not None


def to_bh(x, h):
    """[B, S, H, D] -> the kernel layout [B*H, S, D]."""
    b, s, _, d = x.shape
    return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, s, d)


def from_bh(x, b, h):
    """[B*H, S, D] -> [B, S, H, D]."""
    s, d = x.shape[1], x.shape[2]
    return jnp.transpose(x.reshape(b, h, s, d), (0, 2, 1, 3))


def _causal_mask(qi, ki, bq, bk, offset):
    """[bq, bk] bool: True where key col <= query row + offset."""
    rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + qi * bq
    cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + ki * bk
    return cols <= rows + offset


# ---------------------------------------------------------------- forward

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_scr, l_scr,
                *, scale, causal, bq, bk, nk, offset):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    def compute():
        q = q_ref[0]  # [bq, d]
        k = k_ref[0]  # [bk, d]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [bq, bk]
        if causal:
            s = jnp.where(_causal_mask(qi, ki, bq, bk, offset), s, NEG_INF)

        m_prev = m_scr[:, 0:1]                       # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)   # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                       # [bq, bk]
        alpha = jnp.exp(m_prev - m_new)              # [bq, 1]
        l_new = alpha * l_scr[:, 0:1] + jnp.sum(p, axis=-1, keepdims=True)
        acc[:] = acc[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:, 0:1] = m_new
        l_scr[:, 0:1] = l_new

    if causal:
        # k-blocks entirely above the (offset) diagonal are fully masked
        @pl.when(ki * bk <= qi * bq + (bq - 1) + offset)
        def _():
            compute()
    else:
        compute()

    @pl.when(ki == nk - 1)
    def _():
        l = l_scr[:, 0:1]
        l_safe = jnp.where(l == 0.0, ONE_F32, l)
        o_ref[0] = (acc[:] / l_safe).astype(o_ref.dtype)
        lse_row = m_scr[:, 0] + jnp.log(
            jnp.where(l[:, 0] == 0.0, ONE_F32, l[:, 0]))
        # [8, bq] sublane-padded block: Mosaic needs >=8 sublanes per block
        lse_ref[0] = jnp.broadcast_to(lse_row[None, :], (8, lse_row.shape[0]))


def _kv_index(b_idx, hq, hk):
    """Map a flat (batch*q_head) grid index to its (batch*kv_head) block.

    Uses lax primitives directly: jnp operator dispatch on the int32 grid
    tracer recurses inside Mosaic's index-map tracing."""
    if hq == hk:
        return b_idx
    rep = hq // hk
    hq_c = jnp.int32(hq)
    bi = jax.lax.div(b_idx, hq_c)
    hi = jax.lax.rem(b_idx, hq_c)
    return jax.lax.add(
        jax.lax.mul(bi, jnp.int32(hk)),
        jax.lax.div(hi, jnp.int32(rep)),
    )


def _fwd(q, k, v, scale, causal, interpret, hq, hk):
    bhq, sq, d = q.shape
    sk = k.shape[1]
    # PTPU_FA_KBLOCK decouples the streamed k/v tile from the q tile
    # (with a full-seq q block, a smaller k block keeps the DMA pipeline
    # ahead of the MXU; falls back to PTPU_FA_BLOCK when unset)
    import os as _os

    bq = _block_for(sq)
    bk = _block_for(sk, env="PTPU_FA_KBLOCK",
                    default=int(_os.environ.get("PTPU_FA_BLOCK", "1024")))
    if bq is None or bk is None:
        raise ValueError(
            f"flash_attention: seq lens ({sq}, {sk}) not tileable — pad to a "
            "multiple of 128 (or <= 512) or use the XLA fallback"
        )
    nq, nk = sq // bq, sk // bk
    offset = sk - sq

    kern = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, bq=bq, bk=bk, nk=nk,
        offset=offset,
    )
    # x64 mode (enabled globally for float64 API parity) must not leak into
    # kernel tracing: Mosaic has no 64-bit types and its lowering crashes on
    # the int64 literals x64 promotion produces.
    with jax.enable_x64(False):
        o, lse = _fwd_call(kern, q, k, v, bhq, sq, sk, d, bq, bk, nq, nk,
                           hq, hk, interpret, causal)
    return o, lse[:, 0, :]


def _clamp_kv_j(j, i, bq, bk, offset):
    """Causal fetch clamp: kv blocks past the diagonal are never computed
    (pl.when guards), so point their index map at the LAST VALID block —
    Mosaic skips the DMA when consecutive grid steps map the same block,
    removing the wasted fetches entirely."""
    jmax = jax.lax.div(
        jax.lax.add(jax.lax.mul(i, jnp.int32(bq)),
                    jnp.int32(bq - 1 + offset)),
        jnp.int32(bk))
    return jax.lax.min(j, jax.lax.max(jmax, jnp.int32(0)))


def _clamp_qi(qi, jk, bq, bk, offset):
    """Causal fetch clamp for the dkdv sweep: q blocks strictly above the
    diagonal contribute nothing for kv block jk; clamp to the first valid."""
    qi_min = jax.lax.max(
        jnp.int32(0),
        jax.lax.div(
            jax.lax.sub(jax.lax.mul(jk, jnp.int32(bk)), jnp.int32(offset)),
            jnp.int32(bq)))
    return jax.lax.max(qi, qi_min)


def _fwd_call(kern, q, k, v, bhq, sq, sk, d, bq, bk, nq, nk, hq, hk,
              interpret, causal):
    if causal:
        def kv_j(b, i, j):
            return (_kv_index(b, hq, hk),
                    _clamp_kv_j(j, i, bq, bk, sk - sq), 0)
    else:
        def kv_j(b, i, j):
            return (_kv_index(b, hq, hk), j, 0)

    return pl.pallas_call(
        kern,
        grid=(bhq, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), kv_j),
            pl.BlockSpec((1, bk, d), kv_j),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 8, bq), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bhq, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bhq, 8, sq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=4 * bhq * sq * sk * d,
            bytes_accessed=(2 * bhq * sq * d + 2 * (bhq // (hq // hk)) * sk * d)
            * q.dtype.itemsize,
            transcendentals=bhq * sq * sk,
        ),
    )(q, k, v)


# ---------------------------------------------------------------- backward

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_acc, *, scale, causal, bq, bk, nk, offset):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            s = jnp.where(_causal_mask(qi, ki, bq, bk, offset), s, NEG_INF)
        p = jnp.exp(s - lse_ref[0, 0][:, None])         # [bq, bk]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )                                               # [bq, bk]
        ds = p * (dp - delta_ref[0, 0][:, None])        # [bq, bk]
        dq_acc[:] += scale * jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        @pl.when(ki * bk <= qi * bq + (bq - 1) + offset)
        def _():
            compute()
    else:
        compute()

    @pl.when(ki == nk - 1)
    def _():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc,
                    *, scale, causal, bq, bk, nq, nq_total, offset):
    ki = pl.program_id(1)
    ji = pl.program_id(2)          # sweeps rep * nq q-blocks, sequential
    qi = ji % nq                   # q-block index within one q-head

    @pl.when(ji == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            s = jnp.where(_causal_mask(qi, ki, bq, bk, offset), s, NEG_INF)
        p = jnp.exp(s - lse_ref[0, 0][:, None])         # [bq, bk]
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                               # [bk, d]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta_ref[0, 0][:, None])        # [bq, bk]
        dk_acc[:] += scale * jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                               # [bk, d]

    if causal:
        @pl.when(qi * bq + (bq - 1) + offset >= ki * bk)
        def _():
            compute()
    else:
        compute()

    @pl.when(ji == nq_total - 1)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, dk_ref, dv_ref, dq_scr, dk_acc, dv_acc,
                      *, scale, causal, bq, bk, nq, nq_total, nk, offset,
                      sq):
    """ONE kernel for dq AND dk/dv (VERDICT r3/r4 'fused dq+dkdv' probe,
    unblocked in r5): the dkv sweep already computes s/p/dp/ds per
    (ki, qi) tile — dq's contribution (scale * ds @ k) reuses them for
    one extra MXU op instead of a whole second kernel pass re-reading
    q/k/v/do and re-computing three matmuls per tile.

    The r3 blocker was cross-grid accumulation: dq[qi] accumulates over
    the OUTER grid dim (ki), which Mosaic's consecutive-revisit rule
    forbids for an output block. Resolution: dq lives in a per-(batch,
    kv-head) f32 VMEM scratch [rep*sq, d] (1-4MB — scratch persists
    across the sequential grid), accumulated via dynamic-slice adds, and
    the OUTPUT block (1, rep*sq, d) has a constant index per b — only
    consecutive revisits, written once at the final (ki, ji) step."""
    ki = pl.program_id(1)
    ji = pl.program_id(2)
    qi = ji % nq

    @pl.when((ki == 0) & (ji == 0))
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    @pl.when(ji == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = jnp.where(_causal_mask(qi, ki, bq, bk, offset), s, NEG_INF)
        p = jnp.exp(s - lse_ref[0, 0][:, None])         # [bq, bk]
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # [bk, d]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, 0][:, None])        # [bq, bk]
        dk_acc[:] += scale * jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # [bk, d]
        # the fused extra: dq rows for this q-block accumulate in scratch
        row0 = pl.multiple_of((ji // nq) * sq + qi * bq, bq)
        dq_scr[pl.ds(row0, bq), :] += scale * jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # [bq, d]

    if causal:
        @pl.when(qi * bq + (bq - 1) + offset >= ki * bk)
        def _():
            compute()
    else:
        compute()

    @pl.when(ji == nq_total - 1)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)

    @pl.when((ki == nk - 1) & (ji == nq_total - 1))
    def _():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_index_maps(hq, hk, rep, nq, bq, bk, offset, causal):
    """Shared by the split-dkv and fused backward pallas_calls: the
    q-head owning sweep step j, and the (clamped, causal-skipping)
    q-block fetch index."""
    def q_index(b, j):
        bi = b // hk
        hi = b % hk
        return bi * hq + hi * rep + j // nq

    if causal:
        def qi_of(jk, j):
            return _clamp_qi(jax.lax.rem(j, jnp.int32(nq)), jk, bq, bk,
                             offset)
    else:
        def qi_of(jk, j):
            return jax.lax.rem(j, jnp.int32(nq))
    return q_index, qi_of


def _bwd(q, k, v, o, lse, do, scale, causal, interpret, hq, hk):
    with jax.enable_x64(False):
        return _bwd_impl(q, k, v, o, lse, do, scale, causal, interpret,
                         hq, hk)


def _bwd_impl(q, k, v, o, lse, do, scale, causal, interpret, hq, hk):
    bhq, sq, d = q.shape
    bhk, sk, _ = k.shape
    # PTPU_FA_BWD_KBLOCK decouples the bwd k tile (uniform 2048 holds too
    # many live blocks and compile-OOMs; mixed tiles may fit)
    import os as _os

    bq = _bwd_block_for(sq)
    bk = _block_for(sk, env="PTPU_FA_BWD_KBLOCK",
                    default=int(_os.environ.get("PTPU_FA_BWD_BLOCK",
                                                "1024")))
    nq, nk = sq // bq, sk // bk
    rep = hq // hk
    offset = sk - sq

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    # [bh, 8, sq] sublane-padded control tensors (Mosaic block tiling)
    lse8 = jnp.broadcast_to(lse[:, None, :], (lse.shape[0], 8, lse.shape[1]))
    delta8 = jnp.broadcast_to(delta[:, None, :],
                              (delta.shape[0], 8, delta.shape[1]))

    # Fused single-pass backward (default where the dq scratch fits):
    # measured on v5e 1.3B/b3 GPT 0.5596 -> 0.5788 MFU, LLaMA-arch
    # 0.6382 -> 0.6462 (tools/r5/sweep6). PTPU_FA_FUSED_BWD=1 forces it,
    # =0 forces the split kernels; unset -> auto by VMEM budget (the
    # [rep*sq, d] f32 dq scratch must leave room for the k/v/do blocks).
    flag = _os.environ.get("PTPU_FA_FUSED_BWD", "")
    dq_scratch_bytes = rep * sq * d * 4
    use_fused = (flag != "0" if flag
                 else dq_scratch_bytes <= (8 << 20))
    if use_fused:
        return _bwd_fused(q, k, v, do, lse8, delta8, scale=scale,
                          causal=causal, interpret=interpret, hq=hq,
                          hk=hk, bq=bq, bk=bk, nq=nq, nk=nk, rep=rep,
                          offset=offset)

    if causal:
        def _dq_kv_j(b, i, j):
            return (_kv_index(b, hq, hk), _clamp_kv_j(j, i, bq, bk, offset), 0)
    else:
        def _dq_kv_j(b, i, j):
            return (_kv_index(b, hq, hk), j, 0)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nk=nk, offset=offset),
        grid=(bhq, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), _dq_kv_j),
            pl.BlockSpec((1, bk, d), _dq_kv_j),
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 8, bq), lambda b, i, j: (b, 0, i)),
            pl.BlockSpec((1, 8, bq), lambda b, i, j: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bhq, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse8, delta8)

    # flat (batch*kv_head, j) -> the q-head block owning sweep step j
    _q_index, _qi_of = _bwd_index_maps(hq, hk, rep, nq, bq, bk, offset,
                                       causal)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nq=nq, nq_total=rep * nq,
                          offset=offset),
        grid=(bhk, nk, rep * nq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, jk, j: (_q_index(b, j), _qi_of(jk, j), 0)),
            pl.BlockSpec((1, bk, d), lambda b, jk, j: (b, jk, 0)),
            pl.BlockSpec((1, bk, d), lambda b, jk, j: (b, jk, 0)),
            pl.BlockSpec((1, bq, d), lambda b, jk, j: (_q_index(b, j), _qi_of(jk, j), 0)),
            pl.BlockSpec((1, 8, bq), lambda b, jk, j: (_q_index(b, j), 0, _qi_of(jk, j))),
            pl.BlockSpec((1, 8, bq), lambda b, jk, j: (_q_index(b, j), 0, _qi_of(jk, j))),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, jk, j: (b, jk, 0)),
            pl.BlockSpec((1, bk, d), lambda b, jk, j: (b, jk, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bhk, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bhk, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse8, delta8)
    return dq, dk, dv


def _bwd_fused(q, k, v, do, lse8, delta8, *, scale, causal, interpret,
               hq, hk, bq, bk, nq, nk, rep, offset):
    """Single-pass backward: see _bwd_fused_kernel. dq comes back as
    [bhk, rep*sq, d] with q-heads contiguous per kv head — a pure
    reshape recovers [bhq, sq, d] (row bi*hq + hi*rep + r)."""
    bhq, sq, d = q.shape
    bhk, sk, _ = k.shape
    _q_index, _qi_of = _bwd_index_maps(hq, hk, rep, nq, bq, bk, offset,
                                       causal)

    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_fused_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nq=nq, nq_total=rep * nq, nk=nk,
                          offset=offset, sq=sq),
        grid=(bhk, nk, rep * nq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, jk, j: (_q_index(b, j), _qi_of(jk, j), 0)),
            pl.BlockSpec((1, bk, d), lambda b, jk, j: (b, jk, 0)),
            pl.BlockSpec((1, bk, d), lambda b, jk, j: (b, jk, 0)),
            pl.BlockSpec((1, bq, d), lambda b, jk, j: (_q_index(b, j), _qi_of(jk, j), 0)),
            pl.BlockSpec((1, 8, bq), lambda b, jk, j: (_q_index(b, j), 0, _qi_of(jk, j))),
            pl.BlockSpec((1, 8, bq), lambda b, jk, j: (_q_index(b, j), 0, _qi_of(jk, j))),
        ],
        out_specs=[
            pl.BlockSpec((1, rep * sq, d), lambda b, jk, j: (b, 0, 0)),
            pl.BlockSpec((1, bk, d), lambda b, jk, j: (b, jk, 0)),
            pl.BlockSpec((1, bk, d), lambda b, jk, j: (b, jk, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bhk, rep * sq, d), q.dtype),
            jax.ShapeDtypeStruct((bhk, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bhk, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((rep * sq, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse8, delta8)
    return dq.reshape(bhq, sq, d), dk, dv


# ---------------------------------------------------------------- public api

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, scale, causal, interpret, hq, hk):
    o, _ = _fwd(q, k, v, scale, causal, interpret, hq, hk)
    return o


def _flash_fwd_rule(q, k, v, scale, causal, interpret, hq, hk):
    o, lse = _fwd(q, k, v, scale, causal, interpret, hq, hk)
    # name the residuals for selective remat: with a policy saving
    # attn_res/attn_lse the backward reuses them instead of re-running
    # this kernel just to regenerate lse (o is b*s*h*d, lse a tiny f32
    # sidecar — saving both removes a full fwd-kernel launch per layer
    # from the backward pass). Distinct from the model-level "attn_out"
    # tag so the two never double-save the same activation.
    from jax.ad_checkpoint import checkpoint_name

    o = checkpoint_name(o, "attn_res")
    lse = checkpoint_name(lse, "attn_lse")
    return o, (q, k, v, o, lse)


def _flash_bwd_rule(scale, causal, interpret, hq, hk, res, do):
    q, k, v, o, lse = res
    return _bwd(q, k, v, o, lse, do, scale, causal, interpret, hq, hk)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q, k, v, causal=False, scale=None, interpret=None):
    """[B, S, H, D] flash attention. Differentiable (custom flash backward).

    GQA (fewer kv heads than q heads) reads shared kv heads via the kernel
    index maps — no materialised head repeat.
    """
    from . import use_interpret

    if interpret is None:
        interpret = use_interpret()
    b, sq, hq, d = q.shape
    sk, hk = k.shape[1], k.shape[2]
    if hq % hk != 0:
        raise ValueError(f"q heads ({hq}) must be a multiple of kv heads ({hk})")
    if scale is None:
        scale = 1.0 / math.sqrt(d)

    o = _flash(to_bh(q, hq), to_bh(k, hk), to_bh(v, hk), float(scale),
               bool(causal), bool(interpret), hq, hk)
    return from_bh(o, b, hq)


# Back-compat name used by nn.functional.flash_attention
flash_attention_fwd = flash_attention
