"""Declarative op registry — per-op metadata the reference keeps in YAML.

Parity slot: `paddle/phi/ops/yaml/ops.yaml` + `legacy_ops.yaml` (args,
infer_meta, kernel, inplace contracts, backward names) and the codegen that
consumes them. The TPU design needs none of the codegen (apply_op + jax
tracing replace generated wrappers, `jax.eval_shape` replaces InferMeta,
XLA replaces kernel selection), so what remains *useful* from the YAML is
the queryable metadata itself:

- **inplace contracts**: which public ops mutate their first argument
  (`x -> out` aliasing). The reference encodes `inplace: (x -> out)` per
  YAML entry; here every trailing-underscore Tensor method must have a
  registered contract, enforced by `tests/test_op_registry.py`.
- **spmd_rule**: the per-op sharding rule name, resolving into
  `distributed/spmd_rules.py` (the analogue of the YAML's `spmd_rule:`
  field added for auto-parallel).
- **backward**: whether the op is differentiable on the tape.
- **tags**: coarse grouping (math/manipulation/creation/...) used by the
  surface sweeps.

`get_op_spec(name)` is the lookup the rest of the framework uses (e.g.
static Program recording annotates ops; tests enforce coverage).
"""
from __future__ import annotations

from dataclasses import dataclass, field

__all__ = ["OpSpec", "register_op", "get_op_spec", "registered_ops"]


@dataclass(frozen=True)
class OpSpec:
    name: str
    inplace: dict = field(default_factory=dict)   # {"x": "out"} aliasing
    spmd_rule: str | None = None                  # name in spmd_rules registry
    backward: bool = True                         # differentiable on the tape
    tags: tuple = ()


_REGISTRY: dict[str, OpSpec] = {}


def register_op(name, inplace=None, spmd_rule=None, backward=True, tags=()):
    spec = OpSpec(name, dict(inplace or {}), spmd_rule, backward,
                  tuple(tags))
    _REGISTRY[name] = spec
    return spec


def get_op_spec(name) -> OpSpec | None:
    return _REGISTRY.get(name)


def registered_ops():
    return dict(_REGISTRY)


# ---------------------------------------------------------------------------
# registry population
# ---------------------------------------------------------------------------
_ELEMENTWISE_UNARY = [
    "abs", "acos", "acosh", "asin", "asinh", "atan", "atanh", "ceil", "cos",
    "cosh", "digamma", "erf", "erfinv", "exp", "expm1", "floor", "frac",
    "i0", "lgamma", "log", "log10", "log1p", "log2", "logit", "neg",
    "reciprocal", "round", "rsqrt", "sigmoid", "sin", "sinc", "sinh",
    "sqrt", "square", "tan", "tanh", "trunc", "nan_to_num", "polygamma",
    "multigammaln", "gammaln",
]
_ELEMENTWISE_BINARY = [
    "add", "subtract", "multiply", "divide", "floor_divide", "floor_mod",
    "mod", "remainder", "pow", "maximum", "minimum", "copysign", "hypot",
    "ldexp", "lerp", "gammainc", "gammaincc",
]
_LOGIC = [
    "equal", "not_equal", "greater_equal", "greater_than", "less",
    "less_equal", "less_than", "logical_and", "logical_or", "logical_not",
    "logical_xor", "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
    "bitwise_invert", "bitwise_left_shift", "bitwise_right_shift", "isclose",
    "allclose", "isnan", "isinf", "isfinite",
]
_RANDOM_INPLACE = [
    "bernoulli", "cauchy", "exponential", "geometric", "log_normal",
    "normal", "uniform",
]
_MANIP_INPLACE = [
    "reshape", "squeeze", "unsqueeze", "flatten", "t", "tril", "triu",
    "clip", "scale", "cast", "fill", "zero", "fill_diagonal",
    "fill_diagonal_tensor", "index_add",
    "index_fill", "index_put", "masked_fill", "masked_scatter", "scatter",
    "where", "cumsum", "cumprod", "renorm", "addmm", "gcd", "lcm",
    "detach", "copy", "grad",
]
_NONDIFF = set(_LOGIC) | {
    "bernoulli", "gcd", "lcm", "bitwise_and", "bitwise_or", "bitwise_xor",
    "bitwise_not", "argmax", "argmin", "argsort",
}

for _n in _ELEMENTWISE_UNARY:
    register_op(_n, spmd_rule="elementwise", tags=("math", "unary"))
for _n in _ELEMENTWISE_BINARY:
    register_op(_n, spmd_rule="elementwise", tags=("math", "binary"))
for _n in _LOGIC:
    register_op(_n, spmd_rule="elementwise", backward=False, tags=("logic",))
for _n in _RANDOM_INPLACE:
    register_op(_n, backward=False, tags=("random",))
for _n in _MANIP_INPLACE:
    if _n not in _REGISTRY:
        register_op(_n, tags=("manipulation",))

# structural / compute ops with dedicated spmd rules
register_op("matmul", spmd_rule="matmul", tags=("linalg",))
register_op("einsum", spmd_rule="einsum", tags=("linalg",))
register_op("embedding", spmd_rule="embedding", tags=("nn",))
register_op("c_embedding", spmd_rule="c_embedding", tags=("nn", "dist"))
register_op("softmax", spmd_rule="softmax", tags=("nn",))
register_op("log_softmax", spmd_rule="softmax", tags=("nn",))
register_op("layer_norm", spmd_rule="layer_norm", tags=("nn",))
register_op("rms_norm", spmd_rule="rms_norm", tags=("nn",))
register_op("dropout", spmd_rule="dropout", tags=("nn",))
register_op("cross_entropy_with_softmax",
            spmd_rule="cross_entropy_with_softmax", tags=("loss",))
register_op("flash_attention", spmd_rule="flash_attention", tags=("nn",))
register_op("moe_gate", spmd_rule="moe_gate", backward=True, tags=("moe",))
register_op("moe_dispatch", spmd_rule="moe_dispatch", tags=("moe",))
register_op("transpose", spmd_rule="transpose", tags=("manipulation",))
register_op("concat", spmd_rule="concat", tags=("manipulation",))
register_op("split", spmd_rule="split", tags=("manipulation",))
register_op("slice", spmd_rule="slice", tags=("manipulation",))
register_op("stack", spmd_rule="stack", tags=("manipulation",))
register_op("tile", spmd_rule="tile", tags=("manipulation",))
register_op("gather", spmd_rule="gather", tags=("indexing",))
register_op("topk", spmd_rule="topk", tags=("search",))
register_op("top_p_sampling", backward=False, tags=("search",))
register_op("argmax", spmd_rule="argmax", backward=False, tags=("search",))
register_op("sum", spmd_rule="reduction", tags=("math", "reduce"))
register_op("mean", spmd_rule="reduction", tags=("math", "reduce"))
register_op("max", spmd_rule="reduction", tags=("math", "reduce"))
register_op("min", spmd_rule="reduction", tags=("math", "reduce"))
register_op("prod", spmd_rule="reduction", tags=("math", "reduce"))

# inplace-only framework verbs without out-of-place public variants
register_op("set_value", inplace={"x": "out"}, backward=False,
            tags=("framework",))

# Every op with an `x_` Tensor-method variant carries the x->out inplace
# contract (the YAML `inplace:` field). Applied LAST so dedicated
# registrations above don't drop it. Ops registered above WITHOUT a
# trailing-underscore method are excluded — a contract on a method that
# doesn't exist would be a lie.
_NO_INPLACE_METHOD = {
    "isnan", "isinf", "isfinite", "allclose", "isclose",
    "acosh", "asinh", "atanh", "maximum", "minimum",
}
_INPLACE_VARIANTS = [
    n for n in (_ELEMENTWISE_UNARY + _ELEMENTWISE_BINARY + _LOGIC
                + _RANDOM_INPLACE + _MANIP_INPLACE)
    if n not in _NO_INPLACE_METHOD
]
for _n in _INPLACE_VARIANTS:
    _spec = _REGISTRY.get(_n)
    if _spec is not None:
        _REGISTRY[_n] = OpSpec(_spec.name, {"x": "out"}, _spec.spmd_rule,
                               _spec.backward, _spec.tags)

# non-differentiable ops that the grouped loops registered backward=True
for _n in _NONDIFF:
    _spec = _REGISTRY.get(_n)
    if _spec is not None and _spec.backward:
        _REGISTRY[_n] = OpSpec(_spec.name, _spec.inplace, _spec.spmd_rule,
                               False, _spec.tags)

# ---------------------------------------------------------------------------
# round-4 closure: every op the numeric battery covers carries a spec
# (VERDICT r3 item 5 — the reference's ops.yaml is the single source of
# truth for 470 ops; here the registry is the contract layer feeding
# sharding rules + inplace semantics, enforced against the battery surface
# by tests/test_op_registry.py::test_battery_ops_have_specs).
# ---------------------------------------------------------------------------
_CREATION = [
    "arange", "eye", "full", "full_like", "linspace", "logspace",
    "meshgrid", "ones", "ones_like", "zeros", "zeros_like", "vander",
    "tril_indices", "triu_indices", "empty", "empty_like", "one_hot",
]
_LINALG = [
    "bmm", "cholesky", "cholesky_solve", "det", "eigvalsh",
    "householder_product", "inv", "lstsq", "lu", "matrix_power",
    "matrix_rank", "multi_dot", "pinv", "qr", "slogdet", "solve",
    "svdvals", "svd", "eig", "eigh", "triangular_solve", "dot", "inner",
    "outer", "mv", "kron", "cross", "tensordot", "trace", "norm", "cdist",
    "pdist", "dist", "cov", "corrcoef", "matrix_transpose", "cond",
]
_MANIP = [
    "as_strided", "atleast_1d", "atleast_2d", "atleast_3d",
    "broadcast_to", "chunk", "column_stack", "crop", "diag", "diag_embed",
    "diagflat", "diagonal", "dsplit", "dstack", "expand", "expand_as",
    "flip", "hsplit", "hstack", "moveaxis", "pad", "repeat_interleave",
    "roll", "rot90", "row_stack", "swapaxes", "unbind", "unflatten",
    "unfold", "unstack", "vsplit", "vstack", "view", "view_as",
]
_INDEXING = [
    "gather_nd", "index_select", "masked_select", "put_along_axis",
    "scatter_nd_add", "take", "take_along_axis", "index_sample",
    "getitem", "setitem",
]
_SEARCH_SORT = [
    "argmin", "argsort", "bucketize", "searchsorted", "sort", "unique",
    "histogram", "bincount", "kthvalue", "mode", "median", "nanmedian",
    "quantile", "nanquantile", "cummax", "cummin", "count_nonzero",
    "nonzero",
]
_MATH_MISC = [
    "conj", "diff", "frexp", "i0e", "i1", "i1e", "logcumsumexp",
    "signbit", "trapezoid", "numel", "real", "imag", "angle", "logsumexp",
    "nansum", "nanmean", "amax", "amin", "all", "any", "std", "var",
]
_FFT = ["fft", "ifft", "rfft", "irfft", "fft2", "ifft2", "fftn", "ifftn",
        "hfft", "ihfft", "fftshift", "ifftshift"]
_NN_FUNCTIONAL = [
    "conv1d", "conv2d", "conv3d", "conv1d_transpose", "conv2d_transpose",
    "conv3d_transpose", "avg_pool1d", "avg_pool2d", "avg_pool3d",
    "max_pool1d", "max_pool2d", "max_pool3d", "adaptive_avg_pool2d",
    "adaptive_max_pool2d", "affine_grid", "alpha_dropout", "batch_norm",
    "channel_shuffle", "cosine_similarity", "fold", "glu", "grid_sample",
    "group_norm", "gumbel_softmax", "instance_norm", "interpolate",
    "label_smooth", "linear", "local_response_norm", "normalize",
    "pixel_shuffle", "pixel_unshuffle", "prelu", "rrelu", "upsample",
    "zeropad2d", "relu", "gelu", "silu", "swish", "mish", "elu", "selu",
    "celu", "hardtanh", "hardshrink", "hardsigmoid", "hardswish",
    "leaky_relu", "log_sigmoid", "relu6", "softplus", "softshrink",
    "softsign", "tanhshrink", "thresholded_relu",
]
_LOSSES = [
    "cross_entropy", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "cosine_embedding_loss",
    "hinge_embedding_loss", "kl_div", "l1_loss",
    "margin_ranking_loss", "mse_loss", "multi_label_soft_margin_loss",
    "nll_loss", "pairwise_distance", "poisson_nll_loss", "smooth_l1_loss",
    "soft_margin_loss", "square_error_cost", "triplet_margin_loss",
    "ctc_loss", "sigmoid_focal_loss",
]

_INT_OUTPUT = {
    "argmin", "argsort", "bucketize", "searchsorted", "unique",
    "histogram", "bincount", "numel", "signbit", "count_nonzero",
    "nonzero", "tril_indices", "triu_indices", "one_hot",
    "arange", "eye",
}

for _n in _CREATION:
    register_op(_n, backward=False, tags=("creation",))
for _n in _LINALG:
    if _n not in _REGISTRY:
        register_op(_n, tags=("linalg",))
for _n in _MANIP:
    if _n not in _REGISTRY:
        register_op(_n, tags=("manipulation",))
for _n in _INDEXING:
    if _n not in _REGISTRY:
        tags = ("indexing", "framework") if _n in ("getitem", "setitem") \
            else ("indexing",)
        register_op(_n, tags=tags)
for _n in _SEARCH_SORT:
    register_op(_n, backward=_n not in _INT_OUTPUT, tags=("search",))
for _n in _MATH_MISC:
    if _n not in _REGISTRY:
        register_op(_n, spmd_rule=None,
                    backward=_n not in _INT_OUTPUT, tags=("math",))
for _n in _FFT:
    register_op(_n, tags=("fft",))
for _n in _NN_FUNCTIONAL:
    if _n not in _REGISTRY:
        register_op(_n, tags=("nn",))
for _n in _LOSSES:
    if _n not in _REGISTRY:
        register_op(_n, tags=("loss",))

# reductions registered above keep the reduction rule; these reduce too
for _n in ("logsumexp", "nansum", "nanmean", "amax", "amin", "all", "any",
           "std", "var", "median", "nanmedian", "quantile", "nanquantile",
           "count_nonzero"):
    _spec = _REGISTRY[_n]
    _REGISTRY[_n] = OpSpec(_spec.name, _spec.inplace, "reduction",
                           _spec.backward, _spec.tags)

# r4: resolve the new explicit SPMD rules onto their registry entries
# (reference: the `spmd_rule:` yaml key — ops.yaml:8-17)
_SPMD_WIRING = {
    "bmm": "bmm", "sort": "sort", "argsort": "argsort",
    "cummax": "cummax", "cummin": "cummin",
    "logcumsumexp": "logcumsumexp", "kthvalue": "kthvalue",
    "index_select": "index_select",
    "take_along_axis": "take_along_axis",
    "put_along_axis": "put_along_axis", "one_hot": "one_hot",
    "flip": "flip", "roll": "roll", "pad": "pad", "tril": "tril",
    "scale": "scale", "clip": "clip", "group_norm": "group_norm",
    "conv1d": "conv", "conv2d": "conv", "conv3d": "conv",
    "conv1d_transpose": "conv_transpose",
    "conv2d_transpose": "conv_transpose",
    "conv3d_transpose": "conv_transpose",
    "avg_pool1d": "pool", "avg_pool2d": "pool", "avg_pool3d": "pool",
    "max_pool1d": "pool", "max_pool2d": "pool", "max_pool3d": "pool",
    "adaptive_avg_pool2d": "pool", "adaptive_max_pool2d": "pool",
    "cholesky": "batched_linalg", "inv": "batched_linalg",
    "det": "batched_linalg", "slogdet": "batched_linalg",
    "solve": "batched_linalg", "triangular_solve": "batched_linalg",
    "cholesky_solve": "batched_linalg", "lu": "batched_linalg",
    "qr": "batched_linalg", "svd": "batched_linalg",
    "svdvals": "batched_linalg", "eigh": "batched_linalg",
    "eigvalsh": "batched_linalg", "matrix_power": "batched_linalg",
    "pinv": "batched_linalg", "matrix_rank": "batched_linalg",
}
for _n, _r in _SPMD_WIRING.items():
    _spec = _REGISTRY.get(_n)
    if _spec is not None and _spec.spmd_rule is None:
        _REGISTRY[_n] = OpSpec(_spec.name, _spec.inplace, _r,
                               _spec.backward, _spec.tags)
