"""Device / Place abstraction.

Parity target: the reference's ``phi::Place`` (``paddle/phi/common/place.h:31``)
and ``paddle.device`` python API.  On TPU there is a single accelerator type;
``TPUPlace`` is first-class (the reference survey calls for a new enum value),
``CPUPlace`` maps to the XLA CPU client, and CUDA aliases are accepted for
source compatibility but resolve to the default accelerator.
"""
from __future__ import annotations

import os
import threading

import jax


class Place:
    device_type = "unknown"

    def __init__(self, device_id: int = 0):
        self._device_id = int(device_id)

    def get_device_id(self) -> int:
        return self._device_id

    def __repr__(self):
        return f"Place({self.device_type}:{self._device_id})"

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.device_type == other.device_type
            and self._device_id == other._device_id
        )

    def __hash__(self):
        return hash((self.device_type, self._device_id))

    def is_tpu_place(self):
        return self.device_type == "tpu"

    def is_cpu_place(self):
        return self.device_type == "cpu"

    def is_gpu_place(self):
        return False


class TPUPlace(Place):
    device_type = "tpu"


class CPUPlace(Place):
    device_type = "cpu"


class CUDAPlace(TPUPlace):
    """Source-compat alias: code written for GPU runs on the accelerator."""

    device_type = "tpu"


class CUDAPinnedPlace(CPUPlace):
    device_type = "cpu"


class XPUPlace(TPUPlace):
    device_type = "tpu"


class CustomPlace(TPUPlace):
    device_type = "tpu"

    def __init__(self, dev_type="tpu", device_id=0):
        super().__init__(device_id)


_state = threading.local()
_platform_cache = [None]


def _accelerator_platform():
    """The current jax platform name — WITHOUT initializing device backends.

    Querying jax.default_backend() creates the PJRT client (on real TPU pods
    that can block on the fabric); we answer from JAX_PLATFORMS when set and
    only fall back to a real (cached) backend query on explicit demand.
    """
    env = os.environ.get("JAX_PLATFORMS", "")
    if env:
        return env.split(",")[0].strip() or "cpu"
    if _platform_cache[0] is None:
        try:
            _platform_cache[0] = jax.default_backend()
        except RuntimeError:  # pragma: no cover
            _platform_cache[0] = "cpu"
    return _platform_cache[0]


def set_device(device: str):
    """paddle.device.set_device — accepts 'tpu', 'tpu:0', 'cpu', 'gpu:0'...

    GPU/XPU/custom names are treated as the accelerator for compatibility.
    """
    device = str(device)
    name = device.split(":")[0]
    idx = int(device.split(":")[1]) if ":" in device else 0
    if name in ("cpu",):
        _state.place = CPUPlace(idx)
    else:
        _state.place = TPUPlace(idx)
    return get_device()


def get_device() -> str:
    p = _current_place()
    return f"{p.device_type}:{p.get_device_id()}"


def _current_place() -> Place:
    p = getattr(_state, "place", None)
    if p is None:
        plat = _accelerator_platform()
        p = CPUPlace(0) if plat == "cpu" else TPUPlace(0)
        _state.place = p
    return p


def jax_device_for(place: Place | None = None):
    """Map a Place to a concrete jax.Device, or None for "default device".

    Returning None lets callers skip jax.device_put entirely — arrays land on
    the default device lazily without forcing backend initialization.
    """
    if place is None:
        return None
    devs = jax.devices("cpu") if place.is_cpu_place() else jax.devices()
    return devs[place.get_device_id() % len(devs)]


def device_count() -> int:
    return jax.device_count()


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return True


def cuda_device_count() -> int:  # compat
    return 0


def get_all_device_type():
    return ["cpu", "tpu"]


def get_available_device():
    return [f"tpu:{i}" for i in range(device_count())]
