"""paddle.cost_model (parity: python/paddle/cost_model — CostModel over
profiled programs). TPU-native: costs come from XLA's compiled HLO cost
analysis instead of per-op profiling tables."""
from __future__ import annotations

__all__ = ["CostModel"]


class CostModel:
    """Static cost estimates for a jitted callable / static Program.

    `profile_measure(fn, *args)` compiles under jax and returns XLA's
    flops/bytes-accessed estimates (the analogue of the reference's
    profiler-driven op cost tables)."""

    def profile_measure(self, program_or_fn, *example_args,
                        device="tpu", fetch_cost_list=("time",)):
        import jax

        fn = program_or_fn
        if not callable(fn):
            raise TypeError("CostModel.profile_measure expects a callable "
                            "(jit target) on the TPU build")
        lowered = jax.jit(fn).lower(*example_args)
        compiled = lowered.compile()
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else (ca or {})
        return {
            "flops": float(ca.get("flops", 0.0)),
            "bytes accessed": float(ca.get("bytes accessed", 0.0)),
            "time": float(ca.get("optimal_seconds", 0.0)),
        }

    # reference naming
    def get_static_op_time(self, op_name, forward=True, dtype="float32"):
        raise NotImplementedError(
            "per-op static cost tables are a profiler artifact of the "
            "reference; on TPU use profile_measure over the jitted program")
