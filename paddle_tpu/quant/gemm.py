"""Scaled low-precision GEMMs with delayed scaling: the quantized-compute core.

Every prior quantization in this repo wraps the matmuls — int8 activation
saves (memory/int8_ckpt), the int8 LM head, quantized collectives, int8
param gathers, int8 paged KV. This module quantizes the matmuls themselves:
per-tensor scaled fp8 (e4m3) forward GEMMs — int8 fallback where the
platform can't dot fp8 — with the backward kept wide and exact via
``custom_vjp``, so master weights and grad accumulation never see narrow
dtypes. The contract:

* **forward narrow**: ``out = dequant(q(x/sx) @ q(w/sw)) * sx * sw`` with
  the accumulator wide (f32 for fp8, int32 for int8);
* **backward wide**: ``dx = g @ w.T``, ``dw = x.T @ g`` in f32 against the
  *original* operands — AD never differentiates through round/clip, and the
  scales get zero cotangents;
* **delayed scaling**: scales come from a short per-(site, operand) amax
  history (`PTPU_QUANT_AMAX_HIST`, default 4) threaded through the model as
  a persistable buffer, so they ride ``TrainStep``/``ShardedTrainStep``,
  ``StepGuard`` skip/rollback, and ``CheckpointManager`` exactly like the
  RNG-key chain. The first step bootstraps from the current amax (history
  all-zero) so step 0 is not catastrophically mis-scaled.

Engagement mirrors the int8-head discipline: ``quant:<site>`` entries in
the existing ``names:`` recompute-policy syntax request sites per layer;
``PTPU_QUANT_COMPUTE`` forces (``0`` is the structural escape hatch — no
amax buffer is created, programs are bit-identical to pre-quant builds);
unset, a cached numeric parity probe must pass (drift → loud default-off,
and CPU backends default off). See docs/QUANT.md for the full matrix.
"""
from __future__ import annotations

import functools
import os
import warnings

import numpy as np

import jax
import jax.numpy as jnp

from ..memory.int8_ckpt import SCALE_EPS, quantize_rows_int8

#: saturation bound of float8_e4m3fn (no inf encoding — values past this
#: become NaN on cast, so operands are clamped first)
E4M3_MAX = 448.0
INT8_MAX = 127.0

#: the seven narrow-quantizable GEMM sites of one decoder block, in
#: ``models/gpt.py::_block_pure`` order. Index into the amax state's site
#: axis is ``GEMM_SITES.index(site)``.
GEMM_SITES = ("wq", "wk", "wv", "wo", "wg", "wu", "wd")

#: ``quant:`` policy-entry aliases expanding to site groups
SITE_ALIASES = {
    "attn": ("wq", "wk", "wv", "wo"),
    "ffn": ("wg", "wu", "wd"),
    "all": GEMM_SITES,
}

#: env knobs that change quant-compute decisions — every plan/bench cache
#: key must carry these (the PR 2 staleness class)
QUANT_KNOBS = (
    "PTPU_QUANT_COMPUTE",
    "PTPU_QUANT_DTYPE",
    "PTPU_QUANT_AMAX_HIST",
    "PTPU_QUANT_GATE_TOL",
    "PTPU_QUANT_PARAM_GATHER",
    "PTPU_INT8_WEIGHTS",
)

_OFF_VALUES = ("", "0", "off", "false")


def cache_key_knobs():
    """Tuple of (knob, value) for every quant env knob, for cache keys."""
    return tuple((k, os.environ.get(k, "")) for k in QUANT_KNOBS)


# ---------------------------------------------------------------------------
# dtype resolution


_FP8_DOT_OK = [None]


def fp8_dot_supported():
    """Whether this backend can dot float8_e4m3fn operands (cached probe)."""
    if _FP8_DOT_OK[0] is None:
        try:
            a = jnp.asarray(np.ones((8, 8), np.float32)).astype(
                jnp.float8_e4m3fn)
            out = jnp.matmul(a, a, preferred_element_type=jnp.float32)
            _FP8_DOT_OK[0] = bool(np.isfinite(np.asarray(out)).all())
        except Exception:  # noqa: BLE001 - any failure means "no fp8 here"
            _FP8_DOT_OK[0] = False
    return _FP8_DOT_OK[0]


def quant_dtype():
    """Resolve the narrow GEMM dtype: ``PTPU_QUANT_DTYPE`` = fp8 | int8 |
    auto (default). ``auto`` picks e4m3 where the platform can dot it and
    falls back to int8 elsewhere."""
    env = os.environ.get("PTPU_QUANT_DTYPE", "auto").strip().lower()
    if env in ("fp8", "int8"):
        return env
    if env not in ("auto", ""):
        raise ValueError(
            f"PTPU_QUANT_DTYPE={env!r}: expected fp8, int8 or auto")
    return "fp8" if fp8_dot_supported() else "int8"


def dtype_max(dtype):
    return E4M3_MAX if dtype == "fp8" else INT8_MAX


# ---------------------------------------------------------------------------
# the scaled GEMM: narrow forward, wide exact backward


def _narrow_matmul(dtype, x, w, sx, sw):
    xf = x.astype(jnp.float32) / sx
    wf = w.astype(jnp.float32) / sw
    if dtype == "fp8":
        xq = jnp.clip(xf, -E4M3_MAX, E4M3_MAX).astype(jnp.float8_e4m3fn)
        wq = jnp.clip(wf, -E4M3_MAX, E4M3_MAX).astype(jnp.float8_e4m3fn)
        acc = jnp.matmul(xq, wq, preferred_element_type=jnp.float32)
    else:
        xq = jnp.clip(jnp.round(xf), -INT8_MAX, INT8_MAX).astype(jnp.int8)
        wq = jnp.clip(jnp.round(wf), -INT8_MAX, INT8_MAX).astype(jnp.int8)
        acc = jnp.matmul(xq, wq,
                         preferred_element_type=jnp.int32).astype(jnp.float32)
    return (acc * (sx * sw)).astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _scaled_matmul(dtype, x, w, sx, sw):
    """``x @ w`` computed narrow (fp8/int8) with per-tensor scales sx/sw.

    The vjp is the *wide* exact rule against the original operands — the
    quantization noise is forward-only, grads and master weights stay
    exact (the "forward narrow, backward wide" contract)."""
    return _narrow_matmul(dtype, x, w, sx, sw)


def _scaled_matmul_fwd(dtype, x, w, sx, sw):
    return _narrow_matmul(dtype, x, w, sx, sw), (x, w)


def _scaled_matmul_bwd(dtype, res, g):
    del dtype
    x, w = res
    gf = g.astype(jnp.float32)
    wf = w.astype(jnp.float32)
    dx = jnp.matmul(gf, jnp.swapaxes(wf, -1, -2)).astype(x.dtype)
    xt = x.astype(jnp.float32).reshape(-1, x.shape[-1])
    dw = jnp.matmul(xt.T, gf.reshape(-1, g.shape[-1])).astype(w.dtype)
    return (dx, dw, jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32))


_scaled_matmul.defvjp(_scaled_matmul_fwd, _scaled_matmul_bwd)


def amax_hist_len():
    """Delayed-scaling history length (``PTPU_QUANT_AMAX_HIST``, min 1)."""
    return max(int(os.environ.get("PTPU_QUANT_AMAX_HIST", "4")), 1)


def scaled_gemm(x, w, hist_x, hist_w, *, dtype=None):
    """Delayed-scaling scaled GEMM.

    ``hist_x`` / ``hist_w`` are ``[H]`` f32 amax-history rows (most recent
    first). Scales come from the history max; an all-zero history (fresh
    state) bootstraps from the current step's amax so the first step is
    sanely scaled. Returns ``(out, new_hist_x, new_hist_w)`` — the caller
    threads the shifted histories back into its amax state.
    """
    dtype = dtype or quant_dtype()
    dmax = dtype_max(dtype)
    ax = jax.lax.stop_gradient(jnp.max(jnp.abs(x)).astype(jnp.float32))
    aw = jax.lax.stop_gradient(jnp.max(jnp.abs(w)).astype(jnp.float32))
    hx_max = jnp.max(hist_x)
    hw_max = jnp.max(hist_w)
    eff_x = jnp.where(hx_max > 0, hx_max, ax)
    eff_w = jnp.where(hw_max > 0, hw_max, aw)
    sx = jnp.maximum(eff_x / dmax, SCALE_EPS)
    sw = jnp.maximum(eff_w / dmax, SCALE_EPS)
    out = _scaled_matmul(dtype, x, w, sx, sw)
    new_hx = jnp.concatenate([ax[None], hist_x[:-1]])
    new_hw = jnp.concatenate([aw[None], hist_w[:-1]])
    return out, new_hx, new_hw


def inline_scaled_gemm(x, w, *, dtype=None):
    """One-shot scaled GEMM with inline (current-step) absmax scales — the
    delayed-scaling entry with an empty history, for callers that carry no
    state (incubate fp8_gemm)."""
    h = jnp.zeros((1,), jnp.float32)
    out, _, _ = scaled_gemm(x, w, h, h, dtype=dtype)
    return out


# ---------------------------------------------------------------------------
# per-layer amax state + the trace-time context the decoder block uses


def init_amax_state(num_layers, hist=None):
    """Fresh delayed-scaling state: f32 zeros ``[L, n_sites, 2, H]``
    (2 = x/w operand rows). All-zero rows mean "bootstrap from current"."""
    h = amax_hist_len() if hist is None else int(hist)
    return np.zeros((int(num_layers), len(GEMM_SITES), 2, h), np.float32)


class GemmQuantCtx:
    """Per-trace context for one decoder layer's scaled GEMMs.

    Holds the layer's amax slice ``[n_sites, 2, H]``, routes engaged sites
    through :func:`scaled_gemm`, and collects the updated histories so the
    block can return them as explicit outputs (``jax.checkpoint`` purity —
    the scan threads them back into the stacked buffer).
    """

    def __init__(self, sites, amax_layer, dtype):
        self.sites = frozenset(sites)
        self.dtype = dtype
        self._amax = amax_layer
        self._new = {}

    def gemm(self, x, w, site):
        if site not in self.sites:
            return x @ w
        i = GEMM_SITES.index(site)
        out, nhx, nhw = scaled_gemm(
            x, w, self._amax[i, 0], self._amax[i, 1], dtype=self.dtype)
        self._new[site] = jnp.stack([nhx, nhw])
        return out

    def collect(self):
        """Updated ``[n_sites, 2, H]`` state: new histories for sites that
        ran, passthrough rows for the rest."""
        rows = []
        for i, s in enumerate(GEMM_SITES):
            rows.append(self._new.get(s, self._amax[i]))
        return jnp.stack(rows)


# ---------------------------------------------------------------------------
# policy parsing: quant:<site> entries in the names: syntax


def split_quant_entries(spec):
    """Split ``quant:<site>`` entries out of a ``names:`` policy payload.

    ``"attn_q,int8:resid_mid,quant:attn"`` ->
    ``("attn_q,int8:resid_mid", frozenset({"wq","wk","wv","wo"}))``.
    The remainder feeds ``parse_save_names`` unchanged; sites accept the
    block's GEMM names (wq wk wv wo wg wu wd) or the aliases attn/ffn/all.
    """
    rest, sites = [], set()
    for raw in str(spec).split(","):
        nm = raw.strip()
        if not nm:
            continue
        if nm.startswith("quant:"):
            site = nm[len("quant:"):].strip()
            if not site:
                raise ValueError(f"empty quant: entry in remat names {spec!r}")
            if site in SITE_ALIASES:
                sites.update(SITE_ALIASES[site])
            elif site in GEMM_SITES:
                sites.add(site)
            else:
                raise ValueError(
                    f"quant:{site}: unknown GEMM site — expected one of "
                    f"{GEMM_SITES} or aliases {tuple(SITE_ALIASES)} "
                    "(docs/QUANT.md)")
        else:
            rest.append(nm)
    return ",".join(rest), frozenset(sites)


def quant_sites_from_policy(policy):
    """The quant sites a recompute policy requests (``names:`` only — the
    coarse dots/attn policies carry no quant syntax)."""
    if isinstance(policy, str) and policy.startswith("names:"):
        _, sites = split_quant_entries(policy[len("names:"):])
        return sites
    return frozenset()


# ---------------------------------------------------------------------------
# parity gate (int8-head discipline) + engagement resolution


_GATE_CACHE = {}


def _gate_probe(tol, dtype):
    """Deterministic parity probe: a scaled GEMM chain's loss and grads vs
    the exact bf16-free f32 reference, on skewed inputs."""
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((64, 128)).astype(np.float32))
    w = jnp.asarray((rng.standard_normal((128, 64)) *
                     rng.uniform(0.05, 3.0, (128, 64))).astype(np.float32))

    def loss_exact(xx, ww):
        return jnp.mean(jnp.square(xx @ ww))

    def loss_quant(xx, ww):
        h = jnp.zeros((amax_hist_len(),), jnp.float32)
        out, _, _ = scaled_gemm(xx, ww, h, h, dtype=dtype)
        return jnp.mean(jnp.square(out))

    le, (gxe, gwe) = jax.value_and_grad(loss_exact, argnums=(0, 1))(x, w)
    lq, (gxq, gwq) = jax.value_and_grad(loss_quant, argnums=(0, 1))(x, w)
    le, lq = float(le), float(lq)
    loss_err = abs(lq - le) / max(abs(le), 1e-9)

    def _gerr(a, b):
        a, b = np.asarray(a), np.asarray(b)
        return float(np.mean(np.abs(a - b)) / max(np.mean(np.abs(b)), 1e-9))

    grad_err = max(_gerr(gxq, gxe), _gerr(gwq, gwe))
    ok = bool(np.isfinite(lq)) and loss_err < tol and grad_err < 5 * tol
    return ok, loss_err, grad_err


def quant_gate_report(tol=None, dtype=None):
    """Run (or fetch the cached) parity probe: dict with ``ok``, ``tol``,
    ``max_rel_err``, ``dtype``. A crashed probe warns loudly and reports
    not-ok (default-off) rather than raising — same contract as
    ``int8_head_gate``."""
    if tol is None:
        tol = float(os.environ.get("PTPU_QUANT_GATE_TOL", "0.02"))
    dtype = dtype or quant_dtype()
    key = (round(tol, 9), dtype)
    if key not in _GATE_CACHE:
        try:
            ok, loss_err, grad_err = _gate_probe(tol, dtype)
        except Exception as e:  # noqa: BLE001 - probe crash => default-off
            warnings.warn(
                f"quant-compute parity probe crashed ({e!r}); scaled "
                f"{dtype} GEMMs stay OFF (force with PTPU_QUANT_COMPUTE=1)",
                RuntimeWarning, stacklevel=2)
            ok, loss_err, grad_err = False, float("inf"), float("inf")
        if not ok and np.isfinite(loss_err):
            warnings.warn(
                "quant-compute parity probe drift (loss "
                f"{loss_err:.4f} vs tol={tol}, grad {grad_err:.4f} vs "
                f"{5 * tol}) for dtype={dtype}; scaled GEMMs stay OFF "
                "(force with PTPU_QUANT_COMPUTE=1, or raise "
                "PTPU_QUANT_GATE_TOL)", RuntimeWarning, stacklevel=2)
        _GATE_CACHE[key] = {"ok": ok, "tol": tol, "loss_rel_err": loss_err,
                            "grad_rel_err": grad_err, "grad_tol": 5 * tol,
                            "dtype": dtype}
    return _GATE_CACHE[key]


def quant_gate(tol=None, dtype=None):
    """True iff the cached parity probe passed."""
    return quant_gate_report(tol, dtype)["ok"]


def quant_compute_forced():
    """``PTPU_QUANT_COMPUTE`` set to a truthy value (explicit force-on)."""
    env = os.environ.get("PTPU_QUANT_COMPUTE")
    return env is not None and env.strip().lower() not in _OFF_VALUES


def quant_compute_enabled(requested=False):
    """Master decision, int8-head shaped: ``PTPU_QUANT_COMPUTE`` set
    forces the answer either way; unset, quant runs only when *requested*
    (policy ``quant:`` entries), off CPU, and behind a passing parity
    gate."""
    env = os.environ.get("PTPU_QUANT_COMPUTE")
    if env is not None:
        return env.strip().lower() not in _OFF_VALUES
    if not requested:
        return False
    if jax.default_backend() == "cpu":
        return False
    return quant_gate()


def requested_quant_sites(cfg):
    """Build-time request resolution: which sites this config *asks* for.

    Decides amax-buffer creation, so it deliberately ignores the parity
    gate (a gate flake must not change checkpoint layout). The env force
    with no policy sites means "all"; the env escape hatch (``0``) means
    none — no buffer, programs structurally identical to pre-quant."""
    env = os.environ.get("PTPU_QUANT_COMPUTE")
    if env is not None and env.strip().lower() in _OFF_VALUES:
        return frozenset()
    sites = quant_sites_from_policy(getattr(cfg, "recompute_policy", None))
    if quant_compute_forced():
        return sites or frozenset(GEMM_SITES)
    return sites


def engaged_quant_sites(cfg):
    """Trace-time engagement: requested sites, gated by
    :func:`quant_compute_enabled` (parity probe / CPU default-off)."""
    sites = requested_quant_sites(cfg)
    if not sites:
        return frozenset()
    if not quant_compute_enabled(requested=True):
        return frozenset()
    return sites


# ---------------------------------------------------------------------------
# serving: int8 resident weights + dequant-free int8 x int8 -> int32 GEMM


def quantize_weight_cols_int8(w, eps=SCALE_EPS):
    """Per-output-channel absmax int8 over the contraction axis (-2): one
    f32 scale per output column, so the dequant of ``x_q @ W_q`` is a
    rank-1 rescale (``* sx * sw``) — no per-element dequant pass. Returns
    ``(codes int8 [..., h, n], scales f32 [..., 1, n])``."""
    wf = w.astype(jnp.float32)
    s = jnp.maximum(jnp.max(jnp.abs(wf), axis=-2, keepdims=True) / INT8_MAX,
                    eps)
    q = jnp.clip(jnp.round(wf / s), -INT8_MAX, INT8_MAX).astype(jnp.int8)
    return q, s


def int8_weight_matmul(x, codes, scales):
    """``x @ W`` with W pre-quantized by :func:`quantize_weight_cols_int8`:
    activations quantize per-row on the fly, the GEMM runs int8 x int8 with
    an int32 accumulator, and the f32 result is rescaled separably by the
    row scales and the per-column weight scales."""
    xq, sx = quantize_rows_int8(x)
    acc = jnp.matmul(xq, codes, preferred_element_type=jnp.int32)
    return (acc.astype(jnp.float32) * sx * scales).astype(x.dtype)


def _int8_weights_probe_ok():
    """Round-trip probe on skewed per-column magnitudes: the int8 weight
    GEMM must track the exact product within a few percent."""
    rng = np.random.default_rng(0)
    w = rng.standard_normal((64, 48)).astype(np.float32)
    w *= rng.uniform(0.01, 8.0, (1, 48)).astype(np.float32)
    x = rng.standard_normal((4, 64)).astype(np.float32)
    exact = np.asarray(jnp.asarray(x) @ jnp.asarray(w))
    codes, scales = quantize_weight_cols_int8(jnp.asarray(w))
    got = np.asarray(int8_weight_matmul(jnp.asarray(x), codes, scales))
    if not np.isfinite(got).all():
        return False
    err = np.mean(np.abs(got - exact)) / max(np.mean(np.abs(exact)), 1e-9)
    return bool(err < 0.05)


_INT8_W_PROBE = [None]


def int8_weights_enabled(requested=False):
    """Serving int8-resident-weights gate, shaped like ``int8_kv_enabled``:
    ``PTPU_INT8_WEIGHTS`` forces either way; unset, the engine's request is
    honoured only behind a passing round-trip probe (failure warns loudly
    and falls back to exact weights)."""
    env = os.environ.get("PTPU_INT8_WEIGHTS")
    if env is not None:
        return env.strip().lower() not in _OFF_VALUES
    if not requested:
        return False
    if _INT8_W_PROBE[0] is None:
        try:
            _INT8_W_PROBE[0] = _int8_weights_probe_ok()
        except Exception as e:  # noqa: BLE001
            warnings.warn(f"int8-weights probe crashed ({e!r}); serving "
                          "weights stay exact", RuntimeWarning, stacklevel=2)
            _INT8_W_PROBE[0] = False
    if not _INT8_W_PROBE[0]:
        warnings.warn(
            "int8-weights round-trip probe failed on this backend; serving "
            "weights stay exact (force with PTPU_INT8_WEIGHTS=1)",
            RuntimeWarning, stacklevel=2)
        return False
    return True


# ---------------------------------------------------------------------------
# bench probes: reference-free loss-drift A/B for the QUANT gate


def loss_drift_probe(dtype=None, steps=8, lr=0.05):
    """Tiny deterministic training A/B: fit a 2-GEMM regression with exact
    vs scaled GEMMs (delayed scaling threaded across steps) and return the
    relative final-loss drift. This is the embedded bf16 reference probe
    the bench ``"quant"`` block and tools/bench_gate.py QUANT gate consume
    — self-contained, no baseline file needed."""
    dtype = dtype or quant_dtype()
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((32, 64)).astype(np.float32))
    y = jnp.asarray(rng.standard_normal((32, 32)).astype(np.float32))
    w1_0 = jnp.asarray((rng.standard_normal((64, 64)) * 0.1).astype(np.float32))
    w2_0 = jnp.asarray((rng.standard_normal((64, 32)) * 0.1).astype(np.float32))
    hlen = amax_hist_len()

    def run(quantized):
        w1, w2 = w1_0, w2_0
        hist = jnp.zeros((2, 2, hlen), jnp.float32)

        def loss_fn(w1, w2, hist):
            if quantized:
                h1, nh1x, nh1w = scaled_gemm(x, w1, hist[0, 0], hist[0, 1],
                                             dtype=dtype)
                out, nh2x, nh2w = scaled_gemm(jax.nn.relu(h1), w2,
                                              hist[1, 0], hist[1, 1],
                                              dtype=dtype)
                new_hist = jnp.stack([jnp.stack([nh1x, nh1w]),
                                      jnp.stack([nh2x, nh2w])])
            else:
                out = jax.nn.relu(x @ w1) @ w2
                new_hist = hist
            return jnp.mean(jnp.square(out - y)), new_hist

        loss = None
        for _ in range(steps):
            (loss, hist), (g1, g2) = jax.value_and_grad(
                loss_fn, argnums=(0, 1), has_aux=True)(w1, w2, hist)
            w1 = w1 - lr * g1
            w2 = w2 - lr * g2
        return float(loss)

    le = run(False)
    lq = run(True)
    return abs(lq - le) / max(abs(le), 1e-9)


# ---------------------------------------------------------------------------
# telemetry: gemm_dtype_mode gauge + quant_gemm_flops_total counter


from .. import telemetry as _telemetry  # noqa: E402

#: 0 = wide (bf16/f32), 1 = int8, 2 = fp8 — per GEMM site and path
_GEMM_MODE = _telemetry.gauge(
    "gemm_dtype_mode",
    "Narrow-GEMM dtype per decoder site (0=wide, 1=int8, 2=fp8)",
    labelnames=("site", "path"))
_QUANT_FLOPS = _telemetry.counter(
    "quant_gemm_flops_total",
    "Cumulative forward FLOPs executed through narrow scaled GEMMs",
    labelnames=("dtype",))

_MODE_VALUE = {"int8": 1.0, "fp8": 2.0}

#: last engagement seen at trace time: (path, dtype, flops_per_token) —
#: TrainStep ticks the flops counter from it per step
_LAST_TRACE = [None]


def note_gemm_mode(path, sites, dtype, flops_per_token=0):
    """Record trace-time engagement: one ``gemm_dtype_mode`` series per
    site (0 for sites staying wide) and the per-token narrow-FLOP rate for
    the step counter."""
    mode = _MODE_VALUE.get(dtype, 0.0)
    for s in GEMM_SITES:
        _GEMM_MODE.set(mode if s in sites else 0.0, labels=(s, path))
    if sites:
        _LAST_TRACE[0] = (path, dtype, float(flops_per_token))
    elif _LAST_TRACE[0] is not None and _LAST_TRACE[0][0] == path:
        _LAST_TRACE[0] = None


def note_step_tokens(tokens):
    """Tick ``quant_gemm_flops_total`` for one executed step of ``tokens``
    tokens, using the FLOP rate recorded by the last engaged trace."""
    info = _LAST_TRACE[0]
    if info is None:
        return
    _, dtype, per_tok = info
    if per_tok > 0:
        _QUANT_FLOPS.inc(per_tok * float(tokens), labels=(dtype,))
