"""paddle_tpu.quant: scaled fp8/int8 GEMMs for training and serving.

One shared quantized-compute core (see :mod:`.gemm` for the numerics
contract): delayed-scaling scaled GEMMs engaged per-layer through the
``names:`` recompute-policy syntax (``quant:<site>`` entries), the
int8-head-style parity gate, and the serving engine's int8 resident
weights. ``incubate.nn.functional.fp8`` delegates here (PR 4 discipline —
one quantizer implementation).
"""
from .gemm import (  # noqa: F401
    E4M3_MAX,
    GEMM_SITES,
    INT8_MAX,
    QUANT_KNOBS,
    SITE_ALIASES,
    GemmQuantCtx,
    amax_hist_len,
    cache_key_knobs,
    dtype_max,
    engaged_quant_sites,
    fp8_dot_supported,
    init_amax_state,
    inline_scaled_gemm,
    int8_weight_matmul,
    int8_weights_enabled,
    loss_drift_probe,
    note_gemm_mode,
    note_step_tokens,
    quant_compute_enabled,
    quant_compute_forced,
    quant_dtype,
    quant_gate,
    quant_gate_report,
    quant_sites_from_policy,
    quantize_weight_cols_int8,
    requested_quant_sites,
    scaled_gemm,
    split_quant_entries,
)

__all__ = [
    "E4M3_MAX", "INT8_MAX", "GEMM_SITES", "SITE_ALIASES", "QUANT_KNOBS",
    "GemmQuantCtx", "scaled_gemm", "inline_scaled_gemm", "amax_hist_len",
    "init_amax_state", "split_quant_entries", "quant_sites_from_policy",
    "requested_quant_sites", "engaged_quant_sites", "quant_compute_enabled",
    "quant_compute_forced", "quant_dtype", "dtype_max", "fp8_dot_supported",
    "quant_gate", "quant_gate_report", "cache_key_knobs",
    "quantize_weight_cols_int8", "int8_weight_matmul",
    "int8_weights_enabled", "loss_drift_probe", "note_gemm_mode",
    "note_step_tokens",
]
