"""paddle.amp — autocast + GradScaler (parity: python/paddle/amp/).

TPU-native stance: bf16 is the native mixed-precision dtype (MXU computes in
bf16 natively), so O1 autocast casts matmul/conv inputs to bf16 and loss
scaling is a no-op by default (bf16 has fp32's exponent range). The GradScaler
API is kept for source compatibility — with ``use_fp16=float16`` semantics it
performs real scaling.
"""
from __future__ import annotations

import contextlib
import threading

import jax.numpy as jnp

from .. import dtypes as _dt
from ..core.tensor import Tensor

# per-op lists (parity: amp/amp_lists.py:33-113)
WHITE_LIST = {  # run in low precision
    "matmul", "mm", "bmm", "mv", "linear", "conv1d", "conv2d", "conv3d",
    "conv1d_transpose", "conv2d_transpose", "conv3d_transpose", "einsum",
    "flash_attention", "sdpa",
}
BLACK_LIST = {  # must stay fp32
    "exp", "log", "log2", "log10", "mean", "sum", "softmax", "log_softmax",
    "cross_entropy", "softmax_with_cross_entropy", "layer_norm", "rms_norm",
    "norm", "cumsum", "logsumexp", "erf", "erfinv", "pow",
}


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = _dt.bfloat16
        self.level = "O1"
        self.fp8 = False


_state = _AmpState()


def amp_state():
    return _state


def is_auto_cast_enabled():
    return _state.enabled


def get_amp_dtype():
    return _state.dtype


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None, level="O1", dtype="bfloat16", use_promote=True):
    """paddle.amp.auto_cast (amp/auto_cast.py:1006)."""
    prev = (_state.enabled, _state.dtype, _state.level)
    _state.enabled = enable
    _state.dtype = _dt.convert_dtype(dtype)
    _state.level = level
    added_w = set(custom_white_list or ())
    added_b = set(custom_black_list or ())
    WHITE_LIST.update(added_w)
    BLACK_LIST.update(added_b)
    try:
        yield
    finally:
        _state.enabled, _state.dtype, _state.level = prev
        WHITE_LIST.difference_update(added_w - BLACK_LIST)
        BLACK_LIST.difference_update(added_b)


amp_guard = auto_cast


def is_fp8_enabled():
    return _state.fp8


@contextlib.contextmanager
def fp8_autocast(enabled=True):
    """FP8 matmul region (capability slot: the reference's fp8 gemm
    fusion kernels, phi/kernels/fusion/fp8_gemm/). Inside, Linear-family
    matmuls quantise BOTH operands to float8_e4m3fn with per-tensor
    dynamic scales (incubate.nn.functional.fp8.fp8_gemm); backward stays
    wide. Composes with auto_cast — fp8 applies to the matmul operands,
    amp dtype to everything else."""
    prev = _state.fp8
    _state.fp8 = enabled
    try:
        yield
    finally:
        _state.fp8 = prev


def decorate(models, optimizers=None, level="O1", dtype="bfloat16", master_weight=None, save_dtype=None, master_grad=False, excluded_layers=None):
    """paddle.amp.decorate (amp/auto_cast.py:1091) — O2 casts parameters."""
    from ..nn import Layer

    single_model = isinstance(models, Layer)
    model_list = [models] if single_model else list(models)
    if level == "O2":
        npd = _dt.to_np(dtype)
        for m in model_list:
            excluded = set()
            if excluded_layers:
                ex = excluded_layers if isinstance(excluded_layers, (list, tuple)) else [excluded_layers]
                for l in m.sublayers(include_self=True):
                    for e in ex:
                        if isinstance(e, type) and isinstance(l, e):
                            excluded.update(id(p) for p in l.parameters(include_sublayers=False))
            from ..nn.layer.norm import _BatchNormBase, LayerNorm

            for l in m.sublayers(include_self=True):
                is_norm = isinstance(l, (_BatchNormBase, LayerNorm))
                for p in l.parameters(include_sublayers=False):
                    if id(p) in excluded or is_norm:
                        continue
                    if p.dtype.is_floating_point:
                        p._data = p._data.astype(npd)
    if optimizers is None:
        return models if single_model else model_list
    return (models if single_model else model_list), optimizers


_FOUND_INF = None


def _found_inf_counter():
    """Lazy `amp_found_inf_total` family (docs/TELEMETRY.md)."""
    global _FOUND_INF
    if _FOUND_INF is None:
        from .. import telemetry

        _FOUND_INF = telemetry.counter(
            "amp_found_inf_total",
            "GradScaler.unscale_ detections of nonfinite grads (the "
            "optimizer step is skipped and the loss scale decays)")
    return _FOUND_INF


class GradScaler:
    """parity: amp/grad_scaler.py:657.

    On TPU with bf16 the scaler defaults to pass-through (enable_loss_scaling
    honored when the user opts into float16).
    """

    def __init__(self, enable=True, init_loss_scaling=2.0**16, incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000, decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        inv = 1.0 / self._scale
        flags = []
        for p in optimizer._parameter_list or []:
            if p.grad is None:
                continue
            g = p.grad._data * inv
            flags.append(jnp.all(jnp.isfinite(g)))
            p.grad._data = g
        # ONE fused device reduction + ONE host sync for the whole
        # parameter list (the old loop synced per tensor: with N params
        # that is N round-trips blocking the dispatch pipeline)
        self._found_inf = bool(flags) and not bool(
            jnp.all(jnp.stack(flags)))
        if self._found_inf:
            _found_inf_counter().inc()

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()

    def minimize(self, optimizer, scaled_loss):
        self.step(optimizer)

    def update(self):
        if not self._enable or not self._dynamic:
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self):
        return {
            "scale": self._scale,
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_every_n_steps": self._incr_every,
            "decr_every_n_nan_or_inf": self._decr_every,
        }

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)


# -- debugging (parity: amp/debugging.py) ----------------------------------
def check_numerics(tensor, op_type="", var_name="", debug_mode=None):
    import numpy as np

    arr = tensor.numpy()
    n_nan = int(np.isnan(arr).sum())
    n_inf = int(np.isinf(arr).sum())
    if n_nan or n_inf:
        raise RuntimeError(
            f"check_numerics failed for {op_type}:{var_name}: "
            f"{n_nan} nan, {n_inf} inf values"
        )
    return n_nan, n_inf


class debugging:
    check_numerics = staticmethod(check_numerics)

    @staticmethod
    def enable_operator_stats_collection():
        pass

    @staticmethod
    def disable_operator_stats_collection():
        pass


def is_float16_supported(device=None):
    # TPU compute is bf16-first; fp16 works via XLA but unaccelerated
    return False


def is_bfloat16_supported(device=None):
    return True
