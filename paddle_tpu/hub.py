"""paddle.hub (parity: python/paddle/hapi/hub.py — list/help/load over a
repo's hubconf.py).

The TPU environment has zero network egress, so ``source='local'`` (a
directory containing ``hubconf.py``) is the first-class path — identical
semantics to the reference's local source. github/gitee sources raise
with guidance instead of hanging on a dead network.
"""
from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load", "load_state_dict_from_url"]


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, "hubconf.py")
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no hubconf.py under {repo_dir!r}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)  # fresh module each call (= force_reload)
    return mod


def _resolve(repo_dir, source):
    if source not in ("local", "github", "gitee"):
        raise ValueError(
            f"unknown source {source!r} (expected 'github', 'gitee' or "
            "'local')")
    if source != "local":
        raise RuntimeError(
            f"hub source {source!r} needs network access, which this "
            "environment does not have — clone the repo and use "
            "source='local'")
    return repo_dir


def list(repo_dir, source="local", force_reload=False):  # noqa: A001
    """Entrypoints (public callables) exposed by the repo's hubconf."""
    mod = _load_hubconf(_resolve(repo_dir, source))
    return [n for n in dir(mod)
            if not n.startswith("_") and callable(getattr(mod, n))]


def help(repo_dir, model, source="local", force_reload=False):  # noqa: A001
    mod = _load_hubconf(_resolve(repo_dir, source))
    fn = getattr(mod, model, None)
    if fn is None:
        raise ValueError(f"model {model!r} not in hubconf "
                         f"(has {list(repo_dir, source)})")
    return fn.__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    mod = _load_hubconf(_resolve(repo_dir, source))
    fn = getattr(mod, model, None)
    if fn is None:
        raise ValueError(f"model {model!r} not in hubconf "
                         f"(has {list(repo_dir, source)})")
    return fn(**kwargs)


def load_state_dict_from_url(url, model_dir=None, check_hash=False,
                             file_name=None, map_location=None):
    """Local-path / file:// loads only (zero-egress environment)."""
    import paddle_tpu as paddle

    path = url[len("file://"):] if str(url).startswith("file://") else url
    if not os.path.exists(path):
        raise RuntimeError(
            f"load_state_dict_from_url: {url!r} is not a local path and "
            "this environment has no network — download the weights "
            "out-of-band and pass the file path")
    return paddle.load(path)
