"""Data types for paddle_tpu.

Capability parity with the reference's ``phi::DataType``
(``paddle/phi/common/data_type.h``), re-expressed over numpy/ml_dtypes scalar
types so every dtype maps 1:1 onto an XLA element type.
"""
from __future__ import annotations

import numpy as np

try:
    import ml_dtypes

    _BFLOAT16 = np.dtype(ml_dtypes.bfloat16)
    _FP8_E4M3 = np.dtype(ml_dtypes.float8_e4m3fn)
    _FP8_E5M2 = np.dtype(ml_dtypes.float8_e5m2)
except ImportError:  # pragma: no cover - ml_dtypes ships with jax
    _BFLOAT16 = np.dtype(np.float32)
    _FP8_E4M3 = np.dtype(np.float32)
    _FP8_E5M2 = np.dtype(np.float32)


class DType:
    """A framework dtype: a named wrapper over a numpy dtype.

    Behaves like the reference's ``paddle.float32`` objects: reprs as
    ``paddle_tpu.float32``, compares equal to strings ("float32"), numpy
    dtypes, and other DType instances.
    """

    __slots__ = ("name", "np_dtype")
    _by_name: dict = {}

    def __init__(self, name: str, np_dtype):
        self.name = name
        self.np_dtype = np.dtype(np_dtype)
        DType._by_name[name] = self

    # -- conversions -------------------------------------------------------
    def __repr__(self):
        return f"paddle_tpu.{self.name}"

    __str__ = __repr__

    def __hash__(self):
        return hash(self.name)

    def __eq__(self, other):
        try:
            return convert_dtype(other) is self
        except (TypeError, ValueError):
            return NotImplemented

    def __ne__(self, other):
        eq = self.__eq__(other)
        if eq is NotImplemented:
            return eq
        return not eq

    # numpy interop: np.dtype(paddle_tpu.float32) works
    @property
    def __array_interface__(self):  # pragma: no cover
        raise AttributeError

    # -- classification ----------------------------------------------------
    @property
    def is_floating_point(self) -> bool:
        return np.issubdtype(self.np_dtype, np.floating)

    @property
    def is_complex(self) -> bool:
        return np.issubdtype(self.np_dtype, np.complexfloating)

    @property
    def is_integer(self) -> bool:
        return np.issubdtype(self.np_dtype, np.integer)

    @property
    def is_inexact(self) -> bool:
        return self.is_floating_point or self.is_complex

    @property
    def itemsize(self) -> int:
        return self.np_dtype.itemsize


bool_ = DType("bool", np.bool_)
uint8 = DType("uint8", np.uint8)
int8 = DType("int8", np.int8)
int16 = DType("int16", np.int16)
int32 = DType("int32", np.int32)
int64 = DType("int64", np.int64)
uint16 = DType("uint16", np.uint16)
uint32 = DType("uint32", np.uint32)
uint64 = DType("uint64", np.uint64)
float16 = DType("float16", np.float16)
bfloat16 = DType("bfloat16", _BFLOAT16)
float32 = DType("float32", np.float32)
float64 = DType("float64", np.float64)
float8_e4m3fn = DType("float8_e4m3fn", _FP8_E4M3)
float8_e5m2 = DType("float8_e5m2", _FP8_E5M2)
complex64 = DType("complex64", np.complex64)
complex128 = DType("complex128", np.complex128)

_ALIASES = {
    "bool": bool_,
    "float": float32,
    "double": float64,
    "half": float16,
    "int": int32,
    "long": int64,
}

_BY_NP = {d.np_dtype: d for d in DType._by_name.values()}


def convert_dtype(d) -> DType:
    """Normalize str / numpy dtype / python type / DType into a DType."""
    if isinstance(d, DType):
        return d
    if isinstance(d, str):
        if d in DType._by_name:
            return DType._by_name[d]
        if d in _ALIASES:
            return _ALIASES[d]
        # fall through to numpy name parsing ("float32" already handled)
        return _BY_NP[np.dtype(d)]
    if d is bool:
        return bool_
    if d is int:
        return int64
    if d is float:
        return float32
    if d is complex:
        return complex64
    npd = np.dtype(d)
    if npd in _BY_NP:
        return _BY_NP[npd]
    raise TypeError(f"unsupported dtype: {d!r}")


def to_np(d) -> np.dtype:
    return convert_dtype(d).np_dtype


def dtype_from_array(arr) -> DType:
    return _BY_NP[np.dtype(arr.dtype)]


# Type-promotion table follows numpy/jax semantics; the reference implements
# promotion in eager codegen (eager_gen.py type promotion) — on TPU we simply
# delegate to jax's promotion which XLA understands natively.
def promote_types(a, b) -> DType:
    import jax.numpy as jnp

    return _BY_NP[np.dtype(jnp.promote_types(to_np(a), to_np(b)))]


def iinfo(d):
    return np.iinfo(to_np(d))


class _FInfo:
    def __init__(self, d):
        import ml_dtypes

        self._f = ml_dtypes.finfo(to_np(d))
        self.dtype = convert_dtype(d)

    def __getattr__(self, k):
        return getattr(self._f, k)


def finfo(d):
    return _FInfo(d)
