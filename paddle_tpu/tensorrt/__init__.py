"""paddle.tensorrt (parity: python/paddle/tensorrt) — the reference's
TensorRT export path. On TPU the engine-compiler slot is XLA: `convert`
produces the same serialized StableHLO artifact `jit.save`/`inference`
consume, so code written against this API still gets an AOT-compiled
deployable program (just not a TRT engine)."""
from __future__ import annotations

import enum

__all__ = ["Input", "TensorRTConfig", "convert", "PrecisionMode"]


class PrecisionMode(enum.Enum):
    FP32 = "fp32"
    FP16 = "fp16"
    BF16 = "bf16"
    INT8 = "int8"


class Input:
    def __init__(self, min_input_shape=None, optim_input_shape=None,
                 max_input_shape=None, input_data_type="float32", **kwargs):
        self.min_input_shape = min_input_shape
        self.optim_input_shape = optim_input_shape or min_input_shape
        self.max_input_shape = max_input_shape or self.optim_input_shape
        self.input_data_type = input_data_type


class TensorRTConfig:
    def __init__(self, inputs=None, precision_mode=PrecisionMode.FP32,
                 **kwargs):
        self.inputs = inputs or []
        self.precision_mode = precision_mode
        self.save_model_dir = kwargs.get("save_model_dir")


def convert(model_path, config: TensorRTConfig):
    """Convert a saved model for deployment. On TPU this re-emits the
    XLA artifact (optionally bf16-weighted when the config asks for a
    reduced precision) at config.save_model_dir."""
    import os

    from ..inference import convert_to_mixed_precision

    dst = config.save_model_dir or model_path + "_trt"
    os.makedirs(dst, exist_ok=True)
    base = os.path.basename(model_path)
    out_prefix = os.path.join(dst, base)
    if config.precision_mode in (PrecisionMode.FP16, PrecisionMode.BF16):
        convert_to_mixed_precision(
            model_path + ".pdmodel", model_path + ".pdiparams",
            out_prefix + ".pdmodel", out_prefix + ".pdiparams")
    else:
        import shutil

        for suf in (".pdmodel", ".pdiparams", ".pdmeta.json"):
            shutil.copyfile(model_path + suf, out_prefix + suf)
    return out_prefix
