"""paddle.nn namespace (parity: python/paddle/nn/__init__.py)."""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from . import quant  # noqa: F401
from .layer.layers import Layer  # noqa: F401
from .layer.common import (  # noqa: F401
    Identity, Linear, Dropout, Dropout2D, Dropout3D, AlphaDropout, Embedding,
    Flatten, Upsample, UpsamplingBilinear2D, UpsamplingNearest2D, Bilinear,
    Pad1D, Pad2D, Pad3D, ZeroPad2D, PixelShuffle, PixelUnshuffle,
    ChannelShuffle, CosineSimilarity, Unfold, Fold,
)
from .layer.container import Sequential, LayerList, LayerDict, ParameterList  # noqa: F401
from .layer.conv import (  # noqa: F401
    Conv1D, Conv2D, Conv3D, Conv1DTranspose, Conv2DTranspose, Conv3DTranspose,
)
from .layer.norm import (  # noqa: F401
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, SyncBatchNorm,
    LayerNorm, RMSNorm, GroupNorm, InstanceNorm1D, InstanceNorm2D,
    InstanceNorm3D, LocalResponseNorm,
)
from .layer.activation import (  # noqa: F401
    ReLU, ReLU6, Sigmoid, Tanh, Silu, Mish, LogSigmoid, Tanhshrink, Softsign,
    Hardswish, GELU, LeakyReLU, PReLU, ELU, CELU, SELU, Hardshrink,
    Softshrink, Hardsigmoid, Hardtanh, Softmax, LogSoftmax, Softplus,
    ThresholdedReLU, Maxout, Swish, RReLU, GLU,
)
from .layer.pooling import (  # noqa: F401
    MaxPool1D, MaxPool2D, MaxPool3D, AvgPool1D, AvgPool2D, AvgPool3D,
    AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D,
    AdaptiveMaxPool1D, AdaptiveMaxPool2D, AdaptiveMaxPool3D, LPPool2D,
)
from .layer.loss import (  # noqa: F401
    CrossEntropyLoss, MSELoss, L1Loss, NLLLoss, BCELoss, BCEWithLogitsLoss,
    SmoothL1Loss, KLDivLoss, MarginRankingLoss, CTCLoss, CosineEmbeddingLoss,
    TripletMarginLoss, PoissonNLLLoss, GaussianNLLLoss,
    MultiLabelSoftMarginLoss, SoftMarginLoss, HingeEmbeddingLoss,
)
from .layer.transformer import (  # noqa: F401
    MultiHeadAttention, TransformerEncoderLayer, TransformerEncoder,
    TransformerDecoderLayer, TransformerDecoder, Transformer,
)
from .layer.rnn import (  # noqa: F401
    RNNCellBase, SimpleRNNCell, LSTMCell, GRUCell, RNN, BiRNN,
    SimpleRNN, LSTM, GRU,
)
from .clip import (  # noqa: F401
    ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm,
    clip_grad_norm_, clip_grad_value_, global_grad_norm,
)

from ..param_attr import ParamAttr  # noqa: F401
from .layer.compat import (  # noqa: F401
    AdaptiveLogSoftmaxWithLoss, BeamSearchDecoder, FeatureAlphaDropout,
    FractionalMaxPool2D, FractionalMaxPool3D, HSigmoidLoss, LPPool1D,
    MaxUnPool1D, MaxUnPool2D, MaxUnPool3D, MultiMarginLoss, PairwiseDistance,
    ParameterDict, RNNTLoss, Softmax2D, SpectralNorm,
    TripletMarginWithDistanceLoss, Unflatten, ZeroPad1D, ZeroPad3D,
    dynamic_decode)

from . import quant  # noqa: F401
from . import utils  # noqa: F401
