"""Weight initializers (parity: python/paddle/nn/initializer/).

Each initializer produces a host-side numpy array (deterministic under
``paddle_tpu.seed``) that is then placed on device — matching the reference's
fill-at-creation semantics rather than jax's lazy init style.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ... import dtypes as _dt, framework


def calculate_gain(nonlinearity, param=None):
    recommended = {
        "sigmoid": 1.0,
        "linear": 1.0,
        "conv1d": 1.0,
        "conv2d": 1.0,
        "conv3d": 1.0,
        "tanh": 5.0 / 3.0,
        "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4.0,
    }
    if nonlinearity not in recommended:
        raise ValueError(f"unsupported nonlinearity: {nonlinearity}")
    return recommended[nonlinearity]


def _fans(shape):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        # paddle fc weights are [in, out]
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    fan_in = shape[1] * receptive
    fan_out = shape[0] * receptive
    return fan_in, fan_out


class Initializer:
    def _init_array(self, shape, dtype):
        raise NotImplementedError

    def __call__(self, param, block=None):
        arr = self._init_array(list(param.shape), param.dtype)
        param._data = arr
        return param

    def _key(self):
        return framework.next_rng_key()


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def _init_array(self, shape, dtype):
        return jnp.full(shape, self.value, _dt.to_np(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def _init_array(self, shape, dtype):
        d = _dt.to_np(dtype)
        return self.mean + self.std * jax.random.normal(self._key(), shape, jnp.float32).astype(d)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0, name=None):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def _init_array(self, shape, dtype):
        d = _dt.to_np(dtype)
        lo = (self.a - self.mean) / self.std
        hi = (self.b - self.mean) / self.std
        z = jax.random.truncated_normal(self._key(), lo, hi, shape, jnp.float32)
        return (self.mean + self.std * z).astype(d)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, name=None):
        self.low, self.high = low, high

    def _init_array(self, shape, dtype):
        d = _dt.to_np(dtype)
        return jax.random.uniform(
            self._key(), shape, jnp.float32, self.low, self.high
        ).astype(d)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _init_array(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        d = _dt.to_np(dtype)
        return (std * jax.random.normal(self._key(), shape, jnp.float32)).astype(d)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _init_array(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        d = _dt.to_np(dtype)
        return jax.random.uniform(
            self._key(), shape, jnp.float32, -limit, limit
        ).astype(d)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def _init_array(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        d = _dt.to_np(dtype)
        return (std * jax.random.normal(self._key(), shape, jnp.float32)).astype(d)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def _init_array(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        d = _dt.to_np(dtype)
        return jax.random.uniform(
            self._key(), shape, jnp.float32, -limit, limit
        ).astype(d)


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def _init_array(self, shape, dtype):
        from ...core.tensor import Tensor

        v = self.value
        if isinstance(v, Tensor):
            v = v.numpy()
        arr = jnp.asarray(np.asarray(v), dtype=_dt.to_np(dtype))
        return arr.reshape(shape)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0, name=None):
        self.gain = gain

    def _init_array(self, shape, dtype):
        rows = shape[0]
        cols = int(np.prod(shape[1:])) if len(shape) > 1 else 1
        flat = jax.random.normal(self._key(), (max(rows, cols), min(rows, cols)), jnp.float32)
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(_dt.to_np(dtype))


class Dirac(Initializer):
    def __init__(self, groups=1, name=None):
        self.groups = groups

    def _init_array(self, shape, dtype):
        arr = np.zeros(shape, _dt.to_np(dtype))
        out_c, in_c = shape[0], shape[1]
        mins = min(out_c // self.groups, in_c)
        center = tuple(s // 2 for s in shape[2:])
        for g in range(self.groups):
            for i in range(mins):
                arr[(g * (out_c // self.groups) + i, i) + center] = 1.0
        return jnp.asarray(arr)


# lowercase function-style aliases (paddle.nn.initializer module level)
normal = Normal
uniform = Uniform
constant = Constant
xavier_normal = XavierNormal
xavier_uniform = XavierUniform
kaiming_normal = KaimingNormal
kaiming_uniform = KaimingUniform
truncated_normal = TruncatedNormal
assign = Assign
orthogonal = Orthogonal
dirac = Dirac


def set_global_initializer(weight_init, bias_init=None):
    # stored for Layer.create_parameter defaults (minimal parity)
    global _GLOBAL_WEIGHT_INIT, _GLOBAL_BIAS_INIT
    _GLOBAL_WEIGHT_INIT = weight_init
    _GLOBAL_BIAS_INIT = bias_init


_GLOBAL_WEIGHT_INIT = None
_GLOBAL_BIAS_INIT = None


class Bilinear(Initializer):
    """Bilinear upsampling kernel init (initializer/Bilinear parity)."""

    def _init_array(self, shape, dtype):
        import numpy as np

        w = np.zeros(shape, dtype="float32")
        if len(shape) == 4:
            f = np.ceil(shape[3] / 2.0)
            c = (2 * f - 1 - f % 2) / (2.0 * f)
            for i in range(int(np.prod(shape))):
                x = i % shape[3]
                y = (i // shape[3]) % shape[2]
                idx = np.unravel_index(i, shape)
                w[idx] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        import jax.numpy as jnp

        from ...dtypes import convert_dtype

        return jnp.asarray(w, convert_dtype(dtype).np_dtype)
