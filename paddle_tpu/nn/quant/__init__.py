"""paddle.nn.quant (parity: nn/quant/qat + weight-only linear ops).

weight_quantize/weight_only_linear implement real int8 weight-only
quantization in jnp (per-channel absmax scales, int8 storage, dequant
fused into the matmul) — the TPU form of the reference's CUDA
weight-only kernels."""
from __future__ import annotations

import jax.numpy as jnp

from ...core.dispatch import apply_op
from ...nn.layer.layers import Layer

__all__ = ["Stub", "weight_only_linear", "llm_int8_linear",
           "weight_quantize", "weight_dequantize"]


class Stub(Layer):
    """Quant insertion point marker (nn/quant/stub.py): identity until a
    quant pass replaces it."""

    def __init__(self, observer=None):
        super().__init__()
        self._observer = observer

    def forward(self, x):
        return x


def weight_quantize(x, algo="weight_only_int8", arch=None, group_size=-1):
    """weight [in, out] -> (int8 weight, per-out-channel fp scales)."""
    if algo not in ("weight_only_int8", "llm.int8"):
        raise NotImplementedError(f"algo {algo!r}: int8 weight-only is the "
                                  "TPU path (int4 needs packing support)")

    def _q(w):
        scale = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0) / 127.0
        scale = jnp.maximum(scale, 1e-10)
        q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127)
        return q.astype(jnp.int8), scale.astype(jnp.float32)

    return apply_op(_q, x, _op_name="weight_quantize")


def weight_dequantize(x, scale, algo="weight_only_int8", out_dtype="float16"):
    def _dq(q, s):
        return (q.astype(jnp.float32) * s).astype(jnp.bfloat16)

    return apply_op(_dq, x, scale, _op_name="weight_dequantize")


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", arch=None, group_size=-1):
    """y = x @ dequant(weight) + bias with int8-stored weights."""
    def _wol(a, q, s, b):
        w = q.astype(jnp.float32) * s
        out = a.astype(jnp.float32) @ w
        if b is not None:
            out = out + b
        return out.astype(a.dtype)

    return apply_op(_wol, x, weight, weight_scale, bias,
                    _op_name="weight_only_linear")


def llm_int8_linear(x, weight, bias=None, weight_scale=None,
                    threshold=6.0):
    """LLM.int8(): outlier activation columns in fp, the rest int8."""
    def _l8(a, q, s, b):
        af = a.astype(jnp.float32)
        outlier = jnp.max(jnp.abs(af), axis=tuple(range(af.ndim - 1))) \
            > threshold
        w = q.astype(jnp.float32) * s
        dense = af * (~outlier)   # int8-quantized columns
        sparse = af * outlier     # fp outlier columns (LLM.int8 split)
        out = dense @ w + sparse @ w
        if b is not None:
            out = out + b
        return out.astype(a.dtype)

    return apply_op(_l8, x, weight, weight_scale, bias,
                    _op_name="llm_int8_linear")
