"""paddle.nn.quant — weight-only / LLM.int8 quantized linear surface.

Parity: python/paddle/nn/quant/quantized_linear.py (weight_quantize:64,
weight_dequantize:130, weight_only_linear:230, llm_int8_linear:285,
apply_per_channel_scale:351). TPU-native form: int8 storage with
per-out-channel (or grouped) fp32 absmax scales; the dequant fuses into
the matmul under XLA, and the LLM.int8 inlier path runs a REAL
int8 x int8 matmul (v5e MXU runs int8 at 2x the bf16 rate) with the
fp outlier columns handled densely — the TPU analogue of the CUDA
cutlass int8 kernels the reference dispatches to.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...core.dispatch import apply_op
from ...nn.layer.layers import Layer

__all__ = ["Stub", "weight_only_linear", "llm_int8_linear",
           "weight_quantize", "weight_dequantize",
           "apply_per_channel_scale"]

_QMAX = {"weight_only_int8": 127.0, "llm.int8": 127.0,
         "weight_only_int4": 7.0}


class Stub(Layer):
    """Quant insertion point marker (nn/quant/stub.py): identity until a
    quant pass replaces it."""

    def __init__(self, observer=None):
        super().__init__()
        self._observer = observer

    def forward(self, x):
        return x


def _check_group(group_size):
    if group_size not in (-1, 64, 128):
        raise ValueError(f"group_size must be -1/64/128, got {group_size}")


def weight_quantize(x, algo="weight_only_int8", arch=None, group_size=-1):
    """x [k, n] -> (int8 weight [n, k] (transposed, reference layout),
    scale [n] fp32) — per-out-channel absmax; group_size 64/128 gives
    grouped scales [n, k/g]. int4 quantizes to the +/-7 range (stored
    int8: TPU has no packed-int4 compute; the memory claim is halved not
    quartered, stated honestly)."""
    _check_group(group_size)
    if algo not in _QMAX:
        raise NotImplementedError(f"algo {algo!r}")
    qmax = _QMAX[algo]

    def _q(w):
        wt = w.astype(jnp.float32).T  # [n, k]
        if group_size == -1:
            s = jnp.maximum(jnp.max(jnp.abs(wt), axis=1), 1e-10) / qmax
            q = jnp.clip(jnp.round(wt / s[:, None]), -qmax, qmax)
            return q.astype(jnp.int8), s.astype(jnp.float32)
        n, k = wt.shape
        g = wt.reshape(n, k // group_size, group_size)
        s = jnp.maximum(jnp.max(jnp.abs(g), axis=2), 1e-10) / qmax
        q = jnp.clip(jnp.round(g / s[:, :, None]), -qmax, qmax)
        return (q.reshape(n, k).astype(jnp.int8), s.astype(jnp.float32))

    return apply_op(_q, x, _op_name="weight_quantize")


def weight_dequantize(x, scale, algo="weight_only_int8",
                      out_dtype="float16", group_size=-1):
    """int8 [n, k] + scale -> fp [k, n] (transposed back)."""
    _check_group(group_size)

    def _dq(q, s):
        qf = q.astype(jnp.float32)
        if s.ndim == 1:
            w = qf * s[:, None]
        else:  # grouped [n, k/g]
            n, k = qf.shape
            w = (qf.reshape(n, -1, k // s.shape[1]) * s[:, :, None]
                 ).reshape(n, k)
        return w.T.astype(jnp.dtype(out_dtype))

    return apply_op(_dq, x, scale, _op_name="weight_dequantize")


def _dequant_nk(q, s):
    """[n,k] int8 + per-channel/grouped scale -> fp32 [n,k]."""
    qf = q.astype(jnp.float32)
    if s is None:
        return qf
    if s.ndim == 1:
        return qf * s[:, None]
    n, k = qf.shape
    return (qf.reshape(n, s.shape[1], k // s.shape[1]) * s[:, :, None]
            ).reshape(n, k)


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", arch=None, group_size=-1):
    """y[.., n] = x[.., k] @ dequant(weight[n, k]).T + bias."""
    _check_group(group_size)

    def _wol(a, q, s, b):
        w = _dequant_nk(q, s)  # [n, k]
        out = a.astype(jnp.float32) @ w.T
        if b is not None:
            out = out + b
        return out.astype(a.dtype)

    return apply_op(_wol, x, weight, weight_scale, bias,
                    _op_name="weight_only_linear")


def llm_int8_linear(x, weight, bias=None, weight_scale=None,
                    threshold=6.0):
    """LLM.int8() mixed decomposition (Dettmers et al.): activation
    columns whose absmax exceeds `threshold` go through the fp path;
    the inlier columns run int8(act) x int8(weight) on the MXU."""
    def _l8(a, q, s, b):
        from jax import lax

        if s is not None and s.ndim != 1:
            raise ValueError("llm_int8_linear requires per-channel scales")
        af = a.astype(jnp.float32)
        flat = af.reshape(-1, af.shape[-1])
        outlier = jnp.max(jnp.abs(flat), axis=0) > threshold  # [k]
        inl = jnp.where(outlier[None, :], 0.0, flat)
        # per-row absmax int8 activations on the inlier columns
        a_s = jnp.maximum(jnp.max(jnp.abs(inl), axis=1), 1e-10) / 127.0
        a_q = jnp.clip(jnp.round(inl / a_s[:, None]), -127, 127
                       ).astype(jnp.int8)
        q_in = jnp.where(outlier[None, :], 0, q).astype(jnp.int8)
        acc = lax.dot_general(
            a_q, q_in, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32)  # [rows, n] int32
        dense = acc.astype(jnp.float32) * a_s[:, None]
        if s is not None:
            dense = dense * s[None, :]
        sp = jnp.where(outlier[None, :], flat, 0.0)
        w_out = _dequant_nk(q, s) * outlier[None, :]
        out = dense + sp @ w_out.T
        if b is not None:
            out = out + b
        return out.reshape(*af.shape[:-1], -1).astype(a.dtype)

    return apply_op(_l8, x, weight, weight_scale, bias,
                    _op_name="llm_int8_linear")


def apply_per_channel_scale(x, scales):
    """Pre-quant smoothing: divide activations by per-channel scales
    (SmoothQuant-style; the matching weight absorb happens offline)."""
    return apply_op(lambda a, s: (a / s).astype(a.dtype), x, scales,
                    _op_name="apply_per_channel_scale")
