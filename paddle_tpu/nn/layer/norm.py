"""Norm layers (parity: python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor
from ..initializer import Constant
from .. import functional as F
from .layers import Layer


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None, bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is not False:
            self.weight = self.create_parameter(
                shape=[num_features], attr=weight_attr, default_initializer=Constant(1.0)
            )
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                shape=[num_features], attr=bias_attr, is_bias=True
            )
        else:
            self.bias = None
        self.register_buffer("_mean", Tensor(jnp.zeros([num_features], jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones([num_features], jnp.float32)))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum, epsilon=self._epsilon,
            data_format=self._data_format, use_global_stats=self._use_global_stats,
        )

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """On TPU, batch stats sync falls out of SPMD compilation: when the batch
    axis is sharded over the mesh, XLA computes global-mean/var via psum.
    Eager single-process semantics equal BatchNorm."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            new = SyncBatchNorm(
                layer._num_features, layer._momentum, layer._epsilon,
                data_format=layer._data_format,
            )
            new.set_state_dict(layer.state_dict())
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                shape=self._normalized_shape, attr=weight_attr,
                default_initializer=Constant(1.0),
            )
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                shape=self._normalized_shape, attr=bias_attr, is_bias=True
            )
        else:
            self.bias = None

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class RMSNorm(Layer):
    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            shape=[hidden_size], attr=weight_attr, default_initializer=Constant(1.0)
        )

    def forward(self, x):
        return F.rms_norm(x, self.weight, epsilon=self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05, weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is not False:
            self.weight = self.create_parameter(
                shape=[num_channels], attr=weight_attr, default_initializer=Constant(1.0)
            )
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(shape=[num_channels], attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight, self.bias, self._data_format)


class InstanceNorm1D(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9, weight_attr=None, bias_attr=None, data_format="NCL", name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is not False:
            self.scale = self.create_parameter(shape=[num_features], attr=weight_attr, default_initializer=Constant(1.0))
            self.bias = self.create_parameter(shape=[num_features], attr=bias_attr, is_bias=True)
        else:
            self.scale = None
            self.bias = None

    def forward(self, x):
        return F.instance_norm(x, weight=self.scale, bias=self.bias, eps=self._epsilon)


class InstanceNorm2D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9, weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr, bias_attr, data_format)


class InstanceNorm3D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9, weight_attr=None, bias_attr=None, data_format="NCDHW", name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr, bias_attr, data_format)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=0.0001, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)
