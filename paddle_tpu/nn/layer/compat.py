"""Long-tail nn layers (parity: remaining python/paddle/nn exports)."""
from __future__ import annotations

import collections

import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op
from ...core.tensor import Parameter, Tensor
from .layers import Layer
from .. import functional as F
from ..functional import compat as FC


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        return FC.pairwise_distance(x, y, self.p, self.epsilon, self.keepdim)


class Softmax2D(Layer):
    def forward(self, x):
        return F.softmax(x, axis=-3)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis, self.shape = axis, shape

    def forward(self, x):
        import paddle_tpu as paddle

        return paddle.unflatten(x, self.axis, self.shape)


class FeatureAlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return FC.feature_alpha_dropout(x, self.p, self.training)


class LPPool1D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCL", name=None):
        super().__init__()
        self.args = (norm_type, kernel_size, stride, padding, ceil_mode,
                     data_format)

    def forward(self, x):
        return FC.lp_pool1d(x, *self.args)


class ZeroPad1D(Layer):
    def __init__(self, padding, data_format="NCL", name=None):
        super().__init__()
        self.padding = padding if isinstance(padding, (list, tuple)) else (padding,) * 2

    def forward(self, x):
        pad = [(0, 0), (0, 0), tuple(self.padding)]
        return apply_op(lambda a: jnp.pad(a, pad), x, _op_name="zeropad1d")


class ZeroPad3D(Layer):
    def __init__(self, padding, data_format="NCDHW", name=None):
        super().__init__()
        p = padding if isinstance(padding, (list, tuple)) else [padding] * 6
        self.padding = p

    def forward(self, x):
        p = self.padding
        pad = [(0, 0), (0, 0), (p[4], p[5]), (p[2], p[3]), (p[0], p[1])]
        return apply_op(lambda a: jnp.pad(a, pad), x, _op_name="zeropad3d")


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, data_format, output_size)

    def forward(self, x, indices):
        k, s, p, df, os_ = self.args
        return FC.max_unpool1d(x, indices, k, s, p, df, os_)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, data_format, output_size)

    def forward(self, x, indices):
        k, s, p, df, os_ = self.args
        return FC.max_unpool2d(x, indices, k, s, p, df, os_)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, data_format, output_size)

    def forward(self, x, indices):
        k, s, p, df, os_ = self.args
        return FC.max_unpool3d(x, indices, k, s, p, df, os_)


class FractionalMaxPool2D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return FC.fractional_max_pool2d(x, self.output_size,
                                        return_mask=self.return_mask)


class FractionalMaxPool3D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return FC.fractional_max_pool3d(x, self.output_size,
                                        return_mask=self.return_mask)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self.args = (p, margin, weight, reduction)

    def forward(self, input, label):
        p, m, w, r = self.args
        return FC.multi_margin_loss(input, label, p, m, w, r)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.args = (distance_function, margin, swap, reduction)

    def forward(self, input, positive, negative):
        df, m, sw, r = self.args
        return FC.triplet_margin_with_distance_loss(
            input, positive, negative, df, m, sw, r)


class RNNTLoss(Layer):
    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, logits, labels, input_lengths, label_lengths):
        return FC.rnnt_loss(logits, labels, input_lengths, label_lengths,
                            self.blank, reduction=self.reduction)


class HSigmoidLoss(Layer):
    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        self.num_classes = num_classes
        self.weight = self.create_parameter(
            [num_classes - 1, feature_size])
        self.bias = (None if bias_attr is False
                     else self.create_parameter([num_classes - 1, 1],
                                                is_bias=True))

    def forward(self, input, label, path_table=None, path_code=None):
        return FC.hsigmoid_loss(input, label, self.num_classes, self.weight,
                                self.bias)


class AdaptiveLogSoftmaxWithLoss(Layer):
    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, name=None):
        super().__init__()
        self.cutoffs = list(cutoffs) + [n_classes]
        self.head_size = self.cutoffs[0] + len(self.cutoffs) - 1
        self.head_weight = self.create_parameter(
            [in_features, self.head_size])
        self.head_bias = (self.create_parameter([self.head_size], is_bias=True)
                          if head_bias else None)
        self.tail_weights = []
        for i in range(len(self.cutoffs) - 1):
            sz = self.cutoffs[i + 1] - self.cutoffs[i]
            w = self.create_parameter([in_features, sz])
            self.add_parameter(f"tail_{i}", w)
            self.tail_weights.append(w)

    def forward(self, input, label):
        lp, loss = FC.adaptive_log_softmax_with_loss(
            input, label, self.head_weight, self.tail_weights, self.cutoffs,
            self.head_bias)
        return lp, loss


class ParameterDict(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters:
            for k, v in (parameters.items()
                         if isinstance(parameters, dict) else parameters):
                self.add_parameter(k, v)

    def __getitem__(self, key):
        return self._parameters[key]

    def __setitem__(self, key, value):
        self.add_parameter(key, value)

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters)

    def keys(self):
        return self._parameters.keys()

    def items(self):
        return self._parameters.items()

    def values(self):
        return self._parameters.values()

    def update(self, parameters):
        for k, v in (parameters.items()
                     if isinstance(parameters, dict) else parameters):
            self.add_parameter(k, v)


class SpectralNorm(Layer):
    """Standalone spectral-norm layer (nn/layer/norm.py SpectralNorm):
    power-iterates u/v buffers and returns W / sigma."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 dtype="float32"):
        super().__init__()
        self.dim = dim
        self.power_iters = power_iters
        self.eps = eps
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        import paddle_tpu as paddle

        self.weight_u = self.create_parameter([h])
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter([w])
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        eps, iters, dim = self.eps, self.power_iters, self.dim

        def _sn(w, u, v):
            mat = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            for _ in range(max(1, iters)):
                v = mat.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = mat @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ mat @ v
            return w / sigma, u, v

        out, u_new, v_new = apply_op(
            _sn, weight, self.weight_u, self.weight_v, _op_name="spectral_norm")
        self.weight_u._data = u_new._data
        self.weight_v._data = v_new._data
        return out


# -- seq2seq decoding -------------------------------------------------------
class BeamSearchDecoder:
    """parity: paddle.nn.BeamSearchDecoder (greedy/beam over a RNN cell)."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = start_token
        self.end_token = end_token
        self.beam_size = beam_size
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn


def dynamic_decode(decoder, inits=None, max_step_num=100, **kwargs):
    """Greedy rollout of the decoder cell (beam_size collapses to greedy
    argmax per step — the compiled-TPU-friendly decode path; full beam
    search lives in model libraries)."""
    import paddle_tpu as paddle

    cell = decoder.cell
    state = inits
    token = paddle.full([1], decoder.start_token, dtype="int64")
    outputs = []
    for _ in range(int(max_step_num)):
        inp = (decoder.embedding_fn(token) if decoder.embedding_fn
               else token.astype("float32").unsqueeze(-1))
        out, state = cell(inp, state)
        logits = decoder.output_fn(out) if decoder.output_fn else out
        token = paddle.argmax(logits, axis=-1).reshape([-1])
        outputs.append(token)
        if int(token.numpy()[0]) == decoder.end_token:
            break
    return paddle.stack(outputs, axis=0), state
