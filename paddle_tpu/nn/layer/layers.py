"""nn.Layer — module base class (parity: python/paddle/nn/layer/layers.py).

Holds parameters (trainable Tensors), buffers (non-trainable state like
BatchNorm running stats), and sublayers; supports hooks, train/eval mode,
state_dict round-trips, and functional parameter swapping (the seam the jit
path uses to trace a Layer as a pure function of its parameters).
"""
from __future__ import annotations

import collections
import contextlib

import numpy as np
import jax.numpy as jnp

from ... import framework
from ...core.tensor import Tensor, Parameter

_layer_name_counters = collections.defaultdict(int)


def _unique_layer_name(prefix):
    _layer_name_counters[prefix] += 1
    return f"{prefix}_{_layer_name_counters[prefix] - 1}"


class HookRemoveHelper:
    def __init__(self, container, key):
        self._container = container
        self._key = key

    def remove(self):
        self._container.pop(self._key, None)


_CALL_DEPTH = [0]  # >0 while inside some Layer's forward (sublayer calls)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = dtype
        self._parameters = collections.OrderedDict()
        self._buffers = collections.OrderedDict()
        self._non_persistable_buffer_names_set = set()
        self._sub_layers = collections.OrderedDict()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._hook_id = 0
        self._full_name = _unique_layer_name(
            name_scope or self.__class__.__name__.lower()
        )
        self._init_in_dynamic_mode = True

    # ------------------------------------------------------------------
    # construction helpers
    # ------------------------------------------------------------------
    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias=False,
        default_initializer=None,
    ):
        from ...param_attr import ParamAttr
        from ..initializer import Constant, XavierUniform, Normal

        dtype = dtype or self._dtype or framework.get_default_dtype()
        attr = ParamAttr._to_attr(attr)
        init = None
        if attr is not None and attr.initializer is not None:
            init = attr.initializer
        elif default_initializer is not None:
            init = default_initializer
        elif is_bias:
            init = Constant(0.0)
        else:
            init = XavierUniform()
        data = init._init_array([int(s) for s in shape], dtype)
        name = attr.name if attr is not None and attr.name else None
        p = Parameter(data, trainable=True, name=name)
        if attr is not None:
            if attr.learning_rate != 1.0:
                p.optimize_attr["learning_rate"] = attr.learning_rate
            p.regularizer = attr.regularizer
            if not attr.trainable:
                p.trainable = False
            p.need_clip = attr.need_clip
        return p

    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names_set.add(name)
        return tensor

    # ------------------------------------------------------------------
    # attribute magic
    # ------------------------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning params")
            params[name] = value
            buffers.pop(name, None) if buffers else None
            layers.pop(name, None) if layers else None
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning layers")
            layers[name] = value
            params.pop(name, None) if params else None
        elif params is not None and name in params:
            if value is None:
                params[name] = None
            else:
                raise TypeError(f"cannot assign non-Parameter to parameter {name}")
        elif buffers is not None and name in buffers:
            buffers[name] = value
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        # only called when normal lookup fails
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'"
        )

    def __delattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        extra = (
            list(self._parameters) + list(self._buffers) + list(self._sub_layers)
        )
        return sorted(set(super().__dir__() + extra))

    # ------------------------------------------------------------------
    # traversal
    # ------------------------------------------------------------------
    def children(self):
        for _, l in self.named_children():
            yield l

    def named_children(self):
        seen = set()
        for name, l in self._sub_layers.items():
            if l is not None and id(l) not in seen:
                seen.add(id(l))
                yield name, l

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        if layers_set is None:
            layers_set = set()
        if include_self and id(self) not in layers_set:
            layers_set.add(id(self))
            yield prefix, self
        for name, l in self.named_children():
            if l is None or id(l) in layers_set:
                continue
            layers_set.add(id(l))
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield sub_prefix, l
            yield from l.named_sublayers(
                prefix=sub_prefix, include_self=False, layers_set=layers_set
            )

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        layers = (
            [(prefix, self)]
            + [
                (f"{prefix}.{n}" if prefix else n, l)
                for n, l in self.named_sublayers()
            ]
            if include_sublayers
            else [(prefix, self)]
        )
        for lp, layer in layers:
            for name, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{lp}.{name}" if lp else name), p

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        layers = (
            [(prefix, self)]
            + [
                (f"{prefix}.{n}" if prefix else n, l)
                for n, l in self.named_sublayers()
            ]
            if include_sublayers
            else [(prefix, self)]
        )
        for lp, layer in layers:
            for name, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{lp}.{name}" if lp else name), b

    def apply(self, fn):
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    def full_name(self):
        return self._full_name

    # ------------------------------------------------------------------
    # modes
    # ------------------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    # ------------------------------------------------------------------
    # forward plumbing
    # ------------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        # remember the OUTERMOST call's tensor signature so
        # jit.save(input_spec=None) can re-trace the layer (reference records
        # via SOT capture); sublayer calls only pay a depth counter
        if _CALL_DEPTH[0] == 0:
            spec = tuple(
                (tuple(t.shape), str(t._data.dtype))
                for t in inputs if hasattr(t, "_data")
            )
            if spec and len(spec) == len(inputs):
                object.__setattr__(self, "_last_call_spec", spec)
        _CALL_DEPTH[0] += 1
        try:
            outputs = self.forward(*inputs, **kwargs)
        finally:
            _CALL_DEPTH[0] -= 1
        for hook in self._forward_post_hooks.values():
            o = hook(self, inputs, outputs)
            if o is not None:
                outputs = o
        return outputs

    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # ------------------------------------------------------------------
    # state dict
    # ------------------------------------------------------------------
    def state_dict(
        self, destination=None, include_sublayers=True, structured_name_prefix="",
        use_hook=True,
    ):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(
            prefix=structured_name_prefix.rstrip("."),
            include_sublayers=include_sublayers,
        ):
            dest[name] = p
        for name, b in self.named_buffers(
            prefix=structured_name_prefix.rstrip("."),
            include_sublayers=include_sublayers,
        ):
            if name.split(".")[-1] not in self._non_persistable_buffer_names_set:
                dest[name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        missing, unexpected = [], []
        own = self.state_dict()
        matched = set()
        for name, t in own.items():
            if name in state_dict:
                value = state_dict[name]
                arr = value.numpy() if isinstance(value, Tensor) else np.asarray(value)
                t.set_value(arr)
                matched.add(name)
            else:
                missing.append(name)
        for k in state_dict:
            if k not in matched and k not in own:
                unexpected.append(k)
        return missing, unexpected

    load_dict = set_state_dict
    set_dict = set_state_dict

    # ------------------------------------------------------------------
    # dtype / device movement
    # ------------------------------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        return self._to_impl(device=device, dtype=dtype)

    def _to_impl(self, device=None, dtype=None):
        from ... import dtypes as _dt

        if dtype is not None:
            npd = _dt.to_np(dtype)
            for p in self.parameters():
                if p.dtype.is_floating_point:
                    p._data = p._data.astype(npd)
            for b in self.buffers():
                if b is not None and b.dtype.is_floating_point:
                    b._data = b._data.astype(npd)
            self._dtype = _dt.convert_dtype(dtype).name
        return self

    def astype(self, dtype):
        return self._to_impl(dtype=dtype)

    def float(self):
        return self._to_impl(dtype="float32")

    def half(self):
        return self._to_impl(dtype="float16")

    def bfloat16(self):
        return self._to_impl(dtype="bfloat16")

    # ------------------------------------------------------------------
    # functional parameter swap (jit seam)
    # ------------------------------------------------------------------
    @contextlib.contextmanager
    def _swap_state(self, flat_state: dict):
        """Temporarily replace named params/buffers' payloads with `flat_state`
        values (jax arrays/tracers). Restores on exit. Yields a dict that will
        be filled with the post-forward buffer payloads (mutated state)."""
        saved = {}
        entries = dict(self.state_dict())
        for name, arr in flat_state.items():
            t = entries[name]
            saved[name] = t._data
            t._data = arr
        mutated = {}
        try:
            yield mutated
        finally:
            for name, old in saved.items():
                t = entries[name]
                mutated[name] = t._data
                t._data = old

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, l in self.named_children():
            mod_str = repr(l)
            mod_str = "\n".join(
                ["  " + line for line in mod_str.split("\n")]
            )
            lines.append(f"  ({name}): {mod_str.strip()}")
        main = self.__class__.__name__ + "("
        if extra and not lines:
            return main + extra + ")"
        if lines:
            return main + (extra + "\n" if extra else "\n") + "\n".join(lines) + "\n)"
        return main + ")"

    def extra_repr(self):
        return ""
