"""RNN layers over lax.scan (parity: python/paddle/nn/layer/rnn.py).

The reference's cuDNN RNN kernels (``phi/kernels/gpudnn/rnn_kernel``) map on
TPU to a ``lax.scan`` over fused per-step matmuls — XLA pipelines the scan so
the MXU stays busy across time steps.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op
from ..initializer import Uniform
from .. import functional as F
from .layers import Layer
from .container import LayerList


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None, init_value=0.0, batch_dim_idx=0):
        import paddle_tpu as paddle

        b = batch_ref.shape[batch_dim_idx]
        shape = shape or self.state_shape
        if isinstance(shape[0], (list, tuple)):
            return tuple(
                paddle.full([b] + list(s), init_value, dtype or "float32") for s in shape
            )
        return paddle.full([b] + list(shape), init_value, dtype or "float32")


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh", weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter([hidden_size, input_size], weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter([hidden_size, hidden_size], weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter([hidden_size], bias_ih_attr, is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter([hidden_size], bias_hh_attr, is_bias=True, default_initializer=init)
        self.hidden_size = hidden_size
        self.input_size = input_size
        self.activation = activation

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def _cell(x, h, wi, wh, bi, bh):
            return act(x @ wi.T + bi + h @ wh.T + bh)

        h = apply_op(_cell, inputs, states, self.weight_ih, self.weight_hh,
                     self.bias_ih, self.bias_hh, _op_name="simple_rnn_cell")
        return h, h


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, proj_size=None, name=None):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter([4 * hidden_size, input_size], weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter([4 * hidden_size, hidden_size], weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter([4 * hidden_size], bias_ih_attr, is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter([4 * hidden_size], bias_hh_attr, is_bias=True, default_initializer=init)
        self.hidden_size = hidden_size
        self.input_size = input_size

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs, self.state_shape)
        h, c = states

        def _cell(x, h, c, wi, wh, bi, bh):
            gates = x @ wi.T + bi + h @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            new_c = f * c + i * g
            new_h = o * jnp.tanh(new_c)
            return new_h, new_c

        new_h, new_c = apply_op(
            _cell, inputs, h, c, self.weight_ih, self.weight_hh,
            self.bias_ih, self.bias_hh, _op_name="lstm_cell",
        )
        return new_h, (new_h, new_c)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter([3 * hidden_size, input_size], weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter([3 * hidden_size, hidden_size], weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter([3 * hidden_size], bias_ih_attr, is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter([3 * hidden_size], bias_hh_attr, is_bias=True, default_initializer=init)
        self.hidden_size = hidden_size
        self.input_size = input_size

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def _cell(x, h, wi, wh, bi, bh):
            gi = x @ wi.T + bi
            gh = h @ wh.T + bh
            i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
            h_r, h_z, h_n = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(i_r + h_r)
            z = jax.nn.sigmoid(i_z + h_z)
            n = jnp.tanh(i_n + r * h_n)
            return (1 - z) * n + z * h

        new_h = apply_op(_cell, inputs, states, self.weight_ih, self.weight_hh,
                         self.bias_ih, self.bias_hh, _op_name="gru_cell")
        return new_h, new_h


class RNN(Layer):
    """Runs a cell over time (parity: paddle.nn.RNN wrapper)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        import paddle_tpu as paddle

        time_axis = 0 if self.time_major else 1
        steps = inputs.shape[time_axis]
        xs = paddle.unbind(inputs, axis=time_axis)
        if self.is_reverse:
            xs = xs[::-1]
        states = initial_states
        outs = []
        for x in xs:
            out, states = self.cell(x, states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        outputs = paddle.stack(outs, axis=time_axis)
        return outputs, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        import paddle_tpu as paddle

        s_fw, s_bw = initial_states if initial_states is not None else (None, None)
        out_fw, st_fw = self.rnn_fw(inputs, s_fw)
        out_bw, st_bw = self.rnn_bw(inputs, s_bw)
        return paddle.concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)


class _RNNBase(Layer):
    """Multi-layer (bi)directional recurrent net driven by lax.scan."""

    CELL = None

    def __init__(self, mode, input_size, hidden_size, num_layers=1, direction="forward", time_major=False, dropout=0.0, weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirect = direction in ("bidirect", "bidirectional")
        num_dir = 2 if self.bidirect else 1
        cell_cls = {"LSTM": LSTMCell, "GRU": GRUCell, "RNN_TANH": SimpleRNNCell, "RNN_RELU": SimpleRNNCell}[mode]
        layers = []
        for l in range(num_layers):
            in_sz = input_size if l == 0 else hidden_size * num_dir
            kwargs = {}
            if mode == "RNN_RELU":
                kwargs["activation"] = "relu"
            fw = cell_cls(in_sz, hidden_size, weight_ih_attr, weight_hh_attr, bias_ih_attr, bias_hh_attr, **kwargs)
            if self.bidirect:
                bw = cell_cls(in_sz, hidden_size, weight_ih_attr, weight_hh_attr, bias_ih_attr, bias_hh_attr, **kwargs)
                layers.append(BiRNN(fw, bw, time_major))
            else:
                layers.append(RNN(fw, False, time_major))
        self.layer_list = LayerList(layers)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        import paddle_tpu as paddle

        out = inputs
        final_states = []
        for i, rnn_l in enumerate(self.layer_list):
            st = None if initial_states is None else initial_states
            out, st_out = rnn_l(out, None)
            final_states.append(st_out)
            if self.dropout > 0 and i < self.num_layers - 1:
                out = F.dropout(out, self.dropout, training=self.training)
        # stack final states across layers(+directions)
        if self.mode == "LSTM":
            if self.bidirect:
                hs, cs = [], []
                for st_fw, st_bw in final_states:
                    hs += [st_fw[0], st_bw[0]]
                    cs += [st_fw[1], st_bw[1]]
            else:
                hs = [s[0] for s in final_states]
                cs = [s[1] for s in final_states]
            state = (paddle.stack(hs, axis=0), paddle.stack(cs, axis=0))
        else:
            if self.bidirect:
                hs = []
                for st_fw, st_bw in final_states:
                    hs += [st_fw, st_bw]
            else:
                hs = list(final_states)
            state = paddle.stack(hs, axis=0)
        return out, state


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward", time_major=False, dropout=0.0, activation="tanh", **kw):
        mode = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(mode, input_size, hidden_size, num_layers, direction, time_major, dropout, **kw)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward", time_major=False, dropout=0.0, **kw):
        super().__init__("LSTM", input_size, hidden_size, num_layers, direction, time_major, dropout, **kw)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward", time_major=False, dropout=0.0, **kw):
        super().__init__("GRU", input_size, hidden_size, num_layers, direction, time_major, dropout, **kw)
