"""paddle.nn.utils (parity: python/paddle/nn/utils) — weight
reparameterizations and parameter<->vector helpers."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ...core.tensor import Tensor
from ..clip import clip_grad_norm_  # noqa: F401

__all__ = [
    "weight_norm", "remove_weight_norm", "spectral_norm",
    "parameters_to_vector", "vector_to_parameters", "clip_grad_norm_",
    "clip_grad_value_",
]


def _norm_except(v, dim):
    axes = tuple(i for i in range(v.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(jnp.square(v.astype(jnp.float32)), axis=axes,
                            keepdims=True))


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize `name` as g * v/||v|| (parity: utils/weight_norm.py).

    The decomposition recomputes the weight from (weight_g, weight_v)
    before every forward via a pre-hook, so the optimizer trains g and v.
    """
    w = getattr(layer, name)
    dim = dim if dim is not None else 0
    dim = dim % w.ndim
    v0 = w._data
    g0 = _norm_except(v0, dim)
    g = layer.create_parameter(list(g0.shape), dtype=str(np.dtype(
        np.float32)))
    v = layer.create_parameter(list(v0.shape), dtype=str(w.numpy().dtype))
    g._data = g0.astype(v0.dtype)
    v._data = v0
    layer.add_parameter(name + "_g", g)
    layer.add_parameter(name + "_v", v)
    # the original weight becomes derived state, not a trainable Parameter
    if name in layer._parameters:
        del layer._parameters[name]

    def _recompute(lay, inputs):
        vv = getattr(lay, name + "_v")._data
        gg = getattr(lay, name + "_g")._data
        w_new = vv / jnp.maximum(_norm_except(vv, dim), 1e-12).astype(
            vv.dtype) * gg
        object.__setattr__(lay, name, Tensor(w_new.astype(vv.dtype)))
        return inputs

    handle = layer.register_forward_pre_hook(_recompute)
    layer._weight_norm_hook = handle
    layer._weight_norm_cfg = (name, dim)
    _recompute(layer, None)
    return layer


def remove_weight_norm(layer, name="weight"):
    """Fold (g, v) back into a plain trainable weight."""
    if not hasattr(layer, "_weight_norm_hook"):
        raise ValueError(f"layer has no weight_norm on {name!r}")
    nm, dim = layer._weight_norm_cfg
    vv = getattr(layer, nm + "_v")._data
    gg = getattr(layer, nm + "_g")._data
    w = vv / jnp.maximum(_norm_except(vv, dim), 1e-12).astype(vv.dtype) * gg
    layer._weight_norm_hook.remove()
    del layer._parameters[nm + "_g"]
    del layer._parameters[nm + "_v"]
    p = layer.create_parameter(list(w.shape), dtype=str(np.asarray(vv).dtype))
    p._data = w.astype(vv.dtype)
    layer.add_parameter(nm, p)
    del layer._weight_norm_hook, layer._weight_norm_cfg
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=0):
    """Spectral normalization via power iteration (utils/spectral_norm_hook).

    W / sigma(W) recomputed before each forward; u/v vectors persist as
    buffers and refine every call."""
    w = getattr(layer, name)
    dim = dim % w.ndim
    mat0 = jnp.moveaxis(w._data, dim, 0).reshape(w.shape[dim], -1)
    rng = np.random.default_rng(0)
    u0 = rng.standard_normal(mat0.shape[0]).astype(np.float32)
    v0 = rng.standard_normal(mat0.shape[1]).astype(np.float32)
    layer.register_buffer(name + "_u", Tensor(jnp.asarray(
        u0 / np.linalg.norm(u0))))
    layer.register_buffer(name + "_v", Tensor(jnp.asarray(
        v0 / np.linalg.norm(v0))))
    orig = layer._parameters.pop(name)
    layer.add_parameter(name + "_orig", orig)

    def _recompute(lay, inputs):
        wv = getattr(lay, name + "_orig")._data
        mat = jnp.moveaxis(wv, dim, 0).reshape(wv.shape[dim], -1).astype(
            jnp.float32)
        u = getattr(lay, name + "_u")._data
        v = getattr(lay, name + "_v")._data
        for _ in range(n_power_iterations):
            v = mat.T @ u
            v = v / jnp.maximum(jnp.linalg.norm(v), eps)
            u = mat @ v
            u = u / jnp.maximum(jnp.linalg.norm(u), eps)
        sigma = u @ mat @ v
        getattr(lay, name + "_u")._data = u
        getattr(lay, name + "_v")._data = v
        object.__setattr__(lay, name,
                           Tensor((wv / sigma.astype(wv.dtype))))
        return inputs

    layer.register_forward_pre_hook(_recompute)
    _recompute(layer, None)
    return layer


def parameters_to_vector(parameters, name=None):
    arrs = [jnp.reshape(p._data, (-1,)) for p in parameters]
    return Tensor(jnp.concatenate(arrs))


def vector_to_parameters(vec, parameters, name=None):
    arr = vec._data if isinstance(vec, Tensor) else jnp.asarray(vec)
    off = 0
    for p in parameters:
        n = int(np.prod(p.shape)) if p.shape else 1
        p._data = jnp.reshape(arr[off:off + n], p.shape).astype(p._data.dtype)
        off += n


def clip_grad_value_(parameters, clip_value):
    """In-place clamp of every gradient to [-clip_value, clip_value]."""
    clip_value = float(clip_value)
    for p in parameters:
        if p.grad is not None:
            p.grad._data = jnp.clip(p.grad._data, -clip_value, clip_value)
