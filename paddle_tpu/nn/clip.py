"""Gradient clipping (parity: python/paddle/nn/clip.py)."""
from __future__ import annotations

import jax.numpy as jnp

import paddle_tpu as _p


class ClipGradBase:
    def _dygraph_clip(self, params_grads):
        raise NotImplementedError

    def __call__(self, params_grads):
        return self._dygraph_clip(params_grads)


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, g.clip(self.min, self.max)))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            norm = g.norm()
            scale = _p.clip(
                _p.full([], self.clip_norm, g.dtype) / _p.maximum(norm, _p.full([], self.clip_norm, g.dtype)),
                max=1.0,
            )
            out.append((p, g * scale))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    """Global-norm clip; under hybrid parallel the norm reduction spans all
    model-parallel shards (see distributed.fleet HybridParallelOptimizer)."""

    def __init__(self, clip_norm, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = float(clip_norm)

    def _dygraph_clip(self, params_grads):
        sq = None
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                continue
            s = (g.astype("float32") ** 2).sum()
            sq = s if sq is None else sq + s
        if sq is None:
            return params_grads
        global_norm = sq.sqrt()
        clip_t = _p.full([], self.clip_norm, "float32")
        scale = clip_t / _p.maximum(global_norm, clip_t)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, (g.astype("float32") * scale).astype(g.dtype)))
        return out


def global_grad_norm(parameters, norm_type=2.0):
    """Total gradient norm over `parameters` (Layer or iterable) WITHOUT
    mutating any grad — the single reduction `clip_grad_norm_` scales by
    and `resilience.StepGuard`'s eager path reads, exposed so callers
    never pay a second pass over the grad tree."""
    if hasattr(parameters, "parameters"):
        parameters = parameters.parameters()
    params = [p for p in parameters if p.grad is not None]
    if not params:
        return _p.zeros([])
    if norm_type == float("inf"):
        total = params[0].grad.abs().max()
        for p in params[1:]:
            total = _p.maximum(total, p.grad.abs().max())
        return total
    sq = None
    for p in params:
        s = (p.grad.astype("float32").abs() ** norm_type).sum()
        sq = s if sq is None else sq + s
    return sq ** (1.0 / norm_type)


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    import math

    if hasattr(parameters, "parameters"):
        parameters = parameters.parameters()
    params = [p for p in parameters if p.grad is not None]
    if not params:
        return _p.zeros([])
    total = global_grad_norm(params, norm_type)
    total_f = float(total.item())
    if not math.isfinite(total_f):
        if error_if_nonfinite:
            raise RuntimeError(
                f"the total norm of order {norm_type} for the gradients "
                f"is non-finite ({total_f}), so it cannot be clipped. "
                "Pass error_if_nonfinite=False to return the norm "
                "without clipping (grads left untouched)")
        # a nonfinite norm must never reach the scale factor:
        # max_norm/inf would silently ZERO every grad and max_norm/nan
        # would NaN-poison them — leave the grads unscaled instead
        return total
    clip_coef = float(max_norm) / (total_f + 1e-6)
    if clip_coef < 1.0:
        for p in params:
            p.grad._data = (p.grad._data * clip_coef).astype(p.grad._data.dtype)
    return total


def clip_grad_value_(parameters, clip_value):
    if hasattr(parameters, "parameters"):
        parameters = parameters.parameters()
    for p in parameters:
        if p.grad is not None:
            p.grad._data = jnp.clip(p.grad._data, -clip_value, clip_value)
