"""Convolutions over jax.lax.conv_general_dilated.

The reference dispatches conv to cuDNN (``phi/kernels/gpudnn``); on TPU a
single ``conv_general_dilated`` HLO maps the whole conv onto the MXU, with
layout chosen by XLA — no manual algorithm search needed.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op


def _tuple_n(v, n):
    if isinstance(v, (list, tuple)):
        if len(v) == n:
            return tuple(int(i) for i in v)
        if len(v) == 1:
            return tuple(int(v[0]) for _ in range(n))
        return tuple(int(i) for i in v)
    return tuple(int(v) for _ in range(n))


def _padding_n(padding, n):
    """Normalize paddle padding spec → lax [(lo, hi)] per spatial dim."""
    if isinstance(padding, str):
        return padding.upper()  # "SAME" / "VALID"
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    if all(isinstance(p, (list, tuple)) for p in padding):
        # full-rank form [[0,0],[0,0],[lo,hi],...]
        sp = [p for p in padding if list(p) != [0, 0]]
        if len(sp) == n:
            return [tuple(p) for p in sp]
        return [tuple(p) for p in padding[-n:]]
    return [(int(p), int(p)) for p in padding]


def _conv_nd(
    x, weight, bias, stride, padding, dilation, groups, n, channel_last, op_name
):
    strides = _tuple_n(stride, n)
    dilations = _tuple_n(dilation, n)
    pad = _padding_n(padding, n)

    spatial = "DHW"[-n:] if n <= 3 else None
    if channel_last:
        lhs_spec = "N" + spatial + "C"
    else:
        lhs_spec = "NC" + spatial
    rhs_spec = "OI" + spatial
    out_spec = lhs_spec
    dn = jax.lax.conv_dimension_numbers(
        (1,) * (n + 2), (1,) * (n + 2), (lhs_spec, rhs_spec, out_spec)
    )

    def _conv(a, w, b):
        out = jax.lax.conv_general_dilated(
            a,
            w.astype(a.dtype),
            window_strides=strides,
            padding=pad,
            rhs_dilation=dilations,
            dimension_numbers=dn,
            feature_group_count=groups,
        )
        if b is not None:
            shape = [1] * out.ndim
            ch_axis = out.ndim - 1 if channel_last else 1
            shape[ch_axis] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    return apply_op(_conv, x, weight, bias, _op_name=op_name)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCL", name=None):
    return _conv_nd(
        x, weight, bias, stride, padding, dilation, groups, 1,
        data_format in ("NLC",), "conv1d",
    )


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCHW", name=None):
    return _conv_nd(
        x, weight, bias, stride, padding, dilation, groups, 2,
        data_format == "NHWC", "conv2d",
    )


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCDHW", name=None):
    return _conv_nd(
        x, weight, bias, stride, padding, dilation, groups, 3,
        data_format == "NDHWC", "conv3d",
    )


def _conv_transpose_nd(
    x, weight, bias, stride, padding, output_padding, dilation, groups, n,
    channel_last, output_size, op_name,
):
    strides = _tuple_n(stride, n)
    dilations = _tuple_n(dilation, n)
    pad = _padding_n(padding, n)
    out_pad = _tuple_n(output_padding, n) if output_padding is not None else (0,) * n

    spatial = "DHW"[-n:]
    lhs_spec = ("N" + spatial + "C") if channel_last else ("NC" + spatial)
    # paddle transpose-conv weight layout: [in, out//groups, *k]
    rhs_spec = "IO" + spatial
    dn = jax.lax.conv_dimension_numbers(
        (1,) * (n + 2), (1,) * (n + 2), (lhs_spec, rhs_spec, lhs_spec)
    )

    def _convt(a, w, b):
        # transposed conv = gradient-of-conv: the kernel runs spatially
        # FLIPPED (lax.conv_transpose does not flip by default; without this
        # only symmetric kernels match the reference)
        w = jnp.flip(w, axis=tuple(range(2, 2 + n)))
        if isinstance(pad, str):
            lax_pad = pad
        else:
            # grad-of-conv padding transformation
            k = [
                (w.shape[2 + i] - 1) * dilations[i] + 1 for i in range(n)
            ]
            lax_pad = [
                (k[i] - 1 - pad[i][0], k[i] - 1 - pad[i][1] + out_pad[i])
                for i in range(n)
            ]
        if groups > 1:
            # lax transpose conv with groups: split manually
            a_groups = jnp.split(a, groups, axis=-1 if channel_last else 1)
            w_groups = jnp.split(w, groups, axis=0)
            outs = [
                jax.lax.conv_transpose(
                    ag, wg.astype(a.dtype), strides=strides, padding=lax_pad,
                    rhs_dilation=dilations, dimension_numbers=dn,
                )
                for ag, wg in zip(a_groups, w_groups)
            ]
            out = jnp.concatenate(outs, axis=-1 if channel_last else 1)
        else:
            out = jax.lax.conv_transpose(
                a, w.astype(a.dtype), strides=strides, padding=lax_pad,
                rhs_dilation=dilations, dimension_numbers=dn,
            )
        if b is not None:
            shape = [1] * out.ndim
            ch_axis = out.ndim - 1 if channel_last else 1
            shape[ch_axis] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    return apply_op(_convt, x, weight, bias, _op_name=op_name)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, output_size=None, data_format="NCL", name=None):
    return _conv_transpose_nd(
        x, weight, bias, stride, padding, output_padding, dilation, groups, 1,
        data_format == "NLC", output_size, "conv1d_transpose",
    )


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, output_size=None, data_format="NCHW", name=None):
    return _conv_transpose_nd(
        x, weight, bias, stride, padding, output_padding, dilation, groups, 2,
        data_format == "NHWC", output_size, "conv2d_transpose",
    )


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose_nd(
        x, weight, bias, stride, padding, output_padding, dilation, groups, 3,
        data_format == "NDHWC", output_size, "conv3d_transpose",
    )
