"""paddle.nn.functional namespace."""
from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import (  # noqa: F401
    conv1d,
    conv2d,
    conv3d,
    conv1d_transpose,
    conv2d_transpose,
    conv3d_transpose,
)
from .pooling import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .flash_attention import (  # noqa: F401
    flash_attention,
    flash_attn_unpadded,
    flashmask_attention,
    scaled_dot_product_attention,
    sdp_kernel,
)
from .fused_cross_entropy import (  # noqa: F401
    chunked_lm_loss_arrays,
    fused_chunked_cross_entropy,
    int8_head_enabled,
    int8_head_gate,
    sharded_lm_loss_arrays,
)

from ...ops.manipulation import pad as _ops_pad  # noqa: F401
from .compat import *  # noqa: F401,F403
from .compat import (  # noqa: F401
    adaptive_log_softmax_with_loss, class_center_sample, dice_loss,
    feature_alpha_dropout, flash_attn_qkvpacked,
    flash_attn_varlen_qkvpacked, fractional_max_pool2d,
    fractional_max_pool3d, gather_tree, hardtanh_, hsigmoid_loss,
    leaky_relu_, lp_pool1d, margin_cross_entropy, max_unpool1d,
    max_unpool2d, max_unpool3d, multi_margin_loss, npair_loss,
    pairwise_distance, rnnt_loss, sequence_mask, sparse_attention,
    temporal_shift, thresholded_relu_, triplet_margin_with_distance_loss)
