"""paddle.nn.functional namespace."""
from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import (  # noqa: F401
    conv1d,
    conv2d,
    conv3d,
    conv1d_transpose,
    conv2d_transpose,
    conv3d_transpose,
)
from .pooling import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .flash_attention import (  # noqa: F401
    flash_attention,
    flashmask_attention,
    scaled_dot_product_attention,
    sdp_kernel,
)

from ...ops.manipulation import pad as _ops_pad  # noqa: F401
