"""Loss functionals (parity: python/paddle/nn/functional/loss.py)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op
from ...core.tensor import Tensor


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(
    input,
    label,
    weight=None,
    ignore_index=-100,
    reduction="mean",
    soft_label=False,
    axis=-1,
    use_softmax=True,
    label_smoothing=0.0,
    name=None,
):
    def _ce(logits, lab, w):
        lp = jax.nn.log_softmax(logits, axis=axis) if use_softmax else jnp.log(
            jnp.maximum(logits, 1e-30)
        )
        n_classes = logits.shape[axis]
        if soft_label:
            soft = lab
        else:
            lab_int = lab
            if lab_int.ndim == lp.ndim:  # [..., 1] form
                lab_int = jnp.squeeze(lab_int, axis)
            soft = jax.nn.one_hot(lab_int, n_classes, dtype=lp.dtype, axis=axis)
        if label_smoothing > 0.0:
            soft = soft * (1 - label_smoothing) + label_smoothing / n_classes
        loss = -jnp.sum(soft * lp, axis=axis)
        if w is not None and not soft_label:
            lab_int = lab if lab.ndim < lp.ndim else jnp.squeeze(lab, axis)
            loss = loss * jnp.take(w, jnp.clip(lab_int, 0, n_classes - 1))
        if not soft_label and ignore_index >= 0:
            lab_int = lab if lab.ndim < lp.ndim else jnp.squeeze(lab, axis)
            mask = lab_int != ignore_index
            loss = jnp.where(mask, loss, 0.0)
            if reduction == "mean":
                denom = jnp.maximum(jnp.sum(mask.astype(loss.dtype)), 1.0)
                return jnp.sum(loss) / denom
        return _reduce(loss, reduction)

    return apply_op(_ce, input, label, weight, _op_name="cross_entropy")


def softmax_with_cross_entropy(
    logits, label, soft_label=False, ignore_index=-100, numeric_stable_mode=True,
    return_softmax=False, axis=-1,
):
    loss = cross_entropy(
        logits, label, soft_label=soft_label, ignore_index=ignore_index,
        reduction="none", axis=axis,
    )
    from .activation import softmax as _softmax

    loss = loss.unsqueeze(axis) if loss.ndim < logits.ndim else loss
    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


def mse_loss(input, label, reduction="mean", name=None):
    return apply_op(
        lambda a, b: _reduce(jnp.square(a - b), reduction), input, label,
        _op_name="mse_loss",
    )


def l1_loss(input, label, reduction="mean", name=None):
    return apply_op(
        lambda a, b: _reduce(jnp.abs(a - b), reduction), input, label,
        _op_name="l1_loss",
    )


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def _sl1(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta) * delta
        # paddle huber: 0.5*d^2 if d<delta else delta*(d-0.5*delta)
        loss = jnp.where(d < delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(loss, reduction)

    return apply_op(_sl1, input, label, _op_name="smooth_l1_loss")


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    def _nll(lp, lab, w):
        n_classes = lp.shape[1]
        lab_c = jnp.clip(lab, 0, n_classes - 1)
        picked = -jnp.take_along_axis(lp, lab_c[:, None] if lp.ndim == 2 else jnp.expand_dims(lab_c, 1), axis=1)
        picked = jnp.squeeze(picked, 1)
        wt = jnp.ones_like(picked) if w is None else jnp.take(w, lab_c)
        mask = (lab != ignore_index).astype(picked.dtype)
        picked = picked * wt * mask
        if reduction == "mean":
            return jnp.sum(picked) / jnp.maximum(jnp.sum(wt * mask), 1e-12)
        return _reduce(picked, reduction)

    return apply_op(_nll, input, label, weight, _op_name="nll_loss")


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def _bce(p, y, w):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)

    return apply_op(_bce, input, label, weight, _op_name="binary_cross_entropy")


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean", pos_weight=None, name=None):
    def _bcel(z, y, w, pw):
        if pw is not None:
            log_w = (pw - 1) * y + 1
            loss = (1 - y) * z + log_w * (jnp.logaddexp(0.0, -jnp.abs(z)) + jnp.maximum(-z, 0.0))
        else:
            loss = jnp.maximum(z, 0.0) - z * y + jnp.logaddexp(0.0, -jnp.abs(z))
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)

    return apply_op(_bcel, logit, label, weight, pos_weight, _op_name="bce_with_logits")


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def _kl(lp, t):
        if log_target:
            loss = jnp.exp(t) * (t - lp)
        else:
            loss = jnp.where(t > 0, t * (jnp.log(jnp.maximum(t, 1e-30)) - lp), 0.0)
        if reduction == "batchmean":
            return jnp.sum(loss) / lp.shape[0]
        return _reduce(loss, reduction)

    return apply_op(_kl, input, label, _op_name="kl_div")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    return apply_op(
        lambda a, b, y: _reduce(jnp.maximum(0.0, -y * (a - b) + margin), reduction),
        input, other, label, _op_name="margin_ranking_loss",
    )


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    return apply_op(
        lambda a, y: _reduce(
            jnp.where(y == 1.0, a, jnp.maximum(0.0, margin - a)), reduction
        ),
        input, label, _op_name="hinge_embedding_loss",
    )


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    def _cel(a, b, y):
        cos = jnp.sum(a * b, -1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12
        )
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)

    return apply_op(_cel, input1, input2, label, _op_name="cosine_embedding_loss")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6, swap=False, reduction="mean", name=None):
    def _tml(a, pos, neg):
        dp = jnp.linalg.norm(a - pos + epsilon, ord=p, axis=-1)
        dn = jnp.linalg.norm(a - neg + epsilon, ord=p, axis=-1)
        if swap:
            dn2 = jnp.linalg.norm(pos - neg + epsilon, ord=p, axis=-1)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(0.0, dp - dn + margin), reduction)

    return apply_op(_tml, input, positive, negative, _op_name="triplet_margin_loss")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0, reduction="sum", name=None):
    def _focal(z, y, nz):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0.0) - z * y + jnp.logaddexp(0.0, -jnp.abs(z))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if nz is not None:
            loss = loss / nz
        return _reduce(loss, reduction)

    return apply_op(_focal, logit, label, normalizer, _op_name="sigmoid_focal_loss")


def square_error_cost(input, label):
    return apply_op(lambda a, b: jnp.square(a - b), input, label, _op_name="square_error_cost")


def log_loss(input, label, epsilon=1e-4, name=None):
    return apply_op(
        lambda p, y: -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon),
        input, label, _op_name="log_loss",
    )


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0, reduction="mean", norm_by_times=False):
    """CTC via dynamic-program in log space (lax.scan over time)."""

    def _ctc(lp, lab, in_len, lab_len):
        # lp: [T, B, C] log-probs (paddle feeds logits; apply log_softmax)
        lp = jax.nn.log_softmax(lp, axis=-1)
        T, B, C = lp.shape
        L = lab.shape[1]
        S = 2 * L + 1
        NEG = -1e30
        # extended labels with blanks: [B, S]
        ext = jnp.full((B, S), blank, dtype=lab.dtype)
        ext = ext.at[:, 1::2].set(lab)
        # init alpha at t=0
        alpha0 = jnp.full((B, S), NEG)
        alpha0 = alpha0.at[:, 0].set(lp[0, :, blank])
        alpha0 = alpha0.at[:, 1].set(
            jnp.take_along_axis(lp[0], ext[:, 1:2], axis=1)[:, 0]
        )

        same_as_prev2 = jnp.concatenate(
            [jnp.ones((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1
        )

        def step(alpha, lp_t):
            a_prev = alpha
            a_shift1 = jnp.concatenate([jnp.full((B, 1), NEG), alpha[:, :-1]], 1)
            a_shift2 = jnp.concatenate([jnp.full((B, 2), NEG), alpha[:, :-2]], 1)
            a_shift2 = jnp.where(same_as_prev2, NEG, a_shift2)
            combined = jnp.logaddexp(jnp.logaddexp(a_prev, a_shift1), a_shift2)
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            new_alpha = combined + emit
            return new_alpha, None

        alpha_T, _ = jax.lax.scan(step, alpha0, lp[1:])
        # pick final positions: S-1 and S-2 depend on label_length
        last = 2 * lab_len  # index of final blank
        idx1 = jnp.clip(last, 0, S - 1)[:, None]
        idx2 = jnp.clip(last - 1, 0, S - 1)[:, None]
        ll = jnp.logaddexp(
            jnp.take_along_axis(alpha_T, idx1, 1)[:, 0],
            jnp.take_along_axis(alpha_T, idx2, 1)[:, 0],
        )
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lab_len.astype(loss.dtype), 1.0))
        return _reduce(loss, reduction)

    return apply_op(_ctc, log_probs, labels, input_lengths, label_lengths, _op_name="ctc_loss")


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8, reduction="mean", name=None):
    def _pnll(a, y):
        if log_input:
            loss = jnp.exp(a) - y * a
        else:
            loss = a - y * jnp.log(a + epsilon)
        if full:
            stirling = y * jnp.log(y + epsilon) - y + 0.5 * jnp.log(2 * jnp.pi * (y + epsilon))
            loss = loss + jnp.where(y > 1, stirling, 0.0)
        return _reduce(loss, reduction)

    return apply_op(_pnll, input, label, _op_name="poisson_nll_loss")


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6, reduction="mean", name=None):
    def _gnll(mu, y, var):
        var = jnp.maximum(var, epsilon)
        loss = 0.5 * (jnp.log(var) + jnp.square(y - mu) / var)
        if full:
            loss = loss + 0.5 * jnp.log(2 * jnp.pi)
        return _reduce(loss, reduction)

    return apply_op(_gnll, input, label, variance, _op_name="gaussian_nll_loss")


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean", name=None):
    def _ml(z, y, w):
        loss = -(y * jax.nn.log_sigmoid(z) + (1 - y) * jax.nn.log_sigmoid(-z))
        if w is not None:
            loss = loss * w
        return _reduce(jnp.mean(loss, axis=-1), reduction)

    return apply_op(_ml, input, label, weight, _op_name="multi_label_soft_margin_loss")


def soft_margin_loss(input, label, reduction="mean", name=None):
    return apply_op(
        lambda z, y: _reduce(jnp.log1p(jnp.exp(-y * z)), reduction),
        input, label, _op_name="soft_margin_loss",
    )


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None, name=None):
    """Levenshtein distance between token sequences (parity:
    nn/functional/loss.py edit_distance). Host-side DP — a metric over
    int sequences, not a differentiable op. Returns (distances [B, 1]
    float32, sequence_num [1] int64)."""
    import numpy as np

    from ...core.tensor import Tensor as _T

    a = np.asarray(input.numpy() if hasattr(input, "numpy") else input)
    b = np.asarray(label.numpy() if hasattr(label, "numpy") else label)
    a_len = (np.asarray(input_length.numpy()).reshape(-1)
             if input_length is not None else
             np.full((a.shape[0],), a.shape[1], np.int64))
    b_len = (np.asarray(label_length.numpy()).reshape(-1)
             if label_length is not None else
             np.full((b.shape[0],), b.shape[1], np.int64))
    ignored = set(ignored_tokens or ())

    def _dist(x, y):
        x = [t for t in x if t not in ignored]
        y = [t for t in y if t not in ignored]
        prev = list(range(len(y) + 1))
        for i, xi in enumerate(x, 1):
            cur = [i] + [0] * len(y)
            for j, yj in enumerate(y, 1):
                cur[j] = min(prev[j] + 1, cur[j - 1] + 1,
                             prev[j - 1] + (xi != yj))
            prev = cur
        return prev[-1], len(y)

    out = np.zeros((a.shape[0], 1), np.float32)
    for r in range(a.shape[0]):
        d, ly = _dist(a[r, :a_len[r]].tolist(), b[r, :b_len[r]].tolist())
        out[r, 0] = d / max(ly, 1) if normalized else d
    import jax.numpy as _jnp

    return (_T(_jnp.asarray(out)),
            _T(_jnp.asarray([a.shape[0]], _jnp.int64)))
