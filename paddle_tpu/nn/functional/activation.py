"""Activation functionals (parity: python/paddle/nn/functional/activation.py)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op
from ...core.tensor import Tensor


def _unary(jfn, name):
    def op(x, name=None):
        return apply_op(jfn, x, _op_name=name)

    op.__name__ = name
    return op


relu = _unary(jax.nn.relu, "relu")
relu6 = _unary(jax.nn.relu6, "relu6")
sigmoid = _unary(jax.nn.sigmoid, "sigmoid")
tanh = _unary(jnp.tanh, "tanh")
silu = _unary(jax.nn.silu, "silu")
mish = _unary(lambda x: x * jnp.tanh(jax.nn.softplus(x)), "mish")
log_sigmoid = _unary(jax.nn.log_sigmoid, "log_sigmoid")
tanhshrink = _unary(lambda x: x - jnp.tanh(x), "tanhshrink")
softsign = _unary(jax.nn.soft_sign, "softsign")


def relu_(x, name=None):
    return x._assign_result_(relu(x))


def tanh_(x, name=None):
    return x._assign_result_(tanh(x))


def gelu(x, approximate=False, name=None):
    return apply_op(
        lambda a: jax.nn.gelu(a, approximate=bool(approximate)), x, _op_name="gelu"
    )


def swish(x, name=None):
    return silu(x)


def hardswish(x, name=None):
    return apply_op(jax.nn.hard_swish, x, _op_name="hardswish")


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply_op(
        lambda a: jnp.clip(slope * a + offset, 0.0, 1.0), x, _op_name="hardsigmoid"
    )


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply_op(lambda a: jnp.clip(a, min, max), x, _op_name="hardtanh")


def hardshrink(x, threshold=0.5, name=None):
    return apply_op(
        lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0).astype(a.dtype),
        x,
        _op_name="hardshrink",
    )


def softshrink(x, threshold=0.5, name=None):
    return apply_op(
        lambda a: jnp.where(
            a > threshold, a - threshold, jnp.where(a < -threshold, a + threshold, 0.0)
        ).astype(a.dtype),
        x,
        _op_name="softshrink",
    )


def elu(x, alpha=1.0, name=None):
    return apply_op(lambda a: jax.nn.elu(a, alpha), x, _op_name="elu")


def elu_(x, alpha=1.0, name=None):
    return x._assign_result_(elu(x, alpha))


def celu(x, alpha=1.0, name=None):
    return apply_op(lambda a: jax.nn.celu(a, alpha), x, _op_name="celu")


def selu(
    x,
    scale=1.0507009873554804934193349852946,
    alpha=1.6732632423543772848170429916717,
    name=None,
):
    return apply_op(
        lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)).astype(a.dtype),
        x,
        _op_name="selu",
    )


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply_op(
        lambda a: jax.nn.leaky_relu(a, negative_slope), x, _op_name="leaky_relu"
    )


def prelu(x, weight, data_format="NCHW", name=None):
    def _prelu(a, w):
        if w.size == 1:
            wb = w.reshape(())
        else:
            shape = [1] * a.ndim
            ch_axis = 1 if data_format[1] == "C" else a.ndim - 1
            shape[ch_axis] = w.size
            wb = w.reshape(shape)
        return jnp.where(a > 0, a, wb * a).astype(a.dtype)

    return apply_op(_prelu, x, weight, _op_name="prelu")


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=False, name=None):
    from ... import framework

    if training:
        key = framework.next_rng_key()

        def _rrelu(a):
            slope = jax.random.uniform(key, a.shape, jnp.float32, lower, upper).astype(a.dtype)
            return jnp.where(a >= 0, a, slope * a)

        return apply_op(_rrelu, x, _op_name="rrelu")
    mid = (lower + upper) / 2.0
    return leaky_relu(x, mid)


def softmax(x, axis=-1, dtype=None, name=None):
    from ... import dtypes as _dt

    def _softmax(a):
        if dtype is not None:
            a = a.astype(_dt.to_np(dtype))
        return jax.nn.softmax(a, axis=axis)

    return apply_op(_softmax, x, _op_name="softmax")


def softmax_(x, axis=-1, dtype=None, name=None):
    return x._assign_result_(softmax(x, axis, dtype))


def log_softmax(x, axis=-1, dtype=None, name=None):
    from ... import dtypes as _dt

    def _lsm(a):
        if dtype is not None:
            a = a.astype(_dt.to_np(dtype))
        return jax.nn.log_softmax(a, axis=axis)

    return apply_op(_lsm, x, _op_name="log_softmax")


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply_op(
        lambda a: jnp.where(
            beta * a > threshold, a, (1.0 / beta) * jnp.log1p(jnp.exp(beta * a))
        ).astype(a.dtype),
        x,
        _op_name="softplus",
    )


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return apply_op(
        lambda a: jnp.where(a > threshold, a, value).astype(a.dtype),
        x,
        _op_name="thresholded_relu",
    )


def maxout(x, groups, axis=1, name=None):
    def _maxout(a):
        ax = axis % a.ndim
        c = a.shape[ax]
        shape = list(a.shape)
        shape[ax : ax + 1] = [groups, c // groups]
        return jnp.max(a.reshape(shape), axis=ax + 1)

    return apply_op(_maxout, x, _op_name="maxout")


def glu(x, axis=-1, name=None):
    def _glu(a):
        a1, a2 = jnp.split(a, 2, axis=axis)
        return a1 * jax.nn.sigmoid(a2)

    return apply_op(_glu, x, _op_name="glu")


def swiglu(x, y=None, name=None):
    if y is None:
        def _swiglu(a):
            a1, a2 = jnp.split(a, 2, axis=-1)
            return jax.nn.silu(a1) * a2

        return apply_op(_swiglu, x, _op_name="swiglu")
    return apply_op(lambda a, b: jax.nn.silu(a) * b, x, y, _op_name="swiglu")


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ... import framework

    key = framework.next_rng_key()

    def _gs(a):
        g = jax.random.gumbel(key, a.shape, a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis, inplace=False)
            # straight-through estimator
            y = y_hard - jax.lax.stop_gradient(y) + y
        return y

    return apply_op(_gs, x, _op_name="gumbel_softmax")
