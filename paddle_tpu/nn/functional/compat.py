"""Long-tail nn.functional surface (parity: the remaining
python/paddle/nn/functional exports)."""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op
from ...core.tensor import Tensor


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    return apply_op(
        lambda a, b: jnp.power(
            jnp.sum(jnp.power(jnp.abs(a - b) + epsilon, p), -1,
                    keepdims=keepdim), 1.0 / p),
        x, y, _op_name="pairwise_distance")


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """Row masks from lengths. When maxlen is None the mask width is
    data-dependent — that needs one host sync and is trace-hostile (raises
    the standard concretization error under jit; pass maxlen to stay
    compiled)."""
    from ... import dtypes as _dt

    if maxlen is None:
        lens = np.asarray(x._data if isinstance(x, Tensor) else x)
        maxlen = int(lens.max())
    jdt = _dt.to_np(dtype)

    def _sm(lens):
        return (jnp.arange(maxlen)[None, :] < lens[..., None]).astype(jdt)

    return apply_op(_sm, x, _op_name="sequence_mask")


def feature_alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    from ... import framework

    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    neg = -alpha * scale

    def _fad(a):
        key = framework.next_rng_key()
        shape = (a.shape[0], a.shape[1]) + (1,) * (a.ndim - 2)
        keep = jax.random.bernoulli(key, 1 - p, shape)
        A = (p + p * (1 - p) * neg ** 2) ** -0.5
        B = -A * p * neg
        return A * jnp.where(keep, a, neg) + B

    return apply_op(_fad, x, _op_name="feature_alpha_dropout")


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCL", name=None):
    stride = stride or kernel_size

    def _lp(a):
        k, s = int(kernel_size), int(stride)
        if padding:
            a = jnp.pad(a, ((0, 0), (0, 0), (padding, padding)))
        n = (a.shape[-1] - k) // s + 1
        idx = jnp.arange(n)[:, None] * s + jnp.arange(k)[None, :]
        windows = a[..., idx]  # [N, C, n, k]
        return jnp.power(jnp.sum(jnp.power(jnp.abs(windows), norm_type), -1),
                         1.0 / norm_type)

    return apply_op(_lp, x, _op_name="lp_pool1d")


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    stride = stride or kernel_size

    def _unpool(a, idx):
        n, c, l = a.shape
        out_l = output_size[-1] if output_size else (l - 1) * stride + kernel_size
        flat = jnp.zeros((n, c, out_l), a.dtype)
        return flat.at[
            jnp.arange(n)[:, None, None], jnp.arange(c)[None, :, None],
            idx.astype(jnp.int32)
        ].set(a)

    return apply_op(_unpool, x, indices, _op_name="max_unpool1d")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    ks = kernel_size if isinstance(kernel_size, (list, tuple)) else (kernel_size,) * 2
    st = stride if isinstance(stride, (list, tuple)) else (
        (stride,) * 2 if stride else ks)

    def _unpool(a, idx):
        n, c, h, w = a.shape
        if output_size:
            oh, ow = output_size[-2], output_size[-1]
        else:
            oh = (h - 1) * st[0] + ks[0]
            ow = (w - 1) * st[1] + ks[1]
        flat = jnp.zeros((n, c, oh * ow), a.dtype)
        flat = flat.at[
            jnp.arange(n)[:, None, None], jnp.arange(c)[None, :, None],
            idx.reshape(n, c, -1).astype(jnp.int32)
        ].set(a.reshape(n, c, -1))
        return flat.reshape(n, c, oh, ow)

    return apply_op(_unpool, x, indices, _op_name="max_unpool2d")


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    ks = kernel_size if isinstance(kernel_size, (list, tuple)) else (kernel_size,) * 3
    st = stride if isinstance(stride, (list, tuple)) else (
        (stride,) * 3 if stride else ks)

    def _unpool(a, idx):
        n, c, d, h, w = a.shape
        if output_size:
            od, oh, ow = output_size[-3:]
        else:
            od = (d - 1) * st[0] + ks[0]
            oh = (h - 1) * st[1] + ks[1]
            ow = (w - 1) * st[2] + ks[2]
        flat = jnp.zeros((n, c, od * oh * ow), a.dtype)
        flat = flat.at[
            jnp.arange(n)[:, None, None], jnp.arange(c)[None, :, None],
            idx.reshape(n, c, -1).astype(jnp.int32)
        ].set(a.reshape(n, c, -1))
        return flat.reshape(n, c, od, oh, ow)

    return apply_op(_unpool, x, indices, _op_name="max_unpool3d")


def fractional_max_pool2d(x, output_size, kernel_size=None,
                          random_u=None, return_mask=False, name=None):
    os_ = output_size if isinstance(output_size, (list, tuple)) else (output_size,) * 2

    def _fmp(a):
        n, c, h, w = a.shape
        oh, ow = os_
        # deterministic pseudo-fractional index sequences (alpha spacing)
        ridx = jnp.floor(jnp.arange(oh) * (h / oh)).astype(jnp.int32)
        cidx = jnp.floor(jnp.arange(ow) * (w / ow)).astype(jnp.int32)
        rend = jnp.concatenate([ridx[1:], jnp.asarray([h], jnp.int32)])
        cend = jnp.concatenate([cidx[1:], jnp.asarray([w], jnp.int32)])
        kh = int(jnp.max(rend - ridx)) if not return_mask else int(h // oh + 1)
        kh = max(1, math.ceil(h / oh))
        kw = max(1, math.ceil(w / ow))
        rows = jnp.minimum(ridx[:, None] + jnp.arange(kh)[None, :], h - 1)
        cols = jnp.minimum(cidx[:, None] + jnp.arange(kw)[None, :], w - 1)
        win = a[:, :, rows][:, :, :, :, cols]  # [N,C,oh,kh,ow,kw]
        return jnp.max(win, axis=(3, 5))

    out = apply_op(_fmp, x, _op_name="fractional_max_pool2d")
    if return_mask:
        return out, None
    return out


def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    os_ = output_size if isinstance(output_size, (list, tuple)) else (output_size,) * 3

    def _fmp(a):
        n, c, d, h, w = a.shape
        od, oh, ow = os_
        def mk(sz, o):
            idx = jnp.floor(jnp.arange(o) * (sz / o)).astype(jnp.int32)
            k = max(1, math.ceil(sz / o))
            return jnp.minimum(idx[:, None] + jnp.arange(k)[None, :], sz - 1)
        di, hi, wi = mk(d, od), mk(h, oh), mk(w, ow)
        win = a[:, :, di]                      # [N,C,od,kd,H,W]
        win = win[:, :, :, :, hi]              # [N,C,od,kd,oh,kh,W]
        win = win[:, :, :, :, :, :, wi]        # [N,C,od,kd,oh,kh,ow,kw]
        return jnp.max(win, axis=(3, 5, 7))

    out = apply_op(_fmp, x, _op_name="fractional_max_pool3d")
    if return_mask:
        return out, None
    return out


def dice_loss(input, label, epsilon=1e-5, name=None):
    def _dl(p, y):
        y1 = jax.nn.one_hot(y[..., 0].astype(jnp.int32), p.shape[-1])
        inter = jnp.sum(p * y1, axis=tuple(range(1, p.ndim)))
        union = jnp.sum(p, axis=tuple(range(1, p.ndim))) + jnp.sum(
            y1, axis=tuple(range(1, p.ndim)))
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))

    return apply_op(_dl, input, label, _op_name="dice_loss")


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid with the default complete-binary-tree coding."""
    def _hs(x, y, w, b):
        code_len = int(math.ceil(math.log2(max(2, num_classes))))
        ids = y.reshape(-1).astype(jnp.int32) + num_classes  # leaf position
        losses = []
        cur = ids
        for _ in range(code_len):
            parent = cur // 2
            bit = (cur % 2).astype(jnp.float32)  # 1 = right child
            wrow = w[jnp.clip(parent - 1, 0, w.shape[0] - 1)]
            logit = jnp.sum(wrow * x, -1)
            if b is not None:
                logit = logit + b.reshape(-1)[jnp.clip(parent - 1, 0, b.size - 1)]
            losses.append(
                jnp.maximum(logit, 0) - logit * bit + jnp.log1p(jnp.exp(-jnp.abs(logit))))
            cur = parent
        return jnp.mean(sum(losses))

    return apply_op(_hs, input, label, weight, bias, _op_name="hsigmoid_loss")


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    def _np(a, p, y):
        sim = a @ p.T
        eq = (y[:, None] == y[None, :]).astype(jnp.float32)
        tgt = eq / jnp.sum(eq, -1, keepdims=True)
        xent = jnp.mean(
            jnp.sum(-tgt * jax.nn.log_softmax(sim, -1), -1))
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, -1))
                        + jnp.mean(jnp.sum(p * p, -1))) * 0.25
        return xent + reg

    return apply_op(_np, anchor, positive, labels, _op_name="npair_loss")


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean", name=None):
    """ArcFace-style margin softmax (margin_cross_entropy parity)."""
    def _mce(lg, y):
        yi = y.reshape(-1).astype(jnp.int32)
        theta = jnp.arccos(jnp.clip(lg, -1 + 1e-7, 1 - 1e-7))
        tgt = jnp.cos(margin1 * theta + margin2) - margin3
        onehot = jax.nn.one_hot(yi, lg.shape[-1])
        adj = scale * jnp.where(onehot > 0, tgt, lg)
        losses = -jnp.sum(onehot * jax.nn.log_softmax(adj, -1), -1)
        if reduction == "mean":
            loss = jnp.mean(losses)
        elif reduction == "sum":
            loss = jnp.sum(losses)
        else:
            loss = losses
        if return_softmax:
            return loss, jax.nn.softmax(adj, -1)
        return loss

    return apply_op(_mce, logits, label, _op_name="margin_cross_entropy")


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    """RNN-T loss via the standard alpha-lattice dynamic program (log space).

    input: [B, T, U+1, V] log-probs (or logits — normalised internally).
    """
    def _rnnt(lp, y, tl, ul):
        lp = jax.nn.log_softmax(lp, -1)
        b, t_max, u_max, v = lp.shape
        yi = y.astype(jnp.int32)

        blank_lp = lp[..., blank]                                 # [B,T,U+1]
        idx_u = jnp.arange(u_max - 1)
        lab_lp = jnp.take_along_axis(
            lp[:, :, :-1, :], yi[:, None, :, None].repeat(t_max, 1), -1
        )[..., 0]                                                  # [B,T,U]

        NEG = -1e30

        def step_t(alpha_prev, t):
            # alpha_prev: [B, U+1] at time t-1 -> alpha at t
            def step_u(carry, u):
                pass
            # emit transitions within time t handled by scan over u
            # alpha[t, 0] = alpha[t-1, 0] + blank(t-1, 0)
            first = alpha_prev[:, 0] + blank_lp[:, t - 1, 0]

            def inner(carry, u):
                # carry: alpha[t, u-1]
                from_blank = alpha_prev[:, u] + blank_lp[:, t - 1, u]
                from_emit = carry + lab_lp[:, t, u - 1]
                val = jnp.logaddexp(from_blank, from_emit)
                return val, val

            _, rest = jax.lax.scan(inner, first, jnp.arange(1, u_max))
            alpha_t = jnp.concatenate([first[:, None],
                                       jnp.moveaxis(rest, 0, 1)], 1)
            return alpha_t, alpha_t

        # t = 0 row: only emits
        def inner0(carry, u):
            val = carry + lab_lp[:, 0, u - 1]
            return val, val

        a00 = jnp.zeros((b,))
        _, rest0 = jax.lax.scan(inner0, a00, jnp.arange(1, u_max))
        alpha0 = jnp.concatenate([a00[:, None], jnp.moveaxis(rest0, 0, 1)], 1)

        alpha_T, _ = jax.lax.scan(step_t, alpha0, jnp.arange(1, t_max))
        # gather alpha at (input_len-1, label_len) + final blank
        alphas = jnp.concatenate([alpha0[None], _], 0)  # [T, B, U+1]
        ti = jnp.clip(tl.astype(jnp.int32) - 1, 0, t_max - 1)
        ui = jnp.clip(ul.astype(jnp.int32), 0, u_max - 1)
        bidx = jnp.arange(b)
        final = alphas[ti, bidx, ui] + blank_lp[bidx, ti, ui]
        nll = -final
        if reduction == "mean":
            return jnp.mean(nll)
        if reduction == "sum":
            return jnp.sum(nll)
        return nll

    return apply_op(_rnnt, input, label, input_lengths, label_lengths,
                    _op_name="rnnt_loss")


def gather_tree(ids, parents, name=None):
    def _gt(ids_a, par_a):
        # [T, B, beam]
        t_max = ids_a.shape[0]

        def back(carry, t):
            beam_idx = carry  # [B, beam]
            tok = jnp.take_along_axis(ids_a[t], beam_idx, -1)
            nxt = jnp.take_along_axis(par_a[t], beam_idx, -1)
            return nxt.astype(beam_idx.dtype), tok

        init = jnp.broadcast_to(
            jnp.arange(ids_a.shape[-1], dtype=ids_a.dtype)[None, :],
            ids_a.shape[1:])
        _, toks = jax.lax.scan(back, init, jnp.arange(t_max), reverse=True)
        return toks

    return apply_op(_gt, ids, parents, _op_name="gather_tree")


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    def _ts(a):
        nt, c, h, w = a.shape
        n = nt // seg_num
        a = a.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        left = jnp.concatenate(
            [a[:, 1:, :fold], jnp.zeros_like(a[:, :1, :fold])], 1)
        right = jnp.concatenate(
            [jnp.zeros_like(a[:, :1, fold:2 * fold]), a[:, :-1, fold:2 * fold]], 1)
        mid = a[:, :, 2 * fold:]
        return jnp.concatenate([left, right, mid], 2).reshape(nt, c, h, w)

    return apply_op(_ts, x, _op_name="temporal_shift")


def class_center_sample(label, num_classes, num_samples, group=None):
    import numpy as np

    from ... import framework

    lab = np.asarray(label.numpy() if hasattr(label, "numpy") else label)
    pos = np.unique(lab)
    if len(pos) >= num_samples:
        sampled = pos[:num_samples]
    else:
        rest = np.setdiff1d(np.arange(num_classes), pos)
        rng = np.random.RandomState(0)
        extra = rng.choice(rest, num_samples - len(pos), replace=False)
        sampled = np.concatenate([pos, extra])
    sampled = np.sort(sampled)
    remap = {c: i for i, c in enumerate(sampled)}
    new_lab = np.asarray([remap.get(int(v), -1) for v in lab.reshape(-1)])
    return (Tensor(jnp.asarray(new_lab.reshape(lab.shape))),
            Tensor(jnp.asarray(sampled)))


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """Block-sparse attention fallback: dense logits masked by the CSR
    pattern (capability parity; a pallas splash-mask kernel is the TPU
    optimisation path)."""
    def _sa(q, k, v, offs, cols):
        b, h, s, d = q.shape
        logits = jnp.einsum("bhsd,bhtd->bhst", q / math.sqrt(d), k)

        # expand CSR (offsets, columns) into the dense boolean mask:
        # entry j belongs to the row r with offs[r] <= j < offs[r+1]
        def per_bh(offs_bh, cols_bh):
            row_of = jnp.searchsorted(offs_bh, jnp.arange(cols_bh.shape[0]),
                                      side="right") - 1
            m = jnp.zeros((s, s), bool)
            return m.at[row_of, cols_bh.astype(jnp.int32)].set(True)

        mask = jax.vmap(jax.vmap(per_bh))(offs, cols)
        logits = jnp.where(mask, logits, -1e30)
        probs = jax.nn.softmax(logits, -1)
        return jnp.einsum("bhst,bhtd->bhsd", probs, v)

    return apply_op(_sa, query, key, value, sparse_csr_offset,
                    sparse_csr_columns, _op_name="sparse_attention")


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    dist = distance_function or (lambda a, b: pairwise_distance(a, b))
    dp = dist(input, positive)
    dn = dist(input, negative)
    if swap:
        dn2 = dist(positive, negative)
        dn = apply_op(lambda a, b: jnp.minimum(a, b), dn, dn2,
                      _op_name="min")

    def _tl(dpa, dna):
        losses = jnp.maximum(dpa - dna + margin, 0.0)
        if reduction == "mean":
            return jnp.mean(losses)
        if reduction == "sum":
            return jnp.sum(losses)
        return losses

    return apply_op(_tl, dp, dn, _op_name="triplet_margin_distance")


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    def _mm(x, y):
        yi = y.reshape(-1).astype(jnp.int32)
        correct = jnp.take_along_axis(x, yi[:, None], -1)
        m = jnp.power(jnp.maximum(margin - correct + x, 0.0), p)
        m = m.at[jnp.arange(x.shape[0]), yi].set(0.0)
        losses = jnp.sum(m, -1) / x.shape[-1]
        if reduction == "mean":
            return jnp.mean(losses)
        if reduction == "sum":
            return jnp.sum(losses)
        return losses

    return apply_op(_mm, input, label, _op_name="multi_margin_loss")


def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None, name=None):
    """Adaptive softmax (parity: nn/functional adaptive_log_softmax)."""
    def _als(x, y, hw, *rest):
        n_clusters = len(cutoffs) - 1 if isinstance(cutoffs, (list, tuple)) else 0
        head_logits = x @ hw
        if head_bias is not None:
            head_logits = head_logits + rest[-1]
        head_lsm = jax.nn.log_softmax(head_logits, -1)
        yi = y.reshape(-1).astype(jnp.int32)
        shortlist = cutoffs[0]
        in_short = yi < shortlist
        out = jnp.where(
            in_short,
            jnp.take_along_axis(head_lsm, jnp.clip(yi, 0, shortlist - 1)[:, None], -1)[:, 0],
            0.0,
        )
        for ci in range(n_clusters):
            lo, hi = cutoffs[ci], cutoffs[ci + 1]
            tw = rest[ci]
            # project + cluster softmax
            clust = jax.nn.log_softmax(x @ tw, -1)
            rel = jnp.clip(yi - lo, 0, hi - lo - 1)
            clust_lp = jnp.take_along_axis(clust, rel[:, None], -1)[:, 0]
            gate = head_lsm[:, shortlist + ci]
            out = jnp.where((yi >= lo) & (yi < hi), gate + clust_lp, out)
        return out, -jnp.mean(out)

    rest = list(tail_weights) + ([head_bias] if head_bias is not None else [])
    return apply_op(_als, input, label, head_weight, *rest,
                    _op_name="adaptive_log_softmax")


def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False, return_softmax=False,
                         fixed_seed_offset=None, rng_name="", training=True,
                         name=None):
    from .flash_attention import flash_attention

    q = qkv[:, :, 0]
    k = qkv[:, :, 1]
    v = qkv[:, :, 2]
    return flash_attention(q, k, v, dropout=dropout, causal=causal,
                           return_softmax=return_softmax, training=training)


def flash_attn_varlen_qkvpacked(qkv, cu_seqlens_q, cu_seqlens_k, max_seqlen_q,
                                max_seqlen_k, scale=None, dropout=0.0,
                                causal=False, return_softmax=False,
                                varlen_padded=True, training=True, name=None):
    return flash_attn_qkvpacked(qkv, dropout=dropout, causal=causal,
                                return_softmax=return_softmax,
                                training=training)


# inplace activation variants
def hardtanh_(x, min=-1.0, max=1.0, name=None):
    out = apply_op(lambda a: jnp.clip(a, min, max), x, _op_name="hardtanh_")
    x._data = out._data
    return x


def leaky_relu_(x, negative_slope=0.01, name=None):
    out = apply_op(lambda a: jnp.where(a >= 0, a, negative_slope * a), x,
                   _op_name="leaky_relu_")
    x._data = out._data
    return x


def thresholded_relu_(x, threshold=1.0, value=0.0, name=None):
    out = apply_op(lambda a: jnp.where(a > threshold, a, value), x,
                   _op_name="thresholded_relu_")
    x._data = out._data
    return x
