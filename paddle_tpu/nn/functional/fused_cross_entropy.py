"""Chunked, vocab-sharded cross-entropy LM head.

The dense LM-head loss materializes a fp32 ``[tokens, vocab]`` logits
tensor (and its grad twin in backward) — ~1GB per microbatch at
1.3B/seq2048/batch4, the single largest HBM+bandwidth consumer at that
scale. This module computes the same loss **blockwise over vocab
chunks**:

- forward: an online log-sum-exp scan over ``[tokens, chunk]`` logit
  blocks (running max + rescaled sum, plus the target-logit gather), so
  peak extra HBM is ``O(tokens * chunk)`` fp32;
- backward: a ``custom_vjp`` that *recomputes* each chunk's logits and
  contracts ``softmax_chunk - onehot_chunk`` directly into ``dh`` and the
  per-chunk ``dw`` rows — the ``[tokens, vocab]`` grad-logits tensor
  never exists either.

The **vocab-sharded** variant runs the same kernel per tensor-parallel
shard inside ``shard_map``: each shard computes its partial
(max, sumexp, target-logit) triple and the combine is a ``pmax``/``psum``
of *scalars per token* — never a logits all-gather (the fused
computation-collective discipline of arXiv:2305.06942; EQuARX
arXiv:2506.17615 quantizes the collective itself, here the collective is
already 3 floats/token). Both passes are hand-written shard_maps wrapped
in ONE outer ``custom_vjp`` — autodiff never transposes through the
collectives, so the gradients are exact on every jax version's shard_map
semantics.

The optional int8 head path (per-token-row scales on h, per-vocab-row
scales on w, straight-through backward through the REAL weights —
``incubate.nn.functional._int8_head_core``'s recipe) is **default-on when
a numeric parity gate passes** (:func:`int8_head_enabled`); env
``PTPU_INT8_HEAD`` forces it either way.

Knobs (docs/PERF.md):
- ``PTPU_CE_VCHUNK``: vocab chunk size (default 8192, clamped to vocab).
  Also a memory-planner plan dimension (``memory.Candidate.head_chunk``).
- ``PTPU_LOSS_HEAD``: force ``dense`` | ``chunked`` | ``sharded``.
- ``PTPU_INT8_HEAD``: "0" forces fp head, truthy forces int8; unset →
  the parity gate decides.
- ``PTPU_INT8_HEAD_GATE_TOL``: gate loss tolerance (default 0.02).
"""
from __future__ import annotations

import functools
import os

import numpy as np
import jax
import jax.numpy as jnp

from ... import telemetry as _telemetry
from ...core.dispatch import apply_op

DEFAULT_VOCAB_CHUNK = 8192

_HEAD_MODE = _telemetry.gauge(
    "loss_head_mode",
    "active LM-head loss path: 1 on the (mode, int8) series that produced "
    "the run's loss (mode: dense|chunked|sharded; int8: on|off)",
    labelnames=("mode", "int8"))
_HEAD_CHUNK_BYTES = _telemetry.gauge(
    "loss_head_chunk_bytes",
    "fp32 bytes of ONE [tokens, chunk] logits block resident per CE scan "
    "step (the chunked head's peak logits footprint; dense = the full "
    "[tokens, vocab] tensor)")


_LAST_HEAD_MODE = [None]


def record_head_mode(mode, int8, tokens, chunk):
    """Set the loss-head telemetry gauges (docs/TELEMETRY.md). Only one
    (mode, int8) series reads 1 at a time — the previously active series
    is zeroed, so an A/B that switches paths mid-process still names the
    path that produced the LAST number."""
    active = (mode, "on" if int8 else "off")
    prev = _LAST_HEAD_MODE[0]
    if prev is not None and prev != active:
        _HEAD_MODE.set(0, labels=prev)
    _HEAD_MODE.set(1, labels=active)
    _LAST_HEAD_MODE[0] = active
    _HEAD_CHUNK_BYTES.set(int(tokens) * int(chunk) * 4)


# ---------------------------------------------------------------------------
# int8-head parity gate
# ---------------------------------------------------------------------------
_GATE_CACHE = {}


def int8_head_gate(tol=None):
    """Run (once per tolerance) the int8-head parity probe: chunked CE
    loss + grads on a deterministic probe batch, fp vs int8. Passes when
    the loss shift is < ``tol`` (default 0.02, env
    ``PTPU_INT8_HEAD_GATE_TOL``) and both grad mean-abs errors are < 5x
    that. This is the default-on criterion for the int8 LM head."""
    if tol is None:
        tol = float(os.environ.get("PTPU_INT8_HEAD_GATE_TOL", "0.02"))
    if tol in _GATE_CACHE:
        return _GATE_CACHE[tol]

    def loss_grads(int8):
        rng = np.random.default_rng(0)
        h = jnp.asarray(rng.standard_normal((64, 64)).astype(np.float32) * 0.5)
        w = jnp.asarray(rng.standard_normal((256, 64)).astype(np.float32) * 0.5)
        y = jnp.asarray(rng.integers(0, 256, (64,)).astype(np.int32))

        def f(h, w):
            return chunked_lm_loss_arrays(h, w, y, vocab_chunk=64, int8=int8)

        l, (gh, gw) = jax.value_and_grad(f, argnums=(0, 1))(h, w)
        return float(l), np.asarray(gh), np.asarray(gw)

    try:
        lf, ghf, gwf = loss_grads(False)
        l8, gh8, gw8 = loss_grads(True)
        ok = abs(l8 - lf) / max(abs(lf), 1e-9) < tol
        for g8, gf in ((gh8, ghf), (gw8, gwf)):
            denom = np.abs(gf).mean() + 1e-9
            ok = ok and (np.abs(g8 - gf).mean() / denom < 5 * tol)
    except Exception as e:
        # a failing probe must never take the train step down, but a
        # CRASHED gate (vs a numeric fail) silently turning the default
        # off would only show up as an unexplained tokens/sec drop — be
        # loud about which one happened
        import warnings

        warnings.warn(
            f"int8_head_gate probe crashed ({type(e).__name__}: {e}); "
            "defaulting the int8 LM head OFF. PTPU_INT8_HEAD=1 forces it.",
            RuntimeWarning)
        ok = False
    _GATE_CACHE[tol] = bool(ok)
    return _GATE_CACHE[tol]


def int8_head_enabled():
    """Resolve whether the int8 LM head is active: ``PTPU_INT8_HEAD``
    forces it ("0"/"" = off, anything else = on); unset, the parity gate
    (:func:`int8_head_gate`) decides — default-on when it passes. On the
    CPU backend the unforced default stays off: there is no int8 MXU rate
    to win, only quantization noise."""
    env = os.environ.get("PTPU_INT8_HEAD")
    if env is not None:
        return env not in ("", "0")
    import jax

    if jax.default_backend() == "cpu":
        return False
    return int8_head_gate()


# ---------------------------------------------------------------------------
# chunk-scan building blocks (shared by the unsharded + sharded kernels)
# ---------------------------------------------------------------------------
def _quantize_rows(a):
    """Per-row absmax int8: a [R, H] -> (int8 [R, H], f32 scale [R, 1])."""
    s = jnp.maximum(jnp.max(jnp.abs(a.astype(jnp.float32)), -1,
                            keepdims=True) / 127.0, 1e-12)
    q = jnp.clip(jnp.round(a.astype(jnp.float32) / s),
                 -127, 127).astype(jnp.int8)
    return q, s


def _chunk_logits(h, wc, int8, qh=None, sh=None):
    """One [N, c] fp32 logits block; int8 runs the quantized matmul
    (weight-chunk rows quantized in-loop — never a full int8 weight copy
    resident)."""
    if int8:
        qw, sw = _quantize_rows(wc)
        acc = jnp.einsum("nh,ch->nc", qh, qw,
                         preferred_element_type=jnp.int32)
        return acc.astype(jnp.float32) * sh * sw.T
    return jnp.einsum("nh,ch->nc", h, wc,
                      preferred_element_type=jnp.float32)


def _scan_stats(h, wp, y, off, *, n_chunks, chunk, vocab, int8):
    """Online-LSE scan over [N, chunk] logit blocks of ``wp`` ([K*c, H],
    zero-padded past ``vocab``): returns per-token (running max, rescaled
    sumexp, target-logit sum). ``off`` is this shard's global vocab
    offset (0 unsharded); labels outside [off, off+vocab) contribute no
    gold here (another shard owns them)."""
    qh = sh = None
    if int8:
        qh, sh = _quantize_rows(h)
    neg = jnp.float32(-np.inf)

    def body(carry, i):
        m, s, gold = carry
        wc = jax.lax.dynamic_slice_in_dim(wp, i * chunk, chunk, 0)
        logits = _chunk_logits(h, wc, int8, qh, sh)
        col = i * chunk + jnp.arange(chunk)
        logits = jnp.where(col[None, :] < vocab, logits, neg)
        m_new = jnp.maximum(m, jnp.max(logits, -1))
        s = (s * jnp.exp(m - m_new)
             + jnp.sum(jnp.exp(logits - m_new[:, None]), -1))
        yl = y - off - i * chunk
        hit = (yl >= 0) & (yl < chunk)
        g = jnp.take_along_axis(
            logits, jnp.clip(yl, 0, chunk - 1)[:, None], 1)[:, 0]
        gold = gold + jnp.where(hit, g, 0.0)
        return (m_new, s, gold), None

    n = h.shape[0]
    init = (jnp.full((n,), neg), jnp.zeros((n,), jnp.float32),
            jnp.zeros((n,), jnp.float32))
    (m, s, gold), _ = jax.lax.scan(body, init, jnp.arange(n_chunks))
    return m, s, gold


def _scan_grads(h, wp, y, off, lse, coeff, *, n_chunks, chunk, vocab, int8):
    """Backward chunk scan: recompute each [N, c] logits block, contract
    ``(softmax - onehot) * coeff`` into (dh [N, H] f32, dw [K*c, H] f32).
    The grad-logits block dies with its scan iteration."""
    qh = sh = None
    if int8:
        qh, sh = _quantize_rows(h)
    hf = h.astype(jnp.float32)
    neg = jnp.float32(-np.inf)

    def body(dh, i):
        wc = jax.lax.dynamic_slice_in_dim(wp, i * chunk, chunk, 0)
        logits = _chunk_logits(h, wc, int8, qh, sh)
        col = i * chunk + jnp.arange(chunk)
        logits = jnp.where(col[None, :] < vocab, logits, neg)
        p = jnp.exp(logits - lse[:, None])           # softmax block
        yl = (y - off)[:, None]
        onehot = (col[None, :] == yl) & (yl >= 0) & (yl < vocab)
        q = (p - onehot.astype(jnp.float32)) * coeff[:, None]
        # straight-through: contractions use the REAL operands even when
        # the forward logits were int8
        dh = dh + jnp.einsum("nc,ch->nh", q, wc.astype(jnp.float32),
                             preferred_element_type=jnp.float32)
        dwc = jnp.einsum("nc,nh->ch", q, hf,
                         preferred_element_type=jnp.float32)
        return dh, dwc

    dh0 = jnp.zeros(h.shape, jnp.float32)
    dh, dwc = jax.lax.scan(body, dh0, jnp.arange(n_chunks))
    return dh, dwc.reshape(n_chunks * chunk, h.shape[1])


def resolve_vocab_chunk(vocab, vocab_chunk=None):
    """Effective chunk: explicit arg > PTPU_CE_VCHUNK > default, clamped
    to [1, vocab]."""
    c = vocab_chunk or int(os.environ.get("PTPU_CE_VCHUNK", "0")) \
        or DEFAULT_VOCAB_CHUNK
    return max(1, min(int(c), int(vocab)))


def _pad_rows(w2, rows):
    if w2.shape[0] == rows:
        return w2
    return jnp.concatenate(
        [w2, jnp.zeros((rows - w2.shape[0], w2.shape[1]), w2.dtype)])


# ---------------------------------------------------------------------------
# unsharded kernel: custom_vjp over the chunk scans
# ---------------------------------------------------------------------------
@functools.lru_cache(maxsize=None)
def _chunked_ce_fn(n_chunks, chunk, vocab, int8):
    """Masked-sum chunked CE for a static (K, c, V) chunking:
    f(h [N,H], wp [K*c,H] zero-padded, y [N] int32, mask [N] f32) -> sum.
    The full [N, vocab] logits/grad-logits tensor exists in NEITHER pass.
    """
    dims = dict(n_chunks=n_chunks, chunk=chunk, vocab=vocab, int8=int8)

    @jax.custom_vjp
    def ce_sum(h, wp, y, mask):
        m, s, gold = _scan_stats(h, wp, y, 0, **dims)
        return jnp.sum((m + jnp.log(s) - gold) * mask)

    def ce_fwd(h, wp, y, mask):
        m, s, gold = _scan_stats(h, wp, y, 0, **dims)
        lse = m + jnp.log(s)
        return jnp.sum((lse - gold) * mask), (h, wp, y, mask, lse)

    def ce_bwd(res, g):
        h, wp, y, mask, lse = res
        coeff = (g * mask).astype(jnp.float32)
        dh, dw = _scan_grads(h, wp, y, 0, lse, coeff, **dims)
        return (dh.astype(h.dtype), dw.astype(wp.dtype),
                np.zeros(y.shape, jax.dtypes.float0), jnp.zeros_like(mask))

    ce_sum.defvjp(ce_fwd, ce_bwd)
    return ce_sum


def chunked_ce_sum(h, w2, y, mask, *, vocab_chunk=None, int8=False):
    """Masked-sum chunked CE on arrays. h [N, H]; w2 [V, H] vocab-major;
    y [N] int; mask [N] f32. Divide by the mask count outside for the
    mean."""
    vocab = w2.shape[0]
    c = resolve_vocab_chunk(vocab, vocab_chunk)
    k = -(-vocab // c)
    fn = _chunked_ce_fn(k, c, vocab, bool(int8))
    # pad OUTSIDE the custom_vjp: jnp.pad's own vjp slices dw back to [V]
    return fn(_ensure_2d(h), _pad_rows(w2, k * c),
              y.astype(jnp.int32), mask)


def _ensure_2d(h):
    return h if h.ndim == 2 else h.reshape(-1, h.shape[-1])


# ---------------------------------------------------------------------------
# vocab-sharded kernel: custom_vjp AROUND hand-written shard_maps
# ---------------------------------------------------------------------------
@functools.lru_cache(maxsize=None)
def _sharded_ce_fn(mesh, axis, n_chunks, chunk, v_local, int8):
    """Masked-sum CE with w vocab-sharded over ``axis``: forward combines
    per-shard (max, sumexp, gold) via pmax/psum of per-token scalars;
    backward psums the per-shard dh partials in-ring and emits each
    shard's own dw rows. Both passes are explicit shard_maps — jax never
    differentiates through the collectives, so the semantics don't depend
    on shard_map's transpose rules.

    Each shard's vocab OFFSET rides in as a length-1 slice of a sharded
    iota (in_spec P(axis)) instead of ``lax.axis_index`` — axis_index
    lowers to a PartitionId instruction that this XLA rejects under
    partial-manual SPMD when auto axes remain."""
    from jax.sharding import PartitionSpec as P

    dims = dict(n_chunks=n_chunks, chunk=chunk, vocab=v_local, int8=int8)
    rows = n_chunks * chunk
    tp = int(mesh.shape[axis])
    # numpy, not jnp: the factory is cached across traces, so a staged
    # array here would leak a tracer out of its first jit scope
    offsets = np.arange(tp, dtype=np.int32) * v_local    # [tp] -> [1]/shard

    def _fwd_body(h, wl, y, mask, offs):
        off = offs[0]
        m, s, gold = _scan_stats(h, _pad_rows(wl, rows), y, off, **dims)
        big_m = jax.lax.pmax(m, axis)
        big_s = jax.lax.psum(s * jnp.exp(m - big_m), axis)
        lse = big_m + jnp.log(big_s)
        gold = jax.lax.psum(gold, axis)
        return jnp.sum((lse - gold) * mask), lse

    def _run_fwd(h, w2, y, mask):
        return jax.shard_map(
            _fwd_body, mesh=mesh,
            in_specs=(P(), P(axis), P(), P(), P(axis)),
            out_specs=(P(), P()), axis_names={axis},
        )(h, w2, y, mask, offsets)

    def _bwd_body(h, wl, y, mask, lse, g, offs):
        off = offs[0]
        coeff = (g * mask).astype(jnp.float32)
        dh, dwl = _scan_grads(h, _pad_rows(wl, rows), y, off, lse, coeff,
                              **dims)
        # dh is partial over the tp shards (each saw only its vocab rows)
        return jax.lax.psum(dh, axis), dwl[:v_local]

    def _run_bwd(h, w2, y, mask, lse, g):
        return jax.shard_map(
            _bwd_body, mesh=mesh,
            in_specs=(P(), P(axis), P(), P(), P(), P(), P(axis)),
            out_specs=(P(), P(axis)), axis_names={axis},
        )(h, w2, y, mask, lse, g, offsets)

    @jax.custom_vjp
    def ce_sum(h, w2, y, mask):
        return _run_fwd(h, w2, y, mask)[0]

    def ce_fwd(h, w2, y, mask):
        total, lse = _run_fwd(h, w2, y, mask)
        return total, (h, w2, y, mask, lse)

    def ce_bwd(res, g):
        h, w2, y, mask, lse = res
        dh, dw = _run_bwd(h, w2, y, mask, lse,
                          jnp.asarray(g, jnp.float32))
        return (dh.astype(h.dtype), dw.astype(w2.dtype),
                np.zeros(y.shape, jax.dtypes.float0), jnp.zeros_like(mask))

    ce_sum.defvjp(ce_fwd, ce_bwd)
    return ce_sum


# ---------------------------------------------------------------------------
# public entry points
# ---------------------------------------------------------------------------
def _flatten(h, y, ignore_index):
    hf = h.reshape(-1, h.shape[-1])
    yf = y.reshape(-1).astype(jnp.int32)
    valid = (yf != ignore_index)
    # clamp masked labels into range so no shard's gather sees them
    return hf, jnp.where(valid, yf, 0), valid.astype(jnp.float32)


def chunked_lm_loss_arrays(h, w, y, *, transpose_y=True, vocab_chunk=None,
                           ignore_index=-100, int8=False):
    """Mean chunked CE on raw arrays (jit-traceable; used by models and
    tests). h [..., H]; w [V, H] (transpose_y) or [H, V]; y [...] int."""
    w2 = w if transpose_y else w.T
    hf, yf, mask = _flatten(h, y, ignore_index)
    total = chunked_ce_sum(hf, w2, yf, mask, vocab_chunk=vocab_chunk,
                           int8=int8)
    return total / jnp.maximum(mask.sum(), 1.0)


def sharded_lm_loss_arrays(h, w, y, mesh, axis="mp", *, transpose_y=True,
                           vocab_chunk=None, ignore_index=-100, int8=False):
    """Vocab-sharded chunked CE: w's vocab dim is sharded over ``axis``;
    each shard runs the chunked kernel on its local rows and the combine
    is pmax/psum of (max, sumexp, gold) scalars per token. Runs as a
    PARTIAL shard_map over ``axis`` only, so dp/pp placements of h stay
    visible to GSPMD (the pipeline's last stage holds a SHARD of the
    head, not a replica). Must be called under jit."""
    jax_mesh = getattr(mesh, "jax_mesh", mesh)
    tp = jax_mesh.shape[axis]
    w2 = w if transpose_y else w.T
    vocab = w2.shape[0]
    if vocab % tp != 0:
        raise ValueError(
            f"vocab ({vocab}) must divide over tp axis {axis!r} (size {tp})")
    v_local = vocab // tp
    c = resolve_vocab_chunk(v_local, vocab_chunk)
    k = -(-v_local // c)
    fn = _sharded_ce_fn(jax_mesh, axis, k, c, v_local, bool(int8))
    hf, yf, mask = _flatten(h, y, ignore_index)
    return fn(hf, w2, yf, mask) / jnp.maximum(mask.sum(), 1.0)


def fused_chunked_cross_entropy(x, weight, labels, transpose_y=True,
                                vocab_chunk=None, ignore_index=-100,
                                int8=None, mesh=None, tp_axis=None,
                                name=None):
    """Paddle-level fused chunked CE LM head (Tensor in, Tensor out).

    ``int8=None`` resolves via :func:`int8_head_enabled` (parity-gated
    default-on). ``mesh``/``tp_axis`` select the vocab-sharded variant.
    """
    if int8 is None:
        int8 = int8_head_enabled()
    vocab = weight.shape[0] if transpose_y else weight.shape[-1]
    n_tokens = 1
    for s in labels.shape:
        n_tokens *= int(s)
    if tp_axis is not None:
        jm = getattr(mesh, "jax_mesh", mesh)
        vocab //= int(jm.shape[tp_axis])
    record_head_mode("sharded" if tp_axis else "chunked", int8, n_tokens,
                     resolve_vocab_chunk(vocab, vocab_chunk))

    if tp_axis is not None:
        def _run(h, w, y):
            return sharded_lm_loss_arrays(
                h, w, y, mesh, tp_axis, transpose_y=transpose_y,
                vocab_chunk=vocab_chunk, ignore_index=ignore_index,
                int8=int8)
    else:
        def _run(h, w, y):
            return chunked_lm_loss_arrays(
                h, w, y, transpose_y=transpose_y, vocab_chunk=vocab_chunk,
                ignore_index=ignore_index, int8=int8)

    return apply_op(_run, x, weight, labels,
                    _op_name="fused_chunked_cross_entropy")
