"""Attention functionals (parity: python/paddle/nn/functional/flash_attention.py:358).

On TPU the flash-attention capability slot (reference: CUDA flashattn lib at
``phi/kernels/gpu/flash_attn_kernel.cu``) is filled by a Pallas splash/flash
kernel when running on real TPU hardware, with a pure-XLA fallback that still
fuses well (used on CPU test meshes and for odd shapes).

Layout note: paddle attention tensors are [batch, seq, heads, head_dim].
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op


def _xla_sdpa(q, k, v, mask=None, causal=False, dropout=0.0, scale=None, key=None):
    """Reference attention in pure XLA: [B, S, H, D] layout."""
    q, k, v = _constrain_heads_over_mp(q, k, v)
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    # [B,H,S,D]
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    logits = jnp.einsum("bhsd,bhtd->bhst", qh * scale, kh)
    if causal:
        s, t = logits.shape[-2], logits.shape[-1]
        cmask = jnp.tril(jnp.ones((s, t), bool), t - s)
        logits = jnp.where(cmask, logits, -jnp.inf)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -jnp.inf)
        else:
            logits = logits + mask
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    if dropout > 0.0 and key is not None:
        keep = jax.random.bernoulli(key, 1.0 - dropout, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout), 0.0).astype(q.dtype)
    out = jnp.einsum("bhst,bhtd->bhsd", probs, vh)
    return jnp.swapaxes(out, 1, 2)


def _use_pallas(q_shape):
    from ...ops.pallas import on_tpu_device

    if not on_tpu_device():
        return False
    from ...ops.pallas.flash_attention import supported_seq

    b, s, h, d = q_shape
    # the kernel needs Mosaic-tileable seq blocks and the whole head_dim in
    # VMEM; other shapes fall back to the XLA path
    return supported_seq(s) and d <= 256


def _constrain_heads_over_mp(q, k, v):
    """spmd rule `flash_attention` (distributed/spmd_rules.py): shard the
    heads dim over "mp", never seq_kv or head_dim. Binds the Megatron
    attention layout inside jit instead of trusting propagation (the
    explicit analogue of `flash_attn_spmd_rule`)."""
    from ...distributed.fleet import active_mesh
    from ...distributed.spmd_rules import constraints_enabled

    from ...distributed import collectives as _coll

    if _coll.in_manual_grad_region():
        # inside the composed/quantized manual region (docs/COMMS.md)
        # every live axis is already manual — a with_sharding_constraint
        # naming 'mp' there is illegal, and the per-shard trace already
        # holds exactly its head slice
        return q, k, v
    mesh = active_mesh()
    mp_size = (
        mesh.get_dim_size("mp")
        if mesh is not None and "mp" in mesh.dim_names
        else 1
    )
    if mp_size == 1 or q.ndim != 4 or not constraints_enabled():
        return q, k, v
    from jax.sharding import PartitionSpec

    from ...distributed.auto_parallel import shard_activation
    from ...distributed.spmd_rules import DistTensorSpec, get_spmd_rule

    mp = mesh.dim_names.index("mp")
    specs = [DistTensorSpec(list(t.shape), [-1, -1, mp, -1]) for t in (q, k, v)]
    ins, _ = get_spmd_rule("flash_attention").infer_forward(*specs)
    # Pin only the semantic dims the rule decides: heads over "mp",
    # head_dim replicated. Batch and seq stay UNCONSTRAINED so GSPMD keeps
    # whatever dp/sharding/sep layout the surrounding program chose (sep
    # shards the sequence dim; forcing it here would gather the sequence).
    # GQA: constrain each tensor independently — an MQA/GQA kv with
    # indivisible heads is skipped while q still gets pinned.
    U = PartitionSpec.UNCONSTRAINED
    out = []
    for t, s in zip((q, k, v), ins):
        if t.shape[2] % mp_size != 0:
            out.append(t)
            continue
        rule_spec = s.partition_spec(mesh.dim_names)
        ext = list(rule_spec) + [None] * (4 - len(rule_spec))
        spec = PartitionSpec(U, U, ext[2], ext[3])
        out.append(shard_activation(t, mesh=mesh, spec=spec))
    return tuple(out)


def sdpa_arrays(q, k, v, causal=True, scale=None):
    """Array-level attention: pallas flash kernel when eligible, XLA fallback.

    The single dispatch point shared by the functional API and the pure
    model paths (models/gpt.py stacked decoder)."""
    from ...ops.pallas import log_path_once

    q, k, v = _constrain_heads_over_mp(q, k, v)
    if _use_pallas(q.shape):
        try:
            from ...ops.pallas import flash_attention as _fa_kernel

            out = _fa_kernel(q, k, v, causal=causal, scale=scale)
            log_path_once("sdpa", "pallas_flash")
            return out
        except Exception:
            pass
    log_path_once("sdpa", "xla_sdpa")
    if k.shape[2] != q.shape[2]:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return _xla_sdpa(q, k, v, causal=causal, scale=scale)


def flash_attention(
    query,
    key,
    value,
    dropout=0.0,
    causal=False,
    return_softmax=False,
    fixed_seed_offset=None,
    rng_name="",
    training=True,
    name=None,
):
    from ... import framework

    drop_key = framework.next_rng_key() if (dropout > 0.0 and training) else None

    def _fa(q, k, v):
        if dropout == 0.0 or not training:
            return sdpa_arrays(q, k, v, causal=causal)
        return _xla_sdpa(q, k, v, causal=causal, dropout=dropout, key=drop_key)

    out = apply_op(_fa, query, key, value, _op_name="flash_attention")
    if return_softmax:
        return out, None
    return out, None


def scaled_dot_product_attention(
    query,
    key,
    value,
    attn_mask=None,
    dropout_p=0.0,
    is_causal=False,
    training=True,
    name=None,
):
    """parity: nn/functional/flash_attention.py:1139 — [B,S,H,D] layout."""
    from ... import framework

    drop_key = framework.next_rng_key() if (dropout_p > 0.0 and training) else None

    def _sdpa(q, k, v, m):
        if m is None and (dropout_p == 0.0 or not training):
            return sdpa_arrays(q, k, v, causal=is_causal)
        return _xla_sdpa(
            q, k, v, mask=m, causal=is_causal,
            dropout=dropout_p if training else 0.0, key=drop_key,
        )

    return apply_op(_sdpa, query, key, value, attn_mask, _op_name="sdpa")


def flashmask_attention(
    query, key, value, startend_row_indices=None, dropout=0.0, causal=False,
    window_size=None, return_softmax_lse=False, return_seed_offset=False,
    fixed_seed_offset=None, rng_name="", training=True, name=None,
):
    """Sparse-mask attention (parity: flash_attention.py:1299 flashmask).

    startend_row_indices: [B, H, S, 1] (causal) — LT masking: key j is masked
    for query rows >= start index. Fallback builds the dense mask.
    """
    if startend_row_indices is None:
        return flash_attention(query, key, value, dropout, causal, training=training)[0]

    def _fm(q, k, v, sri):
        b, s, h, d = q.shape
        rows = jnp.arange(s)[:, None, None]  # query index
        start = jnp.swapaxes(sri, 1, 2)  # [B, S, H, n]
        # mask[b, h, i, j]: allowed if i < start[b, j, h, 0]
        st = sri[..., 0]  # [B, H, S_k]
        i_idx = jnp.arange(s)[None, None, :, None]
        allowed = i_idx < st[:, :, None, :]
        if causal:
            j_idx = jnp.arange(s)[None, None, None, :]
            allowed = allowed & (j_idx <= i_idx)
        logits_mask = jnp.where(allowed, 0.0, -jnp.inf)
        return _xla_sdpa(q, k, v, mask=logits_mask, causal=False)

    out = apply_op(_fm, query, key, value, startend_row_indices, _op_name="flashmask_attention")
    if return_softmax_lse or return_seed_offset:
        return (out, None, None)[: 1 + int(return_softmax_lse) + int(return_seed_offset)]
    return out


def sdp_kernel(*a, **k):  # compat context manager
    import contextlib

    return contextlib.nullcontext()


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale, dropout=0.0,
                        causal=False, return_softmax=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """Varlen attention over packed sequences (parity:
    nn/functional/flash_attention.py:756 flash_attn_unpadded).

    query/key/value: [total_tokens, num_heads, head_dim] with sequences
    packed back to back; cu_seqlens_*: [batch+1] int32 cumulative
    offsets. TPU-native form: one dense segment-masked attention — the
    segment-id mask keeps cross-sequence scores at -inf and XLA fuses the
    mask into the softmax; per-sequence dynamic shapes would defeat the
    compiler, so the packed layout IS the fast path on TPU."""
    def _varlen(q, k, v, cq, ck):
        tq, h, d = q.shape
        tk = k.shape[0]
        # segment id per token: index of the sequence it belongs to
        seg_q = jnp.searchsorted(cq, jnp.arange(tq), side="right") - 1
        seg_k = jnp.searchsorted(ck, jnp.arange(tk), side="right") - 1
        # position within the sequence (for causal masking)
        pos_q = jnp.arange(tq) - cq[seg_q]
        pos_k = jnp.arange(tk) - ck[seg_k]
        qf = q.astype(jnp.float32) * scale
        logits = jnp.einsum("qhd,khd->hqk", qf, k.astype(jnp.float32))
        mask = seg_q[:, None] == seg_k[None, :]
        if causal:
            mask = mask & (pos_k[None, :] <= pos_q[:, None])
        logits = jnp.where(mask[None, :, :], logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1)
        if dropout > 0.0 and training:
            from ... import framework

            keep = jax.random.bernoulli(
                framework.next_rng_key(), 1.0 - dropout, probs.shape)
            probs = probs * keep / (1.0 - dropout)
        out = jnp.einsum("hqk,khd->qhd", probs, v.astype(jnp.float32))
        return out.astype(q.dtype)

    out = apply_op(_varlen, query, key, value, cu_seqlens_q, cu_seqlens_k,
                   _op_name="flash_attn_unpadded")
    return out, None
