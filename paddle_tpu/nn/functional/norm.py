"""Normalization functionals (parity: python/paddle/nn/functional/norm.py).

These stay as straight-line jnp so XLA fuses them into neighbouring matmuls;
the Pallas fused variants live in paddle_tpu.incubate.nn.functional.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op
from ...core.tensor import Tensor


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05, name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n_axes = len(list(normalized_shape))

    def _ln(a, w, b):
        axes = tuple(range(a.ndim - n_axes, a.ndim))
        mean = jnp.mean(a, axis=axes, keepdims=True)
        var = jnp.var(a, axis=axes, keepdims=True)
        out = (a - mean) * jax.lax.rsqrt(var + epsilon)
        if w is not None:
            out = out * w
        if b is not None:
            out = out + b
        return out.astype(a.dtype)

    return apply_op(_ln, x, weight, bias, _op_name="layer_norm")


def rms_norm(x, weight=None, bias=None, epsilon=1e-6, begin_norm_axis=-1, name=None):
    def _rms(a, w, b):
        ax = begin_norm_axis % a.ndim
        axes = tuple(range(ax, a.ndim))
        var = jnp.mean(jnp.square(a.astype(jnp.float32)), axis=axes, keepdims=True)
        out = a * jax.lax.rsqrt(var + epsilon).astype(a.dtype)
        if w is not None:
            out = out * w
        if b is not None:
            out = out + b
        return out.astype(a.dtype)

    return apply_op(_rms, x, weight, bias, _op_name="rms_norm")


def batch_norm(
    x,
    running_mean,
    running_var,
    weight=None,
    bias=None,
    training=False,
    momentum=0.9,
    epsilon=1e-05,
    data_format="NCHW",
    use_global_stats=None,
    name=None,
):
    """Returns output; updates running stats in-place when training."""
    use_batch_stats = training and not use_global_stats

    ch_last = data_format in ("NHWC", "NLC", "NDHWC")

    def _stats_axes(a):
        if ch_last:
            return tuple(range(a.ndim - 1))
        return (0,) + tuple(range(2, a.ndim))

    def _shape_for(a, v):
        shape = [1] * a.ndim
        shape[a.ndim - 1 if ch_last else (1 if a.ndim > 1 else 0)] = v.shape[0]
        return v.reshape(shape)

    if use_batch_stats:
        # compute batch stats eagerly so we can fold them into running stats
        def _bn_train(a, rm, rv, w, b):
            axes = _stats_axes(a)
            m = jnp.mean(a, axis=axes)
            v = jnp.var(a, axis=axes)
            out = (a - _shape_for(a, m)) * jax.lax.rsqrt(_shape_for(a, v) + epsilon)
            if w is not None:
                out = out * _shape_for(a, w)
            if b is not None:
                out = out + _shape_for(a, b)
            new_rm = momentum * rm + (1 - momentum) * m
            new_rv = momentum * rv + (1 - momentum) * v
            return out.astype(a.dtype), new_rm, new_rv

        out, new_rm, new_rv = apply_op(
            _bn_train, x, running_mean, running_var, weight, bias,
            _op_name="batch_norm",
        )
        # running stats are buffers: update payloads in place (no grad flow)
        running_mean._data = new_rm._data if isinstance(new_rm, Tensor) else new_rm
        running_var._data = new_rv._data if isinstance(new_rv, Tensor) else new_rv
        return out

    def _bn_eval(a, rm, rv, w, b):
        out = (a - _shape_for(a, rm)) * jax.lax.rsqrt(_shape_for(a, rv) + epsilon)
        if w is not None:
            out = out * _shape_for(a, w)
        if b is not None:
            out = out + _shape_for(a, b)
        return out.astype(a.dtype)

    return apply_op(_bn_eval, x, running_mean, running_var, weight, bias, _op_name="batch_norm")


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None, use_input_stats=True, momentum=0.9, eps=1e-05, data_format="NCHW", name=None):
    def _in(a, w, b):
        axes = tuple(range(2, a.ndim))
        m = jnp.mean(a, axis=axes, keepdims=True)
        v = jnp.var(a, axis=axes, keepdims=True)
        out = (a - m) * jax.lax.rsqrt(v + eps)
        if w is not None:
            shape = [1, w.shape[0]] + [1] * (a.ndim - 2)
            out = out * w.reshape(shape)
        if b is not None:
            shape = [1, b.shape[0]] + [1] * (a.ndim - 2)
            out = out + b.reshape(shape)
        return out.astype(a.dtype)

    return apply_op(_in, x, weight, bias, _op_name="instance_norm")


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None, data_format="NCHW", name=None):
    ch_last = data_format in ("NHWC", "NLC", "NDHWC")

    def _gn(a, w, b):
        if ch_last:
            a_cf = jnp.moveaxis(a, -1, 1)
        else:
            a_cf = a
        n, c = a_cf.shape[0], a_cf.shape[1]
        g = num_groups
        grouped = a_cf.reshape((n, g, c // g) + a_cf.shape[2:])
        axes = tuple(range(2, grouped.ndim))
        m = jnp.mean(grouped, axis=axes, keepdims=True)
        v = jnp.var(grouped, axis=axes, keepdims=True)
        out = ((grouped - m) * jax.lax.rsqrt(v + epsilon)).reshape(a_cf.shape)
        if w is not None:
            out = out * w.reshape([1, c] + [1] * (a_cf.ndim - 2))
        if b is not None:
            out = out + b.reshape([1, c] + [1] * (a_cf.ndim - 2))
        if ch_last:
            out = jnp.moveaxis(out, 1, -1)
        return out.astype(a.dtype)

    return apply_op(_gn, x, weight, bias, _op_name="group_norm")


def local_response_norm(x, size, alpha=0.0001, beta=0.75, k=1.0, data_format="NCHW", name=None):
    def _lrn(a):
        sq = jnp.square(a)
        c_axis = 1 if data_format.startswith("NC") else a.ndim - 1
        half = size // 2
        pads = [(0, 0)] * a.ndim
        pads[c_axis] = (half, size - half - 1)
        padded = jnp.pad(sq, pads)
        window = [1] * a.ndim
        window[c_axis] = size
        summed = jax.lax.reduce_window(
            padded, jnp.zeros((), a.dtype), jax.lax.add, tuple(window),
            (1,) * a.ndim, [(0, 0)] * a.ndim,
        )
        div = (k + alpha * summed) ** beta
        return a / div

    return apply_op(_lrn, x, _op_name="local_response_norm")


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def _normalize(a):
        if p == 2:
            n = jnp.sqrt(jnp.sum(jnp.square(a), axis=axis, keepdims=True))
        else:
            n = jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return a / jnp.maximum(n, epsilon)

    return apply_op(_normalize, x, _op_name="normalize")
