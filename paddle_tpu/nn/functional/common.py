"""Common functionals: linear, dropout, embedding, interpolate, etc.

Parity: python/paddle/nn/functional/common.py + input.py.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ... import framework
from ...core.dispatch import apply_op
from ...core.tensor import Tensor


def linear(x, weight, bias=None, name=None):
    """y = x @ W (+ b). Weight layout [in, out] like the reference.

    Under ``paddle.amp.fp8_autocast()`` the matmul runs on the fp8
    (e4m3, per-tensor-scaled) path with a wide backward."""
    from ...amp import is_fp8_enabled

    if is_fp8_enabled():
        from ...incubate.nn.functional.fp8 import fp8_linear

        return fp8_linear(x, weight, bias)

    def _linear(a, w, b):
        out = jnp.matmul(a, w)
        if b is not None:
            out = out + b
        return out

    return apply_op(_linear, x, weight, bias, _op_name="linear")


def dropout(
    x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None
):
    if not training or (isinstance(p, (int, float)) and p == 0):
        return x if isinstance(x, Tensor) else x
    key = framework.next_rng_key()

    def _dropout(a):
        keep = 1.0 - p
        if axis is None:
            mask_shape = a.shape
        else:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            mask_shape = tuple(
                a.shape[i] if i in [ax % a.ndim for ax in axes] else 1
                for i in range(a.ndim)
            )
        mask = jax.random.bernoulli(key, keep, mask_shape)
        if mode == "upscale_in_train":
            return jnp.where(mask, a / keep, 0.0).astype(a.dtype)
        return jnp.where(mask, a, 0.0).astype(a.dtype)

    return apply_op(_dropout, x, _op_name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0:
        return x
    key = framework.next_rng_key()

    def _ad(a):
        alpha = 1.6732632423543772
        scale = 1.0507009873554805
        alpha_p = -alpha * scale
        keep = 1.0 - p
        mask = jax.random.bernoulli(key, keep, a.shape)
        a_coef = (keep + p * alpha_p**2 * keep) ** -0.5
        b_coef = -a_coef * p * alpha_p
        return (a_coef * jnp.where(mask, a, alpha_p) + b_coef).astype(a.dtype)

    return apply_op(_ad, x, _op_name="alpha_dropout")


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    def _embedding(ids, w):
        out = jnp.take(w, ids, axis=0)
        if padding_idx is not None:
            mask = (ids == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out).astype(w.dtype)
        return out

    return apply_op(_embedding, x, weight, _op_name="embedding")


def one_hot(x, num_classes, name=None):
    from ...ops.manipulation import one_hot as _oh

    return _oh(x, num_classes)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def _ls(l, pd):
        k = l.shape[-1]
        if pd is None:
            return (1 - epsilon) * l + epsilon / k
        return (1 - epsilon) * l + epsilon * pd

    return apply_op(_ls, label, prior_dist, _op_name="label_smooth")


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def _cs(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.linalg.norm(a, axis=axis)
        nb = jnp.linalg.norm(b, axis=axis)
        return dot / jnp.maximum(na * nb, eps)

    return apply_op(_cs, x1, x2, _op_name="cosine_similarity")


def bilinear(x1, x2, weight, bias=None, name=None):
    def _bilinear(a, b, w, bi):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if bi is not None:
            out = out + bi
        return out

    return apply_op(_bilinear, x1, x2, weight, bias, _op_name="bilinear")


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def _ps(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            oc = c // (r * r)
            a = a.reshape(n, oc, r, r, h, w)
            a = a.transpose(0, 1, 4, 2, 5, 3)
            return a.reshape(n, oc, h * r, w * r)
        n, h, w, c = a.shape
        oc = c // (r * r)
        a = a.reshape(n, h, w, r, r, oc)
        a = a.transpose(0, 1, 3, 2, 4, 5)
        return a.reshape(n, h * r, w * r, oc)

    return apply_op(_ps, x, _op_name="pixel_shuffle")


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def _pu(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c, h // r, r, w // r, r)
            a = a.transpose(0, 1, 3, 5, 2, 4)
            return a.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = a.shape
        a = a.reshape(n, h // r, r, w // r, r, c)
        a = a.transpose(0, 2, 4, 5, 1, 3)
        return a.reshape(n, c * r * r, h // r, w // r).transpose(0, 2, 3, 1)

    return apply_op(_pu, x, _op_name="pixel_unshuffle")


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def _cs(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, groups, c // groups, h, w)
            a = a.transpose(0, 2, 1, 3, 4)
            return a.reshape(n, c, h, w)
        n, h, w, c = a.shape
        a = a.reshape(n, h, w, groups, c // groups)
        a = a.transpose(0, 1, 2, 4, 3)
        return a.reshape(n, h, w, c)

    return apply_op(_cs, x, _op_name="channel_shuffle")


def interpolate(
    x,
    size=None,
    scale_factor=None,
    mode="nearest",
    align_corners=False,
    align_mode=0,
    data_format="NCHW",
    name=None,
):
    def _interp(a):
        # operate in NHWC for jax.image
        chan_last = data_format in ("NHWC", "NDHWC", "NWC")
        spatial_nd = a.ndim - 2
        if not chan_last:
            perm = (0,) + tuple(range(2, a.ndim)) + (1,)
            a_cl = jnp.transpose(a, perm)
        else:
            a_cl = a
        in_spatial = a_cl.shape[1:-1]
        if size is not None:
            out_spatial = [
                int(s.item()) if isinstance(s, Tensor) else int(s) for s in (
                    size if isinstance(size, (list, tuple)) else [size]
                )
            ]
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * spatial_nd
            out_spatial = [int(d * f) for d, f in zip(in_spatial, sf)]
        method = {
            "nearest": "nearest",
            "bilinear": "bilinear",
            "trilinear": "trilinear",
            "bicubic": "bicubic",
            "linear": "linear",
            "area": "linear",
        }[mode]
        out_shape = (a_cl.shape[0],) + tuple(out_spatial) + (a_cl.shape[-1],)
        if align_corners and method in ("linear", "bilinear", "trilinear"):
            # jax.image.resize has no align_corners; do separable per-axis
            # linear interpolation on the corner-aligned grid
            out = a_cl
            for ax, o in enumerate(out_spatial, start=1):
                i = out.shape[ax]
                if o == i:
                    continue
                scale = (i - 1) / (o - 1) if o > 1 else 0.0
                coords = jnp.arange(o) * scale
                lo = jnp.floor(coords).astype(jnp.int32)
                hi = jnp.clip(lo + 1, 0, i - 1)
                frac = (coords - lo).astype(jnp.float32)
                shape = [1] * out.ndim
                shape[ax] = o
                frac = frac.reshape(shape)
                lo_v = jnp.take(out, lo, axis=ax).astype(jnp.float32)
                hi_v = jnp.take(out, hi, axis=ax).astype(jnp.float32)
                out = lo_v * (1 - frac) + hi_v * frac
        else:
            out = jax.image.resize(a_cl, out_shape, method=method)
        if not chan_last:
            inv = (0, a.ndim - 1) + tuple(range(1, a.ndim - 1))
            out = jnp.transpose(out, inv)
        return out.astype(a.dtype)

    return apply_op(_interp, x, _op_name="interpolate")


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (reference: phi/kernels/funcs/im2col)."""

    def _pair(v):
        return list(v) if isinstance(v, (list, tuple)) else [v, v]

    k = _pair(kernel_sizes)
    s = _pair(strides)
    p = _pair(paddings)
    d = _pair(dilations)
    if len(p) == 2:
        p = [p[0], p[1], p[0], p[1]]

    def _unfold(a):
        n, c, h, w = a.shape
        a_p = jnp.pad(a, ((0, 0), (0, 0), (p[0], p[2]), (p[1], p[3])))
        oh = (h + p[0] + p[2] - (d[0] * (k[0] - 1) + 1)) // s[0] + 1
        ow = (w + p[1] + p[3] - (d[1] * (k[1] - 1) + 1)) // s[1] + 1
        patches = []
        for i in range(k[0]):
            for j in range(k[1]):
                sl = a_p[
                    :,
                    :,
                    i * d[0] : i * d[0] + oh * s[0] : s[0],
                    j * d[1] : j * d[1] + ow * s[1] : s[1],
                ]
                patches.append(sl)
        out = jnp.stack(patches, axis=2)  # n, c, k*k, oh, ow
        return out.reshape(n, c * k[0] * k[1], oh * ow)

    return apply_op(_unfold, x, _op_name="unfold")


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    def _pair(v):
        return list(v) if isinstance(v, (list, tuple)) else [v, v]

    osz = _pair(output_sizes)
    k = _pair(kernel_sizes)
    s = _pair(strides)
    p = _pair(paddings)
    d = _pair(dilations)
    if len(p) == 2:
        p = [p[0], p[1], p[0], p[1]]

    def _fold(a):
        n, ckk, L = a.shape
        c = ckk // (k[0] * k[1])
        h_p = osz[0] + p[0] + p[2]
        w_p = osz[1] + p[1] + p[3]
        oh = (h_p - (d[0] * (k[0] - 1) + 1)) // s[0] + 1
        ow = (w_p - (d[1] * (k[1] - 1) + 1)) // s[1] + 1
        a_r = a.reshape(n, c, k[0], k[1], oh, ow)
        out = jnp.zeros((n, c, h_p, w_p), a.dtype)
        for i in range(k[0]):
            for j in range(k[1]):
                out = out.at[
                    :,
                    :,
                    i * d[0] : i * d[0] + oh * s[0] : s[0],
                    j * d[1] : j * d[1] + ow * s[1] : s[1],
                ].add(a_r[:, :, i, j])
        return out[:, :, p[0] : h_p - p[2], p[1] : w_p - p[3]]

    return apply_op(_fold, x, _op_name="fold")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None, pad_from_left_axis=True):
    from ...ops.manipulation import pad as _pad

    return _pad(x, pad, mode, value, data_format, pad_from_left_axis)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    from ...ops.manipulation import flatten as _flatten

    return _flatten(x, start_axis, stop_axis)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    def _ag(th):
        n, c, h, w = [int(v) for v in (out_shape if not isinstance(out_shape, Tensor) else out_shape.numpy())]
        ys = jnp.linspace(-1, 1, h) if align_corners else jnp.linspace(-1 + 1 / h, 1 - 1 / h, h)
        xs = jnp.linspace(-1, 1, w) if align_corners else jnp.linspace(-1 + 1 / w, 1 - 1 / w, w)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        grid = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # h,w,3
        out = jnp.einsum("hwk,nik->nhwi", grid.astype(th.dtype), th)
        return out

    return apply_op(_ag, theta, _op_name="affine_grid")


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros", align_corners=True, name=None):
    def _gs(a, g):
        n, c, h, w = a.shape
        gx = (g[..., 0] + 1) * (w - 1) / 2 if align_corners else ((g[..., 0] + 1) * w - 1) / 2
        gy = (g[..., 1] + 1) * (h - 1) / 2 if align_corners else ((g[..., 1] + 1) * h - 1) / 2

        def sample_channel(img):  # h,w
            def bilinear_one(yy, xx):
                x0 = jnp.floor(xx)
                y0 = jnp.floor(yy)
                x1, y1 = x0 + 1, y0 + 1
                wx1 = xx - x0
                wy1 = yy - y0

                def at(yi, xi):
                    valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
                    yi_c = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
                    xi_c = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
                    v = img[yi_c, xi_c]
                    if padding_mode == "zeros":
                        v = jnp.where(valid, v, 0.0)
                    return v

                return (
                    at(y0, x0) * (1 - wy1) * (1 - wx1)
                    + at(y0, x1) * (1 - wy1) * wx1
                    + at(y1, x0) * wy1 * (1 - wx1)
                    + at(y1, x1) * wy1 * wx1
                )

            return bilinear_one

        out = []
        for ni in range(n):
            chans = []
            for ci in range(c):
                f = sample_channel(a[ni, ci])
                if mode == "bilinear":
                    chans.append(f(gy[ni], gx[ni]))
                else:
                    yi = jnp.clip(jnp.round(gy[ni]), 0, h - 1).astype(jnp.int32)
                    xi = jnp.clip(jnp.round(gx[ni]), 0, w - 1).astype(jnp.int32)
                    chans.append(a[ni, ci][yi, xi])
            out.append(jnp.stack(chans))
        return jnp.stack(out).astype(a.dtype)

    return apply_op(_gs, x, grid, _op_name="grid_sample")
