"""Pooling functionals over lax.reduce_window (parity: nn/functional/pooling.py)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op
from .conv import _tuple_n, _padding_n


def _pool_nd(x, kernel, stride, padding, n, channel_last, reducer, init, op_name,
             ceil_mode=False, exclusive=True, count_include_pad=False):
    k = _tuple_n(kernel, n)
    s = _tuple_n(stride if stride is not None else kernel, n)
    pad = _padding_n(padding, n)

    if channel_last:
        window = (1,) + k + (1,)
        strides = (1,) + s + (1,)
        pads = pad if isinstance(pad, str) else [(0, 0)] + pad + [(0, 0)]
    else:
        window = (1, 1) + k
        strides = (1, 1) + s
        pads = pad if isinstance(pad, str) else [(0, 0), (0, 0)] + pad

    def _pool(a):
        return jax.lax.reduce_window(a, init(a.dtype), reducer, window, strides, pads)

    if reducer is jax.lax.add:
        # average pool: divide by window size (or valid count if exclusive)
        no_pad = isinstance(pad, str) or all(p == (0, 0) for p in pad)

        def _avg(a):
            summed = _pool(a)
            if no_pad or count_include_pad or not exclusive:
                denom = float(np.prod(k))
                return (summed / denom).astype(a.dtype)
            counts = jax.lax.reduce_window(
                jnp.ones_like(a), jnp.zeros((), a.dtype), jax.lax.add,
                window, strides, pads,
            )
            return (summed / counts).astype(a.dtype)

        return apply_op(_avg, x, _op_name=op_name)
    return apply_op(_pool, x, _op_name=op_name)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCL", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 1, data_format == "NLC",
                    jax.lax.max, lambda d: -jnp.inf if jnp.issubdtype(d, jnp.floating) else jnp.iinfo(d).min,
                    "max_pool1d", ceil_mode)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCHW", name=None):
    out = _pool_nd(x, kernel_size, stride, padding, 2, data_format == "NHWC",
                   jax.lax.max, lambda d: -jnp.inf if jnp.issubdtype(d, jnp.floating) else jnp.iinfo(d).min,
                   "max_pool2d", ceil_mode)
    if return_mask:
        # mask = flat H*W index of each window's argmax (paddle semantics)
        ks = kernel_size if isinstance(kernel_size, (list, tuple)) else (kernel_size,) * 2
        st = stride if isinstance(stride, (list, tuple)) else (
            (stride,) * 2 if stride else ks)
        pd = padding if isinstance(padding, (list, tuple)) else (padding,) * 2

        def _mask(a):
            n, c, h, w = a.shape
            if pd[0] or pd[1]:
                neg = (-jnp.inf if jnp.issubdtype(a.dtype, jnp.floating)
                       else jnp.iinfo(a.dtype).min)
                a = jnp.pad(a, ((0, 0), (0, 0), (pd[0], pd[0]), (pd[1], pd[1])),
                            constant_values=neg)
            hp, wp = a.shape[2], a.shape[3]
            oh = (hp - ks[0]) // st[0] + 1
            ow = (wp - ks[1]) // st[1] + 1
            rows = jnp.arange(oh)[:, None] * st[0] + jnp.arange(ks[0])[None, :]
            cols = jnp.arange(ow)[:, None] * st[1] + jnp.arange(ks[1])[None, :]
            win = a[:, :, rows][:, :, :, :, cols]  # [N,C,oh,kh,ow,kw]
            win = jnp.moveaxis(win, 3, 4)          # [N,C,oh,ow,kh,kw]
            flat = win.reshape(n, c, oh, ow, -1)
            arg = jnp.argmax(flat, -1)
            di, dj = arg // ks[1], arg % ks[1]
            r0 = jnp.arange(oh)[None, None, :, None] * st[0]
            c0 = jnp.arange(ow)[None, None, None, :] * st[1]
            return ((r0 + di - pd[0]) * w + (c0 + dj - pd[1])).astype(jnp.int32)

        mask = apply_op(_mask, x, _op_name="max_pool2d_mask")
        return out, mask
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCDHW", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 3, data_format == "NDHWC",
                    jax.lax.max, lambda d: -jnp.inf if jnp.issubdtype(d, jnp.floating) else jnp.iinfo(d).min,
                    "max_pool3d", ceil_mode)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, data_format="NCL", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 1, data_format == "NLC",
                    jax.lax.add, lambda d: jnp.zeros((), d), "avg_pool1d",
                    ceil_mode, exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 2, data_format == "NHWC",
                    jax.lax.add, lambda d: jnp.zeros((), d), "avg_pool2d",
                    ceil_mode, exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 3, data_format == "NDHWC",
                    jax.lax.add, lambda d: jnp.zeros((), d), "avg_pool3d",
                    ceil_mode, exclusive)


def _adaptive_pool_nd(x, output_size, n, channel_last, kind, op_name):
    def _norm_out(a):
        sp = a.shape[1:-1] if channel_last else a.shape[2:]
        osz = output_size if isinstance(output_size, (list, tuple)) else [output_size] * n
        return [s if o is None else int(o) for s, o in zip(sp, osz)]

    def _adaptive(a):
        out_sp = _norm_out(a)
        sp_axes = list(range(1, 1 + n)) if channel_last else list(range(2, 2 + n))
        out = a
        for dim_i, (ax, o) in enumerate(zip(sp_axes, out_sp)):
            size = out.shape[ax]
            if size % o == 0:
                k = size // o
                shape = list(out.shape)
                shape[ax : ax + 1] = [o, k]
                r = out.reshape(shape)
                if kind == "avg":
                    out = jnp.mean(r, axis=ax + 1)
                else:
                    out = jnp.max(r, axis=ax + 1)
            else:
                # general adaptive: gather per output index
                starts = (np.arange(o) * size) // o
                ends = -(-((np.arange(o) + 1) * size) // o)
                slices = []
                for st, en in zip(starts, ends):
                    seg = jax.lax.slice_in_dim(out, int(st), int(en), axis=ax)
                    red = jnp.mean(seg, axis=ax, keepdims=True) if kind == "avg" else jnp.max(seg, axis=ax, keepdims=True)
                    slices.append(red)
                out = jnp.concatenate(slices, axis=ax)
        return out

    return apply_op(_adaptive, x, _op_name=op_name)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool_nd(x, output_size, 1, False, "avg", "adaptive_avg_pool1d")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool_nd(x, output_size, 2, data_format == "NHWC", "avg", "adaptive_avg_pool2d")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool_nd(x, output_size, 3, data_format == "NDHWC", "avg", "adaptive_avg_pool3d")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool_nd(x, output_size, 1, False, "max", "adaptive_max_pool1d")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool_nd(x, output_size, 2, False, "max", "adaptive_max_pool2d")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool_nd(x, output_size, 3, False, "max", "adaptive_max_pool3d")


def lp_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, data_format="NCHW", norm_type=2.0, name=None):
    from ...core.tensor import Tensor

    def _lp(a):
        p = norm_type
        powered = jnp.abs(a) ** p
        return None  # replaced below

    # implement via avg pool of |x|^p then scale
    from ...ops.math import abs as _abs

    k = _tuple_n(kernel_size, 2)
    win = float(np.prod(k))
    powered = apply_op(lambda a: jnp.abs(a) ** norm_type, x, _op_name="lp_pow")
    pooled = avg_pool2d(powered, kernel_size, stride, padding, ceil_mode, True, None, data_format)
    return apply_op(lambda a: (a * win) ** (1.0 / norm_type), pooled, _op_name="lp_pool2d")
