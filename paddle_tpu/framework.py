"""Global framework state: grad mode, default dtype, RNG generators.

Parity targets in the reference:
- grad mode: eager ``tracer._has_grad`` toggled by ``paddle.no_grad``
- default dtype: ``paddle.get_default_dtype`` (python/paddle/framework/dtype)
- RNG: ``phi::Generator`` (paddle/phi/core/generator.h:32) per-device Philox
  state — here a jax PRNG key chain with the same seed/state API.
"""
from __future__ import annotations

import contextlib
import threading

import jax
import numpy as np

from . import dtypes as _dtype_mod


class _GlobalState(threading.local):
    def __init__(self):
        self.grad_enabled = True
        self.default_dtype = _dtype_mod.float32
        self.in_to_static = False


_state = _GlobalState()


# ---------------------------------------------------------------------------
# grad mode
# ---------------------------------------------------------------------------
def is_grad_enabled() -> bool:
    return _state.grad_enabled


def set_grad_enabled(mode: bool):
    """Context manager AND direct setter (paddle.set_grad_enabled)."""

    @contextlib.contextmanager
    def _ctx(prev):
        try:
            yield
        finally:
            _state.grad_enabled = prev

    prev = _state.grad_enabled
    _state.grad_enabled = bool(mode)
    return _ctx(prev)


class no_grad:
    """paddle.no_grad — usable as context manager or decorator."""

    def __enter__(self):
        self._prev = _state.grad_enabled
        _state.grad_enabled = False
        return self

    def __exit__(self, *exc):
        _state.grad_enabled = self._prev
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*a, **k):
            with no_grad():
                return fn(*a, **k)

        return wrapper


class enable_grad(no_grad):
    def __enter__(self):
        self._prev = _state.grad_enabled
        _state.grad_enabled = True
        return self


# ---------------------------------------------------------------------------
# default dtype
# ---------------------------------------------------------------------------
def set_default_dtype(d):
    d = _dtype_mod.convert_dtype(d)
    if not d.is_floating_point:
        raise TypeError(f"default dtype must be floating point, got {d}")
    _state.default_dtype = d


def get_default_dtype():
    return _state.default_dtype


# ---------------------------------------------------------------------------
# RNG: Generator with Philox-like seed/offset semantics over jax PRNG keys.
# ---------------------------------------------------------------------------
class Generator:
    """A stateful RNG generator.

    Mirrors ``phi::Generator``: holds (seed, offset); each random op consumes
    one key. ``manual_seed`` resets the chain. Under jit tracing, the key may
    be supplied externally via :func:`rng_key_scope` so traced programs get
    fresh per-step randomness from a key argument instead of a baked constant.
    """

    def __init__(self, seed: int | None = None):
        if seed is None:
            seed = int(np.random.randint(0, 2**31 - 1))
        self.manual_seed(seed)

    def manual_seed(self, seed: int):
        self._seed = int(seed)
        self._offset = 0
        self._key = jax.random.PRNGKey(self._seed)
        return self

    def seed(self):
        return self.manual_seed(int(np.random.randint(0, 2**31 - 1)))

    @property
    def initial_seed(self):
        return self._seed

    def get_state(self):
        return (self._seed, self._offset)

    def set_state(self, state):
        seed, offset = state
        self.manual_seed(seed)
        for _ in range(offset):
            self.next_key()

    def next_key(self):
        override = _rng_scope_key()
        if override is not None:
            return override
        self._key, sub = jax.random.split(self._key)
        self._offset += 1
        return sub


_default_generator = None
_cpu_generator = None


def default_generator() -> Generator:
    global _default_generator
    if _default_generator is None:
        _default_generator = Generator(0)
    return _default_generator


def seed(s: int):
    """paddle.seed"""
    default_generator().manual_seed(int(s))
    return default_generator()


def get_rng_state():
    return [default_generator().get_state()]


def set_rng_state(state):
    default_generator().set_state(state[0])


def next_rng_key():
    return default_generator().next_key()


def _rng_key_state():
    """Raw O(1) snapshot of the default generator's key chain.
    (`get_rng_state` is the paddle-parity surface, but `set_rng_state`
    REPLAYS `offset` splits to rebuild the key — O(steps). The
    resilience guard snapshots/restores per step, so it needs the raw
    triple.)"""
    g = default_generator()
    return (g._seed, g._offset, g._key)


def _set_rng_key_state(state):
    g = default_generator()
    g._seed, g._offset, g._key = state


# -- traced-RNG scope -------------------------------------------------------
class _RngScope(threading.local):
    def __init__(self):
        self.keys = []


_rng_scope = _RngScope()


def _rng_scope_key():
    if not _rng_scope.keys:
        return None
    # fold a fresh subkey off the scope's chain
    key = _rng_scope.keys[-1]
    key, sub = jax.random.split(key)
    _rng_scope.keys[-1] = key
    return sub


@contextlib.contextmanager
def rng_key_scope(key):
    """All random ops inside draw subkeys from `key` (traced-safe)."""
    _rng_scope.keys.append(key)
    try:
        yield
    finally:
        _rng_scope.keys.pop()


# ---------------------------------------------------------------------------
# mode flags (source compat with reference dygraph/static split)
# ---------------------------------------------------------------------------
def in_dynamic_mode() -> bool:
    return not _state.in_to_static


def in_dynamic_or_pir_mode() -> bool:
    return True


def in_pir_mode() -> bool:
    return False
