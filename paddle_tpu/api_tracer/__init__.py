"""paddle.api_tracer (parity: python/paddle/api_tracer) — record which
public APIs a workload calls (used for coverage/compat audits).

The tracer is also a thin client of ``paddle_tpu.telemetry``: when both
are active, every counted call lands in the shared registry as
``api_calls_total{api=...}`` so coverage audits and perf snapshots read
from one export."""
from __future__ import annotations

import atexit
import functools
import json

from .. import telemetry as _telemetry

__all__ = ["api_tracer", "start_api_tracer"]

_CALLS: dict[str, int] = {}
_ACTIVE = False

_API_CALLS = _telemetry.counter(
    "api_calls_total", "public API calls seen by api_tracer",
    labelnames=("api",), max_series=4096)


def api_tracer(fn):
    """Decorator counting calls when tracing is active."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if _ACTIVE:
            key = f"{fn.__module__}.{fn.__qualname__}"
            _CALLS[key] = _CALLS.get(key, 0) + 1
            _API_CALLS.inc(labels=(key,))
        return fn(*args, **kwargs)

    return wrapper


def start_api_tracer(output_path="api_trace.json"):
    """Start recording; the call table is written at interpreter exit
    (reference contract) and also returned as the live dict."""
    global _ACTIVE
    _ACTIVE = True

    def _dump():
        try:
            with open(output_path, "w") as f:
                json.dump(_CALLS, f, indent=1, sort_keys=True)
        except OSError:
            pass

    atexit.register(_dump)
    return _CALLS
