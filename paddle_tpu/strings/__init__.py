"""String tensors + case-conversion ops.

Capability parity: the reference's strings kernel group
(``paddle/phi/kernels/strings/`` — StringTensor at
``paddle/phi/core/string_tensor.h:33``, lower/upper kernels in
``strings_lower_upper_kernel.h``, unicode tables in ``unicode.cc``). The
reference exposes NO public python surface for these (the kernels back
internal tokenization); here the same capability is a small host-side
tensor type — strings are control-plane data on TPU (variable-length
bytes can't ride the MXU), so the design keeps them in host memory as a
numpy object array with tensor-like shape semantics, convertible to/from
the device world via encode/decode.
"""
from __future__ import annotations

import numpy as np

__all__ = ["StringTensor", "to_string_tensor", "empty", "lower", "upper",
           "encode_utf8", "decode_utf8"]


class StringTensor:
    """Dense tensor of python strings (host memory, numpy object array)."""

    def __init__(self, data):
        arr = np.asarray(data, dtype=object)
        self._data = arr

    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def size(self):
        return int(self._data.size)

    def numpy(self):
        return self._data

    def tolist(self):
        return self._data.tolist()

    def reshape(self, shape):
        return StringTensor(self._data.reshape(shape))

    def __getitem__(self, idx):
        out = self._data[idx]
        if isinstance(out, np.ndarray):
            return StringTensor(out)
        return out

    def __eq__(self, other):
        other_arr = other._data if isinstance(other, StringTensor) else other
        return self._data == other_arr

    def __repr__(self):
        return f"StringTensor(shape={self.shape}, {self._data!r})"


def to_string_tensor(data):
    return data if isinstance(data, StringTensor) else StringTensor(data)


def empty(shape):
    """parity: strings_empty_kernel.cc — an uninitialised string tensor."""
    return StringTensor(np.full(shape, "", dtype=object))


def _case_op(x, fn, use_utf8_encoding):
    t = to_string_tensor(x)
    if use_utf8_encoding:
        out = np.frompyfunc(fn, 1, 1)(t._data)
    else:
        # ASCII-only mode (the reference's non-utf8 kernel variant only
        # touches [A-Za-z])
        def ascii_case(s):
            return "".join(fn(c) if c.isascii() else c for c in s)

        out = np.frompyfunc(ascii_case, 1, 1)(t._data)
    return StringTensor(out)


def lower(x, use_utf8_encoding=True, name=None):
    """parity: strings_lower_upper_kernel.h StringLower."""
    return _case_op(x, str.lower, use_utf8_encoding)


def upper(x, use_utf8_encoding=True, name=None):
    """parity: strings_lower_upper_kernel.h StringUpper."""
    return _case_op(x, str.upper, use_utf8_encoding)


def encode_utf8(x, max_bytes=None, pad=0):
    """StringTensor -> (uint8 device tensor [*, max_bytes], lengths):
    the bridge from host strings into the device world (tokenizers etc.)."""
    import jax.numpy as jnp

    from ..core.tensor import Tensor

    t = to_string_tensor(x)
    flat = [s.encode("utf-8") for s in t._data.reshape(-1)]
    width = max_bytes or max((len(b) for b in flat), default=0)
    buf = np.full((len(flat), width), pad, np.uint8)
    lens = np.zeros(len(flat), np.int32)
    for i, b in enumerate(flat):
        n = min(len(b), width)
        # never cut inside a multi-byte UTF-8 sequence: back off past any
        # continuation bytes (0b10xxxxxx) so decode_utf8 round-trips the
        # kept prefix losslessly
        while n > 0 and n < len(b) and (b[n] & 0xC0) == 0x80:
            n -= 1
        buf[i, :n] = np.frombuffer(b[:n], np.uint8)
        lens[i] = n
    shape = tuple(t._data.shape) + (width,)
    return (Tensor(jnp.asarray(buf.reshape(shape))),
            Tensor(jnp.asarray(lens.reshape(t._data.shape))))


def decode_utf8(codes, lengths=None, pad=0):
    """(uint8 tensor [*, W], lengths) -> StringTensor (inverse bridge).

    Without ``lengths``, trailing ``pad`` bytes are stripped — rows
    shorter than the widest would otherwise come back NUL-polluted."""
    from ..core.tensor import Tensor

    arr = np.asarray(codes._data if isinstance(codes, Tensor) else codes,
                     np.uint8)
    lens = None
    if lengths is not None:
        lens = np.asarray(
            lengths._data if isinstance(lengths, Tensor) else lengths,
            np.int64).reshape(-1)
    flat = arr.reshape(-1, arr.shape[-1])
    out = []
    for i, row in enumerate(flat):
        if lens is not None:
            b = bytes(row[: int(lens[i])])
        else:
            b = bytes(row).rstrip(bytes([pad]))
        out.append(b.decode("utf-8", "replace"))
    return StringTensor(
        np.asarray(out, object).reshape(arr.shape[:-1]))
