"""paddle.sysconfig (parity: python/paddle/sysconfig.py) — build-time
paths for extension authors. The TPU package has no bundled C headers
(custom ops build against the CPython API via utils.cpp_extension), so
get_include points at the package dir and get_lib at the native library
directory (core/native holds the compiled runtime .so)."""
import os

__all__ = ["get_include", "get_lib"]

_PKG = os.path.dirname(os.path.abspath(__file__))


def get_include() -> str:
    # no bundled C headers: custom ops build against the CPython API
    # (utils.cpp_extension), so the package dir is the include root
    return _PKG


def get_lib() -> str:
    return os.path.join(_PKG, "core", "native")
