"""paddle.signal — stft/istft (parity: python/paddle/signal.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .core.dispatch import apply_op


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Overlapping frames. axis=-1: [..., L] -> [..., frame_length, n];
    axis=0: [L, ...] -> [n, frame_length, ...] (paddle convention)."""
    def _fr(a):
        moved = jnp.moveaxis(a, axis, -1)
        n = (moved.shape[-1] - frame_length) // hop_length + 1
        idx = (jnp.arange(n)[:, None] * hop_length
               + jnp.arange(frame_length)[None, :])
        out = moved[..., idx]             # [..., n, frame_length]
        if axis == 0:
            # frames-first convention: [n, frame_length, ...]
            return jnp.moveaxis(out, (-2, -1), (0, 1))
        return jnp.swapaxes(out, -1, -2)   # [..., frame_length, n]

    return apply_op(_fr, x, _op_name="frame")


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of frame. axis=-1 input [..., frame_length, n];
    axis=0 input [n, frame_length, ...] (paddle convention)."""
    def _oa(a):
        if axis == 0:
            frames = jnp.moveaxis(a, (0, 1), (-1, -2))  # -> [..., fl, n]
        else:
            frames = a                      # [..., fl, n]
        fl, n = frames.shape[-2], frames.shape[-1]
        out_len = (n - 1) * hop_length + fl
        out = jnp.zeros(frames.shape[:-2] + (out_len,), a.dtype)
        for i in range(n):
            out = out.at[..., i * hop_length:i * hop_length + fl].add(
                frames[..., i])
        if axis == 0:
            return jnp.moveaxis(out, -1, 0)
        return out

    return apply_op(_oa, x, _op_name="overlap_add")


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    hop = hop_length or n_fft // 4
    wl = win_length or n_fft

    def _stft(a, w):
        if w is None:
            w = jnp.ones((wl,), a.dtype)
        if wl < n_fft:
            lpad = (n_fft - wl) // 2
            w = jnp.pad(w, (lpad, n_fft - wl - lpad))
        if center:
            pad = n_fft // 2
            a = jnp.pad(a, [(0, 0)] * (a.ndim - 1) + [(pad, pad)],
                        mode=pad_mode)
        n = (a.shape[-1] - n_fft) // hop + 1
        idx = jnp.arange(n)[:, None] * hop + jnp.arange(n_fft)[None, :]
        frames = a[..., idx] * w  # [..., n, n_fft]
        spec = (jnp.fft.rfft(frames, axis=-1) if onesided
                else jnp.fft.fft(frames, axis=-1))
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        return jnp.swapaxes(spec, -1, -2)  # [..., freq, n_frames]

    return apply_op(_stft, x, window, _op_name="stft")


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    hop = hop_length or n_fft // 4
    wl = win_length or n_fft

    def _istft(spec, w):
        if w is None:
            w = jnp.ones((wl,), jnp.float32)
        if wl < n_fft:
            lpad = (n_fft - wl) // 2
            w = jnp.pad(w, (lpad, n_fft - wl - lpad))
        frames = jnp.swapaxes(spec, -1, -2)  # [..., n, freq]
        if normalized:
            frames = frames * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        t = (jnp.fft.irfft(frames, n=n_fft, axis=-1) if onesided
             else jnp.real(jnp.fft.ifft(frames, axis=-1)))
        t = t * w
        n = t.shape[-2]
        out_len = (n - 1) * hop + n_fft
        out = jnp.zeros(t.shape[:-2] + (out_len,), t.dtype)
        wsum = jnp.zeros((out_len,), t.dtype)
        for i in range(n):
            out = out.at[..., i * hop:i * hop + n_fft].add(t[..., i, :])
            wsum = wsum.at[i * hop:i * hop + n_fft].add(w * w)
        out = out / jnp.maximum(wsum, 1e-10)
        if center:
            pad = n_fft // 2
            out = out[..., pad:out.shape[-1] - pad]
        if length is not None:
            out = out[..., :length]
        return out

    return apply_op(_istft, x, window, _op_name="istft")
