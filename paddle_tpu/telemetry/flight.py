"""Flight recorder: crash forensics bundles from every abort path.

Generalizes the HangWatchdog debris contract (resilience/watchdog.py —
all-thread stacks + a telemetry snapshot on a wedged step) into one
subsystem every abort path shares: a :class:`FlightRecorder` keeps a
rolling in-memory window of the most recent time-series samples
(timeseries.py), SLO alert events (slo.py), and structured flight
events; any abort path calls :func:`maybe_dump` and the window lands
atomically on disk as ONE self-contained JSON forensics bundle.

Abort paths wired through this module (docs/TELEMETRY.md):

- ``guard_abort``    — StepGuard raising GuardAbortError (resilience)
- ``hang``           — HangWatchdog firing (its debris file IS a bundle)
- ``replica_death``  — FleetRouter marking a replica permanently dead
- ``breaker_open``   — a replica circuit breaker opening
- ``brownout_step``  — the brownout ladder stepping DOWN a level
- ``preemption``     — PreemptionGuard catching SIGTERM/SIGINT
- ``soak_end``       — a recorded soak completing (the happy-path dump)

Bundle schema (``SCHEMA``; tools/flight_report.py validates, exits 1 on
malformed)::

    {"schema": "ptpu-flight-1", "reason": str, "ts": float, "pid": int,
     "seq": int, "context": {...caller specifics...},
     "samples": [...recent timeline samples...],
     "alerts":  [...recent SLO alert events...],
     "events":  [...recent flight events (kind, ts, attrs)...],
     "trace_events": [...tail of the span tracer ring...],
     "live_spans": {...per-thread open-span stacks...},
     "telemetry": {...full registry snapshot...},
     "threads": {...all-thread interpreter stacks...}}

Pure stdlib and standalone-loadable (tools/flight_report.py loads this
file by path): the live sources — registry snapshot, tracer ring, open
spans, the bundles-dumped counter — are injected by
``paddle_tpu.telemetry`` at import via :func:`set_default_sources`, so
this module never imports the package it serves.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback

SCHEMA = "ptpu-flight-1"

#: bundle keys every valid dump carries, with their required types
_REQUIRED = (("schema", str), ("reason", str), ("ts", (int, float)),
             ("pid", int), ("samples", list), ("alerts", list),
             ("events", list), ("telemetry", dict))


def thread_stacks():
    """{thread_name:ident -> [stack lines]} for every live thread
    (the HangWatchdog debris field, shared here so every bundle names
    what the host was doing at dump time)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in sys._current_frames().items():
        key = f"{names.get(ident, '?')}:{ident}"
        out[key] = traceback.format_stack(frame)
    return out


def _atomic_write(path, data):
    """tmp + fsync-less os.replace — a torn bundle must never exist
    under its final name (same contract as the checkpoint writer)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


# injected by paddle_tpu.telemetry at import; each returns {} / None
# gracefully so a standalone load of this file still works
_SOURCES = {
    "snapshot": lambda: {},       # registry snapshot
    "trace_events": lambda: [],   # completed-span ring (tail)
    "live_spans": lambda: {},     # per-thread open-span stacks
    "on_dump": lambda reason: None,  # flight_bundles_total counter
}


def set_default_sources(**fns):
    """Bind the live telemetry sources (done once by
    ``paddle_tpu.telemetry``); unknown names raise."""
    for k, fn in fns.items():
        if k not in _SOURCES:
            raise ValueError(f"unknown flight source {k!r}")
        _SOURCES[k] = fn


class FlightRecorder:
    """Rolling forensics window + atomic bundle dumps.

    Windows are bounded (``sample_window`` timeline samples,
    ``alert_window`` SLO events, ``event_window`` flight events,
    ``trace_tail`` tracer events per dump). ``min_dump_interval``
    rate-limits per-reason dumps on the WALL clock (a brownout ladder
    oscillating during a soak must not spray files), ``max_bundles``
    hard-caps files per recorder lifetime; suppressed dumps are counted,
    never silently lost."""

    def __init__(self, dump_dir, *, sample_window=128, alert_window=64,
                 event_window=128, trace_tail=256, max_bundles=64,
                 min_dump_interval=0.25, clock=time.time):
        self.dump_dir = str(dump_dir)
        self.sample_window = int(sample_window)
        self.alert_window = int(alert_window)
        self.event_window = int(event_window)
        self.trace_tail = int(trace_tail)
        self.max_bundles = int(max_bundles)
        self.min_dump_interval = float(min_dump_interval)
        self.clock = clock
        self.samples = []
        self.alerts = []
        self.flight_events = []
        self.bundles = []             # paths written, oldest first
        self.suppressed = {}          # reason -> dumps rate-limited away
        self._last_dump = {}          # reason -> wall ts
        self._lock = threading.Lock()

    # -- window feeds --------------------------------------------------------
    def note_sample(self, sample):
        with self._lock:
            self.samples.append(sample)
            if len(self.samples) > self.sample_window:
                del self.samples[:len(self.samples) - self.sample_window]

    def note_alert(self, event):
        with self._lock:
            self.alerts.append(event)
            if len(self.alerts) > self.alert_window:
                del self.alerts[:len(self.alerts) - self.alert_window]

    def note_event(self, kind, attrs=None):
        """A structured flight event (breaker transition, brownout step,
        requeue storm...) — cheap, in-memory, lands in the next dump."""
        evt = {"ts": self.clock(), "kind": str(kind),
               "attrs": dict(attrs or {})}
        with self._lock:
            self.flight_events.append(evt)
            if len(self.flight_events) > self.event_window:
                del self.flight_events[
                    :len(self.flight_events) - self.event_window]
        return evt

    # -- bundles -------------------------------------------------------------
    def build_bundle(self, reason, context=None):
        """The self-contained forensics dict (no I/O). The watchdog
        builds its debris through this and layers its legacy hang
        fields on top, so a debris file validates as a flight bundle."""
        with self._lock:
            samples = list(self.samples)
            alerts = list(self.alerts)
            events = list(self.flight_events)
        try:
            trace_events = list(_SOURCES["trace_events"]()
                                or [])[-self.trace_tail:]
        except Exception:   # noqa: BLE001 — forensics must not raise
            trace_events = []
        try:
            live = _SOURCES["live_spans"]() or {}
        except Exception:   # noqa: BLE001
            live = {}
        try:
            snap = _SOURCES["snapshot"]() or {}
        except Exception:   # noqa: BLE001
            snap = {}
        return {
            "schema": SCHEMA,
            "reason": str(reason),
            "ts": time.time(),
            "pid": os.getpid(),
            "seq": len(self.bundles),
            "context": dict(context or {}),
            "samples": samples,
            "alerts": alerts,
            "events": events,
            "trace_events": trace_events,
            "live_spans": live,
            "telemetry": snap,
            "threads": thread_stacks(),
        }

    def dump(self, reason, context=None, force=False):
        """Write one bundle; returns its path, or None when suppressed
        (rate limit / cap) or the filesystem is gone — an abort path
        must never die on its own forensics."""
        now = time.time()
        with self._lock:
            if not force:
                if len(self.bundles) >= self.max_bundles:
                    self.suppressed[reason] = (
                        self.suppressed.get(reason, 0) + 1)
                    return None
                last = self._last_dump.get(reason)
                if (last is not None
                        and now - last < self.min_dump_interval):
                    self.suppressed[reason] = (
                        self.suppressed.get(reason, 0) + 1)
                    return None
            self._last_dump[reason] = now
            seq = len(self.bundles)
            self.bundles.append(None)       # reserve the seq slot
        payload = self.build_bundle(reason, context)
        payload["seq"] = seq
        path = os.path.join(
            self.dump_dir,
            f"flight_{reason}_{seq:04d}_pid{os.getpid()}.json")
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            _atomic_write(path, json.dumps(
                payload, indent=1, sort_keys=True).encode())
        except OSError:
            with self._lock:
                self.bundles[seq] = None
            return None
        with self._lock:
            self.bundles[seq] = path
        try:
            _SOURCES["on_dump"](str(reason))
        except Exception:   # noqa: BLE001
            pass
        return path

    def bundle_paths(self):
        with self._lock:
            return [p for p in self.bundles if p]

    def summary(self):
        with self._lock:
            return {"dump_dir": self.dump_dir,
                    "bundles": [p for p in self.bundles if p],
                    "suppressed": dict(self.suppressed),
                    "samples_window": len(self.samples),
                    "alerts_window": len(self.alerts),
                    "events_window": len(self.flight_events)}


# ---------------------------------------------------------------------------
# Process-global recorder: abort paths deep in the stack (guard, router,
# brownout, preemption) call the module functions, which no-op until a
# recorder is installed — forensics are opt-in, never a tax.
# ---------------------------------------------------------------------------
_RECORDER = None
_ENV_DIR = "PTPU_FLIGHT_DIR"


def install(dump_dir, **kw):
    """Install the process flight recorder (returns it; replaces any
    previous one — tests install into tmp dirs repeatedly)."""
    global _RECORDER
    _RECORDER = FlightRecorder(dump_dir, **kw)
    return _RECORDER


def uninstall():
    global _RECORDER
    r, _RECORDER = _RECORDER, None
    return r


def get():
    return _RECORDER


def installed():
    return _RECORDER is not None


def maybe_install_from_env(environ=None):
    """PTPU_FLIGHT_DIR set and no recorder installed -> install one
    there (called by ``paddle_tpu.telemetry.enable()``)."""
    d = (environ if environ is not None else os.environ).get(_ENV_DIR)
    if d and _RECORDER is None:
        return install(d)
    return _RECORDER


def build_bundle(reason, context=None):
    """A self-contained bundle dict through the installed recorder's
    windows — or with empty windows when none is installed (the
    HangWatchdog builds its debris through this either way, so a debris
    file is ALWAYS a valid flight bundle)."""
    r = _RECORDER
    if r is None:
        r = FlightRecorder(".")          # windowless; no I/O happens
    return r.build_bundle(reason, context)


def maybe_dump(reason, context=None):
    """Dump a bundle through the installed recorder; None when no
    recorder is installed (the disabled-telemetry discipline: one
    attribute check, no work)."""
    r = _RECORDER
    return r.dump(reason, context) if r is not None else None


def note_event(kind, attrs=None):
    r = _RECORDER
    return r.note_event(kind, attrs) if r is not None else None


def note_alert(event):
    r = _RECORDER
    if r is not None:
        r.note_alert(event)


def note_sample(sample):
    r = _RECORDER
    if r is not None:
        r.note_sample(sample)


# ---------------------------------------------------------------------------
# Validation — tools/flight_report.py's CI contract
# ---------------------------------------------------------------------------
def validate_bundle(bundle):
    """-> list of problem strings (empty == valid). Checks the typed
    required keys and per-entry shapes of the windows; legacy extras
    (the watchdog's hang fields) are allowed on top."""
    if not isinstance(bundle, dict):
        return ["bundle is not a JSON object"]
    problems = []
    for key, typ in _REQUIRED:
        if key not in bundle:
            problems.append(f"missing required key {key!r}")
        elif not isinstance(bundle[key], typ):
            problems.append(
                f"key {key!r}: expected {getattr(typ, '__name__', typ)}, "
                f"got {type(bundle[key]).__name__}")
    if bundle.get("schema") not in (None, SCHEMA):
        problems.append(f"unknown schema {bundle.get('schema')!r} "
                        f"(expected {SCHEMA!r})")
    if isinstance(bundle.get("reason"), str) and not bundle["reason"]:
        problems.append("empty reason")
    for i, s in enumerate(bundle.get("samples") or []):
        if not isinstance(s, dict) or "ts" not in s or "seq" not in s:
            problems.append(f"samples[{i}]: not a timeline sample "
                            "(needs ts + seq)")
            break
    for i, a in enumerate(bundle.get("alerts") or []):
        if not isinstance(a, dict) or "event" not in a \
                or "objective" not in a:
            problems.append(f"alerts[{i}]: not an SLO alert event "
                            "(needs event + objective)")
            break
    for i, e in enumerate(bundle.get("events") or []):
        if not isinstance(e, dict) or "kind" not in e:
            problems.append(f"events[{i}]: not a flight event "
                            "(needs kind)")
            break
    return problems


def load_bundle(path):
    """Parse + validate one bundle file; raises ValueError listing every
    problem on a malformed bundle."""
    with open(path) as f:
        try:
            bundle = json.load(f)
        except ValueError as e:
            raise ValueError(f"{path}: not JSON ({e})") from e
    problems = validate_bundle(bundle)
    if problems:
        raise ValueError(f"{path}: malformed flight bundle: "
                         + "; ".join(problems))
    return bundle
