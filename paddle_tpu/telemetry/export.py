"""Prometheus-text and JSONL exporters over a MetricRegistry.

Both exporters read live metric objects (not a snapshot) so bucket
layouts are exact; both are pure stdlib.
"""
from __future__ import annotations

import json
import time


def _escape_label_value(v):
    """Prometheus exposition escaping: backslash, double-quote, newline."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(text):
    """HELP-line escaping per the exposition format: backslash and
    newline only (no quote escaping — HELP text is not quoted). A help
    string containing a literal newline would otherwise split into a
    second, unparseable exposition line."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_labels(labelnames, labels, extra=()):
    # extra pairs (today only histogram `le` bounds) go through the
    # SAME value escaping as named labels: the exposition format makes
    # no distinction, and an unescaped quote/backslash/newline in any
    # label value splits or corrupts the line for every parser
    pairs = [f'{k}="{_escape_label_value(v)}"'
             for k, v in zip(labelnames, labels)]
    pairs.extend(f'{k}="{_escape_label_value(v)}"' for k, v in extra)
    return "{" + ",".join(pairs) + "}" if pairs else ""


def _fmt_bucket_bound(b):
    return repr(float(b))


def export_prometheus(registry) -> str:
    """Render every series in the Prometheus text exposition format."""
    lines = []
    for m in registry.metrics():
        series = m.series()
        if not series:
            continue
        if m.help:
            lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
        lines.append(f"# TYPE {m.name} {m.kind}")
        for labels, value in sorted(series.items()):
            if m.kind == "histogram":
                cum = 0
                for bound, count in zip(
                        m.buckets,
                        (value["buckets"][repr(b)] for b in m.buckets)):
                    cum += count
                    lbl = _fmt_labels(m.labelnames, labels,
                                      [("le", _fmt_bucket_bound(bound))])
                    lines.append(f"{m.name}_bucket{lbl} {cum}")
                lbl = _fmt_labels(m.labelnames, labels, [("le", "+Inf")])
                lines.append(f"{m.name}_bucket{lbl} {value['count']}")
                base = _fmt_labels(m.labelnames, labels)
                lines.append(f"{m.name}_sum{base} {value['sum']}")
                lines.append(f"{m.name}_count{base} {value['count']}")
            else:
                lbl = _fmt_labels(m.labelnames, labels)
                lines.append(f"{m.name}{lbl} {value}")
    return "\n".join(lines) + ("\n" if lines else "")


#: keys dump_jsonl owns on every line — a caller tag must not be able to
#: silently clobber them (a run tagged extra={"value": "r06"} would
#: corrupt every counter in the file undetectably)
_RESERVED_JSONL_KEYS = frozenset(
    {"ts", "metric", "kind", "labels", "value", "buckets",
     "count", "sum", "min", "max", "mean", "p50", "p95", "p99"})


def dump_jsonl(registry, path, mode="a", extra=None) -> int:
    """Append one JSON line per live series to `path`.

    Line shape: {"ts", "metric", "kind", "labels": {name: value}, and
    either "value" (counter/gauge) or the histogram stats dict}. Returns
    the number of lines written. `extra` (a dict) is merged into every
    line — callers tag runs (bench round, step number) that way; a tag
    colliding with a reserved record key raises ValueError instead of
    silently overwriting it."""
    if extra:
        bad = sorted(_RESERVED_JSONL_KEYS & set(extra))
        if bad:
            raise ValueError(
                f"dump_jsonl: extra keys {bad} collide with reserved "
                "record fields — rename the tags (e.g. prefix them: "
                f"{', '.join('tag_' + b for b in bad)})")
    ts = time.time()
    n = 0
    with open(path, mode) as f:
        for m in registry.metrics():
            for labels, value in sorted(m.series().items()):
                rec = {"ts": ts, "metric": m.name, "kind": m.kind,
                       "labels": dict(zip(m.labelnames, labels))}
                if m.kind == "histogram":
                    rec.update({k: v for k, v in value.items()
                                if k != "buckets"})
                    rec["buckets"] = value["buckets"]
                else:
                    rec["value"] = value
                if extra:
                    rec.update(extra)
                f.write(json.dumps(rec) + "\n")
                n += 1
    return n


def load_jsonl(path):
    """Parse a dump_jsonl file back into a list of record dicts."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out
