"""Time-series recorder over a MetricRegistry: bounded histories + JSONL.

The registry (registry.py) answers "what are the totals *now*"; nothing
answered "how did any signal evolve". This module samples a registry
snapshot — on a background cadence and/or on demand (the soak harness
samples once per fleet tick) — into a bounded in-memory ring of
flattened samples, derives per-interval rates for counters, and
optionally appends every sample to a JSONL timeline file next to the
bench output. The SLO engine (slo.py) evaluates burn-rate windows over
these histories; the flight recorder (flight.py) keeps the most recent
window for crash forensics; ``/timeline`` on the scrape endpoint
(scrape.py) serves the same view live.

Sample schema (one JSON object per timeline line, ``SCHEMA``):

    {"schema": "ptpu-timeline-1",   # first line only in JSONL files
     "ts":   <recorder clock seconds — sim clock inside a soak>,
     "wall": <wall-clock time.time()>,
     "seq":  <monotone sample index>,
     "counters":   {"name" | "name{k=v,...}": cumulative value},
     "gauges":     {flat_key: value},
     "histograms": {flat_key: {count,sum,min,max,mean,p50,p95,p99}},
     "values":     {name: value}}    # caller extras (per-tick signals)

Signal spec strings (shared with slo.py and the report tools) address
one scalar series inside that schema::

    "gauges:fleet_pending_depth"
    "values:ttft_p99_recent"
    "counters:serving_shed_total{reason=queue_depth}:rate"   # per-sec
    "counters:serving_shed_total{reason=queue_depth}:delta"
    "histograms:serving_ttft_seconds:p99"

Pure stdlib, no imports from the rest of the package — the report tools
(tools/flight_report.py, tools/telemetry_report.py --timeline) load this
file directly by path so the timeline reader is shared without paying a
framework import.
"""
from __future__ import annotations

import json
import os
import threading
import time

SCHEMA = "ptpu-timeline-1"

#: histogram stat fields copied into a sample (buckets are dropped —
#: a timeline line must stay bounded; the full layout lives in the
#: registry snapshot the flight recorder embeds)
HIST_FIELDS = ("count", "sum", "min", "max", "mean", "p50", "p95", "p99")

_GROUPS = ("counters", "gauges", "histograms", "values")


def flat_key(name, label_key=""):
    """``name`` or ``name{k=v,...}`` — the registry's label_key joined
    onto the metric name, matching the Prometheus series identity."""
    return f"{name}{{{label_key}}}" if label_key else str(name)


def flatten_snapshot(snap):
    """Registry ``snapshot()`` dict -> (counters, gauges, histograms)
    flat dicts keyed by :func:`flat_key`."""
    counters, gauges, hists = {}, {}, {}
    for name, series in (snap.get("counters") or {}).items():
        for lk, v in series.items():
            counters[flat_key(name, lk)] = v
    for name, series in (snap.get("gauges") or {}).items():
        for lk, v in series.items():
            gauges[flat_key(name, lk)] = v
    for name, series in (snap.get("histograms") or {}).items():
        for lk, h in series.items():
            hists[flat_key(name, lk)] = {
                k: h.get(k) for k in HIST_FIELDS}
    return counters, gauges, hists


def parse_spec(spec):
    """``"group:key[:field]"`` -> (group, key, field|None). The key may
    itself contain ``:`` only inside ``{...}`` label braces; fields are
    a trailing bare token (``rate``/``delta`` for counters, a
    HIST_FIELDS name for histograms)."""
    parts = str(spec).split(":")
    if len(parts) < 2:
        raise ValueError(
            f"signal spec {spec!r}: expected 'group:key[:field]'")
    group = parts[0]
    if group not in _GROUPS:
        raise ValueError(
            f"signal spec {spec!r}: group {group!r} not in {_GROUPS}")
    field = None
    if len(parts) > 2 and "{" not in parts[-1] and "}" not in parts[-1]:
        field = parts[-1]
        key = ":".join(parts[1:-1])
    else:
        key = ":".join(parts[1:])
    return group, key, field


def sample_value(sample, group, key, field=None):
    """One scalar out of one sample dict (None when absent). Counters
    with field rate/delta need TWO samples — use :func:`series_from`."""
    g = sample.get(group) or {}
    v = g.get(key)
    if v is None:
        return None
    if group == "histograms":
        return v.get(field or "p99")
    return v


def series_from(samples, spec):
    """[(ts, value)] for one signal spec over a sample list. Counter
    ``:rate`` is the per-second derivative between consecutive samples
    (first sample has no rate and is skipped); ``:delta`` the raw
    difference. Samples where the signal is absent are skipped."""
    group, key, field = parse_spec(spec)
    out = []
    if group == "counters" and field in ("rate", "delta"):
        prev = None
        for s in samples:
            v = (s.get("counters") or {}).get(key)
            if v is None:
                continue
            if prev is not None:
                pv, pt = prev
                if field == "delta":
                    out.append((s["ts"], v - pv))
                else:
                    dt = s["ts"] - pt
                    out.append((s["ts"], (v - pv) / dt if dt > 0
                                else 0.0))
            prev = (v, s["ts"])
        return out
    for s in samples:
        v = sample_value(s, group, key, field)
        if v is not None:
            out.append((s["ts"], v))
    return out


class TimeSeriesRecorder:
    """Bounded ring of registry samples + optional JSONL persistence.

    ``source`` is anything with a ``snapshot()`` method (a
    MetricRegistry) or a zero-arg callable returning a snapshot dict;
    None records caller extras only. ``clock`` supplies the sample
    timestamp — a soak rebases it onto its simulated-parallel clock the
    same way the overload controller is rebased. ``flight`` (a
    flight.FlightRecorder) receives every sample into its rolling
    forensics window.
    """

    def __init__(self, source=None, *, capacity=512, clock=None,
                 jsonl_path=None, flight=None):
        self._snapshot_fn = (source.snapshot if hasattr(source, "snapshot")
                             else source)
        self.capacity = int(capacity)
        self._clock = clock or time.time
        self.jsonl_path = str(jsonl_path) if jsonl_path else None
        self.flight = flight
        self.samples = []            # ring, oldest first
        self.seq = 0
        self.dropped = 0             # samples evicted from the ring
        self._lock = threading.Lock()
        self._file = None
        self._stop = threading.Event()
        self._thread = None
        self._wrote_header = False

    # -- clocks --------------------------------------------------------------
    def set_clock(self, clock):
        """Rebase the sample timestamp source (soak: the sim clock)."""
        self._clock = clock
        return self

    # -- sampling ------------------------------------------------------------
    def sample(self, values=None, counters=None, tags=None):
        """Take one sample now; returns the sample dict. ``values``
        merge into the sample's ``values`` group (gauge-like per-tick
        signals: queue depth, brownout level, recent TTFT); ``counters``
        merge into ``counters`` (cumulative — rate derivation applies);
        ``tags`` ride along verbatim (e.g. the soak tick number)."""
        snap = self._snapshot_fn() if self._snapshot_fn else None
        c, g, h = flatten_snapshot(snap) if snap else ({}, {}, {})
        if counters:
            for k, v in counters.items():
                c[str(k)] = v
        s = {"ts": float(self._clock()), "wall": time.time(),
             "seq": self.seq, "counters": c, "gauges": g,
             "histograms": h,
             "values": {str(k): v for k, v in (values or {}).items()}}
        if tags:
            s["tags"] = dict(tags)
        with self._lock:
            self.seq += 1
            self.samples.append(s)
            if len(self.samples) > self.capacity:
                del self.samples[:len(self.samples) - self.capacity]
                self.dropped += 1
            self._append_jsonl(s)
        if self.flight is not None:
            self.flight.note_sample(s)
        return s

    def _append_jsonl(self, s):
        if self.jsonl_path is None:
            return
        if self._file is None:
            d = os.path.dirname(self.jsonl_path)
            if d:
                os.makedirs(d, exist_ok=True)
            self._file = open(self.jsonl_path, "a")
            if self._file.tell() == 0 and not self._wrote_header:
                self._file.write(json.dumps(
                    {"schema": SCHEMA, "wall": time.time()}) + "\n")
            self._wrote_header = True
        self._file.write(json.dumps(s) + "\n")
        self._file.flush()

    # -- background cadence --------------------------------------------------
    def start(self, interval=1.0):
        """Sample every ``interval`` seconds on a daemon thread until
        :meth:`stop` (idempotent; bench.py --record uses this)."""
        if self._thread is None:
            self._stop.clear()

            def _run():
                while not self._stop.wait(interval):
                    try:
                        self.sample()
                    except Exception:   # noqa: BLE001 — a dead registry
                        pass            # must not kill the cadence
            self._thread = threading.Thread(
                target=_run, daemon=True, name="ptpu-timeseries")
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10)
        return self

    def close(self):
        self.stop()
        with self._lock:
            f, self._file = self._file, None
        if f is not None:
            f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- queries -------------------------------------------------------------
    def last(self):
        with self._lock:
            return self.samples[-1] if self.samples else None

    def window(self, n=None, seconds=None):
        """Tail of the ring: last ``n`` samples, or every sample within
        ``seconds`` of the newest (both None -> all)."""
        with self._lock:
            samples = list(self.samples)
        if seconds is not None and samples:
            cut = samples[-1]["ts"] - float(seconds)
            samples = [s for s in samples if s["ts"] >= cut]
        if n is not None:
            samples = samples[-int(n):]
        return samples

    def keys(self, group=None):
        """Sorted flat keys seen across the ring (one group or all,
        prefixed ``group:``)."""
        groups = (group,) if group else _GROUPS
        out = set()
        for s in self.window():
            for g in groups:
                for k in (s.get(g) or {}):
                    out.add(k if group else f"{g}:{k}")
        return sorted(out)

    def series(self, spec, n=None, seconds=None):
        """[(ts, value)] for one signal spec over the (windowed) ring."""
        return series_from(self.window(n=n, seconds=seconds), spec)

    def rates(self, key, n=None):
        """Counter per-second rates: shorthand for
        ``series(f"counters:{key}:rate")``."""
        return self.series(f"counters:{key}:rate", n=n)

    def timeline_view(self, n=50):
        """JSON-able summary for the scrape endpoint's /timeline."""
        samples = self.window(n=n)
        return {"schema": SCHEMA, "samples": samples,
                "total_samples": self.seq, "capacity": self.capacity,
                "dropped": self.dropped}


# ---------------------------------------------------------------------------
# Timeline JSONL reader — THE shared reader (tools/flight_report.py and
# tools/telemetry_report.py --timeline both load this module by path)
# ---------------------------------------------------------------------------
def read_timeline(path):
    """Parse a timeline JSONL file back into a list of sample dicts.
    The optional first header line ({"schema": ...} with no "seq") is
    validated and dropped; malformed JSON raises ValueError with the
    offending line number."""
    samples = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError as e:
                raise ValueError(
                    f"{path}:{i}: not JSON ({e})") from e
            if not isinstance(obj, dict):
                raise ValueError(f"{path}:{i}: expected a JSON object")
            if "seq" not in obj:
                schema = obj.get("schema")
                if schema is not None and schema != SCHEMA:
                    raise ValueError(
                        f"{path}:{i}: unknown timeline schema "
                        f"{schema!r} (expected {SCHEMA!r})")
                continue                     # header / annotation line
            samples.append(obj)
    return samples


def timeline_keys(samples, group=None):
    """Sorted flat keys present in a sample list (mirror of
    :meth:`TimeSeriesRecorder.keys` for on-disk timelines)."""
    groups = (group,) if group else _GROUPS
    out = set()
    for s in samples:
        for g in groups:
            for k in (s.get(g) or {}):
                out.add(k if group else f"{g}:{k}")
    return sorted(out)
