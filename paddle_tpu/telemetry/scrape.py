"""Zero-dependency HTTP scrape endpoint: /metrics + /timeline.

A live soak (or the future multi-process fleet) is watchable without
stopping it: ``ScrapeServer`` serves the Prometheus text exposition of
the registry at ``/metrics``, the recorder's recent timeline as JSON at
``/timeline`` (``?n=K`` bounds the tail), the installed flight
recorder's bundle inventory at ``/flight``, and a liveness probe at
``/healthz`` — stdlib ``http.server`` only, one daemon thread, bound to
loopback by default.

Knobs (docs/TELEMETRY.md): ``PTPU_METRICS_PORT`` (set -> ``enable()``
auto-starts a server there; 0 picks a free port, printed on stderr) and
``PTPU_METRICS_HOST`` (default 127.0.0.1 — never expose a debug
endpoint beyond loopback by default).
"""
from __future__ import annotations

import json
import os
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from . import export as _export
from . import flight as _flight

__all__ = ["ScrapeServer", "start_from_env", "maybe_start_from_env"]

_ENV_PORT = "PTPU_METRICS_PORT"
_ENV_HOST = "PTPU_METRICS_HOST"

#: Prometheus text exposition content type (version is part of the
#: scrape contract, not decoration)
_PROM_CTYPE = "text/plain; version=0.0.4; charset=utf-8"


class ScrapeServer:
    """One registry (+ optional recorder) behind an HTTP endpoint."""

    def __init__(self, registry, recorder=None, *, port=0,
                 host="127.0.0.1", replica_id=None):
        self.registry = registry
        self.recorder = recorder
        self._host = host
        self._want_port = int(port)
        self._httpd = None
        self._thread = None
        #: fleet replica id, if this endpoint serves a child process
        #: (shows up in /healthz and the scrape_endpoint gauge label)
        self.replica_id = replica_id

    # -- lifecycle -----------------------------------------------------------
    @property
    def port(self):
        return (self._httpd.server_address[1] if self._httpd
                else self._want_port)

    @property
    def url(self):
        return f"http://{self._host}:{self.port}"

    def start(self):
        if self._httpd is not None:
            return self
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):       # silent: a scrape every
                pass                         # few seconds is not a log

            def do_GET(self):
                server._handle(self)

        self._httpd = ThreadingHTTPServer(
            (self._host, self._want_port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="ptpu-scrape")
        self._thread.start()
        # Register the bound (possibly auto-picked) port on the registry
        # so a supervisor scraping the parent can discover child
        # endpoints: `port=0` is resolved by the kernel, and the only
        # in-band channel back out is a metric.
        try:
            label = (str(self.replica_id) if self.replica_id is not None
                     else "main")
            self.registry.gauge(
                "scrape_endpoint",
                "bound port of a /metrics scrape endpoint, by replica",
                ("replica",)).set(float(self.port), (label,))
        except Exception:       # a scrape endpoint must never kill boot
            pass
        return self

    def stop(self):
        httpd, self._httpd = self._httpd, None
        t, self._thread = self._thread, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if t is not None:
            t.join(timeout=10)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- routing -------------------------------------------------------------
    def _handle(self, req):
        parsed = urlparse(req.path)
        route = parsed.path.rstrip("/") or "/"
        try:
            if route == "/metrics":
                body = _export.export_prometheus(
                    self.registry).encode()
                self._send(req, 200, _PROM_CTYPE, body)
            elif route == "/timeline":
                q = parse_qs(parsed.query)
                try:
                    n = int(q.get("n", ["50"])[0])
                except ValueError:
                    self._send_json(req, 400,
                                    {"error": "n must be an integer"})
                    return
                view = (self.recorder.timeline_view(n=n)
                        if self.recorder is not None
                        else {"schema": None, "samples": [],
                              "total_samples": 0,
                              "error": "no recorder attached"})
                self._send_json(req, 200, view)
            elif route == "/flight":
                fr = _flight.get()
                self._send_json(req, 200, fr.summary() if fr is not None
                                else {"installed": False})
            elif route in ("/", "/healthz"):
                self._send_json(req, 200, {
                    "ok": True,
                    "enabled": bool(getattr(self.registry, "enabled",
                                            False)),
                    "replica_id": self.replica_id,
                    "pid": os.getpid(),
                    "port": self.port,
                    "routes": ["/metrics", "/timeline", "/flight",
                               "/healthz"]})
            else:
                self._send_json(req, 404, {"error": f"no route "
                                           f"{route!r}"})
        except BrokenPipeError:        # scraper went away mid-response
            pass

    @staticmethod
    def _send(req, code, ctype, body):
        req.send_response(code)
        req.send_header("Content-Type", ctype)
        req.send_header("Content-Length", str(len(body)))
        req.end_headers()
        req.wfile.write(body)

    def _send_json(self, req, code, obj):
        self._send(req, code, "application/json",
                   json.dumps(obj).encode())


def start_from_env(registry, recorder=None, environ=None):
    """PTPU_METRICS_PORT -> a started ScrapeServer, else None."""
    env = environ if environ is not None else os.environ
    port = env.get(_ENV_PORT)
    if not port:
        return None
    try:
        port = int(port)
    except ValueError:
        sys.stderr.write(
            f"# telemetry: ignoring non-integer {_ENV_PORT}={port!r}\n")
        return None
    server = ScrapeServer(registry, recorder, port=port,
                          host=env.get(_ENV_HOST, "127.0.0.1")).start()
    sys.stderr.write(f"# telemetry: scrape endpoint at {server.url}"
                     "/metrics (+ /timeline, /flight)\n")
    return server


_AUTO = [None]


def maybe_start_from_env(registry, recorder=None):
    """Idempotent env auto-start used by ``telemetry.enable()``."""
    if _AUTO[0] is None:
        _AUTO[0] = start_from_env(registry, recorder)
    return _AUTO[0]
