"""paddle_tpu.telemetry — framework-wide metrics and events.

One process-local registry collects everything the runtime knows about
itself: op-dispatch counts (core/dispatch), collective calls and bytes
(distributed/communication), jit compile events and the recompile
watchdog (jit, telemetry.watchdog), optimizer/train-step timing, and the
serving engine's queue/occupancy/KV-page/latency metrics
(inference/serving). ``paddle_tpu.profiler`` and ``paddle_tpu.api_tracer``
are thin clients: their step timings and call counts land in the same
registry, so one snapshot explains a run.

Usage::

    import paddle_tpu.telemetry as telemetry

    telemetry.enable()
    ...                               # run the workload
    snap = telemetry.snapshot()       # JSON-able dict
    print(telemetry.export_prometheus())
    telemetry.dump_jsonl("metrics.jsonl")

Disabled (the default) every instrument is a single attribute check;
``enable()`` also arms the recompile watchdog and mirrors jax's own
compile-duration events into the registry. The metric-name/label
contract is documented in docs/TELEMETRY.md.
"""
from __future__ import annotations

import time

from .registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    Metric,
    MetricRegistry,
    DEFAULT_BUCKETS,
    DEFAULT_MAX_SERIES,
)
from . import export as _export
from . import trace  # noqa: F401  (span tracer: telemetry.trace.span(...))
from . import flight  # noqa: F401  (crash-forensics bundles)
from . import scrape  # noqa: F401  (HTTP /metrics + /timeline endpoint)
from . import slo  # noqa: F401  (burn-rate alerting over histories)
from . import timeseries  # noqa: F401  (ring-buffer histories + JSONL)
from .slo import SloEngine, SloObjective  # noqa: F401
from .timeseries import TimeSeriesRecorder  # noqa: F401
from .watchdog import (  # noqa: F401
    RecompileWarning,
    RecompileWatchdog,
    install_jax_compile_listener,
)

__all__ = [
    "enable", "disable", "enabled", "snapshot", "reset",
    "export_prometheus", "dump_jsonl", "load_jsonl",
    "counter", "gauge", "histogram", "timer",
    "get_registry", "recompile_watchdog", "record_compile",
    "RecompileWarning", "MetricRegistry", "trace",
    "timeseries", "slo", "flight", "scrape",
    "TimeSeriesRecorder", "SloObjective", "SloEngine", "recorder",
]

_REGISTRY = MetricRegistry()
_WATCHDOG = RecompileWatchdog(_REGISTRY)
# span durations mirror into trace_span_seconds{span} when BOTH the
# tracer and the registry are enabled (docs/TELEMETRY.md Tracing)
trace.get_tracer().bind_registry(_REGISTRY)

# the flight recorder (flight.py) is standalone-loadable, so it cannot
# import this package — bind its live sources here instead: registry
# snapshot, the tracer's completed-event ring and open-span stacks, and
# the bundles-dumped counter (docs/TELEMETRY.md flight bundle contract)
_FLIGHT_BUNDLES = _REGISTRY.counter(
    "flight_bundles_total", "flight-recorder forensics bundles dumped",
    labelnames=("reason",))
flight.set_default_sources(
    snapshot=lambda: _REGISTRY.snapshot(),
    trace_events=lambda: trace.get_tracer().events(),
    live_spans=lambda: trace.live_spans(),
    on_dump=lambda reason: _FLIGHT_BUNDLES.inc(labels=(reason,)),
)


def get_registry() -> MetricRegistry:
    return _REGISTRY


def enable():
    """Turn collection on (idempotent). Also arms the jax compile-event
    mirror the first time, installs the flight recorder when
    PTPU_FLIGHT_DIR is set, and starts the HTTP scrape endpoint when
    PTPU_METRICS_PORT is set (docs/TELEMETRY.md)."""
    _REGISTRY.enabled = True
    install_jax_compile_listener(_REGISTRY)
    flight.maybe_install_from_env()
    scrape.maybe_start_from_env(_REGISTRY)
    return _REGISTRY


def disable():
    _REGISTRY.enabled = False
    return _REGISTRY


def enabled() -> bool:
    return _REGISTRY.enabled


def reset():
    """Zero every series and the watchdog's signature history."""
    _REGISTRY.reset()
    _WATCHDOG.reset()


def snapshot() -> dict:
    return _REGISTRY.snapshot()


def export_prometheus(path=None) -> str:
    text = _export.export_prometheus(_REGISTRY)
    if path is not None:
        with open(path, "w") as f:
            f.write(text)
    return text


def dump_jsonl(path, mode="a", extra=None) -> int:
    return _export.dump_jsonl(_REGISTRY, path, mode=mode, extra=extra)


def load_jsonl(path):
    return _export.load_jsonl(path)


def counter(name, help="", labelnames=(), **kw) -> Counter:
    return _REGISTRY.counter(name, help, labelnames, **kw)


def gauge(name, help="", labelnames=(), **kw) -> Gauge:
    return _REGISTRY.gauge(name, help, labelnames, **kw)


def histogram(name, help="", labelnames=(), **kw) -> Histogram:
    return _REGISTRY.histogram(name, help, labelnames, **kw)


def recorder(**kw) -> TimeSeriesRecorder:
    """A TimeSeriesRecorder over the process registry; when a flight
    recorder is installed and none is given, samples feed its forensics
    window too (docs/TELEMETRY.md "Time series...")."""
    kw.setdefault("flight", flight.get())
    return TimeSeriesRecorder(_REGISTRY, **kw)


def recompile_watchdog() -> RecompileWatchdog:
    return _WATCHDOG


def record_compile(fn_name, signature):
    """Report a jit-cache miss to the recompile watchdog."""
    _WATCHDOG.record(fn_name, signature)


class timer:
    """Context manager observing elapsed seconds into a histogram::

        with telemetry.timer(step_hist, labels=("train",)):
            run_step()

    A no-op (no clock reads) while telemetry is disabled."""

    __slots__ = ("_hist", "_labels", "_t0")

    def __init__(self, hist: Histogram, labels=()):
        self._hist = hist
        self._labels = labels
        self._t0 = None

    def __enter__(self):
        if _REGISTRY.enabled:
            self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._t0 is not None:
            self._hist.observe(time.perf_counter() - self._t0,
                               labels=self._labels)
        return False
