"""Recompile watchdog — the single most common silent TPU perf killer.

Every jit-cache miss in the framework (StaticFunction program-cache
misses, TrainStep builds, serving decode-step retraces) reports here as a
(function, abstract-shape-signature) pair. The watchdog keeps the set of
distinct signatures per function; when one function crosses the
threshold it emits a ``RecompileWarning`` naming the function and its
recent signatures — a varying python scalar or an unpadded dynamic shape
is almost always the cause.

Counts land in the shared registry as ``jit_recompiles_total{function}``
so bench snapshots and Prometheus scrapes expose compile churn even when
the warning threshold is never crossed.
"""
from __future__ import annotations

import os
import threading
import warnings


class RecompileWarning(UserWarning):
    """N distinct compilations observed for one traced function."""


DEFAULT_THRESHOLD = int(os.environ.get("PTPU_RECOMPILE_WARN", "5"))

_MAX_SIG_HISTORY = 8


class RecompileWatchdog:
    def __init__(self, registry, threshold=None):
        self._registry = registry
        self.threshold = (DEFAULT_THRESHOLD if threshold is None
                          else int(threshold))
        self._lock = threading.Lock()
        self._sigs = {}    # fn name -> set of distinct signatures
        self._recent = {}  # fn name -> last few signature reprs
        self._warned = set()
        self._counter = registry.counter(
            "jit_recompiles_total",
            "distinct jit compilations per traced function",
            labelnames=("function",))

    def configure(self, threshold):
        self.threshold = int(threshold)
        return self

    def record(self, fn_name, signature):
        """Report one jit-cache miss. `signature` must be hashable (the
        abstract shape/dtype/guard key the cache missed on)."""
        if not self._registry.enabled:
            return
        self._counter.inc(labels=(fn_name,))
        with self._lock:
            sigs = self._sigs.setdefault(fn_name, set())
            if signature in sigs:
                return  # same program recompiled (e.g. cache eviction):
                        # counted above, but not a NEW shape signature
            sigs.add(signature)
            recent = self._recent.setdefault(fn_name, [])
            recent.append(repr(signature))
            del recent[:-_MAX_SIG_HISTORY]
            n = len(sigs)
            should_warn = n >= self.threshold and fn_name not in self._warned
            if should_warn:
                self._warned.add(fn_name)
        if should_warn:
            warnings.warn(
                f"recompile watchdog: '{fn_name}' has compiled {n} distinct "
                f"programs (threshold {self.threshold}). Recompilation "
                "discards the cached XLA program and stalls the device — "
                "common causes are shape-varying inputs (pad or bucket "
                "them) and python scalars mutated between calls. Recent "
                f"signatures: {recent[-3:]}",
                RecompileWarning, stacklevel=3)

    def stats(self):
        with self._lock:
            return {name: len(sigs) for name, sigs in self._sigs.items()}

    def reset(self):
        with self._lock:
            self._sigs.clear()
            self._recent.clear()
            self._warned.clear()


_JAX_LISTENER_INSTALLED = [False]


def install_jax_compile_listener(registry):
    """Mirror jax's own compile events into the registry (best-effort:
    the monitoring API and its event names vary across jax releases).
    Registered once per process; the listener itself checks the enabled
    flag so disable() silences it without deregistration."""
    if _JAX_LISTENER_INSTALLED[0]:
        return
    _JAX_LISTENER_INSTALLED[0] = True
    try:
        from jax import monitoring

        hist = registry.histogram(
            "jax_compilation_seconds",
            "XLA compile wall time as reported by jax.monitoring",
            labelnames=("event",))

        def _on_duration(event, duration, **kw):
            if registry.enabled and "compil" in event:
                hist.observe(duration, labels=(event.strip("/"),))

        monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:  # noqa: BLE001 — telemetry must never break startup
        pass
