"""Declarative SLOs + burn-rate alerting over recorded time series.

SRE-style multiwindow burn-rate alerting (the standard control input for
paging and for the ROADMAP item-4 autoscaler), evaluated over
:class:`~paddle_tpu.telemetry.timeseries.TimeSeriesRecorder` histories:

- an :class:`SloObjective` declares one objective over one signal spec
  (``"values:ttft_p99_recent"`` must stay ``le`` a bound, a goodput
  floor stays ``ge`` one, a shed-rate ceiling bounds a counter
  ``:rate``), plus its error budget and two windows;
- the :class:`SloEngine` computes, per window, the fraction of recent
  samples violating the objective; ``burn_rate = bad_fraction /
  error_budget``; a **fast-burn** alert fires when the short window's
  burn crosses ``fast_burn`` (something is on fire NOW), a **slow-burn**
  alert when the long window crosses ``slow_burn`` (the budget is
  quietly draining);
- alerts are edge-triggered with clears: one structured ``fire`` event
  when a burn crosses its threshold, one ``clear`` when it drops back
  (or the signal disappears — a drained soak stops producing TTFTs, and
  "no evidence of burning" clears the page). Events are telemetered
  (``slo_alerts_total{objective,severity,event}``,
  ``slo_burn_rate{objective,window}``, ``slo_alert_active{objective}``)
  and forwarded to the flight recorder's forensics window.

Windows default to SAMPLE counts (fast 8 / slow 32) so the math is
identical on wall clocks and the soak's simulated-parallel clock;
``fast_window``/``slow_window`` switch to seconds when a deployment has
a real cadence. Declaration syntax and worked examples:
docs/TELEMETRY.md "Time series, SLOs, and the flight recorder".
"""
from __future__ import annotations

from .timeseries import parse_spec, series_from

__all__ = ["SloObjective", "SloEngine"]


class SloObjective:
    """One declarative objective over one timeline signal.

    ``op="le"``: a sample violates when ``value > bound`` (latency,
    shed rate, queue depth). ``op="ge"``: violates when ``value <
    bound`` (goodput floor, healthy-replica floor). ``error_budget`` is
    the tolerated violating fraction of samples; burn rate 1.0 means
    the budget is being consumed exactly as provisioned."""

    OPS = ("le", "ge")

    def __init__(self, name, signal, bound, op="le", *,
                 error_budget=0.05, fast_samples=8, slow_samples=32,
                 fast_window=None, slow_window=None,
                 fast_burn=6.0, slow_burn=1.5, min_points=None,
                 description=""):
        if op not in self.OPS:
            raise ValueError(f"SloObjective {name!r}: op {op!r} not in "
                             f"{self.OPS}")
        parse_spec(signal)                    # fail loud at declaration
        if not (0.0 < float(error_budget) <= 1.0):
            raise ValueError(f"SloObjective {name!r}: error_budget must "
                             "be in (0, 1]")
        self.name = str(name)
        self.signal = str(signal)
        self.bound = float(bound)
        self.op = op
        self.error_budget = float(error_budget)
        self.fast_samples = int(fast_samples)
        self.slow_samples = int(slow_samples)
        self.fast_window = (float(fast_window) if fast_window is not None
                            else None)
        self.slow_window = (float(slow_window) if slow_window is not None
                            else None)
        self.fast_burn = float(fast_burn)
        self.slow_burn = float(slow_burn)
        self.min_points = (int(min_points) if min_points is not None
                           else max(2, self.fast_samples // 2))
        self.description = str(description)

    def violated(self, value):
        return (value > self.bound if self.op == "le"
                else value < self.bound)

    def as_dict(self):
        return {"name": self.name, "signal": self.signal,
                "bound": self.bound, "op": self.op,
                "error_budget": self.error_budget,
                "fast_samples": self.fast_samples,
                "slow_samples": self.slow_samples,
                "fast_window": self.fast_window,
                "slow_window": self.slow_window,
                "fast_burn": self.fast_burn,
                "slow_burn": self.slow_burn,
                "description": self.description or None}


#: evaluation windows, ordered fast first so a fast-burn fire lands in
#: the event stream before the slow-burn confirmation of the same spike
_SEVERITIES = ("fast_burn", "slow_burn")


class SloEngine:
    """Evaluate objectives over a recorder's ring after each sample.

    ``evaluate()`` is cheap enough to run once per soak tick; it
    returns only the NEW edge events (fires + clears) of that
    evaluation, appends them to ``self.events`` (bounded), mirrors them
    into the registry when one is bound, and forwards them to the
    flight recorder's alert window when one is attached."""

    def __init__(self, recorder, objectives, *, registry=None,
                 flight=None, max_events=256):
        self.recorder = recorder
        self.objectives = list(objectives)
        self.flight = flight
        self.events = []
        self.active = {}              # (objective, severity) -> fire evt
        self.fired = {s: 0 for s in _SEVERITIES}
        self.cleared = 0
        self.evaluations = 0
        self.max_events = int(max_events)
        self._alerts_c = self._burn_g = self._active_g = None
        if registry is not None:
            self.bind_registry(registry)

    def bind_registry(self, registry):
        self._alerts_c = registry.counter(
            "slo_alerts_total", "SLO burn-rate alert edge events",
            labelnames=("objective", "severity", "event"))
        self._burn_g = registry.gauge(
            "slo_burn_rate", "error-budget burn rate per window "
            "(1.0 = consuming the budget exactly as provisioned)",
            labelnames=("objective", "window"))
        self._active_g = registry.gauge(
            "slo_alert_active", "number of active alerts per objective",
            labelnames=("objective",))
        return self

    # -- burn math -----------------------------------------------------------
    def _burn(self, obj, severity):
        """(burn_rate, bad_fraction, n_points, last_value) over one
        window. An empty window burns at 0.0 — no evidence of burning —
        which is what lets an alert CLEAR once the signal drains."""
        if severity == "fast_burn":
            samples = (self.recorder.window(seconds=obj.fast_window)
                       if obj.fast_window is not None
                       else self.recorder.window(n=obj.fast_samples))
        else:
            samples = (self.recorder.window(seconds=obj.slow_window)
                       if obj.slow_window is not None
                       else self.recorder.window(n=obj.slow_samples))
        pts = series_from(samples, obj.signal)
        if not pts:
            return 0.0, 0.0, 0, None
        bad = sum(1 for _, v in pts if obj.violated(v))
        frac = bad / len(pts)
        return frac / obj.error_budget, frac, len(pts), pts[-1][1]

    # -- evaluation ----------------------------------------------------------
    def evaluate(self):
        """One pass over every objective x window; returns new events."""
        self.evaluations += 1
        last = self.recorder.last()
        now = last["ts"] if last else 0.0
        new = []
        for obj in self.objectives:
            n_active = 0
            for severity in _SEVERITIES:
                burn, frac, n, value = self._burn(obj, severity)
                thresh = (obj.fast_burn if severity == "fast_burn"
                          else obj.slow_burn)
                if self._burn_g is not None:
                    self._burn_g.set(burn, labels=(
                        obj.name, severity.split("_")[0]))
                key = (obj.name, severity)
                if key not in self.active:
                    if n >= obj.min_points and burn >= thresh:
                        evt = self._event(now, obj, severity, "fire",
                                          burn, frac, n, value)
                        self.active[key] = evt
                        self.fired[severity] += 1
                        new.append(evt)
                elif burn < thresh:
                    evt = self._event(now, obj, severity, "clear",
                                      burn, frac, n, value)
                    del self.active[key]
                    self.cleared += 1
                    new.append(evt)
                if key in self.active:
                    n_active += 1
            if self._active_g is not None:
                self._active_g.set(n_active, labels=(obj.name,))
        if new:
            self.events.extend(new)
            if len(self.events) > self.max_events:
                del self.events[:len(self.events) - self.max_events]
        return new

    def _event(self, ts, obj, severity, kind, burn, frac, n, value):
        evt = {"ts": ts, "objective": obj.name, "severity": severity,
               "event": kind, "burn_rate": round(burn, 4),
               "bad_fraction": round(frac, 4), "window_points": n,
               "signal": obj.signal, "value": value,
               "bound": obj.bound, "op": obj.op}
        if self._alerts_c is not None:
            self._alerts_c.inc(labels=(obj.name, severity, kind))
        if self.flight is not None:
            self.flight.note_alert(evt)
        return evt

    # -- reporting -----------------------------------------------------------
    def summary(self, max_events=32):
        """The JSON-able ``"slo"`` block the soak embeds in its serving/
        overload output and tools/bench_gate.py gates on (a clean soak
        reporting any fast-burn alert fails the round)."""
        return {
            "enabled": True,
            "objectives": [o.as_dict() for o in self.objectives],
            "evaluations": self.evaluations,
            "alerts_fired": sum(self.fired.values()),
            "fast_burn_alerts": self.fired["fast_burn"],
            "slow_burn_alerts": self.fired["slow_burn"],
            "alerts_cleared": self.cleared,
            "active": sorted(f"{n}:{s}" for n, s in self.active),
            "events": self.events[-int(max_events):],
        }

    def decision_input(self):
        """Current burn state per objective — the structured decision
        input the ROADMAP item-4 autoscaler consumes (scale on slow
        burn, page/shed on fast burn)."""
        last = self.recorder.last()
        out = {"ts": last["ts"] if last else None, "objectives": {}}
        for obj in self.objectives:
            fast, ffrac, _, value = self._burn(obj, "fast_burn")
            slow, sfrac, _, _ = self._burn(obj, "slow_burn")
            out["objectives"][obj.name] = {
                "value": value, "bound": obj.bound, "op": obj.op,
                "fast_burn_rate": round(fast, 4),
                "slow_burn_rate": round(slow, 4),
                "active": sorted(s for n, s in self.active
                                 if n == obj.name),
            }
        return out
