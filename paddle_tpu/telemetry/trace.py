"""Span tracer — WHERE the time went, not just how much of it.

The registry (:mod:`.registry`) aggregates; this module keeps the
timeline: thread-aware spans (``trace.span("bwd")`` context manager,
``trace.traced`` decorator), instants, and async request events, ring-
buffered per thread and exported as Chrome/Perfetto trace-event JSON or
a compact JSONL. One flag (``PTPU_TRACE=1`` / ``bench.py --trace``)
turns a bench step from one opaque ``train_step_seconds`` sample into a
step anatomy: jit trace/lower/compile phases, per-call dispatch with a
``cost_analysis()`` roofline estimate, the collectives a plan issues,
checkpoint save/restore phases, and serving request span trees.

Design constraints (same discipline as the registry):

- **Near-zero overhead when disabled.** ``span()`` returns one shared
  no-op singleton — no allocation, no clock read; every other entry
  point is a single attribute check first.
- **Thread-aware, lock-free on the hot path.** Each thread owns its
  ring buffer and live-span stack; the global lock is taken only when a
  thread first appears and at export time. The live stacks are what the
  HangWatchdog attaches to its debris so a hang names the phase it
  wedged in.
- **Bounded.** Per-thread ring capacity (``PTPU_TRACE_BUFFER``, default
  65536 events); past it the oldest events drop and are counted.
- **Pure stdlib.** No jax/numpy imports; span attrs are caller-owned
  dicts serialized with ``default=str``.

Span-name / attrs contract and the bench ``"anatomy"`` schema:
docs/TELEMETRY.md (Tracing section).
"""
from __future__ import annotations

import functools
import json
import os
import threading
import time

__all__ = [
    "enable", "disable", "enabled", "reset",
    "span", "traced", "instant", "complete",
    "async_begin", "async_end", "async_instant",
    "events", "live_spans", "to_perfetto", "dump_jsonl",
    "step_anatomy", "request_trees", "SpanTracer",
]

DEFAULT_CAPACITY = int(os.environ.get("PTPU_TRACE_BUFFER", "65536"))

# event tuples (kept small — one tuple per event):
#   ("X", name, cat, t0, dur, attrs, depth)   completed span
#   ("i", name, cat, t,  attrs)               instant
#   ("b"|"e"|"n", name, cat, t, attrs, id)    async begin/end/instant


class _ThreadBuf:
    __slots__ = ("name", "ident", "ring", "head", "capacity", "dropped",
                 "stack")

    def __init__(self, name, ident, capacity):
        self.name = name
        self.ident = ident
        self.ring = []
        self.head = 0
        self.capacity = capacity
        self.dropped = 0
        self.stack = []   # live spans: (name, t0, attrs)

    def add(self, ev):
        ring = self.ring
        if len(ring) < self.capacity:
            ring.append(ev)
        else:
            ring[self.head] = ev
            self.head = (self.head + 1) % self.capacity
            self.dropped += 1

    def ordered(self):
        return self.ring[self.head:] + self.ring[:self.head]


class _NoopSpan:
    """The shared disabled-path span: no state, no clock reads. One
    module-level instance — ``span()`` while disabled allocates
    nothing (asserted by tests/test_trace.py)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def annotate(self, **attrs):
        return self


_NOOP = _NoopSpan()


class _Span:
    __slots__ = ("_tracer", "name", "cat", "attrs", "_buf", "_t0")

    def __init__(self, tracer, name, cat, attrs):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.attrs = attrs

    def annotate(self, **attrs):
        """Merge attrs into the span (e.g. a result computed inside)."""
        if self.attrs is None:
            self.attrs = {}
        self.attrs.update(attrs)
        return self

    def __enter__(self):
        buf = self._tracer._thread_buf()
        self._buf = buf
        self._t0 = time.perf_counter()
        buf.stack.append((self.name, self._t0, self.attrs))
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        buf = self._buf
        if buf.stack:
            buf.stack.pop()
        buf.add(("X", self.name, self.cat, self._t0, t1 - self._t0,
                 self.attrs, len(buf.stack)))
        self._tracer._mirror(self.name, t1 - self._t0)
        return False


class SpanTracer:
    """One process-local tracer instance (module-level ``_TRACER``)."""

    def __init__(self, capacity=DEFAULT_CAPACITY):
        self.enabled = False
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        # a LIST, not an ident-keyed dict: the OS reuses thread idents,
        # and a short-lived worker's buffer must survive for export
        # after a new thread is born with the same ident
        self._bufs = []          # every thread's _ThreadBuf, birth order
        self._local = threading.local()
        self._epoch = time.perf_counter()
        self._epoch_ts = time.time()
        self._registry = None    # bound by telemetry/__init__
        self._mirror_hist = None

    # -- wiring -------------------------------------------------------------
    def bind_registry(self, registry):
        """Mirror span durations into ``trace_span_seconds{span}`` when
        the metric registry is also enabled (the bench snapshot / the
        telemetry_report ``-- trace --`` section read it)."""
        self._registry = registry
        self._mirror_hist = registry.histogram(
            "trace_span_seconds",
            "span tracer wall seconds by span name (docs/TELEMETRY.md "
            "Tracing section)", labelnames=("span",))

    def _mirror(self, name, dur):
        reg = self._registry
        if reg is not None and reg.enabled:
            self._mirror_hist.observe(dur, labels=(name,))

    def _thread_buf(self) -> _ThreadBuf:
        buf = getattr(self._local, "buf", None)
        if buf is None:
            t = threading.current_thread()
            buf = _ThreadBuf(t.name, t.ident, self.capacity)
            self._local.buf = buf
            with self._lock:
                self._bufs.append(buf)
        return buf

    # -- lifecycle ----------------------------------------------------------
    def enable(self):
        self.enabled = True
        return self

    def disable(self):
        self.enabled = False
        return self

    def reset(self):
        """Drop every recorded event and re-zero the epoch. Live span
        stacks survive (their owners still hold the context managers)."""
        live = {t.ident for t in threading.enumerate()}
        with self._lock:
            # prune buffers of dead threads (DataLoader workers, writer
            # threads): a long-lived process resetting between bench
            # rounds must not accumulate them forever
            self._bufs = [b for b in self._bufs if b.ident in live]
            for buf in self._bufs:
                buf.ring = []
                buf.head = 0
                buf.dropped = 0
        self._epoch = time.perf_counter()
        self._epoch_ts = time.time()

    # -- recording ----------------------------------------------------------
    def span(self, name, attrs=None, cat="phase"):
        if not self.enabled:
            return _NOOP
        return _Span(self, name, cat, attrs)

    def complete(self, name, t0, dur, attrs=None, cat="phase"):
        """Record an already-measured span (the dispatch path measures
        wall time itself to attach derived attrs like host_gap)."""
        if not self.enabled:
            return
        buf = self._thread_buf()
        buf.add(("X", name, cat, t0, dur, attrs, len(buf.stack)))
        self._mirror(name, dur)

    def instant(self, name, attrs=None, cat="phase"):
        if not self.enabled:
            return
        self._thread_buf().add(
            ("i", name, cat, time.perf_counter(), attrs))

    def _async(self, ph, name, aid, attrs, cat):
        if not self.enabled:
            return
        self._thread_buf().add(
            (ph, name, cat, time.perf_counter(), attrs, aid))

    def async_begin(self, name, aid, attrs=None, cat="request"):
        self._async("b", name, aid, attrs, cat)

    def async_end(self, name, aid, attrs=None, cat="request"):
        self._async("e", name, aid, attrs, cat)

    def async_instant(self, name, aid, attrs=None, cat="request"):
        self._async("n", name, aid, attrs, cat)

    # -- introspection / export --------------------------------------------
    def _snapshot_bufs(self):
        with self._lock:
            return list(self._bufs)

    def live_spans(self):
        """{``thread_name:ident`` -> [{name, elapsed_seconds, attrs}]}
        of every thread's CURRENTLY OPEN spans, innermost last — the
        HangWatchdog debris payload. Works while disabled (returns
        whatever is still open, usually nothing)."""
        now = time.perf_counter()
        out = {}
        for buf in self._snapshot_bufs():
            stack = list(buf.stack)
            if not stack:
                continue
            out[f"{buf.name}:{buf.ident}"] = [
                {"name": name,
                 "elapsed_seconds": round(now - t0, 6),
                 "attrs": _json_attrs(attrs)}
                for name, t0, attrs in stack]
        return out

    def events(self):
        """Every recorded event as a list of plain dicts (per thread, in
        record order): {"ph", "name", "cat", "ts" (seconds since the
        trace epoch), "dur" (X only), "attrs", "id" (async only),
        "depth" (X only), "thread", "tid"}."""
        out = []
        epoch = self._epoch
        for buf in self._snapshot_bufs():
            for ev in buf.ordered():
                ph = ev[0]
                rec = {"ph": ph, "name": ev[1], "cat": ev[2],
                       "thread": buf.name, "tid": buf.ident}
                if ph == "X":
                    rec["ts"] = ev[3] - epoch
                    rec["dur"] = ev[4]
                    rec["attrs"] = _json_attrs(ev[5])
                    rec["depth"] = ev[6]
                elif ph == "i":
                    rec["ts"] = ev[3] - epoch
                    rec["attrs"] = _json_attrs(ev[4])
                else:  # b/e/n async
                    rec["ts"] = ev[3] - epoch
                    rec["attrs"] = _json_attrs(ev[4])
                    rec["id"] = ev[5]
                out.append(rec)
        return out

    def dropped_events(self):
        return sum(b.dropped for b in self._snapshot_bufs())

    def to_perfetto(self, path=None):
        """Chrome trace-event JSON (Perfetto/chrome://tracing loadable):
        {"traceEvents": [...], "displayTimeUnit": "ms"}. ``ts`` are
        microseconds since the trace epoch; spans are "X" complete
        events, async request events are nestable "b"/"n"/"e" with the
        request id. Writes to ``path`` when given; returns the dict."""
        pid = os.getpid()
        tev = []
        seen_threads = set()
        for e in self.events():
            tid = e["tid"]
            if tid not in seen_threads:
                seen_threads.add(tid)
                tev.append({"ph": "M", "name": "thread_name", "pid": pid,
                            "tid": tid,
                            "args": {"name": e["thread"]}})
            rec = {"ph": e["ph"], "name": e["name"], "cat": e["cat"],
                   "pid": pid, "tid": tid,
                   "ts": round(e["ts"] * 1e6, 3)}
            if e["ph"] == "X":
                rec["dur"] = round(e["dur"] * 1e6, 3)
            if e["ph"] in ("b", "e", "n"):
                rec["id"] = str(e["id"])
            if e.get("attrs"):
                rec["args"] = e["attrs"]
            tev.append(rec)
        doc = {"traceEvents": tev, "displayTimeUnit": "ms",
               "otherData": {"epoch_unix_ts": self._epoch_ts,
                             "dropped_events": self.dropped_events()}}
        if path is not None:
            with open(path, "w") as f:
                json.dump(doc, f, default=str)
        return doc

    def dump_jsonl(self, path, mode="w"):
        """One JSON line per event (the compact diff-friendly format
        tools/trace_report.py consumes). Returns lines written."""
        evs = self.events()
        with open(path, mode) as f:
            f.write(json.dumps({"ph": "meta",
                                "epoch_unix_ts": self._epoch_ts,
                                "dropped_events": self.dropped_events()})
                    + "\n")
            for e in evs:
                f.write(json.dumps(e, default=str) + "\n")
        return len(evs) + 1

    # -- aggregation --------------------------------------------------------
    def step_anatomy(self, step_span="step"):
        """Decompose the ``step_span`` spans into their contained
        phases: the data behind the bench ``"anatomy"`` block.

        Returns ``{"steps", "step_seconds_total", "step_seconds_mean",
        "phases": {name: {count, seconds, seconds_per_step}},
        "coverage"}`` where ``phases`` aggregates every span that ran
        INSIDE a step span (same thread, time-contained) and
        ``coverage`` is the fraction of step wall time covered by the
        DIRECT children (depth = step depth + 1) — the "per-phase
        seconds sum to within X of step time" check. None when no step
        spans were recorded."""
        by_thread = {}
        for e in self.events():
            if e["ph"] == "X":
                by_thread.setdefault(e["tid"], []).append(e)
        steps = []
        step_tid = None
        for tid, evs in by_thread.items():
            mine = [e for e in evs if e["name"] == step_span]
            if mine:
                steps = mine
                step_tid = tid
                break
        if not steps:
            return None
        total = sum(e["dur"] for e in steps)
        n = len(steps)
        windows = [(e["ts"], e["ts"] + e["dur"], e["depth"]) for e in steps]
        phases = {}
        direct = 0.0
        for e in by_thread[step_tid]:
            if e["name"] == step_span:
                continue
            for w0, w1, wd in windows:
                if e["ts"] >= w0 and e["ts"] + e["dur"] <= w1:
                    row = phases.setdefault(e["name"],
                                            {"count": 0, "seconds": 0.0})
                    row["count"] += 1
                    row["seconds"] += e["dur"]
                    if e["depth"] == wd + 1:
                        direct += e["dur"]
                    break
        for row in phases.values():
            row["seconds"] = round(row["seconds"], 6)
            row["seconds_per_step"] = round(row["seconds"] / n, 6)
        return {
            "steps": n,
            "step_seconds_total": round(total, 6),
            "step_seconds_mean": round(total / n, 6),
            "phases": phases,
            "coverage": round(direct / total, 4) if total else 0.0,
        }

    def request_trees(self, cat="request"):
        """Reassemble async events into per-id span trees:
        ``{id: {"name", "start", "end", "attrs", "children": [...],
        "marks": [...]}}`` — the serving request anatomy (admission →
        queue → prefill → decode → detokenize). The root is the
        longest-covering span per id (the engine opens "request"
        first); unclosed spans get ``end=None``."""
        per_id = {}
        for e in self.events():
            if e["ph"] in ("b", "e", "n") and e["cat"] == cat:
                per_id.setdefault(e["id"], []).append(e)
        out = {}
        for aid, evs in per_id.items():
            evs.sort(key=lambda e: e["ts"])
            spans, marks, open_ = [], [], {}
            for e in evs:
                if e["ph"] == "b":
                    # same-name re-begin (a requeued request re-enters
                    # "queue"): the previous instance must already be
                    # closed; stack per name
                    open_.setdefault(e["name"], []).append(
                        {"name": e["name"], "start": e["ts"], "end": None,
                         "attrs": e.get("attrs"), "children": []})
                elif e["ph"] == "e":
                    stack = open_.get(e["name"])
                    if stack:
                        s = stack.pop()
                        s["end"] = e["ts"]
                        if e.get("attrs"):
                            s["attrs"] = dict(s["attrs"] or {},
                                              **e["attrs"])
                        spans.append(s)
                else:
                    marks.append({"name": e["name"], "ts": e["ts"],
                                  "attrs": e.get("attrs")})
            for stack in open_.values():   # unclosed (live) spans
                spans.extend(stack)
            if not spans:
                continue
            # root = the "request" span when one exists (the engine's
            # submit→retire envelope — a fleet router's still-open
            # "route" span would otherwise win on its infinite cover),
            # else the span covering the most time (open end = +inf)
            def _cover(s):
                end = s["end"] if s["end"] is not None else float("inf")
                return end - s["start"]

            named = [s for s in spans if s["name"] == "request"]
            if named:
                root = max(named, key=_cover)
            else:
                root = max(spans, key=_cover)
            rest = [s for s in spans if s is not root]
            rest.sort(key=lambda s: s["start"])
            root["children"] = rest
            root["marks"] = marks
            out[aid] = root
        return out


def _json_attrs(attrs):
    if not attrs:
        return None
    return {str(k): v for k, v in attrs.items()}


# ---------------------------------------------------------------- module API
_TRACER = SpanTracer()

if os.environ.get("PTPU_TRACE", "") not in ("", "0"):
    _TRACER.enabled = True


def get_tracer() -> SpanTracer:
    return _TRACER


def enable():
    return _TRACER.enable()


def disable():
    return _TRACER.disable()


def enabled() -> bool:
    return _TRACER.enabled


def reset():
    _TRACER.reset()


def span(name, attrs=None, cat="phase"):
    """Context manager timing one phase::

        with trace.span("bwd", attrs={"step": i}):
            run_bwd()

    While tracing is disabled this returns a shared no-op singleton —
    no allocation, no clock reads."""
    tr = _TRACER
    if not tr.enabled:
        return _NOOP
    return _Span(tr, name, cat, attrs)


def traced(name=None, cat="phase"):
    """Decorator form of :func:`span`; the enabled check happens at CALL
    time (decorators are usually applied at import, before tracing is
    on)::

        @trace.traced("ckpt:serialize")
        def _serialize(...): ...
    """

    def deco(fn):
        label = name or getattr(fn, "__qualname__", fn.__name__)

        @functools.wraps(fn)
        def wrapper(*a, **k):
            tr = _TRACER
            if not tr.enabled:
                return fn(*a, **k)
            with _Span(tr, label, cat, None):
                return fn(*a, **k)

        return wrapper

    return deco


def instant(name, attrs=None, cat="phase"):
    _TRACER.instant(name, attrs, cat)


def complete(name, t0, dur, attrs=None, cat="phase"):
    _TRACER.complete(name, t0, dur, attrs, cat)


def async_begin(name, aid, attrs=None, cat="request"):
    _TRACER.async_begin(name, aid, attrs, cat)


def async_end(name, aid, attrs=None, cat="request"):
    _TRACER.async_end(name, aid, attrs, cat)


def async_instant(name, aid, attrs=None, cat="request"):
    _TRACER.async_instant(name, aid, attrs, cat)


def events():
    return _TRACER.events()


def live_spans():
    return _TRACER.live_spans()


def to_perfetto(path=None):
    return _TRACER.to_perfetto(path)


def dump_jsonl(path, mode="w"):
    return _TRACER.dump_jsonl(path, mode)


def step_anatomy(step_span="step"):
    return _TRACER.step_anatomy(step_span)


def request_trees(cat="request"):
    return _TRACER.request_trees(cat)
