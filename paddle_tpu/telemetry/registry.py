"""Process-local metrics registry: counters, gauges, histograms.

Design constraints (the subsystem is wired into op dispatch and the
serving decode tick, both latency-critical):

- **Near-zero overhead when disabled.** Every mutator's first statement
  is a single attribute check on the shared registry; no locks, no
  allocation, no label handling happen before it.
- **Lock-safe.** Each metric owns one ``threading.Lock`` guarding its
  series map — serving callbacks and DataLoader workers may record from
  other threads.
- **Labeled, with a cardinality cap.** A metric holds a bounded number
  of label-value series; past the cap new label sets are dropped and
  counted on the registry's own ``telemetry_series_dropped_total`` so a
  runaway label (e.g. request ids used as labels) degrades telemetry,
  never memory.
- **Pure stdlib.** The module imports no jax/numpy so the hot-path
  import graph stays flat and the disabled path costs nothing extra.
"""
from __future__ import annotations

import threading
import time

DEFAULT_MAX_SERIES = 256

# Latency-oriented default buckets (seconds), exponential 1us..~65s.
DEFAULT_BUCKETS = tuple(1e-6 * (4.0 ** i) for i in range(13))


class Metric:
    """Base: a named family of label-value series."""

    kind = "untyped"

    def __init__(self, name, help="", labelnames=(), registry=None,
                 max_series=DEFAULT_MAX_SERIES):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self.max_series = int(max_series)
        self._registry = registry
        self._lock = threading.Lock()
        self._series = {}

    def _series_slot(self, labels):
        """Return the mutable slot for `labels`, or None past the cap.

        Caller holds self._lock."""
        slot = self._series.get(labels)
        if slot is None:
            if len(labels) != len(self.labelnames):
                raise ValueError(
                    f"{self.name}: got {len(labels)} label values for "
                    f"labelnames {self.labelnames}")
            if len(self._series) >= self.max_series:
                self._registry._note_dropped(self.name)
                return None
            slot = self._new_slot()
            self._series[labels] = slot
        return slot

    def _new_slot(self):
        raise NotImplementedError

    def clear(self):
        with self._lock:
            self._series.clear()

    def series(self):
        """{labels_tuple: plain-python snapshot value}."""
        with self._lock:
            return {k: self._snap_slot(v) for k, v in self._series.items()}


class Counter(Metric):
    kind = "counter"

    def inc(self, amount=1, labels=()):
        reg = self._registry
        if not reg.enabled:
            return
        with self._lock:
            slot = self._series_slot(tuple(labels))
            if slot is not None:
                slot[0] += amount

    def value(self, labels=()):
        with self._lock:
            slot = self._series.get(tuple(labels))
            return slot[0] if slot is not None else 0

    def _new_slot(self):
        return [0]

    def _snap_slot(self, slot):
        return slot[0]


class Gauge(Metric):
    kind = "gauge"

    def set(self, value, labels=()):
        reg = self._registry
        if not reg.enabled:
            return
        with self._lock:
            slot = self._series_slot(tuple(labels))
            if slot is not None:
                slot[0] = value

    def inc(self, amount=1, labels=()):
        reg = self._registry
        if not reg.enabled:
            return
        with self._lock:
            slot = self._series_slot(tuple(labels))
            if slot is not None:
                slot[0] += amount

    def dec(self, amount=1, labels=()):
        self.inc(-amount, labels)

    def value(self, labels=()):
        with self._lock:
            slot = self._series.get(tuple(labels))
            return slot[0] if slot is not None else 0

    def _new_slot(self):
        return [0]

    def _snap_slot(self, slot):
        return slot[0]


class Histogram(Metric):
    """Bucketed histogram with count/sum/min/max and estimated quantiles.

    Buckets are upper bounds (le); one implicit +Inf bucket catches the
    tail. Quantiles are estimated by linear interpolation inside the
    winning bucket — the standard Prometheus ``histogram_quantile``
    rule — so p50/p95/p99 come straight out of ``snapshot()`` without a
    reservoir."""

    kind = "histogram"

    def __init__(self, name, help="", labelnames=(), registry=None,
                 max_series=DEFAULT_MAX_SERIES, buckets=None):
        super().__init__(name, help, labelnames, registry, max_series)
        bounds = tuple(sorted(buckets if buckets is not None
                              else DEFAULT_BUCKETS))
        if not bounds:
            raise ValueError(f"{name}: histogram needs at least one bucket")
        self.buckets = bounds

    def observe(self, value, labels=()):
        reg = self._registry
        if not reg.enabled:
            return
        value = float(value)
        with self._lock:
            slot = self._series_slot(tuple(labels))
            if slot is None:
                return
            counts, stats = slot
            i = 0
            n = len(self.buckets)
            while i < n and value > self.buckets[i]:
                i += 1
            counts[i] += 1
            stats["count"] += 1
            stats["sum"] += value
            if value < stats["min"]:
                stats["min"] = value
            if value > stats["max"]:
                stats["max"] = value

    def _new_slot(self):
        return ([0] * (len(self.buckets) + 1),
                {"count": 0, "sum": 0.0, "min": float("inf"),
                 "max": float("-inf")})

    def _quantile(self, counts, stats, q):
        total = stats["count"]
        if total == 0:
            return 0.0
        rank = q * total
        seen = 0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if seen + c >= rank:
                hi = (self.buckets[i] if i < len(self.buckets)
                      else stats["max"])
                lo = self.buckets[i - 1] if i > 0 else 0.0
                hi = min(hi, stats["max"])
                lo = max(lo, min(stats["min"], hi))
                frac = (rank - seen) / c
                return lo + (hi - lo) * frac
            seen += c
        return stats["max"]

    def _snap_slot(self, slot):
        counts, stats = slot
        out = {
            "count": stats["count"],
            "sum": stats["sum"],
            "min": stats["min"] if stats["count"] else 0.0,
            "max": stats["max"] if stats["count"] else 0.0,
            "mean": stats["sum"] / stats["count"] if stats["count"] else 0.0,
            "p50": self._quantile(counts, stats, 0.50),
            "p95": self._quantile(counts, stats, 0.95),
            "p99": self._quantile(counts, stats, 0.99),
            "buckets": {repr(b): c
                        for b, c in zip(self.buckets, counts)},
        }
        out["buckets"]["+Inf"] = counts[-1]
        return out


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricRegistry:
    """Owns every metric family plus the global enabled flag."""

    def __init__(self):
        self.enabled = False
        self._lock = threading.Lock()
        self._metrics = {}
        self._dropped = {}  # metric name -> series dropped past the cap

    # -- registration -------------------------------------------------------
    def _get_or_create(self, cls, name, help, labelnames, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as {m.kind}")
                if tuple(labelnames) != m.labelnames:
                    raise ValueError(
                        f"metric {name!r} already registered with labels "
                        f"{m.labelnames}, got {tuple(labelnames)}")
                # explicitly-passed config must match too: a second site
                # silently observing into someone else's bucket layout
                # would corrupt its quantiles undetectably
                if isinstance(m, Histogram) and \
                        kw.get("buckets") is not None and \
                        tuple(sorted(kw["buckets"])) != m.buckets:
                    raise ValueError(
                        f"metric {name!r} already registered with buckets "
                        f"{m.buckets}, got {tuple(sorted(kw['buckets']))}")
                if "max_series" in kw and int(kw["max_series"]) != \
                        m.max_series:
                    raise ValueError(
                        f"metric {name!r} already registered with "
                        f"max_series={m.max_series}, got {kw['max_series']}")
                return m
            m = cls(name, help, labelnames, registry=self, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name, help="", labelnames=(), **kw) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames, **kw)

    def gauge(self, name, help="", labelnames=(), **kw) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames, **kw)

    def histogram(self, name, help="", labelnames=(), **kw) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames, **kw)

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    def metrics(self):
        with self._lock:
            return list(self._metrics.values())

    def _note_dropped(self, name):
        # registry-level bookkeeping, not a Metric: the cap must not be
        # able to interfere with its own accounting
        with self._lock:
            self._dropped[name] = self._dropped.get(name, 0) + 1

    # -- lifecycle ----------------------------------------------------------
    def reset(self):
        """Zero every series (registered families survive)."""
        for m in self.metrics():
            m.clear()
        with self._lock:
            self._dropped.clear()

    # -- snapshot -----------------------------------------------------------
    @staticmethod
    def _label_key(labelnames, labels):
        if not labels:
            return ""
        return ",".join(f"{k}={v}" for k, v in zip(labelnames, labels))

    def snapshot(self):
        """Plain-JSON view of every live series, grouped by kind."""
        snap = {"ts": time.time(), "enabled": self.enabled,
                "counters": {}, "gauges": {}, "histograms": {}}
        for m in self.metrics():
            series = m.series()
            if not series:
                continue
            group = snap[m.kind + "s"]
            group[m.name] = {
                self._label_key(m.labelnames, k): v
                for k, v in sorted(series.items())
            }
        with self._lock:
            if self._dropped:
                snap["dropped_series"] = dict(self._dropped)
        return snap
