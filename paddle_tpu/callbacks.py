"""paddle.callbacks — re-export of the hapi callback family
(parity: python/paddle/callbacks/__init__.py)."""
from .hapi.callbacks import (  # noqa: F401
    Callback,
    EarlyStopping,
    LRScheduler,
    ModelCheckpoint,
    ProgBarLogger,
    ReduceLROnPlateau,
    VisualDL,
    WandbCallback,
)

__all__ = [
    "Callback", "ProgBarLogger", "ModelCheckpoint", "VisualDL",
    "LRScheduler", "EarlyStopping", "ReduceLROnPlateau", "WandbCallback",
]
