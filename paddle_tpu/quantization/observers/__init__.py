"""paddle.quantization.observers (parity: observers/abs_max.py etc.)."""
from .. import AbsmaxObserver  # noqa: F401

__all__ = ["AbsmaxObserver", "GroupWiseWeightObserver"]


class GroupWiseWeightObserver(AbsmaxObserver):
    """Per-group absmax over the quant axis (observers/groupwise.py)."""

    def __init__(self, quant_bits=8, group_size=128, **kwargs):
        super().__init__(quant_bits=quant_bits)
        self.group_size = group_size
