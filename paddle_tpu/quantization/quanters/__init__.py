"""paddle.quantization.quanters (parity: quanters/abs_max.py)."""
from .. import FakeQuanterWithAbsMax as FakeQuanterWithAbsMaxObserver  # noqa: F401

__all__ = ["FakeQuanterWithAbsMaxObserver"]
