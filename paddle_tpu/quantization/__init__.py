"""paddle.quantization — config-driven QAT/PTQ.

Parity: `python/paddle/quantization/` (QuantConfig `config.py`, QAT/PTQ
entries, observers + fake quanters). TPU-native: fake-quant is a pure
round-trip (quantize -> dequantize) with a straight-through estimator, so
the whole quantized model still jit-compiles to one XLA program; int8
inference itself maps to the MXU's native int8 path when exported.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import apply_op
from ..nn.layer.layers import Layer
from .. import nn


def _fake_quant(x, scale, bits=8):
    qmax = 2.0 ** (bits - 1) - 1

    def _fq(x, scale):
        s = jnp.maximum(scale, 1e-8)
        q = jnp.clip(jnp.round(x / s * qmax), -qmax - 1, qmax)
        deq = q * s / qmax
        # straight-through estimator: identity gradient
        return x + jax.lax.stop_gradient(deq - x)

    return apply_op(_fq, x, scale, _op_name="fake_quant")


class AbsmaxObserver:
    """Running abs-max activation observer (observers/abs_max.py parity)."""

    def __init__(self, quant_bits=8):
        self.quant_bits = quant_bits
        self._absmax = 0.0

    def observe(self, x):
        import numpy as np

        val = float(jnp.max(jnp.abs(x._data)))
        self._absmax = max(self._absmax, val)

    def scale(self):
        return self._absmax


class FakeQuanterWithAbsMax:
    """QAT weight/activation quanter (fake_quanter.py parity)."""

    def __init__(self, quant_bits=8):
        self.quant_bits = quant_bits

    def __call__(self, x):
        scale = x.abs().max()
        return _fake_quant(x, scale, self.quant_bits)


class QuantConfig:
    """parity: quantization/config.py QuantConfig."""

    def __init__(self, activation=None, weight=None):
        self.activation = activation or FakeQuanterWithAbsMax()
        self.weight = weight or FakeQuanterWithAbsMax()
        self._layer_types = (nn.Linear,)

    def add_layer_config(self, layer=None, activation=None, weight=None,
                         **kw):
        if activation is not None:
            self.activation = activation
        if weight is not None:
            self.weight = weight


class QuantedLinear(Layer):
    """Linear with fake-quantized weight + input (QAT form)."""

    def __init__(self, inner: "nn.Linear", config: QuantConfig,
                 static_scales=None):
        super().__init__()
        self.inner = inner
        self.config = config
        self.static_scales = static_scales  # (act_scale,) from PTQ convert
        self.observer = AbsmaxObserver()
        self.observing = False

    def forward(self, x):
        if self.observing:
            self.observer.observe(x)
            return self.inner(x)
        w = self.config.weight(self.inner.weight)
        if self.static_scales is not None:
            import paddle_tpu as paddle

            x = _fake_quant(x, paddle.to_tensor(self.static_scales))
        else:
            x = self.config.activation(x)
        out = x.matmul(w)
        if self.inner.bias is not None:
            out = out + self.inner.bias
        return out


def _swap_linears(model: Layer, config: QuantConfig):
    for name, sub in list(model._sub_layers.items()):
        if isinstance(sub, nn.Linear):
            model._sub_layers[name] = QuantedLinear(sub, config)
        else:
            _swap_linears(sub, config)
    return model


class QAT:
    """Quantization-aware training (parity: quantization/qat.py)."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model: Layer, inplace=False):
        return _swap_linears(model, self.config)

    def convert(self, model: Layer, inplace=False):
        return model


class PTQ:
    """Post-training quantization: observe -> convert."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model: Layer, inplace=False):
        model = _swap_linears(model, self.config)
        for layer in _quanted_layers(model):
            layer.observing = True
        return model

    def convert(self, model: Layer, inplace=False):
        for layer in _quanted_layers(model):
            layer.observing = False
            layer.static_scales = layer.observer.scale()
        return model


def _quanted_layers(model):
    out = []

    def walk(m):
        for sub in m._sub_layers.values():
            if isinstance(sub, QuantedLinear):
                out.append(sub)
            else:
                walk(sub)

    walk(model)
    return out


class BaseObserver:
    """parity: quantization/base_observer.py."""

    def observe(self, x):
        raise NotImplementedError

    def scale(self):
        raise NotImplementedError


class BaseQuanter:
    def __call__(self, x):
        raise NotImplementedError


# registered implementations (isinstance checks go through these ABCs)
BaseObserver.register = classmethod(lambda cls, c: c)
BaseQuanter.register = classmethod(lambda cls, c: c)


def quanter(class_name):
    """Decorator registering a quanter class (parity: factory.py quanter)."""

    def deco(cls):
        globals()[class_name] = cls
        return cls

    return deco


from . import observers  # noqa: F401,E402
from . import quanters  # noqa: F401,E402
