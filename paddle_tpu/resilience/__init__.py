"""paddle_tpu.resilience — training-step anomaly defense.

The reference framework ships a production defense layer
(`FLAGS_check_nan_inf` per-kernel nonfinite instrumentation plus
fleet-elastic hung-worker detection); this subsystem is its TPU-native
closing of the loop: the trainer *survives* anomalies instead of merely
being restartable after them.

Three pieces (contract in docs/RESILIENCE.md):

- **In-graph step health** (`jit.TrainStep`): every compiled step emits a
  fused 4-scalar `StepHealth` bundle — all-finite flag over loss+grads,
  global grad norm (shared with the grad-clip reduction), the loss, and
  the accept flag — as one tiny extra output. Zero extra HBM arrays, at
  most ONE extra scalar device fetch per step, and no new recompiles:
  guarded and unguarded runs execute the SAME program (guard inputs ride
  in as one f32[4] operand; the skip select is ARMED only while a
  StepGuard drives the step — unguarded runs adopt every update exactly
  as they always did, anomalies merely reported in the bundle).
- **`StepGuard`** (guard.py): policy engine around the step. A nonfinite
  or loss-spike step (rolling median/MAD window) keeps the pre-step
  param/slot trees (the skip happens IN-GRAPH via a select, so buffer
  donation stays on); K consecutive anomalies escalate to a
  `CheckpointManager.restore_last_good` rewind; R rollbacks without a
  cure abort loudly (`GuardAbortError`). Every action is counted:
  `guard_anomalies_total{kind}`, `guard_skips_total`,
  `guard_rollbacks_total`, `guard_last_good_step`.
- **`HangWatchdog`** (watchdog.py): heartbeat thread that fires when a
  step exceeds `hang_factor ×` the rolling p50 step time, dumps
  all-thread stacks + a telemetry snapshot to a debris file under the
  checkpoint root, and optionally exits nonzero so a supervisor
  (fleet elastic) restarts into checkpoint `auto_resume`.

Chaos seam: `_ANOMALY_FAULT_HOOK` mirrors
`distributed.checkpoint._WRITE_FAULT_HOOK` — a callable
``hook(call_index) -> None | (site, value)`` consulted once per train-step
invocation (1-based, per step instance). ``site`` is ``"grads"`` or
``"loss"``; ``value`` is injected INSIDE the compiled step through the
guard operand, so nonfinite grads at step k are produced by the same
program a clean step runs. `paddle_tpu.testing.chaos.inject_nonfinite`
installs hooks here; nothing monkeypatches jit internals.
"""
from __future__ import annotations

import contextlib

# The anomaly fault seam (see module docstring). Installed/restored by
# paddle_tpu.testing.chaos; consulted by jit.TrainStep._guard_operand.
_ANOMALY_FAULT_HOOK = None


@contextlib.contextmanager
def install_anomaly_hook(hook):
    """Temporarily install `hook` as the train-step anomaly seam."""
    global _ANOMALY_FAULT_HOOK
    prev = _ANOMALY_FAULT_HOOK
    _ANOMALY_FAULT_HOOK = hook
    try:
        yield hook
    finally:
        _ANOMALY_FAULT_HOOK = prev


from .guard import (  # noqa: E402,F401
    GuardAbortError,
    StepGuard,
    StepHealth,
    StepOutcome,
)
from .watchdog import HangWatchdog  # noqa: E402,F401

__all__ = [
    "StepGuard", "StepHealth", "StepOutcome", "GuardAbortError",
    "HangWatchdog", "install_anomaly_hook",
]
