"""StepGuard: skip-and-rewind policy over a compiled train step.

Detection is in-graph (jit.TrainStep computes the StepHealth bundle and
applies the skip select); this module is the HOST-side policy: the
rolling spike threshold fed into the step, the consecutive-anomaly
escalation ladder, the CheckpointManager-backed rewind, and the loud
abort. Contract and knobs: docs/RESILIENCE.md.
"""
from __future__ import annotations

import collections
import math
import statistics
from typing import NamedTuple, Optional

from .. import telemetry as _telemetry
from ..telemetry import flight as _flight

_ANOMALIES = _telemetry.counter(
    "guard_anomalies_total",
    "anomalous train steps by detection kind",
    labelnames=("kind",))
_SKIPS = _telemetry.counter(
    "guard_skips_total",
    "train-step updates discarded in-graph (pre-step state kept)")
_ROLLBACKS = _telemetry.counter(
    "guard_rollbacks_total",
    "checkpoint rewinds after persistent anomalies")
_LAST_GOOD = _telemetry.gauge(
    "guard_last_good_step",
    "newest global step the guard accepted")


class StepHealth(NamedTuple):
    """Host view of the fused in-graph health bundle (one device fetch)."""

    finite: bool      # loss AND every grad leaf all-finite
    grad_norm: float  # global L2 grad norm (the clip reduction, reused)
    loss: float       # this step's loss, as float32
    ok: bool          # finite AND loss <= spike threshold (update adopted)

    @property
    def kind(self) -> Optional[str]:
        """Detection kind of the anomaly, or None when healthy.

        Independent of ``ok``: an UNGUARDED nonfinite step adopts its
        update (ok=True, legacy semantics) but still reports
        ``kind == "nonfinite"`` — monitoring that polls ``last_health``
        must see the anomaly, per the module contract."""
        if not self.finite:
            return "nonfinite"
        return None if self.ok else "spike"


class StepOutcome(NamedTuple):
    """What the guard decided for one attempted global step."""

    step: int             # the global step that was attempted
    action: str           # "accept" | "skip" | "rollback"
    loss: object          # Tensor on accept, None otherwise
    health: StepHealth
    next_step: int        # where the loop continues: step+1 on accept,
                          # step on skip (retry), last_good+1 on rollback
    restored_step: Optional[int] = None  # rollback only

    @property
    def accepted(self) -> bool:
        return self.action == "accept"


class GuardAbortError(RuntimeError):
    """The escalation ladder is exhausted — stop the run loudly.

    Raised when K consecutive anomalies persist with no manager to rewind
    through, or when R rollbacks did not cure the anomaly. A supervisor
    must treat this as a poisoned run, not a preemption."""


class StepGuard:
    """Anomaly policy around a ``jit.TrainStep`` / ``ShardedTrainStep``.

    Usage (the loop owns the step counter; the guard owns the verdict)::

        guard = StepGuard(step, manager=ckpt_manager)
        gstep = start + 1
        while gstep <= total:
            out = guard(gstep, *batch_for(gstep))
            if out.accepted:
                consume(out.loss)          # checkpoint, log, ...
            gstep = out.next_step          # retry / rewind / advance

    Args:
        train_step: the compiled step (must expose ``_guard_threshold``,
            ``last_health``, ``model``, ``optimizer``, ``_opt_state``).
        manager: CheckpointManager for the escalation rewind (None =
            skip-only policy; K consecutive anomalies then abort).
        window / min_history: rolling loss window for the spike
            threshold; below ``min_history`` accepted losses no spike
            detection happens (threshold +inf).
        zmax: spike threshold = median + zmax * MAD-scale of the window.
        max_consecutive (K): consecutive anomalies before escalating
            from skip to rollback.
        max_rollbacks (R): rollbacks before ``GuardAbortError``.
    """

    def __init__(self, train_step, manager=None, window=32, zmax=8.0,
                 min_history=8, max_consecutive=3, max_rollbacks=2):
        self.train_step = train_step
        self.manager = manager
        self.zmax = float(zmax)
        self.min_history = int(min_history)
        self.max_consecutive = max(1, int(max_consecutive))
        self.max_rollbacks = int(max_rollbacks)
        # (step, loss) of accepted steps, step-ordered: a rollback trims
        # entries above the restored step instead of clearing, so spike
        # detection stays live through the replay (a cleared window
        # would ACCEPT the very spike the rewind was meant to cure)
        self._losses = collections.deque(maxlen=int(window))
        self._consecutive = 0
        self._last_restore = None
        # post-accept (RNG state, optimizer._step_count) per recent
        # step: a rollback to step S restores S's stream so replayed
        # steps draw the SAME keys the clean run drew, and S's step
        # count so replays don't double-increment it (window-bounded;
        # rewinds reaching further back than this keep
        # deterministic-model bitwise parity only)
        self._rng_history = {}
        self._rng_window = 1024
        # run totals (the bench "resilience" block reads these)
        self.anomalies = {}          # kind -> count
        self.skips = 0
        self.rollbacks = 0
        self.last_good_step = None
        self.aborted = False

    # -- detection inputs ----------------------------------------------------
    def spike_threshold(self) -> float:
        """Rolling median + zmax·MAD upper bound on an acceptable loss.

        The MAD scale is floored (1e-3 of the median's magnitude) so a
        perfectly flat window does not flag the first sub-ulp wiggle."""
        losses = [loss for _, loss in self._losses]
        if len(losses) < self.min_history:
            return math.inf
        med = statistics.median(losses)
        mad = statistics.median(abs(x - med) for x in losses)
        scale = max(1.4826 * mad, 1e-3 * max(1.0, abs(med)))
        return med + self.zmax * scale

    # -- the verdict ---------------------------------------------------------
    def __call__(self, step, *batch) -> StepOutcome:
        from .. import framework

        step = int(step)
        # RNG discipline: a discarded attempt must not shift the random
        # stream (dropout masks etc.) relative to the clean run the
        # guard reproduces — restore the pre-attempt state on skip, and
        # the restored step's post-accept state on rollback, so accepted
        # steps consume exactly one key each, in clean-run order.
        rng_before = framework._rng_key_state()
        # arm the in-graph skip ONLY for this driven call: a later direct
        # call on the raw step must get legacy adopt-everything semantics,
        # not a frozen stale threshold silently discarding its updates
        self.train_step._guard_threshold = self.spike_threshold()
        try:
            loss = self.train_step(*batch)
        finally:
            self.train_step._guard_threshold = None
        # the one extra device fetch — under tracing it gets its own
        # span, because under async dispatch this is where a guarded
        # loop actually blocks on the device
        with _telemetry.trace.span("guard:health_fetch",
                                   attrs={"step": step}, cat="step"):
            health = self.train_step.last_health
        if health.ok:
            self._consecutive = 0
            # accepted progress proves the last rewind target CURED its
            # episode: a later, independent episode rewinding to the
            # same (still-newest) commit must not mark_bad a good state
            self._last_restore = None
            self._losses.append((step, health.loss))
            self.last_good_step = step
            _LAST_GOOD.set(step)
            # post-accept (rng, optimizer step count): a rollback to this
            # step restores BOTH, so replayed steps draw clean-run keys
            # AND re-increment _step_count from the restored value
            # instead of double-counting (the checkpoint itself persists
            # only tensors, never "@step")
            self._rng_history[step] = (framework._rng_key_state(),
                                       self.train_step.optimizer._step_count)
            while len(self._rng_history) > self._rng_window:
                self._rng_history.pop(next(iter(self._rng_history)))
            return StepOutcome(step, "accept", loss, health, step + 1)
        framework._set_rng_key_state(rng_before)
        # the in-graph select discarded the update, so the attempt must
        # not count as an optimizer step: a step-6 checkpoint's "@step"
        # must equal the clean run's 6, not the attempt count (health is
        # already fetched — this costs no extra sync; unguarded anomalies
        # ADOPT the update, so their increment stands)
        self.train_step.optimizer._step_count -= 1

        kind = health.kind
        _ANOMALIES.inc(labels=(kind,))
        _telemetry.trace.instant("guard:anomaly",
                                 {"step": step, "kind": kind}, cat="step")
        self.anomalies[kind] = self.anomalies.get(kind, 0) + 1
        self._consecutive += 1
        if self._consecutive < self.max_consecutive:
            # the update was already discarded in-graph; retry the step
            _SKIPS.inc()
            self.skips += 1
            return StepOutcome(step, "skip", None, health, step)

        # escalate: K consecutive anomalies on the same pre-step state
        if self.manager is None:
            self.aborted = True
            # forensics before the raise: the flight bundle carries the
            # recent sample/alert window the exception message cannot
            _flight.maybe_dump("guard_abort", {
                "step": int(step), "kind": kind,
                "consecutive": self._consecutive,
                "loss": repr(health.loss),
                "grad_norm": repr(health.grad_norm),
                "why": "no CheckpointManager to rewind through"})
            raise GuardAbortError(
                f"step {step}: {self._consecutive} consecutive "
                f"{kind} anomalies and no CheckpointManager to rewind "
                f"through (loss={health.loss!r}, "
                f"grad_norm={health.grad_norm!r})")
        if self.rollbacks >= self.max_rollbacks:
            self.aborted = True
            _flight.maybe_dump("guard_abort", {
                "step": int(step), "kind": kind,
                "rollbacks": self.rollbacks,
                "max_rollbacks": self.max_rollbacks,
                "why": "max_rollbacks exhausted"})
            raise GuardAbortError(
                f"step {step}: {kind} anomaly persisted through "
                f"{self.rollbacks} checkpoint rollbacks "
                f"(max_rollbacks={self.max_rollbacks}); the run is "
                f"poisoned — refusing to continue")
        restored = self._rollback(step)
        return StepOutcome(step, "rollback", None, health, restored + 1,
                           restored_step=restored)

    def _rollback(self, step) -> int:
        mgr = self.manager
        mgr.wait()  # pending async saves must land before we pick a target
        if self._last_restore is not None:
            # _last_restore survives only while NO step has been
            # accepted since the previous rewind (accepts clear it): the
            # state we ACTUALLY restored — which can sit below the
            # newest good step when restore fell back past a corrupt
            # one — did not cure the anomaly, so mark IT bad and reach
            # further back. Comparing against last_good_step() instead
            # would never match the fallback-restored step and the
            # ladder would re-land on the same poisoned state forever.
            mgr.mark_bad(self._last_restore,
                         reason=f"anomaly recurred by step {step}")
        from ..distributed.checkpoint.manager import NoCheckpointError

        try:
            restored = mgr.restore_last_good(
                self.train_step.model, self.train_step.optimizer,
                before_step=step)
        except NoCheckpointError as e:
            self.aborted = True
            _flight.maybe_dump("guard_abort", {
                "step": int(step), "error": repr(e),
                "why": "no good committed checkpoint remains"})
            raise GuardAbortError(
                f"step {step}: rewind needed but no good committed "
                f"checkpoint remains ({e})") from e
        # the compiled step must reseed its functional slots from the
        # restored eager slots (jit._init_opt_state), not keep the
        # poisoned in-flight tree
        self.train_step._opt_state = None
        # rewind the RNG stream with the state: replayed steps must draw
        # the keys the clean run drew at those steps
        from .. import framework

        hist = self._rng_history.get(restored)
        if hist is not None:
            rng, step_count = hist
            framework._set_rng_key_state(rng)
            self.train_step.optimizer._step_count = step_count
            for s in [s for s in self._rng_history if s > restored]:
                self._rng_history.pop(s)
        self._last_restore = restored
        self._consecutive = 0
        # trim (never clear) the window to the restored step: replayed
        # steps reproduce exactly the trimmed-away losses, and keeping
        # the older history means the recurring spike is re-flagged on
        # its first replayed attempt — clearing would return +inf
        # thresholds for min_history steps, adopt the spike, and poison
        # the rolling median with it (the ladder then never aborts)
        while self._losses and self._losses[-1][0] > restored:
            self._losses.pop()
        self.rollbacks += 1
        _ROLLBACKS.inc()
        return restored

    # -- reporting -----------------------------------------------------------
    def summary(self) -> dict:
        """JSON-able run totals (bench.py attaches this as the
        "resilience" block; tools/bench_gate.py gates on it)."""
        return {
            "enabled": True,
            "anomalies": dict(self.anomalies),
            "anomalies_total": sum(self.anomalies.values()),
            "skips": self.skips,
            "rollbacks": self.rollbacks,
            "last_good_step": self.last_good_step,
            "aborted": self.aborted,
        }
