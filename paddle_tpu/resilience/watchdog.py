"""Hang/straggler watchdog: detect a wedged train step, dump debris.

A stuck collective or a deadlocked host thread stalls a multi-day run
silently — the process is alive, the accelerator is idle, and nothing
crashes. The reference detects this fleet-side (elastic heartbeat
leases); single-process we can do better: a daemon thread compares the
in-flight step's age against ``hang_factor ×`` the rolling p50 step time
and, on breach, writes a **debris file** (all-thread stacks + a
telemetry snapshot) under the checkpoint root, then optionally exits
nonzero so a supervisor restarts the worker into checkpoint
``auto_resume``. Debris format and contract: docs/RESILIENCE.md.
"""
from __future__ import annotations

import collections
import json
import os
import statistics
import sys
import threading
import time
import traceback

from .. import telemetry as _telemetry

_FIRES = _telemetry.counter(
    "hang_watchdog_fires_total",
    "hang-watchdog firings (in-flight step exceeded hang_factor x "
    "rolling p50 step time)")


def thread_stacks() -> dict:
    """{thread_name:ident -> [stack lines]} for every live thread."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in sys._current_frames().items():
        key = f"{names.get(ident, '?')}:{ident}"
        out[key] = traceback.format_stack(frame)
    return out


class HangWatchdog:
    """Heartbeat watchdog around a training loop.

    The loop marks boundaries::

        with HangWatchdog(os.path.join(ckpt_root, "debris")) as wd:
            for step in ...:
                wd.step_started(step)
                loss = train_one(step)
                wd.step_finished()

    Until ``min_history`` step durations exist, only the
    ``min_hang_seconds`` floor applies (the first compile+warmup step
    must not look like a hang). After that the limit is
    ``max(min_hang_seconds, hang_factor * rolling_p50)``. The watchdog
    fires AT MOST ONCE per step: debris is dumped, the
    ``hang_watchdog_fires_total`` counter ticks, ``on_hang(path)`` runs
    if given, and with ``exit_on_hang=True`` the process hard-exits
    ``exit_code`` (``os._exit`` — a wedged step cannot be unwound; the
    supervisor restart into ``auto_resume`` is the recovery path).
    """

    def __init__(self, debris_dir, hang_factor=4.0, min_hang_seconds=30.0,
                 poll_interval=0.25, window=64, min_history=3,
                 exit_on_hang=False, exit_code=43, on_hang=None):
        self.debris_dir = str(debris_dir)
        self.hang_factor = float(hang_factor)
        self.min_hang_seconds = float(min_hang_seconds)
        self.poll_interval = float(poll_interval)
        self.min_history = int(min_history)
        self.exit_on_hang = bool(exit_on_hang)
        self.exit_code = int(exit_code)
        self.on_hang = on_hang
        self.debris_files = []
        self._durations = collections.deque(maxlen=int(window))
        self._lock = threading.Lock()
        self._current = None      # (step, t_started)
        self._fired_for = None    # (step, t_started) attempt already
                                  # reported — a RETRY of the same step
                                  # number (guard skip/rollback replay)
                                  # is a new attempt and must fire again
        self._stop = threading.Event()
        self._thread = None
        self._exit = os._exit    # test seam: patched to observe the exit

    # -- loop heartbeat ------------------------------------------------------
    def step_started(self, step):
        with self._lock:
            self._current = (int(step), time.monotonic())

    def step_finished(self):
        with self._lock:
            if self._current is None:
                return
            _, t0 = self._current
            self._durations.append(time.monotonic() - t0)
            self._current = None

    def p50_step_seconds(self):
        with self._lock:
            if len(self._durations) < self.min_history:
                return None
            return statistics.median(self._durations)

    def hang_limit_seconds(self):
        p50 = self.p50_step_seconds()
        if p50 is None:
            return self.min_hang_seconds
        return max(self.min_hang_seconds, self.hang_factor * p50)

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="ptpu-hang-watchdog")
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- the watchdog thread -------------------------------------------------
    def _run(self):
        while not self._stop.wait(self.poll_interval):
            with self._lock:
                current = self._current
            if current is None:
                continue
            step, t0 = current
            if self._fired_for == current:
                continue
            elapsed = time.monotonic() - t0
            limit = self.hang_limit_seconds()
            if elapsed < limit:
                continue
            self._fired_for = current
            try:
                path = self.dump_debris(step, elapsed, limit)
            except OSError:
                path = None  # a dead filesystem must not mask the hang
            _FIRES.inc()
            if self.on_hang is not None:
                try:
                    self.on_hang(path)
                except Exception:
                    pass
            if self.exit_on_hang:
                sys.stderr.write(
                    f"HangWatchdog: step {step} wedged for "
                    f"{elapsed:.1f}s (limit {limit:.1f}s); debris at "
                    f"{path}; exiting {self.exit_code} for supervisor "
                    "restart\n")
                sys.stderr.flush()
                self._exit(self.exit_code)

    def dump_debris(self, step, elapsed, limit, reason="hang"):
        """Write one debris JSON file; returns its path. Atomic (tmp +
        os.replace via the checkpoint writer, sharing its chaos seam).

        The payload is built through the flight-recorder bundle contract
        (telemetry.flight): a debris file IS a valid flight bundle —
        recent timeline samples, SLO alerts, and flight events ride
        along when a recorder is installed — with the legacy hang fields
        (step, elapsed_seconds, limit_seconds, p50_step_seconds,
        hang_factor, trace_spans) layered on top for older tooling."""
        from ..distributed.checkpoint import _atomic_write_bytes
        from ..telemetry import flight as _flight

        payload = _flight.build_bundle(reason, context={
            "step": int(step),
            "elapsed_seconds": round(float(elapsed), 3),
            "limit_seconds": round(float(limit), 3),
        })
        payload.update({
            "step": int(step),
            "elapsed_seconds": round(float(elapsed), 3),
            "limit_seconds": round(float(limit), 3),
            "p50_step_seconds": self.p50_step_seconds(),
            "hang_factor": self.hang_factor,
            # legacy alias of the bundle's "live_spans": each thread's
            # open span stack names the exact phase the step wedged in
            "trace_spans": payload.get("live_spans", {}),
        })
        os.makedirs(self.debris_dir, exist_ok=True)
        path = os.path.join(
            self.debris_dir,
            f"debris_{reason}_step{int(step):08d}"
            f"_a{len(self.debris_files)}_pid{os.getpid()}.json")
        _atomic_write_bytes(
            path, json.dumps(payload, indent=1, sort_keys=True).encode(),
            fsync=False)
        self.debris_files.append(path)
        return path
