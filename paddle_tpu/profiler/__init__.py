"""paddle.profiler over jax.profiler/XPlane (parity: python/paddle/profiler).

The reference's CUPTI tracer + chrome export (SURVEY §5 tracing) maps to
jax.profiler traces viewable in TensorBoard/Perfetto; RecordEvent maps to
TraceAnnotation so host-side ranges appear in the device timeline.
"""
from __future__ import annotations

import contextlib
import enum
import os
import time


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM_DEVICE = 3
    TPU = 4


def make_scheduler(*, closed, ready, record, repeat=0, skip_first=0):
    def scheduler(step):
        s = step - skip_first
        if s < 0:
            return ProfilerState.CLOSED
        period = closed + ready + record
        if repeat and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def export_chrome_tracing(dir_name, worker_name=None):
    def handler(prof):
        prof._export_dir = dir_name

    return handler


class Profiler:
    """parity: profiler/profiler.py:89-341."""

    def __init__(self, *, targets=None, scheduler=None, on_trace_ready=None, timer_only=False, record_shapes=False, profile_memory=False, with_flops=False):
        self._targets = targets
        self._scheduler = scheduler if callable(scheduler) else None
        if isinstance(scheduler, (tuple, list)):
            lo, hi = scheduler
            self._scheduler = make_scheduler(closed=lo, ready=0, record=hi - lo, repeat=1)
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self._step = 0
        self._state = ProfilerState.CLOSED
        self._export_dir = None
        self._jax_active = False
        self._step_times = []
        self._last_step_t = None

    def start(self):
        self._last_step_t = time.perf_counter()
        self._transition()
        return self

    def stop(self):
        self._stop_jax()
        if self._on_trace_ready:
            self._on_trace_ready(self)

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._last_step_t is not None:
            self._step_times.append(now - self._last_step_t)
        self._last_step_t = now
        self._step += 1
        self._transition()

    def _transition(self):
        if self._scheduler is None:
            self._start_jax()
            return
        state = self._scheduler(self._step)
        if state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            self._start_jax()
        else:
            self._stop_jax()
        self._state = state

    def _start_jax(self):
        if self._jax_active or self._timer_only:
            return
        try:
            import jax

            logdir = self._export_dir or os.path.join(os.getcwd(), "profiler_log")
            os.makedirs(logdir, exist_ok=True)
            jax.profiler.start_trace(logdir)
            self._jax_active = True
        except Exception:
            pass

    def _stop_jax(self):
        if not self._jax_active:
            return
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:
            pass
        self._jax_active = False

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False, time_unit="ms"):
        if not self._step_times:
            print("no steps recorded")
            return
        import numpy as np

        times = np.asarray(self._step_times)
        print(
            f"steps: {len(times)}  mean: {times.mean()*1e3:.3f} ms  "
            f"p50: {np.percentile(times, 50)*1e3:.3f} ms  "
            f"p99: {np.percentile(times, 99)*1e3:.3f} ms"
        )

    def export(self, path, format="json"):
        self._export_dir = path


class RecordEvent:
    """parity: paddle.profiler.RecordEvent → jax TraceAnnotation."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._ctx = None

    def begin(self):
        try:
            import jax

            self._ctx = jax.profiler.TraceAnnotation(self.name)
            self._ctx.__enter__()
        except Exception:
            self._ctx = None

    def end(self):
        if self._ctx is not None:
            self._ctx.__exit__(None, None, None)
            self._ctx = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def load_profiler_result(filename):
    raise NotImplementedError("use TensorBoard / Perfetto on the XPlane trace dir")
