"""paddle.profiler over jax.profiler/XPlane (parity: python/paddle/profiler).

The reference's CUPTI tracer + chrome export (SURVEY §5 tracing) maps to
jax.profiler traces viewable in TensorBoard/Perfetto; RecordEvent maps to
TraceAnnotation so host-side ranges appear in the device timeline.
"""
from __future__ import annotations

import contextlib
import enum
import os
import time

from .. import telemetry as _telemetry

# the Profiler is a thin client of the shared registry: step timings and
# trace-window counts land next to the framework's own metrics so one
# telemetry snapshot explains a run (docs/TELEMETRY.md)
_PROF_STEP_SECONDS = _telemetry.histogram(
    "profiler_step_seconds", "wall time between Profiler.step() calls")
_PROF_TRACE_WINDOWS = _telemetry.counter(
    "profiler_trace_windows_total", "device trace windows captured")


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM_DEVICE = 3
    TPU = 4


def make_scheduler(*, closed, ready, record, repeat=0, skip_first=0):
    def scheduler(step):
        s = step - skip_first
        if s < 0:
            return ProfilerState.CLOSED
        period = closed + ready + record
        if repeat and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def export_chrome_tracing(dir_name, worker_name=None):
    def handler(prof):
        prof._export_dir = dir_name

    return handler


class Profiler:
    """parity: profiler/profiler.py:89-341."""

    def __init__(self, *, targets=None, scheduler=None, on_trace_ready=None, timer_only=False, record_shapes=False, profile_memory=False, with_flops=False):
        self._targets = targets
        self._scheduler = scheduler if callable(scheduler) else None
        if isinstance(scheduler, (tuple, list)):
            lo, hi = scheduler
            self._scheduler = make_scheduler(closed=lo, ready=0, record=hi - lo, repeat=1)
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self._step = 0
        self._state = ProfilerState.CLOSED
        self._export_dir = None
        self._jax_active = False
        self._step_times = []
        self._last_step_t = None
        self._host_events = []

    def start(self):
        from ..core import native

        native.tracer_enable(True)
        self._last_step_t = time.perf_counter()
        self._transition()
        return self

    def stop(self):
        from ..core import native

        self._stop_jax()
        self._drain_host_events()
        native.tracer_enable(False)
        if self._on_trace_ready:
            self._on_trace_ready(self)

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._last_step_t is not None:
            self._step_times.append(now - self._last_step_t)
            _PROF_STEP_SECONDS.observe(now - self._last_step_t)
        self._last_step_t = now
        self._step += 1
        self._transition()

    def _transition(self):
        if self._scheduler is None:
            self._start_jax()
            return
        state = self._scheduler(self._step)
        if state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            self._start_jax()
        else:
            self._stop_jax()
        self._state = state

    def _start_jax(self):
        if self._jax_active or self._timer_only:
            return
        try:
            import jax

            logdir = self._export_dir or os.path.join(os.getcwd(), "profiler_log")
            os.makedirs(logdir, exist_ok=True)
            jax.profiler.start_trace(logdir)
            self._jax_active = True
        except Exception:
            pass

    def _stop_jax(self):
        if not self._jax_active:
            return
        try:
            import jax

            jax.profiler.stop_trace()
            self._trace_written = True   # a trace from THIS session exists
            _PROF_TRACE_WINDOWS.inc()
        except Exception:
            pass
        self._jax_active = False

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False, time_unit="ms"):
        """Print the statistic tables (parity: profiler_statistic.py)."""
        import numpy as np

        lines = []
        if self._step_times:
            times = np.asarray(self._step_times)
            lines.append(
                f"steps: {len(times)}  mean: {times.mean()*1e3:.3f} ms  "
                f"p50: {np.percentile(times, 50)*1e3:.3f} ms  "
                f"p99: {np.percentile(times, 99)*1e3:.3f} ms"
            )
        stats = host_event_statistics(self._host_events)
        if stats:
            lines.append(
                f"{'Name':<32}{'Calls':>8}{'Total(ms)':>12}{'Avg(ms)':>10}"
                f"{'Max(ms)':>10}{'Min(ms)':>10}"
            )
            order = sorted(stats.items(), key=lambda kv: -kv[1]["total"])
            for name, s in order:
                lines.append(
                    f"{name[:31]:<32}{s['calls']:>8}"
                    f"{s['total']*1e3:>12.3f}{s['avg']*1e3:>10.3f}"
                    f"{s['max']*1e3:>10.3f}{s['min']*1e3:>10.3f}"
                )
        # device-side per-op table (parity: profiler_statistic.py's
        # device-kernel summary from CUPTI; here decoded from the XPlane
        # trace jax wrote — see profiler/xplane.py)
        dev = self.device_summary(limit=20)
        if dev:
            lines.append("")
            lines.append("-- device ops (XPlane) --")
            lines.append(dev)
        out = "\n".join(lines) if lines else "no events recorded"
        print(out)
        return out

    def device_summary(self, limit=30, by_family=False, logdir=None):
        """Per-op device-time table decoded from the XPlane trace dir
        (the reference builds the same table from CUPTI in
        profiler_statistic.py; on TPU the device plane is the XPlane
        protobuf). Returns "" when THIS session captured no device trace
        — stale runs from a previous process in the same logdir are never
        presented as current (pass ``logdir`` explicitly to inspect one)."""
        if logdir is None:
            if not getattr(self, "_trace_written", False):
                return ""
            logdir = self._export_dir or os.path.join(os.getcwd(),
                                                      "profiler_log")
        try:
            from .xplane import (device_op_stats, format_table,
                                 summarize_families)

            rows = device_op_stats(logdir)
        except (OSError, ValueError):
            return ""
        if not rows:
            return ""
        if by_family:
            fams = summarize_families(rows)
            return "\n".join(
                f"{r['family']:<16}{r['calls']:>8}{r['total_us']:>14.1f}us"
                for r in fams)
        return format_table(rows, limit=limit)

    def _drain_host_events(self):
        from ..core import native

        self._host_events.extend(native.tracer_drain())

    def export(self, path, format="json"):
        """Write host events as a chrome trace (chrometracing_logger.cc
        parity). Device-side XPlane traces live in the jax trace dir."""
        import json
        import os as _os

        self._drain_host_events()
        events = []
        for name, start, end, tid, kind in self._host_events:
            events.append({
                "name": name, "ph": "X", "pid": 0, "tid": tid,
                "ts": start / 1e3, "dur": (end - start) / 1e3,
                "cat": "host",
            })
        d = _os.path.dirname(path)
        if d:
            _os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump({"traceEvents": events,
                       "displayTimeUnit": "ms"}, f)
        return path


def host_event_statistics(events):
    """Aggregate (name, start, end, tid, kind) host events per name."""
    stats = {}
    for name, start, end, tid, kind in events:
        dur = max(0, end - start) / 1e9
        s = stats.setdefault(
            name, {"calls": 0, "total": 0.0, "max": 0.0, "min": float("inf")}
        )
        s["calls"] += 1
        s["total"] += dur
        s["max"] = max(s["max"], dur)
        s["min"] = min(s["min"], dur)
    for s in stats.values():
        s["avg"] = s["total"] / s["calls"]
        if s["min"] == float("inf"):
            s["min"] = 0.0
    return stats


class RecordEvent:
    """parity: paddle.profiler.RecordEvent.

    Dual sink: the native C++ tracer buffer (host timeline, chrome export)
    and jax TraceAnnotation (shows up inside the device XPlane trace)."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._ctx = None
        self._t0 = None

    def begin(self):
        from ..core import native

        self._t0 = native.tracer_now_ns()
        try:
            import jax

            self._ctx = jax.profiler.TraceAnnotation(self.name)
            self._ctx.__enter__()
        except Exception:
            self._ctx = None

    def end(self):
        from ..core import native

        if self._t0 is not None:
            import threading

            native.tracer_record(
                self.name, self._t0, native.tracer_now_ns(),
                tid=threading.get_ident() % (1 << 31),
            )
            self._t0 = None
        if self._ctx is not None:
            self._ctx.__exit__(None, None, None)
            self._ctx = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def load_profiler_result(filename):
    import json

    with open(filename) as f:
        return json.load(f)


class SortedKeys:
    """parity: profiler SortedKeys enum."""

    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


class SummaryView:
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


def export_protobuf(dir_name, worker_name=None):
    """Trace-ready handler writing the chrome-trace JSON (the TPU trace
    protobuf is the XPlane dir jax.profiler already writes)."""
    def handler(prof):
        import os as _os

        path = _os.path.join(dir_name, f"{worker_name or 'worker'}.json")
        prof.export(path)

    return handler
