"""XPlane (.xplane.pb) parser + per-op device-time statistics.

Capability slot: the reference builds per-op device-time summary tables
from CUPTI traces (``python/paddle/profiler/profiler_statistic.py`` over
``fluid/platform/profiler/cuda_tracer.cc``). On TPU the device trace is
the XPlane protobuf that ``jax.profiler`` writes; this module decodes it
with a self-contained protobuf *wire-format* reader (no tensorflow /
tensorboard dependency — the schema is pinned to openxla's
``tsl/profiler/protobuf/xplane.proto``) and aggregates XLA-op events into
the same kind of table the reference prints.

Wire schema (field numbers are load-bearing, the rest of the proto is
skipped generically):
  XSpace.planes=1 ; XPlane{id=1, name=2, lines=3, event_metadata=4(map),
  stat_metadata=5(map)} ; XLine{id=1, name=2, timestamp_ns=3, events=4} ;
  XEvent{metadata_id=1, offset_ps=2, duration_ps=3} ;
  XEventMetadata{id=1, name=2, display_name=4} ; map entry {key=1, value=2}.
"""
from __future__ import annotations

import collections
import glob
import os


# ---------------------------------------------------------------- wire reader
def _read_varint(buf, pos):
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _fields(buf):
    """Yield (field_number, wire_type, value) over a message buffer.
    Length-delimited values come back as memoryview slices."""
    pos, end = 0, len(buf)
    while pos < end:
        key, pos = _read_varint(buf, pos)
        fnum, wtype = key >> 3, key & 7
        if wtype == 0:
            val, pos = _read_varint(buf, pos)
        elif wtype == 1:
            val = buf[pos:pos + 8]
            pos += 8
        elif wtype == 2:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wtype == 5:
            val = buf[pos:pos + 4]
            pos += 4
        else:  # groups (3/4) do not appear in xplane.proto
            raise ValueError(f"unsupported wire type {wtype}")
        yield fnum, wtype, val


def _submessages(buf, want_fnum):
    return [v for f, w, v in _fields(buf) if f == want_fnum and w == 2]


def _scalar(buf, want_fnum, default=0):
    for f, w, v in _fields(buf):
        if f == want_fnum and w == 0:
            return v
    return default


def _string(buf, want_fnum, default=""):
    for f, w, v in _fields(buf):
        if f == want_fnum and w == 2:
            return bytes(v).decode("utf-8", "replace")
    return default


# ---------------------------------------------------------------- model
class XEvent:
    __slots__ = ("name", "offset_ps", "duration_ps")

    def __init__(self, name, offset_ps, duration_ps):
        self.name = name
        self.offset_ps = offset_ps
        self.duration_ps = duration_ps


class XLine:
    __slots__ = ("name", "timestamp_ns", "events")

    def __init__(self, name, timestamp_ns, events):
        self.name = name
        self.timestamp_ns = timestamp_ns
        self.events = events


class XPlane:
    __slots__ = ("name", "lines")

    def __init__(self, name, lines):
        self.name = name
        self.lines = lines


def parse_xspace(path):
    """Parse one .xplane.pb file into a list of XPlane objects."""
    with open(path, "rb") as f:
        data = memoryview(f.read())
    planes = []
    for pbuf in _submessages(data, 1):
        name = _string(pbuf, 2)
        # event metadata id -> display-or-plain name
        meta = {}
        for entry in _submessages(pbuf, 4):
            key = _scalar(entry, 1)
            mbufs = _submessages(entry, 2)
            if mbufs:
                mname = _string(mbufs[0], 4) or _string(mbufs[0], 2)
                meta[key] = mname
        lines = []
        for lbuf in _submessages(pbuf, 3):
            lname = _string(lbuf, 2)
            ts = _scalar(lbuf, 3)
            events = []
            for ebuf in _submessages(lbuf, 4):
                mid = _scalar(ebuf, 1)
                events.append(XEvent(meta.get(mid, str(mid)),
                                     _scalar(ebuf, 2), _scalar(ebuf, 3)))
            lines.append(XLine(lname, ts, events))
        planes.append(XPlane(name, lines))
    return planes


# ---------------------------------------------------------------- statistics
def _classify(op_name):
    """Bucket an XLA HLO op name into a coarse family (for the summary)."""
    n = op_name.lower()
    if "fusion" in n:
        return "fusion"
    for kw, fam in (("dot", "matmul"), ("conv", "conv"),
                    ("custom-call", "custom_call"), ("copy", "copy"),
                    ("all-reduce", "collective"), ("all-gather", "collective"),
                    ("collective", "collective"), ("reduce-scatter", "collective"),
                    ("scatter", "scatter"), ("gather", "gather"),
                    ("dynamic-update-slice", "dus"), ("rng", "rng")):
        if kw in n:
            return fam
    return "other"


def device_op_stats(logdir_or_file):
    """Aggregate device-plane XLA op events into per-op totals.

    Returns a list of dicts {name, calls, total_us, avg_us, family},
    sorted by total time descending — the TPU analogue of the reference's
    ``profiler_statistic.py`` device-kernel table.
    """
    if os.path.isdir(logdir_or_file):
        paths = sorted(glob.glob(os.path.join(
            logdir_or_file, "**", "*.xplane.pb"), recursive=True))
        # jax writes each trace under plugins/profile/<timestamp>/ —
        # restrict to the NEWEST run so repeated profiling into one
        # logdir doesn't aggregate stale runs
        by_dir = collections.defaultdict(list)
        for p in paths:
            by_dir[os.path.dirname(p)].append(p)
        if by_dir:
            paths = by_dir[max(by_dir)]
    else:
        paths = [logdir_or_file]

    def _is_device(pname):
        return ("device" in pname or "tpu" in pname or "/gpu" in pname
                or "xla op" in pname)

    planes = [pl for p in paths for pl in parse_xspace(p)]
    device_planes = [pl for pl in planes if _is_device(pl.name.lower())]
    if not device_planes:
        # XLA:CPU runs put op events on "/host:CPU"; only fall back to it
        # when NO real device plane exists (on TPU/GPU that plane holds
        # host TraceMe events, not device time)
        device_planes = [pl for pl in planes
                         if pl.name.lower() == "/host:cpu"]
    acc = collections.defaultdict(lambda: [0, 0])  # name -> [calls, ps]
    for plane in device_planes:
            for line in plane.lines:
                # device planes carry one line per core/stream of XLA ops
                if "step" in line.name.lower():
                    continue
                for ev in line.events:
                    slot = acc[ev.name]
                    slot[0] += 1
                    slot[1] += ev.duration_ps
    rows = [
        {"name": k, "calls": c, "total_us": ps / 1e6,
         "avg_us": ps / 1e6 / max(c, 1), "family": _classify(k)}
        for k, (c, ps) in acc.items()
    ]
    rows.sort(key=lambda r: -r["total_us"])
    return rows


def summarize_families(rows):
    """Collapse an op table into per-family totals (matmul/fusion/...)."""
    fam = collections.defaultdict(lambda: [0, 0.0])
    for r in rows:
        fam[r["family"]][0] += r["calls"]
        fam[r["family"]][1] += r["total_us"]
    out = [{"family": k, "calls": c, "total_us": us}
           for k, (c, us) in fam.items()]
    out.sort(key=lambda r: -r["total_us"])
    return out


def format_table(rows, limit=30):
    """Render the op table the way the reference's summary prints."""
    total = sum(r["total_us"] for r in rows) or 1.0
    lines = [f"{'op':<64} {'calls':>6} {'total_us':>12} {'avg_us':>10} {'%':>6}"]
    for r in rows[:limit]:
        lines.append(
            f"{r['name'][:64]:<64} {r['calls']:>6} {r['total_us']:>12.1f} "
            f"{r['avg_us']:>10.2f} {100 * r['total_us'] / total:>5.1f}%")
    return "\n".join(lines)
