"""paddle.onnx (parity: python/paddle/onnx/export.py:35 — delegates to
paddle2onnx). The TPU build's interchange format is StableHLO (jax.export),
which this module emits; classic .onnx export requires paddle2onnx, absent
from this image, and raises with guidance."""
from __future__ import annotations


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """Export the layer's forward as StableHLO text (TPU-native interchange).

    Writes `<path>.stablehlo.mlir`. For .onnx specifically install
    paddle2onnx and convert from the saved jit model.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from .jit import functional_call

    if input_spec is None:
        raise ValueError("input_spec is required for export")

    def make_arg(spec):
        shape = [1 if (s is None or int(s) < 0) else int(s)
                 for s in (spec.shape or [1])]
        return jnp.zeros(shape, getattr(np, str(spec.dtype), np.float32))

    args = tuple(make_arg(s) for s in input_spec)
    state = {k: v._data for k, v in layer.state_dict().items()}

    def fwd(state, *xs):
        out, _ = functional_call(layer, state, *xs)
        return out

    lowered = jax.jit(fwd).lower(state, *args)
    mlir = lowered.as_text()
    out_path = str(path) + ".stablehlo.mlir"
    with open(out_path, "w") as f:
        f.write(mlir)
    return out_path
