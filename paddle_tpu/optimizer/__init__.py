"""paddle.optimizer — 17 optimizers over the functional update core.

Parity: python/paddle/optimizer/. Each _update is pure jnp: eager step() and
the jit'd TrainStep share it.
"""
from __future__ import annotations

import jax.numpy as jnp

from . import lr  # noqa: F401
from .optimizer import Optimizer
from .regularizer import L1Decay, L2Decay  # noqa: F401


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _update(self, p, g, slots, lr):
        return p - lr * g, slots


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None, use_nesterov=False, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _init_slots(self, p):
        return {"velocity": jnp.zeros_like(p)}

    def _update(self, p, g, slots, lr):
        v = self._momentum * slots["velocity"] + g
        if self._nesterov:
            new_p = p - lr * (g + self._momentum * v)
        else:
            new_p = p - lr * v
        return new_p, {"velocity": v}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-06, parameters=None, weight_decay=None, grad_clip=None, initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._init_val = initial_accumulator_value

    def _init_slots(self, p):
        return {"moment": jnp.full_like(p, self._init_val)}

    def _update(self, p, g, slots, lr):
        m = slots["moment"] + g * g
        new_p = p - lr * g / (jnp.sqrt(m) + self._epsilon)
        return new_p, {"moment": m}


_Q8_BLOCK = 2048


def _q8_signed(x, block=_Q8_BLOCK):
    """Blockwise absmax int8 over the flattened array -> (q [nb, B],
    scale [nb]). Dettmers-style 8-bit optimizer-state storage (published
    8-bit Adam recipe), TPU-native: pure elementwise, jit-fusable."""
    flat = x.reshape(-1)
    pad = (-flat.size) % block
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    xb = flat.reshape(-1, block)
    s = jnp.maximum(jnp.max(jnp.abs(xb), 1, keepdims=True), 1e-20) / 127.0
    q = jnp.clip(jnp.round(xb / s), -127, 127).astype(jnp.int8)
    return q, s[:, 0]


def _dq8_signed(q, s, shape, size):
    flat = (q.astype(jnp.float32) * s[:, None]).reshape(-1)
    return flat[:size].reshape(shape)


_dq8_unsigned = _dq8_signed  # dequant is quantizer-agnostic


def _q8_unsigned(x, block=_Q8_BLOCK):
    """uint8 variant for non-negative values (sqrt of the second moment —
    the sqrt compresses its dynamic range before linear quantisation)."""
    flat = x.reshape(-1)
    pad = (-flat.size) % block
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    xb = flat.reshape(-1, block)
    s = jnp.maximum(jnp.max(xb, 1, keepdims=True), 1e-20) / 255.0
    q = jnp.clip(jnp.round(xb / s), 0, 255).astype(jnp.uint8)
    return q, s[:, 0]


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-08, parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False, multi_precision=False, use_multi_tensor=False, amsgrad=False, moment_dtype=None, factored=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._amsgrad = amsgrad
        self._multi_precision = multi_precision
        # factored=True: Adafactor-style (Shazeer & Stern 2018) rank-1
        # factorization of the SECOND moment over the last two dims of
        # every >=2-D parameter — row/col EMA statistics in fp32, exact
        # first-moment semantics unchanged. Replaces the full m2 tensor
        # (param-sized) with two vectors, freeing ~half the Adam state
        # (1.3B bf16: m2 2.6GB -> ~3MB) with fp32 math and NO
        # quant/dequant in the hot path (the int8-storage route measured
        # a 3-13% loss, docs/ROUND4_RESPONSE.md). 1-D params keep exact
        # m2. Reference capability slot: optimizer zoo
        # (python/paddle/optimizer/adamw.py).
        self._factored = bool(factored)
        if factored and (amsgrad or moment_dtype):
            raise ValueError("factored=True does not compose with "
                             "amsgrad/moment_dtype")
        # moment_dtype="int8": blockwise-quantised moments (8-bit Adam) —
        # m stored signed int8, sqrt(v) stored uint8, per-2048-block f32
        # scales. Optimizer HBM drops 4x vs fp32 / 2x vs bf16 moments
        # (1.3B bf16: 5.4G -> 1.35G). MEASURED SLOWER on v5e-16G pretrain
        # (-13% MFU: the quant/dequant round-trips break XLA fusion —
        # docs/ROUND4_RESPONSE.md) — use only for memory-bound
        # fine-tuning where the state simply must fit; for pretrain
        # headroom prefer factored=True, which measured FASTER (r5).
        # Parity bounded by tests/test_optimizer.py.
        if moment_dtype not in (None, "int8"):
            raise ValueError("moment_dtype must be None or 'int8'")
        if moment_dtype == "int8" and (amsgrad or multi_precision):
            raise ValueError("moment_dtype='int8' does not compose with "
                             "amsgrad/multi_precision")
        self._moment_dtype = moment_dtype

    def _init_slots(self, p):
        f32 = jnp.float32
        if self._moment_dtype == "int8":
            size = 1
            for s in p.shape:
                size *= int(s)
            nb = (size + _Q8_BLOCK - 1) // _Q8_BLOCK
            return {
                "moment1_q": jnp.zeros((nb, _Q8_BLOCK), jnp.int8),
                "moment1_s": jnp.zeros((nb,), f32),
                "moment2_q": jnp.zeros((nb, _Q8_BLOCK), jnp.uint8),
                "moment2_s": jnp.zeros((nb,), f32),
                "beta1_pow": jnp.ones((), f32),
                "beta2_pow": jnp.ones((), f32),
            }
        # reference semantics (optimizer.py _add_accumulator): moments live in
        # the PARAM dtype; fp32 moments + master weights only under
        # multi_precision. At 1.3B bf16 this halves optimizer HBM (10.8G→5.4G).
        mdt = f32 if (self._multi_precision and p.dtype != f32) else p.dtype
        if self._factored and p.ndim >= 2:
            slots = {
                "moment1": jnp.zeros(p.shape, mdt),
                # row stats: mean of g^2 over the last axis; col stats:
                # mean over the second-to-last. Leading (stacked-layer)
                # dims stay unfactored.
                "vr": jnp.zeros(p.shape[:-1], f32),
                "vc": jnp.zeros(p.shape[:-2] + p.shape[-1:], f32),
                "beta1_pow": jnp.ones((), f32),
                "beta2_pow": jnp.ones((), f32),
            }
            if self._multi_precision and p.dtype != f32:
                slots["master_weight"] = p.astype(f32)
            return slots
        slots = {
            "moment1": jnp.zeros(p.shape, mdt),
            "moment2": jnp.zeros(p.shape, mdt),
            "beta1_pow": jnp.ones((), f32),
            "beta2_pow": jnp.ones((), f32),
        }
        if self._amsgrad:
            slots["moment2_max"] = jnp.zeros(p.shape, mdt)
        if self._multi_precision and p.dtype != jnp.float32:
            slots["master_weight"] = p.astype(f32)
        return slots

    def _update_factored(self, p, g, slots, lr):
        """Rank-1 second moment: v_ij ~= r_i * c_j / mean(r). For the
        rank-1 MLE fit (R C^T)/(1^T R 1) the mean form is exact when the
        true v is rank-1; bias correction stays multiplicative so the
        usual 1/(1-b2^t) applies to the r/c EMAs unchanged."""
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        gf = g.astype(jnp.float32)
        g2 = gf * gf
        vr = b2 * slots["vr"] + (1 - b2) * jnp.mean(g2, axis=-1)
        vc = b2 * slots["vc"] + (1 - b2) * jnp.mean(g2, axis=-2)
        b1p = slots["beta1_pow"] * b1
        b2p = slots["beta2_pow"] * b2
        m1 = b1 * slots["moment1"].astype(jnp.float32) + (1 - b1) * gf
        m1_hat = m1 / (1 - b1p)
        r_mean = jnp.mean(vr, axis=-1, keepdims=True)
        v_hat = (vr[..., :, None] * vc[..., None, :]
                 / jnp.maximum(r_mean[..., None], 1e-30)) / (1 - b2p)
        update = m1_hat / (jnp.sqrt(v_hat) + eps)
        new_slots = {"moment1": m1.astype(slots["moment1"].dtype),
                     "vr": vr, "vc": vc,
                     "beta1_pow": b1p, "beta2_pow": b2p}
        master = slots.get("master_weight")
        if master is not None:
            new_master = master - lr * update
            new_slots["master_weight"] = new_master
            new_p = new_master.astype(p.dtype)
        else:
            new_p = (p.astype(jnp.float32) - lr * update).astype(p.dtype)
        return new_p, new_slots

    def _update(self, p, g, slots, lr):
        if self._factored and "vr" in slots:
            return self._update_factored(p, g, slots, lr)
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        gf = g.astype(jnp.float32)
        if self._moment_dtype == "int8":
            size = 1
            for s in p.shape:
                size *= int(s)
            m1_prev = _dq8_signed(slots["moment1_q"], slots["moment1_s"],
                                  p.shape, size)
            sq_prev = _dq8_unsigned(slots["moment2_q"], slots["moment2_s"],
                                    p.shape, size)
            m2_prev = sq_prev * sq_prev
        else:
            mdt = slots["moment1"].dtype
            m1_prev = slots["moment1"].astype(jnp.float32)
            m2_prev = slots["moment2"].astype(jnp.float32)
        m1 = b1 * m1_prev + (1 - b1) * gf
        m2 = b2 * m2_prev + (1 - b2) * gf * gf
        b1p = slots["beta1_pow"] * b1
        b2p = slots["beta2_pow"] * b2
        m1_hat = m1 / (1 - b1p)
        denom_m2 = m2
        if self._moment_dtype == "int8":
            q1, s1 = _q8_signed(m1)
            q2, s2 = _q8_unsigned(jnp.sqrt(m2))
            new_slots = {"moment1_q": q1, "moment1_s": s1,
                         "moment2_q": q2, "moment2_s": s2,
                         "beta1_pow": b1p, "beta2_pow": b2p}
        else:
            new_slots = {"moment1": m1.astype(mdt),
                         "moment2": m2.astype(mdt),
                         "beta1_pow": b1p, "beta2_pow": b2p}
        if self._amsgrad:
            m2max = jnp.maximum(slots["moment2_max"].astype(jnp.float32), m2)
            denom_m2 = m2max
            new_slots["moment2_max"] = m2max.astype(mdt)
        m2_hat = denom_m2 / (1 - b2p)
        update = m1_hat / (jnp.sqrt(m2_hat) + eps)
        master = slots.get("master_weight")
        if master is not None:
            new_master = master - lr * update
            new_slots["master_weight"] = new_master
            new_p = new_master.astype(p.dtype)
        else:
            new_p = (p.astype(jnp.float32) - lr * update).astype(p.dtype)
        return new_p, new_slots


class AdamW(Adam):
    """Decoupled weight decay (parity: python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-08, parameters=None, weight_decay=0.01, lr_ratio=None, apply_decay_param_fun=None, grad_clip=None, lazy_mode=False, multi_precision=False, amsgrad=False, moment_dtype=None, factored=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters, None, grad_clip, lazy_mode, multi_precision, amsgrad=amsgrad, moment_dtype=moment_dtype, factored=factored, name=name)
        self._wd = float(weight_decay) if not callable(weight_decay) else weight_decay
        self._apply_decay_param_fun = apply_decay_param_fun
        self._current_param_name = None

    @property
    def _coeff(self):
        return self._wd

    def step(self):
        # decay applies per-param (apply_decay_param_fun filter) before update
        self._decay_names = None
        super().step()

    def _regularized_grad_arr(self, p, g_arr):
        # mark current param so _update can decide decay
        self._current_param_name = getattr(p, "name", None)
        return g_arr

    def _update(self, p, g, slots, lr):
        decay = True
        if self._apply_decay_param_fun is not None and self._current_param_name is not None:
            decay = self._apply_decay_param_fun(self._current_param_name)
        if decay and self._wd:
            master = slots.get("master_weight")
            base = master if master is not None else p.astype(jnp.float32)
            base = base * (1.0 - lr * self._wd)
            if master is not None:
                slots = dict(slots)
                slots["master_weight"] = base
                p = base.astype(p.dtype)
            else:
                p = base.astype(p.dtype)
        return super()._update(p, g, slots, lr)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-08, parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _init_slots(self, p):
        return {
            "moment": jnp.zeros_like(p, jnp.float32),
            "inf_norm": jnp.zeros_like(p, jnp.float32),
            "beta1_pow": jnp.ones((), jnp.float32),
        }

    def _update(self, p, g, slots, lr):
        gf = g.astype(jnp.float32)
        m = self._beta1 * slots["moment"] + (1 - self._beta1) * gf
        u = jnp.maximum(self._beta2 * slots["inf_norm"], jnp.abs(gf) + self._epsilon)
        b1p = slots["beta1_pow"] * self._beta1
        new_p = (p.astype(jnp.float32) - (lr / (1 - b1p)) * m / u).astype(p.dtype)
        return new_p, {"moment": m, "inf_norm": u, "beta1_pow": b1p}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-06, momentum=0.0, centered=False, parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _init_slots(self, p):
        slots = {
            "mean_square": jnp.zeros_like(p, jnp.float32),
            "momentum": jnp.zeros_like(p, jnp.float32),
        }
        if self._centered:
            slots["mean_grad"] = jnp.zeros_like(p, jnp.float32)
        return slots

    def _update(self, p, g, slots, lr):
        gf = g.astype(jnp.float32)
        ms = self._rho * slots["mean_square"] + (1 - self._rho) * gf * gf
        new_slots = {"mean_square": ms}
        if self._centered:
            mg = self._rho * slots["mean_grad"] + (1 - self._rho) * gf
            denom = jnp.sqrt(ms - mg * mg + self._epsilon)
            new_slots["mean_grad"] = mg
        else:
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._momentum * slots["momentum"] + lr * gf / denom
        new_slots["momentum"] = mom
        new_p = (p.astype(jnp.float32) - mom).astype(p.dtype)
        return new_p, new_slots


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-06, rho=0.95, parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon, self._rho = epsilon, rho

    def _init_slots(self, p):
        return {
            "avg_squared_grad": jnp.zeros_like(p, jnp.float32),
            "avg_squared_update": jnp.zeros_like(p, jnp.float32),
        }

    def _update(self, p, g, slots, lr):
        gf = g.astype(jnp.float32)
        asg = self._rho * slots["avg_squared_grad"] + (1 - self._rho) * gf * gf
        update = (
            jnp.sqrt(slots["avg_squared_update"] + self._epsilon)
            / jnp.sqrt(asg + self._epsilon)
            * gf
        )
        asu = self._rho * slots["avg_squared_update"] + (1 - self._rho) * update * update
        new_p = (p.astype(jnp.float32) - lr * update).astype(p.dtype)
        return new_p, {"avg_squared_grad": asg, "avg_squared_update": asu}


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9, beta2=0.999, epsilon=1e-06, parameters=None, grad_clip=None, exclude_from_weight_decay_fn=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn
        self._current_param = None

    def _init_slots(self, p):
        return {
            "moment1": jnp.zeros_like(p, jnp.float32),
            "moment2": jnp.zeros_like(p, jnp.float32),
            "beta1_pow": jnp.ones((), jnp.float32),
            "beta2_pow": jnp.ones((), jnp.float32),
        }

    def _regularized_grad_arr(self, p, g_arr):
        self._current_param = p
        return g_arr

    def _update(self, p, g, slots, lr):
        gf = g.astype(jnp.float32)
        pf = p.astype(jnp.float32)
        m1 = self._beta1 * slots["moment1"] + (1 - self._beta1) * gf
        m2 = self._beta2 * slots["moment2"] + (1 - self._beta2) * gf * gf
        b1p = slots["beta1_pow"] * self._beta1
        b2p = slots["beta2_pow"] * self._beta2
        m1h = m1 / (1 - b1p)
        m2h = m2 / (1 - b2p)
        r = m1h / (jnp.sqrt(m2h) + self._epsilon)
        wd = self._lamb_wd
        if self._exclude_fn is not None and self._current_param is not None and self._exclude_fn(self._current_param):
            wd = 0.0
        update = r + wd * pf
        w_norm = jnp.linalg.norm(pf)
        u_norm = jnp.linalg.norm(update)
        ratio = jnp.where(
            (w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0
        )
        new_p = (pf - lr * ratio * update).astype(p.dtype)
        return new_p, {"moment1": m1, "moment2": m2, "beta1_pow": b1p, "beta2_pow": b2p}


class NAdam(Optimizer):
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, epsilon=1e-8, momentum_decay=0.004, parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._psi = momentum_decay

    def _init_slots(self, p):
        return {
            "moment1": jnp.zeros_like(p, jnp.float32),
            "moment2": jnp.zeros_like(p, jnp.float32),
            "mu_prod": jnp.ones((), jnp.float32),
            "step": jnp.zeros((), jnp.float32),
        }

    def _update(self, p, g, slots, lr):
        gf = g.astype(jnp.float32)
        t = slots["step"] + 1
        mu_t = self._beta1 * (1 - 0.5 * 0.96 ** (t * self._psi))
        mu_t1 = self._beta1 * (1 - 0.5 * 0.96 ** ((t + 1) * self._psi))
        mu_prod = slots["mu_prod"] * mu_t
        m1 = self._beta1 * slots["moment1"] + (1 - self._beta1) * gf
        m2 = self._beta2 * slots["moment2"] + (1 - self._beta2) * gf * gf
        m1h = mu_t1 * m1 / (1 - mu_prod * mu_t1) + (1 - mu_t) * gf / (1 - mu_prod)
        m2h = m2 / (1 - self._beta2**t)
        new_p = (p.astype(jnp.float32) - lr * m1h / (jnp.sqrt(m2h) + self._epsilon)).astype(p.dtype)
        return new_p, {"moment1": m1, "moment2": m2, "mu_prod": mu_prod, "step": t}


class RAdam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _init_slots(self, p):
        return {
            "moment1": jnp.zeros_like(p, jnp.float32),
            "moment2": jnp.zeros_like(p, jnp.float32),
            "step": jnp.zeros((), jnp.float32),
        }

    def _update(self, p, g, slots, lr):
        gf = g.astype(jnp.float32)
        t = slots["step"] + 1
        m1 = self._beta1 * slots["moment1"] + (1 - self._beta1) * gf
        m2 = self._beta2 * slots["moment2"] + (1 - self._beta2) * gf * gf
        m1h = m1 / (1 - self._beta1**t)
        rho_inf = 2 / (1 - self._beta2) - 1
        rho_t = rho_inf - 2 * t * self._beta2**t / (1 - self._beta2**t)
        def _rect():
            m2h = jnp.sqrt(m2 / (1 - self._beta2**t))
            r = jnp.sqrt(
                ((rho_t - 4) * (rho_t - 2) * rho_inf)
                / ((rho_inf - 4) * (rho_inf - 2) * rho_t)
            )
            return r * m1h / (m2h + self._epsilon)

        update = jnp.where(rho_t > 5.0, _rect(), m1h)
        new_p = (p.astype(jnp.float32) - lr * update).astype(p.dtype)
        return new_p, {"moment1": m1, "moment2": m2, "step": t}


class ASGD(SGD):
    pass


class Rprop(Optimizer):
    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50), parameters=None, etas=(0.5, 1.2), grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._lr_min, self._lr_max = learning_rate_range
        self._eta_neg, self._eta_pos = etas

    def _init_slots(self, p):
        return {
            "prev_grad": jnp.zeros_like(p, jnp.float32),
            "lr": jnp.full(p.shape, float(self._learning_rate), jnp.float32),
        }

    def _update(self, p, g, slots, lr):
        gf = g.astype(jnp.float32)
        sign = jnp.sign(gf * slots["prev_grad"])
        factor = jnp.where(sign > 0, self._eta_pos, jnp.where(sign < 0, self._eta_neg, 1.0))
        new_lr = jnp.clip(slots["lr"] * factor, self._lr_min, self._lr_max)
        new_p = (p.astype(jnp.float32) - new_lr * jnp.sign(gf)).astype(p.dtype)
        return new_p, {"prev_grad": gf, "lr": new_lr}


class LBFGS(Optimizer):
    """Limited-memory BFGS with closure API (simplified two-loop recursion)."""

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None, tolerance_grad=1e-07, tolerance_change=1e-09, history_size=100, line_search_fn=None, parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._max_iter = max_iter
        self._history_size = history_size
        self._s_list = []
        self._y_list = []
        self._prev_flat_grad = None
        self._prev_flat_param = None

    def _flat(self, arrays):
        return jnp.concatenate([a.reshape(-1).astype(jnp.float32) for a in arrays])

    def step(self, closure=None):
        if closure is None:
            raise ValueError("LBFGS.step requires a closure returning the loss")
        loss = closure()
        params = [p for p in self._parameter_list if p.trainable and p.grad is not None]
        flat_g = self._flat([p.grad._data for p in params])
        flat_p = self._flat([p._data for p in params])
        if self._prev_flat_grad is not None:
            s = flat_p - self._prev_flat_param
            y = flat_g - self._prev_flat_grad
            if float(jnp.dot(s, y)) > 1e-10:
                self._s_list.append(s)
                self._y_list.append(y)
                if len(self._s_list) > self._history_size:
                    self._s_list.pop(0)
                    self._y_list.pop(0)
        q = flat_g
        alphas = []
        for s, y in zip(reversed(self._s_list), reversed(self._y_list)):
            rho = 1.0 / jnp.dot(y, s)
            alpha = rho * jnp.dot(s, q)
            q = q - alpha * y
            alphas.append((alpha, rho))
        if self._y_list:
            y_last, s_last = self._y_list[-1], self._s_list[-1]
            q = q * (jnp.dot(s_last, y_last) / jnp.dot(y_last, y_last))
        for (alpha, rho), s, y in zip(reversed(alphas), self._s_list, self._y_list):
            beta = rho * jnp.dot(y, q)
            q = q + (alpha - beta) * s
        direction = -q
        lr = self.get_lr()
        self._prev_flat_grad = flat_g
        self._prev_flat_param = flat_p
        offset = 0
        for p in params:
            n = p.size
            upd = direction[offset : offset + n].reshape(p._data.shape)
            p._data = (p._data.astype(jnp.float32) + lr * upd).astype(p._data.dtype)
            offset += n
        return loss


__all__ = [
    "Optimizer", "SGD", "Momentum", "Adagrad", "Adam", "AdamW", "Adamax",
    "RMSProp", "Adadelta", "Lamb", "NAdam", "RAdam", "ASGD", "Rprop",
    "LBFGS", "lr", "L1Decay", "L2Decay",
]
