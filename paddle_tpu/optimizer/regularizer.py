"""Regularizers (parity: python/paddle/regularizer.py)."""
from __future__ import annotations

import jax.numpy as jnp


class WeightDecayRegularizer:
    def _apply_arr(self, p_arr, g_arr):
        raise NotImplementedError

    def _apply(self, p, g):
        from ..core.dispatch import apply_op

        return apply_op(lambda pa, ga: self._apply_arr(pa, ga), p, g)


class L2Decay(WeightDecayRegularizer):
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def _apply_arr(self, p_arr, g_arr):
        return g_arr + self.coeff * p_arr

    def __repr__(self):
        return f"L2Decay({self.coeff})"


class L1Decay(WeightDecayRegularizer):
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def _apply_arr(self, p_arr, g_arr):
        return g_arr + self.coeff * jnp.sign(p_arr)

    def __repr__(self):
        return f"L1Decay({self.coeff})"
