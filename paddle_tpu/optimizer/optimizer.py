"""Optimizer base (parity: python/paddle/optimizer/optimizer.py:128).

Each optimizer is defined by two *pure* functions — ``_init_slots`` and
``_update`` — used both by the eager ``step()`` loop and, unchanged, inside
jit-compiled functional train steps (paddle_tpu.jit.TrainStep).  That single
source of truth is the TPU-native replacement for the reference's per-device
optimizer kernels (``phi/kernels/gpu/adam_kernel.cu`` etc.): XLA fuses the
whole update into one kernel per parameter, or one fused loop when the step
is jitted.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from .. import framework
from .. import telemetry as _telemetry
from ..core.tensor import Tensor, Parameter
from .lr import LRScheduler

_OPT_STEP_SECONDS = _telemetry.histogram(
    "optimizer_step_seconds", "eager Optimizer.step wall time",
    labelnames=("optimizer",))


class Optimizer:
    def __init__(
        self,
        learning_rate=0.001,
        parameters=None,
        weight_decay=None,
        grad_clip=None,
        name=None,
    ):
        if parameters is not None:
            parameters = list(parameters)
            if parameters and isinstance(parameters[0], dict):
                # param groups
                self._param_groups = parameters
                flat = []
                for g in parameters:
                    flat.extend(g["params"])
                parameters = flat
            else:
                self._param_groups = None
        else:
            self._param_groups = None
        self._parameter_list = parameters
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        from .regularizer import L2Decay

        if isinstance(weight_decay, float):
            self.regularization = L2Decay(weight_decay)
        else:
            self.regularization = weight_decay
        self._slots = {}  # id(param) -> {slot_name: jax array}
        self._step_count = 0
        self._name = name

    # -- lr ----------------------------------------------------------------
    def get_lr(self):
        if isinstance(self._learning_rate, LRScheduler):
            return self._learning_rate()
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    @property
    def _lr_scheduler(self):
        return self._learning_rate if isinstance(self._learning_rate, LRScheduler) else None

    # -- functional core (override) ----------------------------------------
    def _init_slots(self, param_array):
        """Pure: initial slot dict for one parameter array."""
        return {}

    def _update(self, p, g, slots, lr):
        """Pure: returns (new_p, new_slots)."""
        raise NotImplementedError

    # -- regularization ----------------------------------------------------
    def _regularized_grad(self, p, g):
        reg = getattr(p, "regularizer", None) or self.regularization
        if reg is None:
            return g
        return reg._apply(p, g)

    # -- eager step --------------------------------------------------------
    @framework.no_grad()
    def step(self):
        with _telemetry.timer(_OPT_STEP_SECONDS,
                              labels=(type(self).__name__,)):
            self._step_impl()

    def _step_impl(self):
        params = self._parameter_list
        if params is None:
            raise RuntimeError("Optimizer created without parameters")
        params_grads = [
            (p, p.grad) for p in params if p.trainable and p.grad is not None
        ]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        lr = self.get_lr()
        for p, g in params_grads:
            g_arr = g._data if isinstance(g, Tensor) else g
            g_arr = self._regularized_grad_arr(p, g_arr)
            slots = self._slots.get(id(p))
            if slots is None:
                slots = self._init_slots(p._data)
                self._slots[id(p)] = slots
            p_lr = lr * p.optimize_attr.get("learning_rate", 1.0) if hasattr(p, "optimize_attr") else lr
            new_p, new_slots = self._update(p._data, g_arr.astype(p._data.dtype), slots, p_lr)
            p._data = new_p
            self._slots[id(p)] = new_slots
        self._step_count += 1

    def _regularized_grad_arr(self, p, g_arr):
        reg = getattr(p, "regularizer", None)
        if reg is None:
            reg = self.regularization
        if reg is None:
            return g_arr
        return reg._apply_arr(p._data, g_arr)

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        from ..static import _active_program

        prog = _active_program()
        if prog is not None:
            # static capture: Executor.run performs the jitted train step
            prog._minimize = (self, loss)
            return None, None
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None

    @framework.no_grad()
    def clear_grad(self, set_to_zero=False):
        if self._parameter_list is None:
            return
        for p in self._parameter_list:
            p.clear_gradient(set_to_zero)

    clear_gradients = clear_grad

    # -- state dict --------------------------------------------------------
    def state_dict(self):
        state = {}
        if self._parameter_list is not None:
            for p in self._parameter_list:
                slots = self._slots.get(id(p))
                if not slots:
                    continue
                for slot_name, arr in slots.items():
                    state[f"{p.name}_{slot_name}"] = Tensor(jnp.asarray(arr))
        if isinstance(self._learning_rate, LRScheduler):
            state["LR_Scheduler"] = self._learning_rate.state_dict()
        state["@step"] = self._step_count
        return state

    def set_state_dict(self, state_dict):
        if "LR_Scheduler" in state_dict and isinstance(self._learning_rate, LRScheduler):
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])
        self._step_count = int(state_dict.get("@step", 0))
        if self._parameter_list is None:
            return
        for p in self._parameter_list:
            slots = self._slots.get(id(p))
            if slots is None:
                slots = self._init_slots(p._data)
            for slot_name in list(slots.keys()):
                key = f"{p.name}_{slot_name}"
                if key in state_dict:
                    v = state_dict[key]
                    slots[slot_name] = v._data if isinstance(v, Tensor) else jnp.asarray(v)
            self._slots[id(p)] = slots

    # -- functional step (jit path) ----------------------------------------
    def functional_state(self, named_params, shard_spec=None):
        """Initial slot pytree for a dict of name->array.

        ``shard_spec`` (ZeRO stage>=2, distributed/collectives/zero.py):
        ``{param_name: padded_flat_len}`` — param-shaped slots for those
        names are created FLATTENED and zero-padded to ``padded_flat_len``
        so the dp-sharded weight update can own a contiguous 1/degree
        chunk per rank (the flat global array then shards evenly over the
        data axis). Scalar slots (beta-power accumulators) are left
        untouched; value-seeded slots (master weights) flatten their
        seeded bytes, so the shard layout never changes slot VALUES."""
        state = {}
        for name, arr in named_params.items():
            slots = self._init_slots(arr)
            padded = (shard_spec or {}).get(name)
            if padded:
                pshape = tuple(arr.shape)

                def _flat(leaf, _p=int(padded), _shape=pshape):
                    if (hasattr(leaf, "shape")
                            and tuple(leaf.shape) == _shape):
                        flat = jnp.ravel(leaf)
                        return jnp.pad(flat, (0, _p - flat.size))
                    return leaf

                slots = {k: _flat(v) for k, v in slots.items()}
            state[name] = slots
        return state

    def slot_nbytes(self, named_params, shard_degree=1, shard_names=None):
        """Total bytes of this optimizer's functional slot state for the
        given name->array (or name->aval) dict — what the memory planner
        charges against the HBM budget for optimizer state. Computed via
        ``eval_shape`` over ``_init_slots``: no arrays are materialized,
        so pricing a flagship config costs nothing. Factored/int8-moment
        variants are priced exactly (their _init_slots shapes differ).

        ``shard_degree`` > 1 prices ZeRO-sharded slots (stage>=1,
        docs/ZERO.md): param-SHAPED slot leaves divide by the sharding
        degree (each rank holds 1/degree of every sharded slot);
        ``shard_names`` restricts the division to those params (None =
        all). Scalar slots replicate and never divide."""
        import jax

        total = 0
        for name, arr in named_params.items():
            shapes = jax.eval_shape(
                self._init_slots,
                jax.ShapeDtypeStruct(tuple(arr.shape), jnp.dtype(arr.dtype)))
            divide = (int(shard_degree) > 1
                      and (shard_names is None or name in shard_names))
            for leaf in jax.tree_util.tree_leaves(shapes):
                n = 1
                for d in leaf.shape:
                    n *= int(d)
                nbytes = n * jnp.dtype(leaf.dtype).itemsize
                if divide and tuple(leaf.shape) == tuple(arr.shape):
                    nbytes = -(-nbytes // int(shard_degree))
                total += nbytes
        return total

    def functional_update(self, params, grads, state, lr):
        """Pure pytree update usable inside jax.jit. Returns (params, state)."""
        new_params, new_state = {}, {}
        for name, p in params.items():
            g = grads.get(name)
            if g is None:
                new_params[name] = p
                new_state[name] = state[name]
                continue
            np_, ns_ = self._update(p, g.astype(p.dtype), state[name], lr)
            new_params[name] = np_
            new_state[name] = ns_
        return new_params, new_state

    def _sync_from_functional(self, named_params, state):
        """Write back functional-step results into eager slots."""
        for name, p in named_params.items():
            self._slots[id(p)] = state[name]
