"""Functional autodiff: jvp/vjp/jacobian/hessian.

Parity: `python/paddle/incubate/autograd/` (jvp/vjp/Jacobian/Hessian).
TPU-native: these delegate straight to jax's transforms over the pure
payload function — no tape involved, arbitrarily composable (hessian is
jacfwd-of-jacrev, exactly how the reference composes them numerically).
"""
from __future__ import annotations

import jax
from jax import tree_util

from ..core.tensor import Tensor


def _unwrap(tree):
    return tree_util.tree_map(
        lambda t: t._data if isinstance(t, Tensor) else t, tree,
        is_leaf=lambda x: isinstance(x, Tensor))


def _wrap(tree):
    return tree_util.tree_map(Tensor, tree)


def _pure(func):
    def fn(*arrays):
        out = func(*_wrap(arrays))
        return _unwrap(out)

    return fn


def jvp(func, xs, v=None):
    """Forward-mode: returns (func(xs), J @ v). xs/v: Tensor or sequence."""
    xs_t = tuple(xs) if isinstance(xs, (list, tuple)) else (xs,)
    if v is None:
        import jax.numpy as jnp

        v_t = tuple(jnp.ones_like(x._data) for x in xs_t)
    else:
        v_t = tuple(_unwrap(tuple(v) if isinstance(v, (list, tuple)) else (v,)))
    out, tangent = jax.jvp(_pure(func), tuple(_unwrap(xs_t)), v_t)
    return _wrap(out), _wrap(tangent)


def vjp(func, xs, v=None):
    """Reverse-mode: returns (func(xs), vT @ J)."""
    xs_t = tuple(xs) if isinstance(xs, (list, tuple)) else (xs,)
    out, pullback = jax.vjp(_pure(func), *_unwrap(xs_t))
    if v is None:
        import jax.numpy as jnp

        v_arr = tree_util.tree_map(jnp.ones_like, out)
    else:
        v_arr = _unwrap(v)
    grads = pullback(v_arr)
    if len(xs_t) == 1:
        return _wrap(out), _wrap(grads[0])
    return _wrap(out), _wrap(list(grads))


class Jacobian:
    """Lazy full jacobian (parity: incubate/autograd Jacobian)."""

    def __init__(self, func, xs, is_batched=False):
        xs_t = tuple(xs) if isinstance(xs, (list, tuple)) else (xs,)
        arrays = tuple(_unwrap(xs_t))
        jac_fn = jax.jacrev(_pure(func), argnums=tuple(range(len(arrays))))
        self._jac = jac_fn(*arrays)
        self._single = len(arrays) == 1

    def __getitem__(self, idx):
        j = self._jac[0] if self._single else self._jac
        return _wrap(j)[idx] if not isinstance(j, tuple) else _wrap(j[idx])

    @property
    def shape(self):
        j = self._jac[0] if self._single else self._jac[0]
        return j.shape

    def numpy(self):
        import numpy as np

        j = self._jac[0] if self._single else self._jac
        return np.asarray(j)


class Hessian:
    """Lazy hessian of a scalar function (jacfwd over jacrev)."""

    def __init__(self, func, xs, is_batched=False):
        xs_t = tuple(xs) if isinstance(xs, (list, tuple)) else (xs,)
        arrays = tuple(_unwrap(xs_t))
        h_fn = jax.hessian(_pure(func))
        self._h = h_fn(*arrays)

    def __getitem__(self, idx):
        return _wrap(self._h)[idx] if not isinstance(self._h, tuple) else _wrap(self._h[0])[idx]

    def numpy(self):
        import numpy as np

        h = self._h[0] if isinstance(self._h, tuple) else self._h
        return np.asarray(h)


def jacobian(func, xs, create_graph=False, allow_unused=False):
    return Jacobian(func, xs)


def hessian(func, xs, create_graph=False, allow_unused=False):
    return Hessian(func, xs)
