"""User-facing autograd API (parity: python/paddle/autograd + paddle.grad).

``backward``/``grad`` drive the tape engine (core/autograd_engine.py);
``PyLayer`` lets users define custom forward/backward pairs recorded on the
same tape (reference: ``paddle/fluid/eager/pylayer/``).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import GradNode, apply_op
from ..core import autograd_engine
from ..framework import no_grad, enable_grad, set_grad_enabled, is_grad_enabled  # noqa: F401


def backward(tensors, grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward"""
    if isinstance(tensors, Tensor):
        tensors = [tensors]
    autograd_engine.run_backward(
        list(tensors), grad_tensors, retain_graph=retain_graph
    )


def grad(
    outputs,
    inputs,
    grad_outputs=None,
    retain_graph=None,
    create_graph=False,
    only_inputs=True,
    allow_unused=False,
    no_grad_vars=None,
    name=None,
):
    """paddle.grad — gradients of outputs w.r.t. inputs (GeneralGrad analogue)."""
    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    if retain_graph is None:
        retain_graph = create_graph
    return autograd_engine.run_backward(
        list(outputs),
        grad_outputs,
        retain_graph=retain_graph,
        create_graph=create_graph,
        inputs=list(inputs),
        allow_unused=allow_unused,
    )


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = tuple(tensors)

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensor_list(self):
        return list(self._saved)


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    """Custom op with user-defined forward and backward.

    class Exp(PyLayer):
        @staticmethod
        def forward(ctx, x):
            y = paddle.exp(x)
            ctx.save_for_backward(y)
            return y

        @staticmethod
        def backward(ctx, dy):
            (y,) = ctx.saved_tensor
            return dy * y
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *args):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from .. import framework

        ctx = PyLayerContext()
        with no_grad():
            outputs = cls.forward(ctx, *args, **kwargs)

        if not framework.is_grad_enabled():
            return outputs

        single = isinstance(outputs, Tensor)
        out_list = [outputs] if single else [o for o in outputs if isinstance(o, Tensor)]

        in_tensors = [
            a for a in list(args) + list(kwargs.values())
            if isinstance(a, Tensor) and not a.stop_gradient
        ]
        if not in_tensors:
            return outputs

        # Build a GradNode whose backward runs the user's python backward.
        import numpy as np

        edges = []
        for t in in_tensors:
            if t._grad_node is not None:
                edges.append(("node", t._grad_node, t._out_index))
            else:
                edges.append(("leaf", t))
        out_avals = [(tuple(o._data.shape), np.dtype(o._data.dtype)) for o in out_list]
        from jax import tree_util

        _, out_treedef = tree_util.tree_flatten([0] * len(out_list))

        node = _PyLayerGradNode(
            cls, ctx, [t._data for t in in_tensors], in_tensors, edges, out_avals, out_treedef
        )
        for idx, o in enumerate(out_list):
            o.stop_gradient = False
            o._grad_node = node
            o._out_index = idx
        return outputs


class _PyLayerGradNode(GradNode):
    __slots__ = ("cls", "ctx")

    def __init__(self, cls, ctx, in_arrays, in_tensors, edges, out_avals, out_treedef):
        def pure_fn(diff_arrays):  # only used for shape metadata; never vjp'd
            raise RuntimeError("PyLayer backward is user-defined")

        super().__init__(
            f"PyLayer_{cls.__name__}", pure_fn, in_arrays, in_tensors, edges,
            out_avals, out_treedef,
        )
        self.cls = cls
        self.ctx = ctx


def _pylayer_backward(node, cts, create_graph):
    """Engine hook: run the user's backward for PyLayer nodes."""
    ct_tensors = [c if isinstance(c, Tensor) else Tensor(jnp.asarray(c)) for c in cts]
    with set_grad_enabled(create_graph):
        grads = node.cls.backward(node.ctx, *ct_tensors)
    if isinstance(grads, Tensor) or grads is None:
        grads = (grads,)
    out = []
    for g in grads:
        if g is None:
            out.append(None)
        elif create_graph:
            out.append(g)
        else:
            out.append(g._data)
    if len(out) != len(node.edges):
        raise RuntimeError(
            f"PyLayer.backward returned {len(out)} grads for {len(node.edges)} inputs"
        )
    return out


autograd_engine.PYLAYER_BACKWARD = _pylayer_backward


def is_pylayer_node(node):
    return isinstance(node, _PyLayerGradNode)


class saved_tensors_hooks:
    """API-compat stub: registers pack/unpack hooks for saved tensors.

    On TPU the eager tape stores device arrays; offloading hooks are a no-op
    unless the user supplies host-offload functions.
    """

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

from .functional import (  # noqa: E402,F401
    Hessian, Jacobian, hessian, jacobian, jvp, vjp)
