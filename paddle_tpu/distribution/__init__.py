"""paddle.distribution (parity: python/paddle/distribution — ~24 dists).

Core distributions with sample/log_prob/entropy/kl over jax.random; the
remaining long tail follows the same pattern and lands incrementally.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from .. import framework
from ..core.tensor import Tensor
from ..core.dispatch import apply_op


def _arr(x):
    if isinstance(x, Tensor):
        return x._data
    return jnp.asarray(x, jnp.float32)


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return list(self._batch_shape)

    @property
    def event_shape(self):
        return list(self._event_shape)

    def sample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return self.log_prob(value).exp()

    def entropy(self):
        raise NotImplementedError

    def rsample(self, shape=()):
        return self.sample(shape)

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = Tensor(_arr(loc))
        self.scale = Tensor(_arr(scale))
        super().__init__(tuple(self.loc.shape))

    def sample(self, shape=(), seed=0):
        key = jax.random.PRNGKey(seed) if seed else framework.next_rng_key()
        shp = tuple(shape) + tuple(self.loc._data.shape)
        z = jax.random.normal(key, shp, jnp.float32)
        return Tensor(self.loc._data + self.scale._data * z)

    rsample = sample

    def log_prob(self, value):
        return apply_op(
            lambda v, m, s: -((v - m) ** 2) / (2 * s**2) - jnp.log(s) - 0.5 * math.log(2 * math.pi),
            value, self.loc, self.scale,
        )

    def entropy(self):
        return apply_op(
            lambda s: 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s), self.scale
        )

    def cdf(self, value):
        return apply_op(
            lambda v, m, s: 0.5 * (1 + jax.scipy.special.erf((v - m) / (s * math.sqrt(2)))),
            value, self.loc, self.scale,
        )


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = Tensor(_arr(low))
        self.high = Tensor(_arr(high))
        super().__init__(tuple(self.low.shape))

    def sample(self, shape=(), seed=0):
        key = jax.random.PRNGKey(seed) if seed else framework.next_rng_key()
        shp = tuple(shape) + tuple(self.low._data.shape)
        u = jax.random.uniform(key, shp, jnp.float32)
        return Tensor(self.low._data + (self.high._data - self.low._data) * u)

    def log_prob(self, value):
        return apply_op(
            lambda v, lo, hi: jnp.where(
                (v >= lo) & (v < hi), -jnp.log(hi - lo), -jnp.inf
            ),
            value, self.low, self.high,
        )

    def entropy(self):
        return apply_op(lambda lo, hi: jnp.log(hi - lo), self.low, self.high)


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = Tensor(_arr(logits))
        super().__init__(tuple(self.logits.shape[:-1]))

    def sample(self, shape=()):
        key = framework.next_rng_key()
        out = jax.random.categorical(key, self.logits._data, shape=tuple(shape) + tuple(self.logits._data.shape[:-1]))
        return Tensor(out.astype(np.int64))

    def log_prob(self, value):
        return apply_op(
            lambda l, v: jnp.take_along_axis(
                jax.nn.log_softmax(l, -1), v[..., None].astype(jnp.int32), -1
            )[..., 0],
            self.logits, value,
        )

    def probs(self, value=None):
        p = apply_op(lambda l: jax.nn.softmax(l, -1), self.logits)
        if value is None:
            return p
        return apply_op(
            lambda pr, v: jnp.take_along_axis(pr, v[..., None].astype(jnp.int32), -1)[..., 0],
            p, value,
        )

    def entropy(self):
        return apply_op(
            lambda l: -jnp.sum(jax.nn.softmax(l, -1) * jax.nn.log_softmax(l, -1), -1),
            self.logits,
        )


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_t = Tensor(_arr(probs))
        super().__init__(tuple(self.probs_t.shape))

    def sample(self, shape=()):
        key = framework.next_rng_key()
        shp = tuple(shape) + tuple(self.probs_t._data.shape)
        return Tensor(jax.random.bernoulli(key, self.probs_t._data, shp).astype(jnp.float32))

    def log_prob(self, value):
        return apply_op(
            lambda p, v: v * jnp.log(jnp.maximum(p, 1e-12)) + (1 - v) * jnp.log(jnp.maximum(1 - p, 1e-12)),
            self.probs_t, value,
        )

    def entropy(self):
        return apply_op(
            lambda p: -(p * jnp.log(jnp.maximum(p, 1e-12)) + (1 - p) * jnp.log(jnp.maximum(1 - p, 1e-12))),
            self.probs_t,
        )


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = Tensor(_arr(rate))
        super().__init__(tuple(self.rate.shape))

    def sample(self, shape=()):
        key = framework.next_rng_key()
        shp = tuple(shape) + tuple(self.rate._data.shape)
        return Tensor(jax.random.exponential(key, shp, jnp.float32) / self.rate._data)

    def log_prob(self, value):
        return apply_op(lambda r, v: jnp.log(r) - r * v, self.rate, value)

    def entropy(self):
        return apply_op(lambda r: 1.0 - jnp.log(r), self.rate)


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = Tensor(_arr(alpha))
        self.beta = Tensor(_arr(beta))
        super().__init__(tuple(self.alpha.shape))

    def sample(self, shape=()):
        key = framework.next_rng_key()
        shp = tuple(shape) + tuple(self.alpha._data.shape)
        return Tensor(jax.random.beta(key, self.alpha._data, self.beta._data, shp))

    def log_prob(self, value):
        def _lp(a, b, v):
            lbeta = (
                jax.scipy.special.gammaln(a)
                + jax.scipy.special.gammaln(b)
                - jax.scipy.special.gammaln(a + b)
            )
            return (a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v) - lbeta

        return apply_op(_lp, self.alpha, self.beta, value)


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = Tensor(_arr(concentration))
        self.rate = Tensor(_arr(rate))
        super().__init__(tuple(self.concentration.shape))

    def sample(self, shape=()):
        key = framework.next_rng_key()
        shp = tuple(shape) + tuple(self.concentration._data.shape)
        return Tensor(jax.random.gamma(key, self.concentration._data, shp) / self.rate._data)

    def log_prob(self, value):
        def _lp(a, r, v):
            return a * jnp.log(r) + (a - 1) * jnp.log(v) - r * v - jax.scipy.special.gammaln(a)

        return apply_op(_lp, self.concentration, self.rate, value)


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = total_count
        self.probs_t = Tensor(_arr(probs))
        super().__init__(tuple(self.probs_t.shape[:-1]), tuple(self.probs_t.shape[-1:]))

    def sample(self, shape=()):
        key = framework.next_rng_key()
        logits = jnp.log(jnp.maximum(self.probs_t._data, 1e-30))
        draws = jax.random.categorical(
            key, logits, shape=tuple(shape) + (self.total_count,) + tuple(logits.shape[:-1])
        )
        k = logits.shape[-1]
        onehot = jax.nn.one_hot(draws, k)
        axis = len(tuple(shape))
        return Tensor(jnp.sum(onehot, axis=axis))


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = Tensor(_arr(loc))
        self.scale = Tensor(_arr(scale))
        super().__init__(tuple(self.loc.shape))

    def sample(self, shape=()):
        key = framework.next_rng_key()
        shp = tuple(shape) + tuple(self.loc._data.shape)
        return Tensor(self.loc._data + self.scale._data * jax.random.laplace(key, shp))

    def log_prob(self, value):
        return apply_op(
            lambda m, s, v: -jnp.abs(v - m) / s - jnp.log(2 * s),
            self.loc, self.scale, value,
        )

    def entropy(self):
        return apply_op(lambda s: 1 + jnp.log(2 * s), self.scale)


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = Tensor(_arr(loc))
        self.scale = Tensor(_arr(scale))
        super().__init__(tuple(self.loc.shape))

    def sample(self, shape=()):
        key = framework.next_rng_key()
        shp = tuple(shape) + tuple(self.loc._data.shape)
        return Tensor(self.loc._data + self.scale._data * jax.random.gumbel(key, shp))

    def log_prob(self, value):
        def _lp(m, s, v):
            z = (v - m) / s
            return -(z + jnp.exp(-z)) - jnp.log(s)

        return apply_op(_lp, self.loc, self.scale, value)


# KL registry (parity: distribution/kl.py)
_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn

    return deco


def kl_divergence(p, q):
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is None:
        raise NotImplementedError(f"KL({type(p).__name__} || {type(q).__name__})")
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    return apply_op(
        lambda m1, s1, m2, s2: jnp.log(s2 / s1) + (s1**2 + (m1 - m2) ** 2) / (2 * s2**2) - 0.5,
        p.loc, p.scale, q.loc, q.scale,
    )


@register_kl(Categorical, Categorical)
def _kl_cat_cat(p, q):
    return apply_op(
        lambda lp, lq: jnp.sum(
            jax.nn.softmax(lp, -1) * (jax.nn.log_softmax(lp, -1) - jax.nn.log_softmax(lq, -1)), -1
        ),
        p.logits, q.logits,
    )


@register_kl(Uniform, Uniform)
def _kl_unif_unif(p, q):
    return apply_op(
        lambda al, ah, bl, bh: jnp.where(
            (bl <= al) & (ah <= bh), jnp.log((bh - bl) / (ah - al)), jnp.inf
        ),
        p.low, p.high, q.low, q.high,
    )


# ---------------------------------------------------------------------------
# Long-tail distributions (parity: python/paddle/distribution/* modules)
# ---------------------------------------------------------------------------
class ExponentialFamily(Distribution):
    """Base for exponential-family dists (paddle ExponentialFamily)."""


class Cauchy(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = Tensor(_arr(loc))
        self.scale = Tensor(_arr(scale))
        super().__init__(tuple(self.loc.shape))

    def sample(self, shape=()):
        key = framework.next_rng_key()
        shp = tuple(shape) + tuple(self.loc._data.shape)
        return Tensor(self.loc._data + self.scale._data * jax.random.cauchy(key, shp))

    def log_prob(self, value):
        return apply_op(
            lambda m, s, v: -jnp.log(math.pi * s * (1 + ((v - m) / s) ** 2)),
            self.loc, self.scale, value,
        )

    def entropy(self):
        return apply_op(lambda s: jnp.log(4 * math.pi * s), self.scale)

    def cdf(self, value):
        return apply_op(
            lambda m, s, v: jnp.arctan((v - m) / s) / math.pi + 0.5,
            self.loc, self.scale, value,
        )


class Chi2(Distribution):
    def __init__(self, df, name=None):
        self.df = Tensor(_arr(df))
        super().__init__(tuple(self.df.shape))

    def sample(self, shape=()):
        key = framework.next_rng_key()
        shp = tuple(shape) + tuple(self.df._data.shape)
        return Tensor(2.0 * jax.random.gamma(key, self.df._data / 2.0, shp))

    def log_prob(self, value):
        def _lp(k, v):
            h = k / 2.0
            return (h - 1) * jnp.log(v) - v / 2.0 - jax.scipy.special.gammaln(h) - h * math.log(2.0)

        return apply_op(_lp, self.df, value)


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = Tensor(_arr(concentration))
        shp = tuple(self.concentration.shape)
        super().__init__(shp[:-1], shp[-1:])

    def sample(self, shape=()):
        key = framework.next_rng_key()
        out = jax.random.dirichlet(
            key, self.concentration._data,
            tuple(shape) + tuple(self.concentration._data.shape[:-1]))
        return Tensor(out)

    def log_prob(self, value):
        def _lp(a, v):
            return (
                jnp.sum((a - 1) * jnp.log(v), -1)
                + jax.scipy.special.gammaln(jnp.sum(a, -1))
                - jnp.sum(jax.scipy.special.gammaln(a), -1)
            )

        return apply_op(_lp, self.concentration, value)

    def entropy(self):
        def _ent(a):
            a0 = jnp.sum(a, -1)
            k = a.shape[-1]
            return (
                jnp.sum(jax.scipy.special.gammaln(a), -1)
                - jax.scipy.special.gammaln(a0)
                + (a0 - k) * jax.scipy.special.digamma(a0)
                - jnp.sum((a - 1) * jax.scipy.special.digamma(a), -1)
            )

        return apply_op(_ent, self.concentration)


class ContinuousBernoulli(Distribution):
    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self.probs = Tensor(_arr(probs))
        self._lims = lims
        super().__init__(tuple(self.probs.shape))

    def _log_norm(self, lam):
        # log C(lambda); near 0.5 use the taylor-stable limit log(2)
        safe = jnp.where(jnp.abs(lam - 0.5) < (self._lims[1] - 0.5), 0.4, lam)
        c = jnp.log(jnp.abs(2.0 * jnp.arctanh(1.0 - 2.0 * safe))) - jnp.log(
            jnp.abs(1.0 - 2.0 * safe))
        return jnp.where(jnp.abs(lam - 0.5) < (self._lims[1] - 0.5),
                         jnp.log(2.0), c)

    def log_prob(self, value):
        def _lp(p, v):
            return (v * jnp.log(p) + (1 - v) * jnp.log1p(-p)
                    + self._log_norm(p))

        return apply_op(_lp, self.probs, value)

    def sample(self, shape=()):
        key = framework.next_rng_key()
        shp = tuple(shape) + tuple(self.probs._data.shape)
        u = jax.random.uniform(key, shp)
        lam = self.probs._data
        # inverse cdf; the lambda == 0.5 limit is u itself
        safe = jnp.where(jnp.abs(lam - 0.5) < 1e-3, 0.4, lam)
        x = (jnp.log1p(u * (2 * safe - 1) / (1 - safe))
             / (jnp.log(safe) - jnp.log1p(-safe)))
        return Tensor(jnp.where(jnp.abs(lam - 0.5) < 1e-3, u, x))


class Geometric(Distribution):
    """P(X=k) = (1-p)^k p, k = 0, 1, ... (paddle geometric.py)."""

    def __init__(self, probs, name=None):
        self.probs = Tensor(_arr(probs))
        super().__init__(tuple(self.probs.shape))

    def sample(self, shape=()):
        key = framework.next_rng_key()
        shp = tuple(shape) + tuple(self.probs._data.shape)
        u = jax.random.uniform(key, shp, minval=1e-7, maxval=1.0)
        return Tensor(jnp.floor(jnp.log(u) / jnp.log1p(-self.probs._data)))

    def log_prob(self, value):
        return apply_op(
            lambda p, v: v * jnp.log1p(-p) + jnp.log(p), self.probs, value
        )

    def entropy(self):
        return apply_op(
            lambda p: (-(1 - p) * jnp.log1p(-p) - p * jnp.log(p)) / p,
            self.probs,
        )

    @property
    def mean(self):
        return apply_op(lambda p: (1 - p) / p, self.probs)


class Binomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = Tensor(jnp.asarray(_arr(total_count)))
        self.probs = Tensor(_arr(probs))
        super().__init__(tuple(self.probs.shape))

    def sample(self, shape=()):
        key = framework.next_rng_key()
        n = jnp.broadcast_to(self.total_count._data, self.probs._data.shape)
        shp = tuple(shape) + tuple(self.probs._data.shape)
        out = jax.random.binomial(key, n.astype(jnp.float32),
                                  self.probs._data, shape=shp)
        return Tensor(out)

    def log_prob(self, value):
        def _lp(n, p, v):
            n = n.astype(jnp.float32)
            comb = (jax.scipy.special.gammaln(n + 1)
                    - jax.scipy.special.gammaln(v + 1)
                    - jax.scipy.special.gammaln(n - v + 1))
            return comb + v * jnp.log(p) + (n - v) * jnp.log1p(-p)

        return apply_op(_lp, self.total_count, self.probs, value)


class Poisson(Distribution):
    def __init__(self, rate, name=None):
        self.rate = Tensor(_arr(rate))
        super().__init__(tuple(self.rate.shape))

    def sample(self, shape=()):
        key = framework.next_rng_key()
        shp = tuple(shape) + tuple(self.rate._data.shape)
        return Tensor(jax.random.poisson(key, self.rate._data, shp).astype(jnp.float32))

    def log_prob(self, value):
        return apply_op(
            lambda r, v: v * jnp.log(r) - r - jax.scipy.special.gammaln(v + 1),
            self.rate, value,
        )

    @property
    def mean(self):
        return self.rate


class StudentT(Distribution):
    def __init__(self, df, loc, scale, name=None):
        self.df = Tensor(_arr(df))
        self.loc = Tensor(_arr(loc))
        self.scale = Tensor(_arr(scale))
        super().__init__(tuple(jnp.broadcast_shapes(
            self.df._data.shape, self.loc._data.shape, self.scale._data.shape)))

    def sample(self, shape=()):
        key = framework.next_rng_key()
        shp = tuple(shape) + tuple(self._batch_shape)
        t = jax.random.t(key, self.df._data, shp)
        return Tensor(self.loc._data + self.scale._data * t)

    def log_prob(self, value):
        def _lp(df, m, s, v):
            z = (v - m) / s
            return (jax.scipy.special.gammaln((df + 1) / 2)
                    - jax.scipy.special.gammaln(df / 2)
                    - 0.5 * jnp.log(df * math.pi) - jnp.log(s)
                    - (df + 1) / 2 * jnp.log1p(z * z / df))

        return apply_op(_lp, self.df, self.loc, self.scale, value)


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = Tensor(_arr(loc))
        self.scale = Tensor(_arr(scale))
        self._base = Normal(loc, scale)
        super().__init__(tuple(self.loc.shape))

    def sample(self, shape=()):
        return self._base.sample(shape).exp()

    def log_prob(self, value):
        def _lp(m, s, v):
            lv = jnp.log(v)
            return (-((lv - m) ** 2) / (2 * s**2) - jnp.log(s)
                    - 0.5 * math.log(2 * math.pi) - lv)

        return apply_op(_lp, self.loc, self.scale, value)

    def entropy(self):
        return apply_op(
            lambda m, s: m + 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s),
            self.loc, self.scale,
        )


class MultivariateNormal(Distribution):
    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None, name=None):
        self.loc = Tensor(_arr(loc))
        if covariance_matrix is not None:
            cov = _arr(covariance_matrix)
        elif scale_tril is not None:
            st = _arr(scale_tril)
            cov = st @ jnp.swapaxes(st, -1, -2)
        elif precision_matrix is not None:
            cov = jnp.linalg.inv(_arr(precision_matrix))
        else:
            raise ValueError("need covariance_matrix/precision_matrix/scale_tril")
        self.covariance_matrix = Tensor(cov)
        self._chol = jnp.linalg.cholesky(cov)
        super().__init__(tuple(self.loc.shape[:-1]), tuple(self.loc.shape[-1:]))

    def sample(self, shape=()):
        key = framework.next_rng_key()
        shp = tuple(shape) + tuple(self.loc._data.shape)
        z = jax.random.normal(key, shp)
        return Tensor(self.loc._data + jnp.einsum("...ij,...j->...i", self._chol, z))

    def log_prob(self, value):
        chol = self._chol

        def _lp(m, v):
            d = m.shape[-1]
            diff = v - m
            sol = jax.scipy.linalg.solve_triangular(chol, diff[..., None],
                                                    lower=True)[..., 0]
            logdet = jnp.sum(jnp.log(jnp.diagonal(chol, axis1=-2, axis2=-1)), -1)
            return (-0.5 * jnp.sum(sol * sol, -1) - logdet
                    - 0.5 * d * math.log(2 * math.pi))

        return apply_op(_lp, self.loc, value)

    def entropy(self):
        chol = self._chol

        def _ent(m):
            d = m.shape[-1]
            logdet = jnp.sum(jnp.log(jnp.diagonal(chol, axis1=-2, axis2=-1)), -1)
            return 0.5 * d * (1 + math.log(2 * math.pi)) + logdet

        return apply_op(_ent, self.loc)


class Independent(Distribution):
    """Reinterpret batch dims as event dims (paddle Independent)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self._rank = reinterpreted_batch_rank
        bs = tuple(base.batch_shape)
        super().__init__(bs[:len(bs) - reinterpreted_batch_rank],
                         bs[len(bs) - reinterpreted_batch_rank:])

    def sample(self, shape=()):
        return self.base.sample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)
        return lp.sum(axis=tuple(range(-self._rank, 0)))

    def entropy(self):
        ent = self.base.entropy()
        return ent.sum(axis=tuple(range(-self._rank, 0)))


class TransformedDistribution(Distribution):
    """base pushed through a chain of transforms (paddle
    TransformedDistribution)."""

    def __init__(self, base, transforms):
        self.base = base
        self.transforms = list(transforms)
        super().__init__(tuple(base.batch_shape), tuple(base.event_shape))

    def sample(self, shape=()):
        x = self.base.sample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    rsample = sample

    def log_prob(self, value):
        lp = 0.0
        y = value
        for t in reversed(self.transforms):
            x = t.inverse(y)
            lp = lp - t.forward_log_det_jacobian(x)
            y = x
        return self.base.log_prob(y) + lp


@register_kl(Bernoulli, Bernoulli)
def _kl_bern_bern(p, q):
    return apply_op(
        lambda a, b: a * (jnp.log(a) - jnp.log(b))
        + (1 - a) * (jnp.log1p(-a) - jnp.log1p(-b)),
        p.probs_t, q.probs_t,
    )


@register_kl(Exponential, Exponential)
def _kl_exp_exp(p, q):
    return apply_op(
        lambda rp, rq: jnp.log(rp) - jnp.log(rq) + rq / rp - 1.0,
        p.rate, q.rate,
    )


@register_kl(Laplace, Laplace)
def _kl_laplace_laplace(p, q):
    return apply_op(
        lambda mp_, sp, mq, sq: (
            jnp.log(sq / sp)
            + jnp.abs(mp_ - mq) / sq
            + sp / sq * jnp.exp(-jnp.abs(mp_ - mq) / sp)
            - 1
        ),
        p.loc, p.scale, q.loc, q.scale,
    )


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    def _kl(a1, b1, a2, b2):
        g = jax.scipy.special.gammaln
        dg = jax.scipy.special.digamma
        t = g(a2) + g(b2) - g(a2 + b2) - (g(a1) + g(b1) - g(a1 + b1))
        return (t + (a1 - a2) * dg(a1) + (b1 - b2) * dg(b1)
                + (a2 - a1 + b2 - b1) * dg(a1 + b1))

    return apply_op(_kl, p.alpha, p.beta, q.alpha, q.beta)


@register_kl(Gamma, Gamma)
def _kl_gamma_gamma(p, q):
    def _kl(c1, r1, c2, r2):
        g = jax.scipy.special.gammaln
        dg = jax.scipy.special.digamma
        return ((c1 - c2) * dg(c1) - g(c1) + g(c2)
                + c2 * (jnp.log(r1) - jnp.log(r2)) + c1 * (r2 / r1 - 1.0))

    return apply_op(_kl, p.concentration, p.rate, q.concentration, q.rate)


@register_kl(Dirichlet, Dirichlet)
def _kl_dir_dir(p, q):
    def _kl(a, b):
        g = jax.scipy.special.gammaln
        dg = jax.scipy.special.digamma
        a0 = jnp.sum(a, -1)
        return (g(a0) - jnp.sum(g(a), -1) - g(jnp.sum(b, -1))
                + jnp.sum(g(b), -1)
                + jnp.sum((a - b) * (dg(a) - dg(a0)[..., None]), -1))

    return apply_op(_kl, p.concentration, q.concentration)


from . import transform  # noqa: E402,F401
from .transform import (  # noqa: E402,F401
    AbsTransform, AffineTransform, ChainTransform, ExpTransform,
    IndependentTransform, PowerTransform, ReshapeTransform,
    SigmoidTransform, SoftmaxTransform, StackTransform,
    StickBreakingTransform, TanhTransform, Transform,
)


class LKJCholesky(Distribution):
    """LKJ prior over correlation-matrix Cholesky factors
    (distribution/lkj_cholesky.py parity; onion-method sampling)."""

    def __init__(self, dim=2, concentration=1.0,
                 sample_method="onion", name=None):
        self.dim = dim
        self.concentration = Tensor(_arr(concentration))
        super().__init__(tuple(self.concentration.shape), (dim, dim))

    def sample(self, shape=()):
        key = framework.next_rng_key()
        d = self.dim
        eta = float(np.asarray(self.concentration._data).reshape(-1)[0])
        # onion method
        keys = jax.random.split(key, d)
        l = jnp.zeros(tuple(shape) + (d, d))
        l = l.at[..., 0, 0].set(1.0)
        for i in range(1, d):
            beta = jax.random.beta(
                keys[i], eta + (d - 1 - i) / 2.0, (i + 1) / 2.0,
                tuple(shape))
            u = jax.random.normal(keys[i], tuple(shape) + (i,))
            u = u / jnp.linalg.norm(u, axis=-1, keepdims=True)
            w = jnp.sqrt(beta)[..., None] * u
            l = l.at[..., i, :i].set(w)
            l = l.at[..., i, i].set(jnp.sqrt(1 - beta))
        return Tensor(l)

    def log_prob(self, value):
        def _lp(conc, l):
            d = self.dim
            diag = jnp.diagonal(l, axis1=-2, axis2=-1)[..., 1:]
            powers = jnp.asarray([d - 2 - 2.0 * i for i in range(d - 1)])
            unnorm = jnp.sum((2 * conc - 2 + powers) * jnp.log(diag), -1)
            # normalisation constant (Stan reference form)
            g = jax.scipy.special.gammaln
            order = jnp.arange(1, d)
            t1 = jnp.sum((2 * (conc - 1 + order) - order)
                         * jnp.log(jnp.asarray(2.0)))
            t2 = jnp.sum(2 * (g(conc + (d - 1 - order) / 2)
                              - g(conc + (d - 1) / 2 - order / 2)))
            return unnorm  # unnormalised density (matches rel. comparisons)

        return apply_op(_lp, self.concentration, value, _op_name="lkj_lp")
