"""paddle.distribution (parity: python/paddle/distribution — ~24 dists).

Core distributions with sample/log_prob/entropy/kl over jax.random; the
remaining long tail follows the same pattern and lands incrementally.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from .. import framework
from ..core.tensor import Tensor
from ..core.dispatch import apply_op


def _arr(x):
    if isinstance(x, Tensor):
        return x._data
    return jnp.asarray(x, jnp.float32)


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return list(self._batch_shape)

    @property
    def event_shape(self):
        return list(self._event_shape)

    def sample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return self.log_prob(value).exp()

    def entropy(self):
        raise NotImplementedError

    def rsample(self, shape=()):
        return self.sample(shape)

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = Tensor(_arr(loc))
        self.scale = Tensor(_arr(scale))
        super().__init__(tuple(self.loc.shape))

    def sample(self, shape=(), seed=0):
        key = jax.random.PRNGKey(seed) if seed else framework.next_rng_key()
        shp = tuple(shape) + tuple(self.loc._data.shape)
        z = jax.random.normal(key, shp, jnp.float32)
        return Tensor(self.loc._data + self.scale._data * z)

    rsample = sample

    def log_prob(self, value):
        return apply_op(
            lambda v, m, s: -((v - m) ** 2) / (2 * s**2) - jnp.log(s) - 0.5 * math.log(2 * math.pi),
            value, self.loc, self.scale,
        )

    def entropy(self):
        return apply_op(
            lambda s: 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s), self.scale
        )

    def cdf(self, value):
        return apply_op(
            lambda v, m, s: 0.5 * (1 + jax.scipy.special.erf((v - m) / (s * math.sqrt(2)))),
            value, self.loc, self.scale,
        )


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = Tensor(_arr(low))
        self.high = Tensor(_arr(high))
        super().__init__(tuple(self.low.shape))

    def sample(self, shape=(), seed=0):
        key = jax.random.PRNGKey(seed) if seed else framework.next_rng_key()
        shp = tuple(shape) + tuple(self.low._data.shape)
        u = jax.random.uniform(key, shp, jnp.float32)
        return Tensor(self.low._data + (self.high._data - self.low._data) * u)

    def log_prob(self, value):
        return apply_op(
            lambda v, lo, hi: jnp.where(
                (v >= lo) & (v < hi), -jnp.log(hi - lo), -jnp.inf
            ),
            value, self.low, self.high,
        )

    def entropy(self):
        return apply_op(lambda lo, hi: jnp.log(hi - lo), self.low, self.high)


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = Tensor(_arr(logits))
        super().__init__(tuple(self.logits.shape[:-1]))

    def sample(self, shape=()):
        key = framework.next_rng_key()
        out = jax.random.categorical(key, self.logits._data, shape=tuple(shape) + tuple(self.logits._data.shape[:-1]))
        return Tensor(out.astype(np.int64))

    def log_prob(self, value):
        return apply_op(
            lambda l, v: jnp.take_along_axis(
                jax.nn.log_softmax(l, -1), v[..., None].astype(jnp.int32), -1
            )[..., 0],
            self.logits, value,
        )

    def probs(self, value=None):
        p = apply_op(lambda l: jax.nn.softmax(l, -1), self.logits)
        if value is None:
            return p
        return apply_op(
            lambda pr, v: jnp.take_along_axis(pr, v[..., None].astype(jnp.int32), -1)[..., 0],
            p, value,
        )

    def entropy(self):
        return apply_op(
            lambda l: -jnp.sum(jax.nn.softmax(l, -1) * jax.nn.log_softmax(l, -1), -1),
            self.logits,
        )


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_t = Tensor(_arr(probs))
        super().__init__(tuple(self.probs_t.shape))

    def sample(self, shape=()):
        key = framework.next_rng_key()
        shp = tuple(shape) + tuple(self.probs_t._data.shape)
        return Tensor(jax.random.bernoulli(key, self.probs_t._data, shp).astype(jnp.float32))

    def log_prob(self, value):
        return apply_op(
            lambda p, v: v * jnp.log(jnp.maximum(p, 1e-12)) + (1 - v) * jnp.log(jnp.maximum(1 - p, 1e-12)),
            self.probs_t, value,
        )

    def entropy(self):
        return apply_op(
            lambda p: -(p * jnp.log(jnp.maximum(p, 1e-12)) + (1 - p) * jnp.log(jnp.maximum(1 - p, 1e-12))),
            self.probs_t,
        )


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = Tensor(_arr(rate))
        super().__init__(tuple(self.rate.shape))

    def sample(self, shape=()):
        key = framework.next_rng_key()
        shp = tuple(shape) + tuple(self.rate._data.shape)
        return Tensor(jax.random.exponential(key, shp, jnp.float32) / self.rate._data)

    def log_prob(self, value):
        return apply_op(lambda r, v: jnp.log(r) - r * v, self.rate, value)

    def entropy(self):
        return apply_op(lambda r: 1.0 - jnp.log(r), self.rate)


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = Tensor(_arr(alpha))
        self.beta = Tensor(_arr(beta))
        super().__init__(tuple(self.alpha.shape))

    def sample(self, shape=()):
        key = framework.next_rng_key()
        shp = tuple(shape) + tuple(self.alpha._data.shape)
        return Tensor(jax.random.beta(key, self.alpha._data, self.beta._data, shp))

    def log_prob(self, value):
        def _lp(a, b, v):
            lbeta = (
                jax.scipy.special.gammaln(a)
                + jax.scipy.special.gammaln(b)
                - jax.scipy.special.gammaln(a + b)
            )
            return (a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v) - lbeta

        return apply_op(_lp, self.alpha, self.beta, value)


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = Tensor(_arr(concentration))
        self.rate = Tensor(_arr(rate))
        super().__init__(tuple(self.concentration.shape))

    def sample(self, shape=()):
        key = framework.next_rng_key()
        shp = tuple(shape) + tuple(self.concentration._data.shape)
        return Tensor(jax.random.gamma(key, self.concentration._data, shp) / self.rate._data)

    def log_prob(self, value):
        def _lp(a, r, v):
            return a * jnp.log(r) + (a - 1) * jnp.log(v) - r * v - jax.scipy.special.gammaln(a)

        return apply_op(_lp, self.concentration, self.rate, value)


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = total_count
        self.probs_t = Tensor(_arr(probs))
        super().__init__(tuple(self.probs_t.shape[:-1]), tuple(self.probs_t.shape[-1:]))

    def sample(self, shape=()):
        key = framework.next_rng_key()
        logits = jnp.log(jnp.maximum(self.probs_t._data, 1e-30))
        draws = jax.random.categorical(
            key, logits, shape=tuple(shape) + (self.total_count,) + tuple(logits.shape[:-1])
        )
        k = logits.shape[-1]
        onehot = jax.nn.one_hot(draws, k)
        axis = len(tuple(shape))
        return Tensor(jnp.sum(onehot, axis=axis))


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = Tensor(_arr(loc))
        self.scale = Tensor(_arr(scale))
        super().__init__(tuple(self.loc.shape))

    def sample(self, shape=()):
        key = framework.next_rng_key()
        shp = tuple(shape) + tuple(self.loc._data.shape)
        return Tensor(self.loc._data + self.scale._data * jax.random.laplace(key, shp))

    def log_prob(self, value):
        return apply_op(
            lambda m, s, v: -jnp.abs(v - m) / s - jnp.log(2 * s),
            self.loc, self.scale, value,
        )

    def entropy(self):
        return apply_op(lambda s: 1 + jnp.log(2 * s), self.scale)


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = Tensor(_arr(loc))
        self.scale = Tensor(_arr(scale))
        super().__init__(tuple(self.loc.shape))

    def sample(self, shape=()):
        key = framework.next_rng_key()
        shp = tuple(shape) + tuple(self.loc._data.shape)
        return Tensor(self.loc._data + self.scale._data * jax.random.gumbel(key, shp))

    def log_prob(self, value):
        def _lp(m, s, v):
            z = (v - m) / s
            return -(z + jnp.exp(-z)) - jnp.log(s)

        return apply_op(_lp, self.loc, self.scale, value)


# KL registry (parity: distribution/kl.py)
_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn

    return deco


def kl_divergence(p, q):
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is None:
        raise NotImplementedError(f"KL({type(p).__name__} || {type(q).__name__})")
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    return apply_op(
        lambda m1, s1, m2, s2: jnp.log(s2 / s1) + (s1**2 + (m1 - m2) ** 2) / (2 * s2**2) - 0.5,
        p.loc, p.scale, q.loc, q.scale,
    )


@register_kl(Categorical, Categorical)
def _kl_cat_cat(p, q):
    return apply_op(
        lambda lp, lq: jnp.sum(
            jax.nn.softmax(lp, -1) * (jax.nn.log_softmax(lp, -1) - jax.nn.log_softmax(lq, -1)), -1
        ),
        p.logits, q.logits,
    )


@register_kl(Uniform, Uniform)
def _kl_unif_unif(p, q):
    return apply_op(
        lambda al, ah, bl, bh: jnp.where(
            (bl <= al) & (ah <= bh), jnp.log((bh - bl) / (ah - al)), jnp.inf
        ),
        p.low, p.high, q.low, q.high,
    )
