"""paddle.distribution.transform (parity: python/paddle/distribution/
transform.py): bijectors with forward/inverse/log-det-jacobian."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core.dispatch import apply_op


class Type:
    BIJECTION = "bijection"
    INJECTION = "injection"
    SURJECTION = "surjection"
    OTHER = "other"


class Transform:
    _type = Type.OTHER

    def forward(self, x):
        return apply_op(self._forward, x, _op_name=type(self).__name__)

    def inverse(self, y):
        return apply_op(self._inverse, y, _op_name=type(self).__name__ + "_inv")

    def forward_log_det_jacobian(self, x):
        return apply_op(self._fldj, x, _op_name=type(self).__name__ + "_fldj")

    def inverse_log_det_jacobian(self, y):
        return -self.forward_log_det_jacobian(self.inverse(y))

    def __call__(self, x):
        return self.forward(x)


class AbsTransform(Transform):
    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y

    def _fldj(self, x):
        return jnp.zeros_like(x)


class AffineTransform(Transform):
    _type = Type.BIJECTION

    def __init__(self, loc, scale):
        from ..core.tensor import Tensor

        self.loc = loc._data if isinstance(loc, Tensor) else jnp.asarray(loc)
        self.scale = scale._data if isinstance(scale, Tensor) else jnp.asarray(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _fldj(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class ExpTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        return x


class PowerTransform(Transform):
    _type = Type.BIJECTION

    def __init__(self, power):
        from ..core.tensor import Tensor

        self.power = power._data if isinstance(power, Tensor) else jnp.asarray(power)

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _fldj(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class SigmoidTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _fldj(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _fldj(self, x):
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class SoftmaxTransform(Transform):
    _type = Type.OTHER

    def _forward(self, x):
        return jax.nn.softmax(x, axis=-1)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        raise NotImplementedError("softmax is not a bijection")


class StickBreakingTransform(Transform):
    """R^{K-1} -> K-simplex via stick breaking."""

    _type = Type.BIJECTION

    @staticmethod
    def _offsets(k, dtype):
        return jnp.log(jnp.arange(k, 0, -1).astype(dtype))

    def _forward(self, x):
        z = jax.nn.sigmoid(x - self._offsets(x.shape[-1], x.dtype))
        one = jnp.ones_like(z[..., :1])
        return jnp.concatenate([z, one], -1) * jnp.concatenate(
            [one, jnp.cumprod(1 - z, -1)], -1
        )

    def _inverse(self, y):
        y_crop = y[..., :-1]
        sum_prev = jnp.cumsum(y_crop, -1) - y_crop
        z = y_crop / (1 - sum_prev)
        return (jnp.log(z) - jnp.log1p(-z)
                + self._offsets(y_crop.shape[-1], y.dtype))

    def _fldj(self, x):
        z = jax.nn.sigmoid(x - self._offsets(x.shape[-1], x.dtype))
        one = jnp.ones_like(z[..., :1])
        rem_prev = jnp.concatenate(
            [one, jnp.cumprod(1 - z, -1)[..., :-1]], -1)
        return jnp.sum(jnp.log(z) + jnp.log1p(-z) + jnp.log(rem_prev), -1)


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def forward_log_det_jacobian(self, x):
        total = None
        for t in self.transforms:
            j = t.forward_log_det_jacobian(x)
            total = j if total is None else total + j
            x = t.forward(x)
        return total


class IndependentTransform(Transform):
    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self._rank = reinterpreted_batch_rank

    def forward(self, x):
        return self.base.forward(x)

    def inverse(self, y):
        return self.base.inverse(y)

    def forward_log_det_jacobian(self, x):
        j = self.base.forward_log_det_jacobian(x)
        return j.sum(axis=tuple(range(-self._rank, 0)))


class ReshapeTransform(Transform):
    _type = Type.BIJECTION

    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)

    def _forward(self, x):
        batch = x.shape[: x.ndim - len(self.in_event_shape)]
        return x.reshape(tuple(batch) + self.out_event_shape)

    def _inverse(self, y):
        batch = y.shape[: y.ndim - len(self.out_event_shape)]
        return y.reshape(tuple(batch) + self.in_event_shape)

    def _fldj(self, x):
        batch = x.shape[: x.ndim - len(self.in_event_shape)]
        return jnp.zeros(batch, x.dtype)


class StackTransform(Transform):
    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = axis

    def forward(self, x):
        import paddle_tpu as paddle

        parts = paddle.unstack(x, axis=self.axis)
        outs = [t.forward(p) for t, p in zip(self.transforms, parts)]
        return paddle.stack(outs, axis=self.axis)

    def inverse(self, y):
        import paddle_tpu as paddle

        parts = paddle.unstack(y, axis=self.axis)
        outs = [t.inverse(p) for t, p in zip(self.transforms, parts)]
        return paddle.stack(outs, axis=self.axis)
