"""cinn.auto_schedule.cost_model — scheduling cost models. The XLA slot:
costs come from compiled cost analysis (see paddle.cost_model)."""
from ....cost_model import CostModel  # noqa: F401

__all__ = ["CostModel", "CostModelType", "XgbCostModel"]


class CostModelType:
    XGB = "xgb"
    ANALYTIC = "analytic"


class XgbCostModel(CostModel):
    """The reference trains an XGBoost regressor on measured schedules;
    xgboost is not in the TPU image and XLA owns scheduling, so this
    subclass keeps the surface and raises on train()."""

    def train(self, samples, labels):
        raise NotImplementedError(
            "schedule search is XLA's job on TPU; use CostModel."
            "profile_measure for compiled cost estimates")
