"""cinn.compiler — `compile` maps to jax.jit (the TPU graph compiler)."""
__all__ = ["compile"]


def compile(fn=None, *, static_argnums=None, **kwargs):
    import builtins

    import jax

    if isinstance(fn, builtins.str):
        raise NotImplementedError(
            "compiling CINN IR source text is reference-internal; pass a "
            "python callable (compiled via XLA)")
    return jax.jit(fn, static_argnums=static_argnums)
