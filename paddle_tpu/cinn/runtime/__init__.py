"""cinn.runtime — jit-callable module shims over XLA compilation."""
__all__ = ["CinnLowerLevelIrJit", "Module"]


class Module:
    """A compiled-function container (cinn runtime Module analogue)."""

    def __init__(self):
        self._fns = {}

    def add(self, name, fn):
        import jax

        self._fns[name] = jax.jit(fn)
        return self._fns[name]

    def get_function(self, name):
        return self._fns[name]


def CinnLowerLevelIrJit(fn=None, **kwargs):
    import jax

    return jax.jit(fn) if fn is not None else jax.jit
