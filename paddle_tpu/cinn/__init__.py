"""paddle.cinn — the reference's tensor compiler. XLA fills this slot on
TPU (SURVEY: CINN's capability = fused codegen from graphs, which is
exactly what jax.jit/XLA do for every program here)."""
from . import compiler, runtime  # noqa: F401
