"""paddle.version (parity: generated python/paddle/version.py)."""
full_version = "3.0.0-tpu"
major = "3"
minor = "0"
patch = "0"
rc = "0"
istaged = True
commit = "tpu-native"
with_mkl = "OFF"
cuda_version = "False"
cudnn_version = "False"
xpu_version = "False"


def show():
    print(f"full_version: {full_version}")
    print(f"commit: {commit}")


def cuda():
    return False


def cudnn():
    return False


def xpu():
    return False


def nccl():
    return False
