"""paddle.text — text datasets (parity: python/paddle/text/datasets)."""
from __future__ import annotations

import numpy as np

from ..io import Dataset


class Imdb(Dataset):
    """Synthetic-fallback IMDB (reference downloads the corpus; zero-egress
    environments get a deterministic generated stand-in)."""

    def __init__(self, data_file=None, mode="train", cutoff=150):
        rng = np.random.RandomState(0 if mode == "train" else 1)
        n = 256
        self.docs = [rng.randint(1, 5000, (rng.randint(20, 200),)) for _ in range(n)]
        self.labels = rng.randint(0, 2, (n,))

    def __getitem__(self, idx):
        return self.docs[idx], int(self.labels[idx])

    def __len__(self):
        return len(self.docs)


class UCIHousing(Dataset):
    def __init__(self, data_file=None, mode="train"):
        rng = np.random.RandomState(0 if mode == "train" else 1)
        n = 404 if mode == "train" else 102
        self.x = rng.randn(n, 13).astype(np.float32)
        w = rng.randn(13).astype(np.float32)
        self.y = (self.x @ w + 0.1 * rng.randn(n)).astype(np.float32)[:, None]

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]

    def __len__(self):
        return len(self.x)


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """CRF viterbi decode (parity: paddle.text.viterbi_decode)."""
    import jax
    import jax.numpy as jnp

    from ..core.dispatch import apply_op

    def _vd(pot, trans):
        # pot: [B, T, N], trans: [N, N]
        def step(carry, emit):
            score = carry  # [B, N]
            cand = score[:, :, None] + trans[None]
            best = jnp.max(cand, axis=1) + emit
            idx = jnp.argmax(cand, axis=1)
            return best, idx

        init = pot[:, 0]
        scores, idxs = jax.lax.scan(step, init, jnp.swapaxes(pot[:, 1:], 0, 1))
        last = jnp.argmax(scores, axis=-1)

        def back(carry, idx_t):
            tag = carry
            prev = jnp.take_along_axis(idx_t, tag[:, None], 1)[:, 0]
            return prev, prev

        _, path = jax.lax.scan(back, last, idxs, reverse=True)
        path = jnp.concatenate([jnp.swapaxes(path, 0, 1), last[:, None]], 1)
        return jnp.max(scores, -1), path

    return apply_op(_vd, potentials, transition_params, _op_name="viterbi")


class Conll05st(Dataset):
    """Synthetic-fallback SRL dataset (zero-egress stand-in)."""

    def __init__(self, data_file=None, mode="train", **kw):
        rng = np.random.RandomState(0 if mode == "train" else 1)
        n = 128
        self.samples = [
            tuple(rng.randint(0, 100, (rng.randint(5, 30),))
                  for _ in range(8)) + (rng.randint(0, 20, (30,)),)
            for _ in range(n)
        ]

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class Imikolov(Dataset):
    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, **kw):
        rng = np.random.RandomState(0 if mode == "train" else 1)
        self.window = window_size
        self.data = [rng.randint(1, 2000, (window_size,)) for _ in range(512)]

    def __getitem__(self, idx):
        return tuple(self.data[idx])

    def __len__(self):
        return len(self.data)


class Movielens(Dataset):
    def __init__(self, data_file=None, mode="train", **kw):
        rng = np.random.RandomState(0 if mode == "train" else 1)
        n = 256
        self.rows = [
            (rng.randint(1, 6000), rng.randint(0, 2), rng.randint(1, 8),
             rng.randint(0, 21), rng.randint(1, 4000),
             rng.randint(0, 19, (3,)), rng.randint(1, 6))
            for _ in range(n)
        ]

    def __getitem__(self, idx):
        return self.rows[idx]

    def __len__(self):
        return len(self.rows)


class WMT14(Dataset):
    def __init__(self, data_file=None, mode="train", dict_size=30000, **kw):
        rng = np.random.RandomState(0 if mode == "train" else 1)
        self.pairs = [
            (rng.randint(1, dict_size, (rng.randint(5, 25),)),
             rng.randint(1, dict_size, (rng.randint(5, 25),)),
             rng.randint(1, dict_size, (rng.randint(5, 25),)))
            for _ in range(128)
        ]

    def __getitem__(self, idx):
        return self.pairs[idx]

    def __len__(self):
        return len(self.pairs)


class WMT16(WMT14):
    def __init__(self, data_file=None, mode="train", src_dict_size=30000,
                 trg_dict_size=30000, lang="en", **kw):
        super().__init__(data_file, mode, max(src_dict_size, 2))


class ViterbiDecoder:
    """Layer form of viterbi_decode (text/viterbi_decode.py parity)."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
