"""paddle.text — text datasets (parity: python/paddle/text/datasets)."""
from __future__ import annotations

import numpy as np

from ..io import Dataset


class Imdb(Dataset):
    """Synthetic-fallback IMDB (reference downloads the corpus; zero-egress
    environments get a deterministic generated stand-in)."""

    def __init__(self, data_file=None, mode="train", cutoff=150):
        rng = np.random.RandomState(0 if mode == "train" else 1)
        n = 256
        self.docs = [rng.randint(1, 5000, (rng.randint(20, 200),)) for _ in range(n)]
        self.labels = rng.randint(0, 2, (n,))

    def __getitem__(self, idx):
        return self.docs[idx], int(self.labels[idx])

    def __len__(self):
        return len(self.docs)


class UCIHousing(Dataset):
    def __init__(self, data_file=None, mode="train"):
        rng = np.random.RandomState(0 if mode == "train" else 1)
        n = 404 if mode == "train" else 102
        self.x = rng.randn(n, 13).astype(np.float32)
        w = rng.randn(13).astype(np.float32)
        self.y = (self.x @ w + 0.1 * rng.randn(n)).astype(np.float32)[:, None]

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]

    def __len__(self):
        return len(self.x)


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """CRF viterbi decode (parity: paddle.text.viterbi_decode)."""
    import jax
    import jax.numpy as jnp

    from ..core.dispatch import apply_op

    def _vd(pot, trans):
        # pot: [B, T, N], trans: [N, N]
        def step(carry, emit):
            score = carry  # [B, N]
            cand = score[:, :, None] + trans[None]
            best = jnp.max(cand, axis=1) + emit
            idx = jnp.argmax(cand, axis=1)
            return best, idx

        init = pot[:, 0]
        scores, idxs = jax.lax.scan(step, init, jnp.swapaxes(pot[:, 1:], 0, 1))
        last = jnp.argmax(scores, axis=-1)

        def back(carry, idx_t):
            tag = carry
            prev = jnp.take_along_axis(idx_t, tag[:, None], 1)[:, 0]
            return prev, prev

        _, path = jax.lax.scan(back, last, idxs, reverse=True)
        path = jnp.concatenate([jnp.swapaxes(path, 0, 1), last[:, None]], 1)
        return jnp.max(scores, -1), path

    return apply_op(_vd, potentials, transition_params, _op_name="viterbi")
