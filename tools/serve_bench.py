"""Fleet serving soak benchmark (docs/SERVING.md soak recipe).

Drives Poisson-arrival synthetic traffic (mixed prompt lengths,
optional shared system prefix / sampled fraction / deadlines) against
1..N engine replicas behind a FleetRouter and prints ONE JSON metric
line per replica count:

    {"metric": "serve_goodput_tokens_per_sec_rN", "value": <goodput>,
     "unit": "tokens/sec", "serving": {<gateable block>}}

``tools/bench_gate.py`` consumes these lines like any bench artifact:
reference-free gates on ``p99_ttft_seconds`` vs ``p99_ttft_budget``
(derived from the single-replica run's p50 unless --ttft-budget pins
it) and ``goodput_x_single`` vs ``--scaling-target`` (the acceptance
bar: 4 replicas >= 3.5x single-replica goodput), plus a referenced
cold-start gate at the same scan mode.

Goodput and TTFT run on the soak harness's simulated-parallel clock
(replicas tick concurrently in deployment; see
paddle_tpu/inference/fleet/soak.py). Run from /root/repo:

    python tools/serve_bench.py                      # CPU smoke, r1+r2
    python tools/serve_bench.py --replicas 1 4 --requests 2000 \
        --scaling-target 3.5                         # the soak gate run
    python tools/serve_bench.py --disagg --spec --int8-kv \
        --prefix-cache --shared-prefix 64            # full topology
    python tools/serve_bench.py --overload           # 2x-capacity
        # overload scenario: mixed priorities, one chaos-flapping
        # replica, admission/shedding/breakers/brownout on — emits the
        # OVERLOAD-gated "overload" block (docs/SERVING.md)
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.getcwd())

import numpy as np


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="fleet serving soak benchmark (docs/SERVING.md)")
    ap.add_argument("--replicas", type=int, nargs="+", default=None,
                    help="replica counts to sweep (default: 1 2 on CPU, "
                    "1 4 on TPU; 1 is always prepended as the baseline)")
    ap.add_argument("--requests", type=int, default=None,
                    help="synthetic requests per sweep point "
                    "(default 96 CPU / 2000 TPU)")
    ap.add_argument("--rate", type=float, default=None,
                    help="Poisson arrival rate, req/sim-second "
                    "(default: saturating)")
    ap.add_argument("--policy", default="least_loaded",
                    help="router policy: least_loaded | round_robin | "
                    "prefix_affinity")
    ap.add_argument("--disagg", action="store_true",
                    help="replicas are disaggregated prefill/decode pairs")
    ap.add_argument("--spec", action="store_true",
                    help="attach a 1-layer draft model (speculative "
                    "decoding) to every replica")
    ap.add_argument("--spec-tokens", type=int, default=3)
    ap.add_argument("--int8-kv", action="store_true",
                    help="request the int8 paged KV mode (engages only "
                    "behind the parity probe; PTPU_INT8_KV overrides)")
    ap.add_argument("--prefix-cache", action="store_true")
    ap.add_argument("--shared-prefix", type=int, default=0,
                    help="tokens of shared system prompt per request")
    ap.add_argument("--sampled-fraction", type=float, default=0.0)
    ap.add_argument("--deadline", type=float, default=None,
                    help="per-request deadline_seconds")
    ap.add_argument("--scaling-target", type=float, default=None,
                    help="gate: multi-replica goodput must reach this "
                    "multiple of the single-replica run (e.g. 3.5 at 4 "
                    "replicas)")
    ap.add_argument("--ttft-budget", type=float, default=None,
                    help="gate: absolute p99 TTFT bound in sim-seconds "
                    "(default: 10x the single-replica p50)")
    ap.add_argument("--ttft-budget-x", type=float, default=10.0,
                    help="derived budget = this x single-replica p50")
    ap.add_argument("--overload", action="store_true",
                    help="after the sweep, run the overload scenario: "
                    "sustained arrivals at --overload-x the measured "
                    "fleet capacity, mixed interactive/batch "
                    "priorities, one chaos-flapping replica, overload "
                    "control on — emits the gateable 'overload' block "
                    "(docs/SERVING.md 'Overload & degradation')")
    ap.add_argument("--overload-x", type=float, default=2.0,
                    help="overload arrival rate as a multiple of the "
                    "measured capacity (default 2.0)")
    ap.add_argument("--overload-requests", type=int, default=None,
                    help="requests in the overload scenario (default: "
                    "same as --requests)")
    ap.add_argument("--procs", type=int, default=None,
                    help="run the multi-process fleet scenario instead "
                    "of the in-process sweep: N replicas as real OS "
                    "processes behind the socket transport "
                    "(FleetSupervisor), one replica SIGKILLed "
                    "mid-soak, a chaos-injected link, and a rolling "
                    "weight upgrade — emits the gateable 'upgrade' "
                    "block (docs/SERVING.md 'Process topology'). "
                    "PTPU_FLEET_PROC=0 falls back to in-process "
                    "loopback children, bitwise")
    ap.add_argument("--hosts", type=int, default=None,
                    help="run the cross-host fleet scenario instead of "
                    "the in-process sweep: replicas spread across N "
                    "host agents discovered through the rendezvous "
                    "store, one whole host partitioned away mid-soak "
                    "(fenced leases + fleet-wide replay), then healed "
                    "— emits the gateable 'partition' block "
                    "(docs/SERVING.md 'Cross-host topology'). "
                    "PTPU_FLEET_HOSTS=0 collapses to the single-host "
                    "topology, bitwise")
    ap.add_argument("--sever-tick", type=int, default=4,
                    help="soak tick at which the host partition starts "
                    "(--hosts scenario)")
    ap.add_argument("--heal-tick", type=int, default=None,
                    help="soak tick at which the partition heals "
                    "(--hosts scenario; default: after the soak drains)")
    ap.add_argument("--kill-agent", action="store_true",
                    help="also SIGKILL the severed host's agent "
                    "(--hosts scenario; the host stays lost and the "
                    "fleet must reconverge on the survivors)")
    ap.add_argument("--kill-tick", type=int, default=3,
                    help="soak tick at which one replica is SIGKILLed "
                    "(--procs scenario; negative disables the kill)")
    ap.add_argument("--upgrade-tick", type=int, default=6,
                    help="soak tick at which the rolling weight "
                    "upgrade starts (--procs scenario)")
    ap.add_argument("--no-chaos", action="store_true",
                    help="skip the ChaosTransport link faults in the "
                    "--procs scenario")
    ap.add_argument("--window-goodput-floor", type=float, default=None,
                    help="gate: goodput inside the upgrade window must "
                    "stay above this fraction of whole-run goodput "
                    "(opt-in — completion-based goodput is lumpy at "
                    "smoke scale)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--timeline-dir", default=None,
                    help="record a per-tick timeline JSONL per soak "
                    "into this directory (serve_rN.jsonl / "
                    "serve_overload_rN.jsonl) and run the SLO engine "
                    "live — the blocks then embed 'timeline' and 'slo' "
                    "sub-blocks (docs/TELEMETRY.md)")
    args = ap.parse_args(argv)

    import jax

    import paddle_tpu as paddle
    from paddle_tpu.inference.fleet import build_workload, soak_block
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    on_tpu = jax.default_backend() not in ("cpu",)
    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                          num_layers=16, num_heads=16, max_seq_len=1024,
                          dropout=0.0)
        requests = args.requests or 2000
        prompt_lens = (64, 128, 256, 512)
        max_new, page, slots, chunk, max_seq = 64, 64, 16, 128, 1024
        replica_counts = args.replicas or [1, 4]
    else:
        cfg = LlamaConfig(vocab_size=256, hidden_size=64, num_layers=2,
                          num_heads=4, num_kv_heads=2, max_seq_len=128,
                          dropout=0.0)
        requests = args.requests or 96
        prompt_lens = (6, 10, 14, 20)
        max_new, page, slots, chunk, max_seq = 8, 8, 4, 8, 64
        replica_counts = args.replicas or [1, 2]
    if replica_counts[0] != 1:
        replica_counts = [1] + list(replica_counts)
    # a shared prefix longer than the drawn prompt length yields
    # prefix+1 tokens — grow the sequence geometry to fit the longest
    # possible prompt + generation (+ spec headroom) instead of
    # crashing the first submit
    max_prompt = max(max(prompt_lens), args.shared_prefix + 1)
    need = max_prompt + max_new + (args.spec_tokens if args.spec else 0)
    if need > max_seq:
        max_seq = need
        cfg.max_seq_len = max(cfg.max_seq_len, max_seq)

    paddle.seed(args.seed)
    model = LlamaForCausalLM(cfg)
    if on_tpu:
        for _, p in model.named_parameters():
            p._data = p._data.astype(jax.numpy.bfloat16)
    draft = None
    if args.spec:
        dcfg = LlamaConfig(
            vocab_size=cfg.vocab_size, hidden_size=cfg.hidden_size // 2,
            num_layers=1, num_heads=max(1, cfg.num_heads // 2),
            num_kv_heads=max(1, cfg.num_kv_heads // 2),
            max_seq_len=cfg.max_seq_len, dropout=0.0)
        paddle.seed(args.seed + 1)
        draft = LlamaForCausalLM(dcfg)
        if on_tpu:
            for _, p in draft.named_parameters():
                p._data = p._data.astype(jax.numpy.bfloat16)

    workload = build_workload(
        requests, args.rate or (requests * 4.0), prompt_lens,
        cfg.vocab_size, shared_prefix=args.shared_prefix,
        sampled_fraction=(0.0 if args.spec else args.sampled_fraction),
        deadline_seconds=args.deadline, seed=args.seed)

    engine_kw = dict(max_seq_len=max_seq, max_new_tokens=max_new,
                     prefill_chunk=chunk, int8_kv=args.int8_kv,
                     spec_tokens=args.spec_tokens)
    disagg_kw = None
    if args.disagg:
        disagg_kw = dict(prefill_slots=max(2, slots // 2),
                         decode_slots=slots, page_size=page,
                         enable_prefix_cache=args.prefix_cache)
    else:
        engine_kw.update(max_slots=slots, page_size=page,
                         enable_prefix_cache=args.prefix_cache)

    if args.hosts:
        from paddle_tpu.inference.fleet import (FleetSupervisor,
                                                fleet_hosts_enabled,
                                                fleet_proc_enabled,
                                                make_model_spec,
                                                partition_block)

        n_hosts = args.hosts
        if not fleet_hosts_enabled():
            sys.stderr.write("# serve_bench: PTPU_FLEET_HOSTS=0 — "
                             "cross-host scenario collapses to the "
                             "single-host topology; skipping\n")
            return
        n = max(max(replica_counts), n_hosts)
        he_kw = dict(engine_kw)
        he_kw.setdefault("max_slots", slots)
        he_kw.setdefault("page_size", page)
        he_kw["seed"] = args.seed
        spec = make_model_spec(
            dict(vocab_size=cfg.vocab_size, hidden_size=cfg.hidden_size,
                 num_layers=cfg.num_layers, num_heads=cfg.num_heads,
                 num_kv_heads=cfg.num_kv_heads,
                 max_seq_len=cfg.max_seq_len, dropout=0.0),
            seed=args.seed, engine_kw=he_kw)
        proc = fleet_proc_enabled()
        sup = FleetSupervisor(
            spec, n, proc=proc, policy=args.policy, hosts=n_hosts,
            lease_seconds=120.0, host_lease_seconds=1.0,
            transport_kw=dict(timeouts={"step": 10.0, "submit": 10.0},
                              backoff=0.01))
        try:
            block = partition_block(
                sup, workload, host="host0",
                sever_tick=args.sever_tick, heal_tick=args.heal_tick,
                kill_agent=args.kill_agent,
                upgrade_version=(1 if args.upgrade_tick >= 0 else None),
                upgrade_tick=(args.upgrade_tick
                              if args.upgrade_tick >= 0 else None))
        finally:
            sup.close()
        print(json.dumps({
            "metric": f"serve_crosshost_goodput_h{n_hosts}_r{n}",
            "value": block.get("goodput_tokens_per_sec"),
            "unit": "tokens/sec",
            "partition": block,
        }), flush=True)
        return

    if args.procs:
        from paddle_tpu.inference.fleet import (FleetSupervisor,
                                                fleet_proc_enabled,
                                                make_model_spec,
                                                upgrade_block)
        from paddle_tpu.testing.chaos import ChaosTransport

        n = args.procs
        proc = fleet_proc_enabled()
        if not proc:
            sys.stderr.write("# serve_bench: PTPU_FLEET_PROC=0 — "
                             "in-process loopback children (bitwise "
                             "fallback)\n")
        # the multi-process scenario always runs plain engines (the
        # transport/supervisor mechanics are topology-independent)
        pe_kw = dict(engine_kw)
        pe_kw.setdefault("max_slots", slots)
        pe_kw.setdefault("page_size", page)
        pe_kw["seed"] = args.seed
        spec = make_model_spec(
            dict(vocab_size=cfg.vocab_size, hidden_size=cfg.hidden_size,
                 num_layers=cfg.num_layers, num_heads=cfg.num_heads,
                 num_kv_heads=cfg.num_kv_heads,
                 max_seq_len=cfg.max_seq_len, dropout=0.0),
            seed=args.seed, engine_kw=pe_kw)
        chaos = None
        if not args.no_chaos and n > 1:
            # deterministic small fault schedule on replica 1's link:
            # one dropped request (timeout + idempotent re-send), one
            # duplicated frame (served from the reply cache), one
            # corrupted frame (CRC reject, re-send)
            chaos = {1: lambda t: ChaosTransport(
                t, drop_sends={5}, duplicate_sends={9},
                corrupt_sends={13})}
        sup = FleetSupervisor(
            spec, n, proc=proc, policy=args.policy, chaos=chaos,
            lease_seconds=120.0,
            transport_kw=dict(timeouts={"step": 10.0, "submit": 10.0},
                              backoff=0.01))
        try:
            block = upgrade_block(
                sup, workload, version=1,
                upgrade_tick=args.upgrade_tick,
                kill_tick=(args.kill_tick if args.kill_tick >= 0
                           and n > 1 else None),
                kill_replica=0,
                window_goodput_floor=args.window_goodput_floor,
                window_ttft_budget=args.ttft_budget)
        finally:
            sup.close()
        block["chaos"] = (None if chaos is None else
                          {"link": 1, "drop_sends": [5],
                           "duplicate_sends": [9], "corrupt_sends": [13]})
        print(json.dumps({
            "metric": f"serve_upgrade_procs_r{n}",
            "value": block.get("goodput_tokens_per_sec"),
            "unit": "tokens/sec",
            "upgrade": block,
        }), flush=True)
        return

    baseline = None
    for n in replica_counts:
        budget = args.ttft_budget
        if budget is None and baseline is not None:
            p50 = (baseline.get("ttft") or {}).get("p50")
            budget = args.ttft_budget_x * p50 if p50 else None
        timeline = (os.path.join(args.timeline_dir,
                                 f"serve_r{n}.jsonl")
                    if args.timeline_dir else None)
        block = soak_block(
            model, replicas=n, workload=workload, policy=args.policy,
            disagg=args.disagg, draft_model=draft, engine_kw=engine_kw,
            disagg_kw=disagg_kw, baseline=baseline,
            scaling_target=(args.scaling_target if n > 1 else None),
            ttft_budget=(budget if n > 1 or args.ttft_budget else None),
            timeline_path=timeline)
        if baseline is None:
            baseline = block
        print(json.dumps({
            "metric": f"serve_goodput_tokens_per_sec_r{n}",
            "value": block.get("goodput_tokens_per_sec"),
            "unit": "tokens/sec",
            "serving": block,
        }), flush=True)

    if args.overload:
        from paddle_tpu.inference.fleet import OverloadConfig
        from paddle_tpu.inference.fleet.soak import (overload_block,
                                                     overload_workload)
        from paddle_tpu.testing.chaos import ChaosReplica

        n = max(replica_counts)
        # measured capacity: what ONE replica actually served per
        # simulated second in the baseline sweep run
        base_rate = (baseline["completed"]
                     / max(baseline["sim_seconds"], 1e-9))
        p50 = (baseline.get("ttft") or {}).get("p50") or 0.1
        slo = args.ttft_budget or args.ttft_budget_x * p50
        n_over = args.overload_requests or requests
        wl = overload_workload(
            base_rate * n, n_over, prompt_lens, cfg.vocab_size,
            rate_x_capacity=args.overload_x, batch_fraction=0.4,
            seed=args.seed + 7)
        depth = 2 * n * slots
        ov_cfg = OverloadConfig(
            ttft_slo=slo, admit_depth=2 * depth, shed_depth=depth,
            breaker_backoff=0.02, breaker_threshold=2,
            breaker_close_after=2, brownout_up_ticks=3,
            brownout_down_ticks=6)
        flap = (12, 3)
        holder = []

        def wrap(e):
            holder.append(ChaosReplica(e, flap=flap))
            return holder[-1]

        # the overload scenario always runs plain engines (the breaker /
        # brownout mechanics are topology-independent); a --disagg sweep
        # kept slots/page in disagg_kw, so re-add them here
        ov_engine_kw = dict(engine_kw)
        ov_engine_kw.setdefault("max_slots", slots)
        ov_engine_kw.setdefault("page_size", page)
        block = overload_block(
            model, replicas=n, workload=wl, overload_cfg=ov_cfg,
            policy=args.policy, engine_kw=ov_engine_kw,
            chaos_wrap={0: wrap}, ttft_budget=2.0 * slo,
            shed_ceiling=0.9, rate_x_capacity=args.overload_x,
            timeline_path=(os.path.join(
                args.timeline_dir, f"serve_overload_r{n}.jsonl")
                if args.timeline_dir else None))
        # bound the breaker flap count by the fault bursts the chaos
        # schedule actually fired: at most two opens per down-phase
        # (threshold-crossing + one failed half-open probe inside the
        # same burst), never one per fault
        chaos = holder[0]
        bursts = chaos.steps // (flap[0] + flap[1]) + 1
        block["breaker_flap_bound"] = 2 * bursts + 2
        block["chaos"] = {"flap": list(flap), "steps": chaos.steps,
                          "faults": chaos.faults}
        print(json.dumps({
            "metric": f"serve_overload_goodput_r{n}",
            "value": block.get("goodput_tokens_per_sec"),
            "unit": "tokens/sec",
            "overload": block,
        }), flush=True)


if __name__ == "__main__":
    main()
