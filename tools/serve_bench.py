"""Continuous-batching serving throughput on the real chip.

r3 weak #9 / r4: the serving stack (batched chunked prefill + paged
decode) had no recorded on-chip throughput. Run from /root/repo:
    python tools/serve_bench.py [--policy recompute|swap] [--roomy]
        [--prefix-cache] [--shared-prefix N] [--prompt-len M]
Prints tok/s at several concurrency levels for a 1.3B-class decoder.
--policy picks the preemption strategy for the tight-pool regime;
--roomy sizes the pool at worst case (no preemption) instead;
--shared-prefix N makes every prompt share its first N tokens (a system
prompt), the workload where --prefix-cache (automatic prefix caching)
skips the shared prefill;
--ttft measures median time-to-first-token for single shared-prefix
requests on a WARM engine (compile + cache seeded first) instead of
batch throughput — the metric prefix caching targets.
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.getcwd())

import numpy as np


def main():
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import ContinuousBatchingEngine
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    policy = "recompute"
    if "--policy" in sys.argv:
        i = sys.argv.index("--policy")
        if i + 1 >= len(sys.argv) or sys.argv[i + 1] not in (
                "recompute", "swap"):
            sys.exit("--policy requires a value: recompute | swap")
        policy = sys.argv[i + 1]
    roomy = "--roomy" in sys.argv
    prefix_cache = "--prefix-cache" in sys.argv
    shared_prefix = 0
    if "--shared-prefix" in sys.argv:
        shared_prefix = int(sys.argv[sys.argv.index("--shared-prefix") + 1])
    prompt_len_arg = 0
    if "--prompt-len" in sys.argv:
        prompt_len_arg = int(sys.argv[sys.argv.index("--prompt-len") + 1])

    on_tpu = jax.default_backend() not in ("cpu",)
    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                          num_layers=16, num_heads=16, max_seq_len=1024,
                          dropout=0.0)
        new_tokens, prompt_len = 64, 128
    else:
        cfg = LlamaConfig(vocab_size=256, hidden_size=64, num_layers=2,
                          num_heads=4, max_seq_len=128, dropout=0.0)
        new_tokens, prompt_len = 8, 16
    if prompt_len_arg:
        prompt_len = prompt_len_arg
        if prompt_len + new_tokens > cfg.max_seq_len:
            cfg.max_seq_len = prompt_len + new_tokens

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    if on_tpu:
        for _, p in model.named_parameters():
            p._data = p._data.astype(jax.numpy.bfloat16)
    rng = np.random.default_rng(0)

    if "--ttft" in sys.argv:
        shared = shared_prefix or (prompt_len - prompt_len // 8)
        sys_prompt = list(rng.integers(1, cfg.vocab_size, shared))

        def tail():
            return list(rng.integers(1, cfg.vocab_size,
                                     prompt_len - shared))

        eng = ContinuousBatchingEngine(
            model, max_slots=4, page_size=64,
            max_new_tokens=min(new_tokens, 8), prefill_chunk=64,
            enable_prefix_cache=prefix_cache)
        eng.submit(sys_prompt + tail())     # warm: compile + seed cache
        eng.run_until_complete(max_ticks=100000)
        samples = []
        for _ in range(7):
            got = []
            eng.submit(sys_prompt + tail(),
                       on_token=lambda r, t: got.append(
                           time.perf_counter()))
            t0 = time.perf_counter()
            while not got:
                eng.step()
            samples.append(got[0] - t0)
            eng.run_until_complete(max_ticks=100000)
        med = sorted(samples)[len(samples) // 2]
        print(f"ttft: shared {shared}/{prompt_len} tokens, "
              f"prefix_cache={prefix_cache}: median "
              f"{med * 1000:.0f}ms over {len(samples)} "
              f"({[int(s * 1000) for s in samples]}ms, "
              f"cache hits {eng.prefix_cache_hits} pages)", flush=True)
        return

    for slots in (8, 16, 32) if on_tpu else (2, 4):
        # r5: pool sized BELOW worst-case — prompt pages for every slot
        # plus ~half the decode growth — so incremental allocation +
        # preemption carry the load instead of head-of-line blocking on
        # worst-case reservations
        per_seq_worst = -(-(prompt_len + new_tokens) // 64)
        prompt_pages = -(-prompt_len // 64)
        grow = per_seq_worst - prompt_pages
        tight = max(slots * prompt_pages + (slots * grow) // 2,
                    per_seq_worst) + 1
        if roomy:
            tight = slots * per_seq_worst + 2
        eng = ContinuousBatchingEngine(
            model, max_slots=slots, page_size=64, num_pages=tight,
            max_new_tokens=new_tokens, prefill_chunk=64,
            preempt_policy=policy, enable_prefix_cache=prefix_cache)
        n_req = slots * 2
        sys_prompt = list(rng.integers(1, cfg.vocab_size, shared_prefix))
        for _ in range(n_req):
            tail = list(rng.integers(1, cfg.vocab_size,
                                     prompt_len - shared_prefix))
            eng.submit(sys_prompt + tail)
        t0 = time.perf_counter()
        done = eng.run_until_complete(max_ticks=100000)
        dt = time.perf_counter() - t0
        gen = sum(len(v) - prompt_len for v in done.values())
        print(f"slots={slots}: {n_req} reqs x {prompt_len}p+{new_tokens}g"
              f" -> {gen} generated in {dt:.1f}s = {gen / dt:.1f} tok/s"
              f" (prefill passes: {eng.prefill_chunk_steps},"
              f" preemptions: {eng.preemptions},"
              f" swaps: {eng.swaps_out},"
              f" cache hits: {eng.prefix_cache_hits} pages,"
              f" policy: {policy}, pool: {tight} pages)", flush=True)


if __name__ == "__main__":
    main()
