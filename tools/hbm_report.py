#!/usr/bin/env python
"""Pretty-print a bench "memory" block, or diff two rounds' blocks.

Usage:
    python tools/hbm_report.py RUN.json
    python tools/hbm_report.py OLD.json NEW.json

The sibling of tools/telemetry_report.py for the memory dimension:
accepts a raw planner decision dict (``paddle_tpu.memory.PlanDecision
.as_json()``), a bench JSON line carrying it under ``"memory"``, or a
BENCH_r*.json round record ({"n", "cmd", "tail", "parsed"}). Diff mode
explains "why did this round's memory state change" — chosen batch/
policy, peak vs budget, and the byte deltas — from data instead of a
re-profile. Contract: docs/MEMORY.md.
"""
from __future__ import annotations

import argparse
import json
import sys

_BYTE_FIELDS = ("peak_bytes", "budget_bytes", "act_saved_bytes",
                "act_int8_bytes", "opt_state_bytes")


def _is_memory(d):
    return isinstance(d, dict) and "peak_bytes" in d and "policy" in d


def _scan_lines(text):
    """LAST JSON-object line carrying a memory block (bench stdout prints
    log lines and, on TPU, TWO metric lines — the headline one is last)."""
    best = None
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            d = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(d, dict) and ("memory" in d or _is_memory(d)):
            best = d
    return best


def _extract(data):
    if not isinstance(data, dict):
        return None
    if _is_memory(data):
        return data
    if _is_memory(data.get("memory")):
        return data["memory"]
    parsed = data.get("parsed")
    if isinstance(parsed, dict) and _is_memory(parsed.get("memory")):
        return parsed["memory"]
    tail = data.get("tail")
    if isinstance(tail, str):
        return _extract(_scan_lines(tail))
    return None


def load_memory(path):
    with open(path) as f:
        text = f.read()
    try:
        data = json.loads(text)
    except json.JSONDecodeError:
        data = _scan_lines(text)
        if data is None:
            raise ValueError(f"{path}: no JSON object found")
    mem = _extract(data)
    if mem is None:
        raise ValueError(
            f"{path}: no memory block found (expected a planner decision "
            "dict, a bench JSON line with a 'memory' key, or a "
            "BENCH_r*.json round record — rounds before the memory "
            "planner don't carry one)")
    return mem


def _fmt_bytes(v):
    if v is None:
        return "-"
    v = float(v)
    for unit in ("B", "KB", "MB", "GB"):
        if abs(v) < 1024 or unit == "GB":
            return (f"{v:.2f}{unit}" if unit != "B" else f"{int(v)}B")
        v /= 1024
    return f"{v:.2f}GB"


def print_memory(mem, out=None):
    # resolve stdout at call time (a def-time default would pin whatever
    # stream was active at first import — e.g. a pytest capture buffer)
    w = (out or sys.stdout).write
    w(f"plan: batch={mem.get('batch')} source={mem.get('source')} "
      f"chip={mem.get('chip')} fits={mem.get('fits')}\n")
    w(f"policy: {mem.get('policy')}\n")
    for k in _BYTE_FIELDS:
        if mem.get(k) is not None:
            w(f"  {k}: {_fmt_bytes(mem[k])}\n")
    pk, bd = mem.get("peak_bytes"), mem.get("budget_bytes")
    if pk and bd:
        w(f"  headroom: {_fmt_bytes(bd - pk)} ({pk / bd:.1%} of budget used)\n")
    cands = mem.get("candidates") or []
    if cands:
        w(f"-- candidates evaluated ({len(cands)}) --\n")
        for c in cands:
            if "error" in c:
                w(f"  b{c.get('batch')} {c.get('policy')}: "
                  f"ERROR {c['error']}\n")
            else:
                tag = "fits" if c.get("fits") else "over budget"
                w(f"  b{c.get('batch')} {c.get('policy')}: "
                  f"peak={_fmt_bytes(c.get('peak_bytes'))} "
                  f"score={c.get('score', 0):.3f} [{tag}]\n")


def diff_memory(old, new, out=None):
    w = (out or sys.stdout).write
    changed = []
    for k in ("batch", "policy", "source", "chip", "fits"):
        if old.get(k) != new.get(k):
            changed.append(f"  {k}: {old.get(k)} -> {new.get(k)}")
    w("plan changes (new vs old):\n")
    w(("\n".join(changed) + "\n") if changed
      else "  (same batch/policy/source)\n")
    w("byte deltas:\n")
    any_delta = False
    for k in _BYTE_FIELDS:
        ov, nv = old.get(k), new.get(k)
        if ov is None and nv is None:
            continue
        if ov == nv:
            continue
        any_delta = True
        delta = (nv or 0) - (ov or 0)
        rel = f" ({delta / ov:+.1%})" if ov else ""
        w(f"  {k}: {_fmt_bytes(ov)} -> {_fmt_bytes(nv)} "
          f"[{'+' if delta >= 0 else ''}{_fmt_bytes(delta)}{rel}]\n")
    if not any_delta:
        w("  (no byte-field changes)\n")
    return changed


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("run", help="bench JSON / memory block")
    ap.add_argument("other", nargs="?",
                    help="second run: diff mode (old=first, new=second)")
    args = ap.parse_args(argv)
    if args.other is None:
        print_memory(load_memory(args.run))
    else:
        diff_memory(load_memory(args.run), load_memory(args.other))
    return 0


if __name__ == "__main__":
    sys.exit(main())
