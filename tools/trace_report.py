#!/usr/bin/env python
"""Summarize a span trace, or diff two traces by phase.

Usage:
    python tools/trace_report.py TRACE            # summary
    python tools/trace_report.py OLD NEW [--top N]  # phase diff

Accepts both formats the tracer exports (docs/TELEMETRY.md Tracing):

- Perfetto/Chrome trace-event JSON (``trace.to_perfetto`` /
  ``bench.py --trace``): ``{"traceEvents": [...]}`` with ``ts``/``dur``
  in microseconds,
- the compact JSONL (``trace.dump_jsonl``): one event per line with
  ``ts``/``dur`` in seconds and a leading ``{"ph": "meta", ...}`` line.

The summary prints per-phase totals (count / total / mean seconds) for
complete spans, instant counts (the plan-collective events), and async
request stats (count, mean duration, unclosed). Diff mode ranks phases
by total-seconds growth — "which phase ate the regression".

A malformed trace (unparseable JSON, missing required event fields,
negative durations) **exits 1** so CI can gate trace integrity on the
same artifact Perfetto loads.
"""
from __future__ import annotations

import argparse
import json
import sys


class MalformedTrace(ValueError):
    pass


_REQUIRED = {"ph", "name"}


def _validate_event(e, scale):
    if not isinstance(e, dict):
        raise MalformedTrace(f"event is not an object: {e!r}")
    ph = e.get("ph")
    if ph == "meta":
        return None
    missing = _REQUIRED - set(e)
    if missing:
        raise MalformedTrace(f"event missing {sorted(missing)}: {e!r}")
    if ph == "M":   # perfetto metadata (thread names)
        return None
    if ph not in ("X", "i", "I", "b", "e", "n"):
        raise MalformedTrace(f"unknown event phase {ph!r}: {e!r}")
    if "ts" not in e:
        raise MalformedTrace(f"event missing 'ts': {e!r}")
    try:
        ts = float(e["ts"]) * scale
    except (TypeError, ValueError):
        raise MalformedTrace(f"non-numeric ts: {e!r}")
    dur = None
    if ph == "X":
        if "dur" not in e:
            raise MalformedTrace(f"complete span missing 'dur': {e!r}")
        try:
            dur = float(e["dur"]) * scale
        except (TypeError, ValueError):
            raise MalformedTrace(f"non-numeric dur: {e!r}")
        if dur < 0:
            raise MalformedTrace(f"negative span duration: {e!r}")
    return {"ph": ph, "name": str(e["name"]), "ts": ts, "dur": dur,
            "id": e.get("id"),
            "attrs": e.get("attrs") or e.get("args"),
            "cat": e.get("cat", "")}


def load_trace(path):
    """-> normalized event list (seconds). Raises MalformedTrace."""
    with open(path) as f:
        text = f.read()
    if not text.strip():
        raise MalformedTrace(f"{path}: empty file")
    events, raw = [], None
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        doc = None
    if isinstance(doc, dict) and isinstance(doc.get("traceEvents"), list):
        raw, scale = doc["traceEvents"], 1e-6   # perfetto: microseconds
    elif isinstance(doc, list):
        raw, scale = doc, 1e-6                  # bare chrome event array
    elif doc is None:
        raw, scale = [], 1.0                    # JSONL: seconds
        for i, line in enumerate(text.splitlines()):
            line = line.strip()
            if not line:
                continue
            try:
                raw.append(json.loads(line))
            except json.JSONDecodeError:
                raise MalformedTrace(f"{path}:{i + 1}: not JSON: "
                                     f"{line[:80]!r}")
    else:
        raise MalformedTrace(
            f"{path}: neither a traceEvents JSON nor JSONL")
    for e in raw:
        ev = _validate_event(e, scale)
        if ev is not None:
            events.append(ev)
    if not events:
        raise MalformedTrace(f"{path}: no trace events")
    return events


def phase_totals(events):
    """{name: {"count", "seconds"}} over complete spans."""
    out = {}
    for e in events:
        if e["ph"] != "X":
            continue
        row = out.setdefault(e["name"], {"count": 0, "seconds": 0.0})
        row["count"] += 1
        row["seconds"] += e["dur"]
    return out


def instant_counts(events):
    out = {}
    for e in events:
        if e["ph"] in ("i", "I", "n"):
            out[e["name"]] = out.get(e["name"], 0) + 1
    return out


def request_stats(events):
    """Async b/e pairing per (name, id): count, mean seconds, unclosed."""
    open_, durs, unclosed = {}, {}, 0
    for e in events:
        if e["ph"] == "b":
            open_.setdefault((e["name"], e["id"]), []).append(e["ts"])
        elif e["ph"] == "e":
            stack = open_.get((e["name"], e["id"]))
            if stack:
                t0 = stack.pop()
                durs.setdefault(e["name"], []).append(e["ts"] - t0)
    unclosed = sum(len(v) for v in open_.values())
    return {name: {"count": len(ds),
                   "mean_seconds": sum(ds) / len(ds)}
            for name, ds in durs.items()}, unclosed


_SERVE_SPANS = ("admission", "prefill_group", "prefill_tick",
                "decode_tick", "spec_draft", "spec_verify", "detokenize")
_SERVE_ASYNC = ("request", "route", "queue", "prefill")


def serving_stats(events):
    """Aggregate the serving span contract (docs/TELEMETRY.md Tracing,
    docs/SERVING.md): engine tick phases, per-request async spans
    (route/queue/prefill/request), handoff transfers, and speculative-
    decode acceptance from the ``spec_accept`` instants. None when the
    trace carries no serving activity."""
    ticks = {}
    for e in events:
        if e["ph"] == "X" and e["name"] in _SERVE_SPANS:
            row = ticks.setdefault(e["name"], {"count": 0, "seconds": 0.0})
            row["count"] += 1
            row["seconds"] += e["dur"]
    reqs, _unclosed = request_stats(events)
    async_rows = {n: reqs[n] for n in _SERVE_ASYNC if n in reqs}
    handoffs = {"count": 0, "bytes": 0}
    spec = {"accepted": 0, "drafted": 0}
    for e in events:
        attrs = e.get("attrs") or {}
        if e["ph"] == "n" and e["name"] == "handoff":
            handoffs["count"] += 1
            handoffs["bytes"] += int(attrs.get("bytes") or 0)
        elif e["ph"] in ("i", "I") and e["name"] == "spec_accept":
            spec["accepted"] += int(attrs.get("accepted") or 0)
            spec["drafted"] += int(attrs.get("drafted") or 0)
    if not ticks and not async_rows and not handoffs["count"]:
        return None
    out = {"ticks": ticks, "requests": async_rows}
    if handoffs["count"]:
        out["handoffs"] = handoffs
    if spec["drafted"]:
        spec["acceptance_rate"] = round(spec["accepted"]
                                        / spec["drafted"], 4)
        out["spec"] = spec
    return out


def print_summary(path, events, out=None):
    w = (out or sys.stdout).write
    w(f"{path}: {len(events)} events\n")
    phases = phase_totals(events)
    if phases:
        w("-- phases (complete spans) --\n")
        for name in sorted(phases, key=lambda n: -phases[n]["seconds"]):
            p = phases[name]
            w(f"  {name}: n={p['count']} total={p['seconds']:.6f}s "
              f"mean={p['seconds'] / p['count']:.6f}s\n")
    inst = instant_counts(events)
    if inst:
        w("-- instants --\n")
        for name in sorted(inst, key=lambda n: -inst[n]):
            w(f"  {name}: n={inst[name]}\n")
    reqs, unclosed = request_stats(events)
    if reqs or unclosed:
        w("-- async (request spans) --\n")
        for name in sorted(reqs):
            r = reqs[name]
            w(f"  {name}: n={r['count']} "
              f"mean={r['mean_seconds']:.6f}s\n")
        if unclosed:
            w(f"  (unclosed spans: {unclosed})\n")
    serve = serving_stats(events)
    if serve:
        w("-- serving --\n")
        for name, row in sorted(serve["ticks"].items(),
                                key=lambda kv: -kv[1]["seconds"]):
            w(f"  {name}: n={row['count']} "
              f"total={row['seconds']:.6f}s\n")
        for name, row in sorted(serve["requests"].items()):
            w(f"  {name} (async): n={row['count']} "
              f"mean={row['mean_seconds']:.6f}s\n")
        if "handoffs" in serve:
            h = serve["handoffs"]
            w(f"  handoffs: n={h['count']} bytes={h['bytes']}\n")
        if "spec" in serve:
            s = serve["spec"]
            w(f"  spec: accepted {s['accepted']}/{s['drafted']} "
              f"(rate {s['acceptance_rate']})\n")


def diff(old_events, new_events, top=15, out=None):
    out = out or sys.stdout
    old_p, new_p = phase_totals(old_events), phase_totals(new_events)
    rows = []
    for name in set(old_p) | set(new_p):
        o = old_p.get(name, {}).get("seconds", 0.0)
        n = new_p.get(name, {}).get("seconds", 0.0)
        rel = (n - o) / o if o else (float("inf") if n else 0.0)
        rows.append((n - o, rel, name, o, n))
    rows.sort(key=lambda r: -r[0])
    out.write(f"top {top} phases by total-seconds growth (new vs old):\n")
    for delta, rel, name, o, n in rows[:top]:
        tag = ("new phase" if o == 0.0 and n > 0.0
               else f"{rel:+.1%}")
        out.write(f"  {name}: {o:.6f}s -> {n:.6f}s "
                  f"({delta:+.6f}s, {tag})\n")
    if not rows:
        out.write("  (no comparable phases)\n")
    return rows


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", help="trace file (perfetto JSON or JSONL)")
    ap.add_argument("other", nargs="?",
                    help="second trace: diff mode (old=first, new=second)")
    ap.add_argument("--top", type=int, default=15,
                    help="diff mode: phases to show")
    args = ap.parse_args(argv)
    try:
        events = load_trace(args.trace)
        other = load_trace(args.other) if args.other else None
    except (MalformedTrace, OSError) as e:
        print(f"trace_report: malformed trace: {e}", file=sys.stderr)
        return 1
    if other is None:
        print_summary(args.trace, events)
    else:
        diff(events, other, top=args.top)
    return 0


if __name__ == "__main__":
    sys.exit(main())
