#!/usr/bin/env python
"""Validate a CheckpointManager root offline, or diff two steps.

Usage:
    python tools/ckpt_inspect.py ROOT                # validate every step
    python tools/ckpt_inspect.py ROOT --step 42      # one step
    python tools/ckpt_inspect.py ROOT --diff 40 42   # what changed
    python tools/ckpt_inspect.py ROOT --json         # machine-readable

Validation goes one level deeper than the runtime's restore-time check
(manager.validate_step): on top of COMMIT manifest presence, per-file
size + CRC32C, and metadata unpicklability, it verifies
metadata <-> shard-file COMPLETENESS — every shard box the metadata
records must exist as a payload entry in its .distcp file, and every
referenced shard file must be listed in the COMMIT manifest. Exit code
is non-zero when any committed step fails validation, so this gates CI
and ops runbooks (docs/CHECKPOINT.md). Uncommitted step directories are
reported but are NOT failures — readers ignore them by contract (they
are in-flight saves or crash debris awaiting GC).

Diff mode compares two committed steps' metadata + payload bytes per
key: added/removed keys, shape/dtype changes, and content changes
(per-box checksums — no full-tensor assembly).
"""
from __future__ import annotations

import argparse
import json
import os
import pickle
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _manager(root):
    from paddle_tpu.distributed.checkpoint.manager import CheckpointManager

    return CheckpointManager(root)


def _load_step(step_dir):
    """(metadata list, {filename: payload dict}) for one step dir."""
    from paddle_tpu.distributed.checkpoint import _load_metadata

    metas = _load_metadata(step_dir)
    payloads = {}
    for fn in sorted(os.listdir(step_dir)):
        if fn.endswith(".distcp"):
            with open(os.path.join(step_dir, fn), "rb") as f:
                payloads[fn] = pickle.load(f)
    return metas, payloads


def _completeness_problems(step_dir):
    """metadata <-> shard-file cross-check (beyond checksums)."""
    problems = []
    try:
        metas, payloads = _load_step(step_dir)
    except Exception as e:
        return [f"unreadable metadata/payload: {e!r}"]
    for meta in metas:
        for idx, fn in meta.storage_metadata.items():
            payload = payloads.get(fn)
            if payload is None:
                problems.append(
                    f"{idx.tensor_key!r}: shard file {fn} missing")
                continue
            pkey = f"{idx.tensor_key}|{','.join(map(str, idx.global_offset))}"
            if pkey not in payload:
                problems.append(
                    f"{idx.tensor_key!r}: payload entry {pkey!r} missing "
                    f"from {fn}")
    return problems


def validate(root, step=None):
    """[{step, committed, problems}] for every (or one) step directory.

    Walking the root, uncommitted directories are benign (in-flight or
    debris readers ignore). An EXPLICITLY requested --step is a gate:
    missing or uncommitted is a failure — the operator asked for THAT
    step to be valid, and 'it does not exist' must not exit 0."""
    mgr = _manager(root)
    results = []
    if step is not None:
        problems = mgr.validate_step(step)
        if not problems:
            problems = _completeness_problems(mgr.step_dir(step))
        results.append({"step": step, "committed": mgr.is_committed(step),
                        "problems": problems})
        return results
    for s in mgr.all_steps(committed_only=False):
        committed = mgr.is_committed(s)
        if not committed:
            results.append({"step": s, "committed": False, "problems": []})
            continue
        problems = mgr.validate_step(s)
        if not problems:
            problems = _completeness_problems(mgr.step_dir(s))
        results.append({"step": s, "committed": True, "problems": problems})
    return results


def diff(root, step_a, step_b):
    """Per-key comparison of two steps: added/removed/changed/identical."""
    from paddle_tpu.distributed.checkpoint import checksum_bytes

    mgr = _manager(root)

    def _keys(step):
        metas, payloads = _load_step(mgr.step_dir(step))
        out = {}
        for meta in metas:
            for key, boxes in meta.state_dict_metadata.items():
                digest = []
                for m in boxes:
                    idx_key = f"{key}|{','.join(map(str, m.global_offset))}"
                    for payload in payloads.values():
                        block = payload.get(idx_key)
                        if block is not None:
                            digest.append(
                                (tuple(m.global_offset),
                                 checksum_bytes(block.tobytes())))
                            break
                out[key] = {
                    "shape": tuple(meta.flat_mapping.get(key, ())),
                    "dtype": boxes[0].dtype if boxes else None,
                    "digest": tuple(sorted(digest)),
                }
        return out

    a, b = _keys(step_a), _keys(step_b)
    report = {"added": sorted(set(b) - set(a)),
              "removed": sorted(set(a) - set(b)),
              "changed": [], "identical": []}
    for key in sorted(set(a) & set(b)):
        if a[key]["shape"] != b[key]["shape"]:
            report["changed"].append(
                f"{key}: shape {a[key]['shape']} -> {b[key]['shape']}")
        elif a[key]["dtype"] != b[key]["dtype"]:
            report["changed"].append(
                f"{key}: dtype {a[key]['dtype']} -> {b[key]['dtype']}")
        elif a[key]["digest"] != b[key]["digest"]:
            report["changed"].append(f"{key}: content")
        else:
            report["identical"].append(key)
    return report


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="validate/diff a crash-safe checkpoint root")
    ap.add_argument("root")
    ap.add_argument("--step", type=int, default=None)
    ap.add_argument("--diff", nargs=2, type=int, metavar=("A", "B"))
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    if not os.path.isdir(args.root):
        print(f"ckpt_inspect: no such directory: {args.root}",
              file=sys.stderr)
        return 2

    if args.diff:
        report = diff(args.root, args.diff[0], args.diff[1])
        if args.json:
            print(json.dumps(report, indent=1))
        else:
            print(f"diff step {args.diff[0]} -> step {args.diff[1]}:")
            for k in ("added", "removed", "changed"):
                for item in report[k]:
                    print(f"  {k}: {item}")
            print(f"  identical: {len(report['identical'])} key(s)")
        return 0

    results = validate(args.root, step=args.step)
    if args.json:
        print(json.dumps(results, indent=1))
    else:
        if not results:
            print(f"{args.root}: no step directories")
        for r in results:
            if r["problems"]:
                print(f"step {r['step']}: "
                      f"{'CORRUPT' if r['committed'] else 'INVALID'}")
                for p in r["problems"]:
                    print(f"  - {p}")
            elif not r["committed"]:
                print(f"step {r['step']}: UNCOMMITTED "
                      f"(invisible to readers; in-flight or crash debris)")
            else:
                print(f"step {r['step']}: OK")
    return 1 if any(r["problems"] for r in results) else 0


if __name__ == "__main__":
    sys.exit(main())
