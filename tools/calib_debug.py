import os, sys
sys.path.insert(0, os.getcwd())
from paddle_tpu.distributed.auto_tuner import AutoTuner
from paddle_tpu.distributed.auto_tuner.measure import build_trial_runner

t = AutoTuner({
    "world_size": 1,
    "model_cfg": dict(
        hidden_size=2048, num_layers=24, num_attention_heads=16,
        vocab_size=32000, seq_length=2048, global_batch_size=4,
        bytes_per_param=2, hbm_gb=15.75, mxu_tflops=197.0,
        ici_gbps=100.0),
    "max_mp_degree": 1,
    "max_pp_degree": 1,
    "tune_recompute": True,
})
run_fn = build_trial_runner(t.model, steps=2)
for _ in range(3):
    cfg = t.search_once()
    if cfg is None:
        print("no more cfgs"); break
    print("cfg:", cfg)
    try:
        m = run_fn(cfg)
        print("  ok:", float(m), getattr(m, "details", None))
    except Exception as e:
        print("  FAIL:", type(e).__name__, str(e)[:300])
