#!/usr/bin/env python
"""Pretty-print a bench "layout" block, or diff two rounds' blocks.

Usage:
    python tools/layout_report.py RUN.json
    python tools/layout_report.py OLD.json NEW.json

The sibling of tools/hbm_report.py for the parallelism-layout dimension:
accepts a raw autotune decision dict (``paddle_tpu.memory.LayoutDecision
.as_json()``), a bench JSON line carrying it under ``"layout"``, or a
BENCH_r*.json round record ({"n", "cmd", "tail", "parsed"}). Diff mode
explains "why did this round's layout change" — winning mesh/schedule,
predicted throughput, and the search-space deltas — from recorded data
instead of a re-search. A present-but-malformed block exits 1: a bench
that claims to have autotuned must carry a readable decision.
Contract: docs/AUTOTUNE.md.
"""
from __future__ import annotations

import argparse
import json
import sys

_AXES = ("dp", "sharding", "mp", "pp", "sep")


def _is_layout(d):
    return (isinstance(d, dict) and "label" in d
            and "predicted_score" in d and "layout" in d)


def _is_disabled(d):
    return isinstance(d, dict) and d.get("enabled") is False


def _scan_lines(text):
    """LAST JSON-object line carrying a layout block (bench stdout prints
    log lines and, on TPU, TWO metric lines — the headline one is last)."""
    best = None
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            d = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(d, dict) and ("layout" in d or _is_layout(d)):
            best = d
    return best


def _extract(data):
    if not isinstance(data, dict):
        return None
    if _is_layout(data) or _is_disabled(data):
        return data
    blk = data.get("layout")
    if _is_layout(blk) or _is_disabled(blk):
        return blk
    if isinstance(blk, dict):
        raise ValueError(
            "malformed layout block: expected an autotune decision "
            f"(label/predicted_score/layout) or {{'enabled': false}}, "
            f"got keys {sorted(blk.keys())}")
    parsed = data.get("parsed")
    if isinstance(parsed, dict):
        got = _extract(parsed)
        if got is not None:
            return got
    tail = data.get("tail")
    if isinstance(tail, str):
        return _extract(_scan_lines(tail))
    return None


def load_layout(path):
    with open(path) as f:
        text = f.read()
    try:
        data = json.loads(text)
    except json.JSONDecodeError:
        data = _scan_lines(text)
        if data is None:
            raise ValueError(f"{path}: no JSON object found")
    blk = _extract(data)
    if blk is None:
        raise ValueError(
            f"{path}: no layout block found (expected an autotune decision "
            "dict, a bench JSON line with a 'layout' key, or a "
            "BENCH_r*.json round record — rounds before the autotuner "
            "don't carry one)")
    return blk


def _fmt_bytes(v):
    if v is None:
        return "-"
    v = float(v)
    for unit in ("B", "KB", "MB", "GB"):
        if abs(v) < 1024 or unit == "GB":
            return (f"{v:.2f}{unit}" if unit != "B" else f"{int(v)}B")
        v /= 1024
    return f"{v:.2f}GB"


def _fmt_rate(v):
    return "-" if v is None else f"{float(v):,.0f} tok/s"


def _mesh(layout):
    live = [f"{a}{layout[a]}" for a in _AXES if int(layout.get(a, 1)) > 1]
    return "x".join(live) or "single"


def print_layout(blk, out=None):
    # resolve stdout at call time (a def-time default would pin whatever
    # stream was active at first import — e.g. a pytest capture buffer)
    w = (out or sys.stdout).write
    if _is_disabled(blk):
        w("layout: autotune disabled for this round\n")
        return
    lay = blk["layout"]
    w(f"winner: {blk.get('label')} source={blk.get('source')} "
      f"chip={blk.get('chip')} devices={blk.get('device_count')}\n")
    w(f"  mesh: {_mesh(lay)} zero_stage={lay.get('zero_stage')} "
      f"schedule={lay.get('pp_schedule')}"
      f"@{lay.get('pp_microbatches') or lay.get('pp')}\n")
    w(f"  predicted: {_fmt_rate(blk.get('predicted_score'))} "
      f"({blk.get('predicted_step_seconds', 0):.6f}s/step)\n")
    pk, bd = blk.get("peak_bytes"), blk.get("budget_bytes")
    w(f"  peak: {_fmt_bytes(pk)} of {_fmt_bytes(bd)} "
      f"fits={blk.get('fits')}\n")
    link = blk.get("link") or {}
    if link:
        tag = " (placeholder)" if link.get("placeholder") else ""
        w(f"  link: {_fmt_bytes(link.get('bytes_per_sec'))}/s{tag}\n")
    w(f"  search: {blk.get('searched')} lowered, "
      f"{blk.get('pruned_total')} pruned, "
      f"{blk.get('search_seconds', 0):.1f}s key={blk.get('key')}\n")
    if blk.get("fallback_reason"):
        w(f"  FALLBACK: {blk['fallback_reason']}\n")
    for reason, n in sorted((blk.get("pruned_by_reason") or {}).items()):
        w(f"    pruned[{reason}]: {n}\n")
    base = blk.get("baseline")
    if base:
        w(f"  baseline: {base.get('label')} "
          f"{_fmt_rate(base.get('predicted_tokens_per_sec'))} "
          f"fits={base.get('fits')}\n")
    cands = blk.get("candidates") or []
    if cands:
        w(f"-- top candidates ({len(cands)}) --\n")
        for c in cands:
            tag = "fits" if c.get("fits") else "over budget"
            star = "*" if c.get("is_baseline") else " "
            w(f" {star}{c.get('label')}: "
              f"{_fmt_rate(c.get('predicted_tokens_per_sec'))} "
              f"idle={c.get('idle_fraction', 0):.2f} "
              f"wire={_fmt_bytes(c.get('wire_bytes_per_step'))} [{tag}]\n")
    errors = blk.get("errors") or []
    if errors:
        w(f"-- lowering errors ({len(errors)}) --\n")
        for e in errors:
            w(f"  {e.get('label')}: {e.get('error')}\n")


def diff_layout(old, new, out=None):
    w = (out or sys.stdout).write
    if _is_disabled(old) or _is_disabled(new):
        w(f"autotune enabled: {not _is_disabled(old)} -> "
          f"{not _is_disabled(new)}\n")
        if _is_disabled(old) and not _is_disabled(new):
            print_layout(new, out)
        return []
    changed = []
    for k in ("label", "source", "chip", "device_count", "fits",
              "fallback_reason", "key"):
        if old.get(k) != new.get(k):
            changed.append(f"  {k}: {old.get(k)} -> {new.get(k)}")
    for a in (*_AXES, "zero_stage", "pp_schedule", "pp_microbatches",
              "bucket_mb", "batch", "head_chunk", "quant"):
        ov, nv = old["layout"].get(a), new["layout"].get(a)
        if ov != nv:
            changed.append(f"  layout.{a}: {ov} -> {nv}")
    w("layout changes (new vs old):\n")
    w(("\n".join(changed) + "\n") if changed
      else "  (same winner/source)\n")
    w("prediction deltas:\n")
    any_delta = False
    for k in ("predicted_score", "predicted_step_seconds", "peak_bytes",
              "searched", "pruned_total", "search_seconds"):
        ov, nv = old.get(k), new.get(k)
        if ov is None and nv is None or ov == nv:
            continue
        any_delta = True
        delta = (nv or 0) - (ov or 0)
        rel = f" ({delta / ov:+.1%})" if ov else ""
        fmt = _fmt_bytes if k == "peak_bytes" else (
            lambda v: "-" if v is None else f"{float(v):,.2f}")
        w(f"  {k}: {fmt(ov)} -> {fmt(nv)}{rel}\n")
    if not any_delta:
        w("  (no prediction changes)\n")
    return changed


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("run", help="bench JSON / layout decision block")
    ap.add_argument("other", nargs="?",
                    help="second run: diff mode (old=first, new=second)")
    args = ap.parse_args(argv)
    try:
        if args.other is None:
            print_layout(load_layout(args.run))
        else:
            diff_layout(load_layout(args.run), load_layout(args.other))
    except ValueError as e:
        print(f"layout_report: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
