#!/usr/bin/env python
"""Inspect flight-recorder forensics bundles and timeline JSONL files.

Usage:
    python tools/flight_report.py BUNDLE.json [...]        # validate + summarize
    python tools/flight_report.py --diff OLD.json NEW.json # window deltas
    python tools/flight_report.py --timeline TIMELINE.jsonl

Validates every bundle against the ``ptpu-flight-1`` contract
(paddle_tpu/telemetry/flight.py) and **exits 1 on any malformed file** —
the CI hook: a crash path that writes unreadable forensics is itself a
bug. HangWatchdog debris files are flight bundles too and validate the
same way.

Standalone by design: this tool loads ``telemetry/flight.py`` and
``telemetry/timeseries.py`` directly by file path (they are pure-stdlib
and import nothing from the package), so validating a bundle in CI never
pays the paddle_tpu/jax import. ``tools/telemetry_report.py --timeline``
reuses :func:`load_timeseries` for the same reason.
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

_TELEMETRY_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "paddle_tpu", "telemetry")


def _load_by_path(name, filename):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_TELEMETRY_DIR, filename))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def load_flight():
    """The flight module, loaded by path (no package import)."""
    return _load_by_path("_ptpu_flight", "flight.py")


def load_timeseries():
    """The timeseries module (shared timeline JSONL reader)."""
    return _load_by_path("_ptpu_timeseries", "timeseries.py")


# ---------------------------------------------------------------------------
# Bundle summaries
# ---------------------------------------------------------------------------
def _fmt_ts(ts):
    try:
        import datetime
        return datetime.datetime.fromtimestamp(ts).strftime(
            "%Y-%m-%d %H:%M:%S")
    except (OverflowError, OSError, ValueError):
        return str(ts)


def summarize(bundle, path=""):
    """Human summary lines for one validated bundle."""
    lines = [f"flight bundle {path or '<dict>'}"]
    lines.append(f"  reason      {bundle['reason']}"
                 f"   pid {bundle['pid']}   seq {bundle.get('seq')}"
                 f"   at {_fmt_ts(bundle['ts'])}")
    ctx = bundle.get("context") or {}
    if ctx:
        kv = ", ".join(f"{k}={v}" for k, v in sorted(ctx.items()))
        lines.append(f"  context     {kv}")
    samples = bundle.get("samples") or []
    lines.append(f"  samples     {len(samples)}"
                 + (f"   ts {samples[0]['ts']:.3f}"
                    f" .. {samples[-1]['ts']:.3f}" if samples else ""))
    alerts = bundle.get("alerts") or []
    lines.append(f"  alerts      {len(alerts)}")
    for a in alerts[-8:]:
        lines.append(f"    {a.get('event', '?'):5s} {a.get('objective')}"
                     f" [{a.get('severity')}] burn="
                     f"{a.get('burn_rate')} value={a.get('value')}")
    events = bundle.get("events") or []
    kinds = {}
    for e in events:
        kinds[e.get("kind", "?")] = kinds.get(e.get("kind", "?"), 0) + 1
    lines.append("  events      " + (", ".join(
        f"{k} x{n}" for k, n in sorted(kinds.items())) or "0"))
    threads = bundle.get("threads") or {}
    lines.append(f"  threads     {len(threads)}: "
                 + ", ".join(sorted(t.split(':')[0] for t in threads)))
    live = bundle.get("live_spans") or bundle.get("trace_spans") or {}
    for tname, stack in sorted(live.items()):
        if stack:
            names = [s.get("name", "?") if isinstance(s, dict) else str(s)
                     for s in stack]
            lines.append(f"  open spans  {tname}: {' > '.join(names)}")
    # legacy hang fields (debris files)
    if "elapsed_seconds" in bundle:
        lines.append(f"  hang        step {bundle.get('step')}: "
                     f"{bundle.get('elapsed_seconds')}s elapsed vs "
                     f"limit {bundle.get('limit_seconds')}s "
                     f"(p50 {bundle.get('p50_step_seconds')})")
    return lines


def _window_stats(bundle):
    return {"samples": len(bundle.get("samples") or []),
            "alerts": len(bundle.get("alerts") or []),
            "events": len(bundle.get("events") or []),
            "trace_events": len(bundle.get("trace_events") or [])}


def diff(old, new):
    """Window-size and alert deltas between two bundles."""
    lines = [f"flight diff: {old['reason']} (seq {old.get('seq')})"
             f" -> {new['reason']} (seq {new.get('seq')}),"
             f" dt {new['ts'] - old['ts']:.3f}s"]
    so, sn = _window_stats(old), _window_stats(new)
    for k in sorted(so):
        lines.append(f"  {k:14s} {so[k]:6d} -> {sn[k]:6d}"
                     f"  ({sn[k] - so[k]:+d})")

    def _alert_keys(b):
        return {(a.get("objective"), a.get("severity"), a.get("event"))
                for a in b.get("alerts") or []}
    fresh = _alert_keys(new) - _alert_keys(old)
    for key in sorted(fresh, key=str):
        lines.append(f"  new alert     {key[2]} {key[0]} [{key[1]}]")
    return lines


def summarize_timeline(path, ts_mod):
    samples = ts_mod.read_timeline(path)
    lines = [f"timeline {path}: {len(samples)} samples"]
    if samples:
        lines.append(f"  ts {samples[0]['ts']:.3f}"
                     f" .. {samples[-1]['ts']:.3f}")
        keys = ts_mod.timeline_keys(samples)
        lines.append(f"  signals ({len(keys)}):")
        for k in keys:
            lines.append(f"    {k}")
    return lines


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="+",
                    help="flight bundle JSON files (or a timeline "
                    "JSONL with --timeline)")
    ap.add_argument("--diff", action="store_true",
                    help="diff exactly two bundles")
    ap.add_argument("--timeline", action="store_true",
                    help="treat paths as timeline JSONL files")
    ap.add_argument("--quiet", action="store_true",
                    help="validate only, print problems only")
    args = ap.parse_args(argv)

    if args.timeline:
        ts_mod = load_timeseries()
        status = 0
        for p in args.paths:
            try:
                for line in summarize_timeline(p, ts_mod):
                    print(line)
            except (OSError, ValueError) as e:
                print(f"MALFORMED {p}: {e}", file=sys.stderr)
                status = 1
        return status

    fl = load_flight()
    bundles = []
    status = 0
    for p in args.paths:
        try:
            bundles.append((p, fl.load_bundle(p)))
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"MALFORMED {p}: {e}", file=sys.stderr)
            status = 1
    if status:
        return status
    if args.diff:
        if len(bundles) != 2:
            print("--diff needs exactly two bundles", file=sys.stderr)
            return 2
        for line in diff(bundles[0][1], bundles[1][1]):
            print(line)
        return 0
    for p, b in bundles:
        if args.quiet:
            print(f"OK {p}")
        else:
            for line in summarize(b, p):
                print(line)
    return 0


if __name__ == "__main__":
    sys.exit(main())
