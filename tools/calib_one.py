import os, sys
sys.path.insert(0, os.getcwd())
from paddle_tpu.distributed.auto_tuner import AutoTuner, TunerCfg
from paddle_tpu.distributed.auto_tuner.measure import build_trial_runner
t = AutoTuner({
    "world_size": 1,
    "model_cfg": dict(hidden_size=2048, num_layers=24,
                      num_attention_heads=16, vocab_size=32000,
                      seq_length=2048, global_batch_size=4,
                      bytes_per_param=2, hbm_gb=15.75, mxu_tflops=197.0,
                      ici_gbps=100.0),
    "max_mp_degree": 1, "max_pp_degree": 1, "tune_recompute": True,
})
run_fn = build_trial_runner(t.model, steps=2)
cfg = TunerCfg(dp=1, mp=1, pp=1, sharding=1, micro_batch=1,
               vpp=1, sharding_stage=1, recompute="full")
m = run_fn(cfg)
print("ok:", float(m), m.details)
