#!/usr/bin/env python
"""Pretty-print a telemetry snapshot, or diff two bench telemetry blocks.

Usage:
    python tools/telemetry_report.py RUN.json
    python tools/telemetry_report.py OLD.json NEW.json [--top N]

Accepts either a raw ``paddle_tpu.telemetry.snapshot()`` dict or a bench
JSON record carrying the snapshot under its ``"telemetry"`` key
(BENCH_r*.json rounds). The diff mode ranks the top-N regressed metrics —
histogram series by mean-time increase, counters by relative growth — so
"why is this round slower" starts from data instead of a re-profile.
"""
from __future__ import annotations

import argparse
import json
import sys


def _is_snapshot(d):
    return isinstance(d, dict) and any(
        k in d for k in ("counters", "gauges", "histograms"))


def _scan_lines(text):
    """LAST JSON-object line carrying telemetry (bench stdout prints log
    lines and, on TPU, TWO metric lines — the headline one is last)."""
    best = None
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            d = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(d, dict) and ("telemetry" in d or _is_snapshot(d)):
            best = d
    return best


def _extract(data):
    """Pull the snapshot out of any of the shapes we meet in the wild:
    a raw snapshot, a bench JSON line ({"metric", ..., "telemetry"}), or
    a BENCH_r*.json round record ({"n", "cmd", "tail", "parsed"})."""
    if not isinstance(data, dict):
        return None
    if _is_snapshot(data):
        return data
    if _is_snapshot(data.get("telemetry")):
        return data["telemetry"]
    parsed = data.get("parsed")
    if isinstance(parsed, dict) and _is_snapshot(parsed.get("telemetry")):
        return parsed["telemetry"]
    tail = data.get("tail")
    if isinstance(tail, str):
        return _extract(_scan_lines(tail))
    return None


def load_snapshot(path):
    with open(path) as f:
        text = f.read()
    try:
        data = json.loads(text)
    except json.JSONDecodeError:
        # stdout capture: log lines + one JSON record per bench model
        data = _scan_lines(text)
        if data is None:
            raise ValueError(f"{path}: no JSON object found")
    snap = _extract(data)
    if snap is None:
        raise ValueError(
            f"{path}: no telemetry snapshot found (expected 'counters'/"
            "'gauges'/'histograms' keys, a bench JSON line with a "
            "'telemetry' block, or a BENCH_r*.json round record)")
    return snap


def _hist_line(name, labels, h):
    lbl = f"{{{labels}}}" if labels else ""
    return (f"  {name}{lbl}: n={h['count']} mean={h['mean']:.6f}s "
            f"p50={h['p50']:.6f} p95={h['p95']:.6f} p99={h['p99']:.6f} "
            f"max={h['max']:.6f}")


def _comms_rows(snap):
    """Aggregate the collective_* families into per-(op, axis) rows with
    the exact-vs-int8 traffic split (docs/COMMS.md). Standalone
    reimplementation of collectives.comms_summary so this tool keeps
    working on a bare snapshot file without importing paddle_tpu."""
    counters = snap.get("counters") or {}
    hists = snap.get("histograms") or {}

    def _parse(labels):
        d = dict(p.split("=", 1) for p in labels.split(",") if "=" in p)
        return f"{d.get('op', '?')}@{d.get('axis', '?')}"

    rows = {}
    for name, field in (("collective_bytes_total", "bytes"),
                        ("collective_calls_total", "calls"),
                        ("collective_quantized_bytes_total", "q8_bytes")):
        for labels, v in (counters.get(name) or {}).items():
            key = _parse(labels)
            rows.setdefault(key, {})[field] = (
                rows.get(key, {}).get(field, 0) + int(v))
    for labels, h in (hists.get("collective_seconds") or {}).items():
        rows.setdefault(_parse(labels), {})["seconds"] = float(
            h.get("sum", 0.0))
    return rows


def print_comms(snap, out=None):
    rows = _comms_rows(snap)
    if not rows:
        return
    w = (out or sys.stdout).write
    w("-- comms (exact vs int8 traffic split) --\n")
    total = sum(r.get("bytes", 0) for r in rows.values())
    qtotal = sum(r.get("q8_bytes", 0) for r in rows.values())
    for key in sorted(rows):
        r = rows[key]
        secs = (f" seconds={r['seconds']:.4f}" if "seconds" in r else "")
        q8 = (f" q8_bytes={r['q8_bytes']}" if r.get("q8_bytes") else "")
        w(f"  {key}: calls={r.get('calls', 0)} bytes={r.get('bytes', 0)}"
          f"{q8}{secs}\n")
    if total:
        w(f"  TOTAL: bytes={total} quantized={qtotal} "
          f"({qtotal / total:.1%} int8, exact={total - qtotal})\n")


def print_zero(snap, out=None):
    """ZeRO traffic section (docs/ZERO.md): gathered-param bytes and
    reduce-scattered grad bytes by (axis, int8-vs-exact)."""
    counters = snap.get("counters") or {}
    rows = []
    for name, label in (("zero3_param_gather_bytes_total", "param_gather"),
                        ("zero3_grad_rs_bytes_total", "grad_rs")):
        for labels, v in sorted((counters.get(name) or {}).items()):
            d = dict(p.split("=", 1) for p in labels.split(",") if "=" in p)
            wire = "int8" if d.get("quantized") == "1" else "exact"
            rows.append(f"  {label}@{d.get('axis', '?')} [{wire}]: "
                        f"bytes={int(v)}")
    if not rows:
        return
    w = (out or sys.stdout).write
    w("-- zero (sharded-state traffic) --\n")
    for r in rows:
        w(r + "\n")


def print_ring(snap, out=None):
    """Ring-attention traffic section (docs/ATTENTION.md): KV block
    bytes rotated around the sep ring per phase (fwd = k+v hops, bwd =
    k+v plus the traveling dk/dv accumulators)."""
    counters = snap.get("counters") or {}
    series = counters.get("ring_attn_kv_bytes_total") or {}
    if not series:
        return
    w = (out or sys.stdout).write
    w("-- ring (sep kv rotation traffic) --\n")
    for labels, v in sorted(series.items()):
        d = dict(p.split("=", 1) for p in labels.split(",") if "=" in p)
        w(f"  ppermute@{d.get('axis', '?')} [{d.get('phase', '?')}]: "
          f"bytes={int(v)}\n")


def print_plans(snap, out=None):
    """Plan-engagement section (docs/COMMS.md lattice): one row per
    (plan, verdict, reason) resolution at step build — a hybrid config
    whose quantized/zero/ring machinery silently declined shows up here
    with its structured reason instead of just running slower."""
    counters = snap.get("counters") or {}
    series = counters.get("plan_engagement_total") or {}
    if not series:
        return
    w = (out or sys.stdout).write
    w("-- plans (engagement verdicts at step build) --\n")
    for labels, v in sorted(series.items()):
        d = dict(p.split("=", 1) for p in labels.split(",") if "=" in p)
        w(f"  {d.get('plan', '?')}: {d.get('verdict', '?')} "
          f"[{d.get('reason', '?')}] x{int(v)}\n")


def print_quant(snap, out=None):
    """Low-precision compute section (docs/QUANT.md): the per-site GEMM
    dtype mode (0=wide, 1=int8, 2=fp8) recorded at trace time, the
    cumulative narrow-GEMM forward FLOPs by dtype, and the serving
    resident-weight footprint by storage dtype."""
    counters = snap.get("counters") or {}
    gauges = snap.get("gauges") or {}
    mode = gauges.get("gemm_dtype_mode") or {}
    flops = counters.get("quant_gemm_flops_total") or {}
    wbytes = gauges.get("serving_weight_bytes") or {}
    if not (mode or flops or wbytes):
        return
    w = (out or sys.stdout).write
    w("-- quant (scaled-GEMM compute) --\n")
    names = {0.0: "wide", 1.0: "int8", 2.0: "fp8"}

    def _d(labels):
        return dict(p.split("=", 1) for p in labels.split(",") if "=" in p)

    for labels, v in sorted(mode.items()):
        d = _d(labels)
        w(f"  gemm[{d.get('site', '?')}]@{d.get('path', '?')}: "
          f"{names.get(float(v), v)}\n")
    for labels, v in sorted(flops.items()):
        d = _d(labels)
        w(f"  narrow_flops[{d.get('dtype', '?')}]: {int(v)}\n")
    for labels, v in sorted(wbytes.items()):
        d = _d(labels)
        w(f"  serving_weight_bytes[{d.get('dtype', '?')}]: {int(v)}\n")


def print_overload(snap, out=None):
    """Overload section (docs/SERVING.md "Overload & degradation"):
    admission rejects by reason/priority, shed counts by reason, breaker
    states/transitions per replica, and the brownout ladder level."""
    counters = snap.get("counters") or {}
    gauges = snap.get("gauges") or {}
    rows = []

    def _d(labels):
        return dict(p.split("=", 1) for p in labels.split(",")
                    if "=" in p)

    for labels, v in sorted((counters.get(
            "serving_admission_rejects_total") or {}).items()):
        d = _d(labels)
        rows.append(f"  reject[{d.get('reason', '?')}] "
                    f"({d.get('priority', '?')}): {int(v)}")
    for labels, v in sorted((counters.get("serving_shed_total")
                             or {}).items()):
        rows.append(f"  shed[{_d(labels).get('reason', '?')}]: {int(v)}")
    for labels, v in sorted((counters.get(
            "serving_breaker_transitions_total") or {}).items()):
        d = _d(labels)
        rows.append(f"  breaker r{d.get('replica', '?')} -> "
                    f"{d.get('to', '?')}: x{int(v)}")
    state_names = {0: "closed", 1: "half_open", 2: "open"}
    for labels, v in sorted((gauges.get("serving_breaker_state")
                             or {}).items()):
        d = _d(labels)
        rows.append(f"  breaker r{d.get('replica', '?')} state: "
                    f"{state_names.get(int(float(v)), v)}")
    for labels, v in sorted((counters.get(
            "serving_brownout_transitions_total") or {}).items()):
        rows.append(f"  brownout step {_d(labels).get('direction', '?')}:"
                    f" x{int(v)}")
    lvl = (gauges.get("serving_brownout_level") or {}).get("")
    if lvl is not None:
        rows.append(f"  brownout level: {int(float(lvl))}")
    if not rows:
        return
    w = (out or sys.stdout).write
    w("-- overload (admission / shedding / breakers / brownout) --\n")
    for r in rows:
        w(r + "\n")


def print_layout(snap, out=None):
    """Layout-autotuner section (docs/AUTOTUNE.md): one row per
    (verdict, reason) over the candidate lattice — ``pruned`` rows never
    paid a lowering (the compose probe declined their mesh shell),
    ``lowered`` rows were AOT-compiled and priced, ``error`` rows failed
    to lower — plus the wall seconds the search spent."""
    counters = snap.get("counters") or {}
    gauges = snap.get("gauges") or {}
    series = counters.get("autotune_candidates_total") or {}
    secs = (gauges.get("autotune_search_seconds") or {}).get("")
    if not series and secs is None:
        return
    w = (out or sys.stdout).write
    w("-- layout (autotune candidate verdicts) --\n")
    for labels, v in sorted(series.items()):
        d = dict(p.split("=", 1) for p in labels.split(",") if "=" in p)
        w(f"  {d.get('verdict', '?')} [{d.get('reason', '?')}]: "
          f"x{int(v)}\n")
    if secs is not None:
        w(f"  search_seconds: {float(secs):.3f}\n")


def print_trace(snap, out=None):
    """Span-tracer section (docs/TELEMETRY.md Tracing): the
    ``trace_span_seconds`` histogram family mirrors every completed
    span's wall time by name while both the tracer and the registry are
    enabled — this is the aggregate view; the timeline lives in the
    trace files (tools/trace_report.py)."""
    series = (snap.get("histograms") or {}).get("trace_span_seconds") or {}
    if not series:
        return
    w = (out or sys.stdout).write
    w("-- trace (span wall seconds by name) --\n")

    def _span_name(labels):
        d = dict(p.split("=", 1) for p in labels.split(",") if "=" in p)
        return d.get("span", labels or "?")

    rows = sorted(series.items(), key=lambda kv: -float(kv[1].get("sum",
                                                                  0.0)))
    for labels, h in rows:
        w(f"  {_span_name(labels)}: n={h['count']} "
          f"total={h.get('sum', 0.0):.6f}s mean={h['mean']:.6f}s "
          f"p99={h['p99']:.6f}\n")


def print_snapshot(snap, out=None):
    out = out or sys.stdout
    w = out.write
    print_trace(snap, out)
    print_layout(snap, out)
    print_plans(snap, out)
    print_comms(snap, out)
    print_zero(snap, out)
    print_ring(snap, out)
    print_quant(snap, out)
    print_overload(snap, out)
    for kind in ("counters", "gauges"):
        group = snap.get(kind) or {}
        if group:
            w(f"-- {kind} --\n")
            for name in sorted(group):
                series = group[name]
                for labels, v in sorted(series.items(),
                                        key=lambda kv: -_num(kv[1])):
                    lbl = f"{{{labels}}}" if labels else ""
                    w(f"  {name}{lbl}: {v}\n")
    hists = snap.get("histograms") or {}
    if hists:
        w("-- histograms --\n")
        for name in sorted(hists):
            for labels, h in sorted(hists[name].items()):
                w(_hist_line(name, labels, h) + "\n")
    dropped = snap.get("dropped_series")
    if dropped:
        w(f"-- dropped series (label-cardinality cap) --\n")
        for name, n in sorted(dropped.items()):
            w(f"  {name}: {n}\n")


def _num(v):
    try:
        return float(v)
    except (TypeError, ValueError):
        return 0.0


def diff_snapshots(old, new, top=15, out=None):
    """Rank series by regression: histogram relative mean growth and
    counter relative growth. Series absent from the old snapshot rank at
    0 (flagged "new series") so they cannot crowd real regressions out
    of the top-N window."""
    out = out or sys.stdout
    rows = []
    old_h = old.get("histograms") or {}
    for name, series in (new.get("histograms") or {}).items():
        for labels, h in series.items():
            prev = (old_h.get(name) or {}).get(labels)
            if not prev or not prev["count"] or not h["count"]:
                continue
            delta = h["mean"] - prev["mean"]
            rel = delta / prev["mean"] if prev["mean"] else 0.0
            rows.append((rel, "hist", name, labels,
                         f"mean {prev['mean']:.6f}s -> {h['mean']:.6f}s "
                         f"({rel:+.1%}), p99 {prev['p99']:.6f} -> "
                         f"{h['p99']:.6f}"))
    old_c = old.get("counters") or {}
    for name, series in (new.get("counters") or {}).items():
        for labels, v in series.items():
            pv = _num((old_c.get(name) or {}).get(labels, 0))
            nv = _num(v)
            if pv == 0 and nv == 0:
                continue
            rel = (nv - pv) / pv if pv else 0.0
            tag = "new series" if pv == 0 else format(rel, "+.1%")
            rows.append((rel, "counter", name, labels,
                         f"{pv:g} -> {nv:g} ({tag})"))
    rows.sort(key=lambda r: -r[0])
    out.write(f"top {top} regressed metrics (new vs old):\n")
    for rel, kind, name, labels, desc in rows[:top]:
        lbl = f"{{{labels}}}" if labels else ""
        out.write(f"  [{kind}] {name}{lbl}: {desc}\n")
    if not rows:
        out.write("  (no comparable series)\n")
    return rows


def _timeseries_mod():
    """The shared timeline JSONL reader, via tools/flight_report.py's
    by-path loader (no paddle_tpu/jax import — same discipline as the
    rest of this tool)."""
    try:
        from tools import flight_report
    except ImportError:
        import flight_report
    return flight_report.load_timeseries()


def print_timeline(path, top=15):
    """Per-metric delta/rate table between consecutive timeline samples:
    for every counter, the total delta across the file and the mean/max
    per-second rate; for every values/gauges signal, min/mean/max/last.
    """
    ts_mod = _timeseries_mod()
    samples = ts_mod.read_timeline(path)
    print(f"timeline {path}: {len(samples)} samples"
          + (f", ts {samples[0]['ts']:.3f} .. {samples[-1]['ts']:.3f}"
             if samples else ""))
    if not samples:
        return
    counter_keys = ts_mod.timeline_keys(samples, group="counters")
    rows = []
    for k in counter_keys:
        deltas = ts_mod.series_from(samples, f"counters:{k}:delta")
        rates = ts_mod.series_from(samples, f"counters:{k}:rate")
        if not deltas:
            continue
        total = sum(v for _, v in deltas)
        rvals = [v for _, v in rates]
        rows.append((k, total, sum(rvals) / len(rvals) if rvals else 0.0,
                     max(rvals) if rvals else 0.0))
    rows.sort(key=lambda r: -abs(r[1]))
    if rows:
        print(f"\n  {'counter':44s} {'delta':>12s} {'rate/s mean':>12s}"
              f" {'rate/s max':>12s}")
        for k, total, mean_r, max_r in rows[:top]:
            print(f"  {k[:44]:44s} {total:12.6g} {mean_r:12.6g}"
                  f" {max_r:12.6g}")
    for group in ("values", "gauges"):
        keys = ts_mod.timeline_keys(samples, group=group)
        rows = []
        for k in keys:
            vals = [v for _, v in ts_mod.series_from(samples,
                                                     f"{group}:{k}")]
            if vals:
                rows.append((k, min(vals), sum(vals) / len(vals),
                             max(vals), vals[-1]))
        if rows:
            print(f"\n  {group + ':':44s} {'min':>10s} {'mean':>10s}"
                  f" {'max':>10s} {'last':>10s}")
            for k, lo, mean, hi, last in rows[:top * 2]:
                print(f"  {k[:44]:44s} {lo:10.4g} {mean:10.4g}"
                      f" {hi:10.4g} {last:10.4g}")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("snapshot", help="telemetry snapshot or bench JSON "
                    "(a timeline JSONL with --timeline)")
    ap.add_argument("other", nargs="?",
                    help="second snapshot: diff mode (old=first, new=second)")
    ap.add_argument("--top", type=int, default=15,
                    help="diff mode: how many regressed metrics to show")
    ap.add_argument("--timeline", action="store_true",
                    help="the input is a timeline JSONL (recorded by "
                    "TimeSeriesRecorder / a soak / bench.py --record): "
                    "print per-metric delta/rate columns between "
                    "consecutive samples")
    args = ap.parse_args(argv)
    if args.timeline:
        print_timeline(args.snapshot, top=args.top)
    elif args.other is None:
        print_snapshot(load_snapshot(args.snapshot))
    else:
        diff_snapshots(load_snapshot(args.snapshot),
                       load_snapshot(args.other), top=args.top)
    return 0


if __name__ == "__main__":
    sys.exit(main())
