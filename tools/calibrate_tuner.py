"""Calibrate the auto_tuner's time/memory models at BENCH scale on the
real chip (VERDICT r3 item 10): run measure() over the top single-chip
configs of the GPT-3 1.3B bench model and record predicted-vs-measured in
docs/TUNER_CALIBRATION.md. Run from /root/repo (axon platform pinned by
sitecustomize); takes a few minutes of chip time (one compile per config).
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.getcwd())  # run as `python tools/calibrate_tuner.py`
                                 # from /root/repo (axon needs that cwd;
                                 # PYTHONPATH breaks the sitecustomize)


def main():
    import jax

    from paddle_tpu.distributed.auto_tuner import AutoTuner

    kind = jax.devices()[0].device_kind.lower()
    on_tpu = jax.default_backend() not in ("cpu",)
    tflops = (197.0 if on_tpu else 0.05)
    hbm = (15.75 if on_tpu else 64.0)

    t = AutoTuner({
        "world_size": 1,
        "model_cfg": dict(
            hidden_size=2048, num_layers=24, num_attention_heads=16,
            vocab_size=32000, seq_length=2048, global_batch_size=4,
            bytes_per_param=2, hbm_gb=hbm, mxu_tflops=tflops,
            ici_gbps=100.0),
        "max_mp_degree": 1,
        "max_pp_degree": 1,
        "tune_recompute": True,   # nothing single-chip fits without remat
    })
    best, ranked = t.measure(top_k=3, steps=3)
    rows = []
    for r in t.calibration:
        c = r["cfg"]
        rows.append({
            "cfg": f"dp{c.dp}/mp{c.mp}/pp{c.pp}/shard{c.sharding}"
                   f"/mbs{c.micro_batch}/rc:{c.recompute}",
            "predicted_ms": round(r["predicted_ms"], 1),
            "measured_ms": round(r.get("measured_ms", float("nan")), 1),
            "time_ratio": round(r.get("time_ratio", float("nan")), 2),
            "predicted_gb": round(r["predicted_gb"], 2),
            "measured_gb": round(r.get("measured_gb", float("nan")), 2),
            "memory_ratio": round(r.get("memory_ratio", float("nan")), 2),
            "tokens_per_sec": round(r["tokens_per_sec"], 0),
        })
    print(json.dumps(rows, indent=1))
    dev = kind if on_tpu else "cpu"
    lines = [
        "# auto_tuner calibration at bench scale (round 4)",
        "",
        f"`tools/calibrate_tuner.py` on ONE real chip ({dev}): "
        "`AutoTuner.measure()` over the top single-chip configs of the "
        "GPT-3 1.3B bench model (BASELINE.md config 4), 3 timed steps "
        "each. VERDICT r3 item 10: the 2x memory-model bound had only "
        "been checked at toy scale on the CPU mesh.",
        "",
        "| cfg | pred ms | meas ms | t-ratio | pred GB | meas GB "
        "| m-ratio | tok/s |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        lines.append(
            f"| {r['cfg']} | {r['predicted_ms']} | {r['measured_ms']} "
            f"| {r['time_ratio']} | {r['predicted_gb']} "
            f"| {r['measured_gb']} | {r['memory_ratio']} "
            f"| {r['tokens_per_sec']} |")
    lines += [
        "",
        "Bound check: time_ratio and memory_ratio must sit in [0.5, 2.0] "
        "for the static models to stay trustworthy rankers; rows outside "
        "the bound are a model bug to fix, not a footnote.",
        "",
    ]
    with open("docs/TUNER_CALIBRATION.md", "w") as f:
        f.write("\n".join(lines))
    print("wrote docs/TUNER_CALIBRATION.md")
    bad = [r for r in rows
           if not (0.5 <= r["time_ratio"] <= 2.0
                   and 0.5 <= r["memory_ratio"] <= 2.0)]
    if bad:
        print("OUT OF BOUND:", json.dumps(bad, indent=1))


if __name__ == "__main__":
    main()
