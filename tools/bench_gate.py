"""bench_gate: fail CI on a tokens/sec regression between bench rounds.

Usage::

    python tools/bench_gate.py                 # newest BENCH_r*.json vs
                                               # the previous round
    python tools/bench_gate.py NEW.json        # explicit candidate
    python tools/bench_gate.py NEW.json --against OLD.json [OLD2.json ...]
    python tools/bench_gate.py --threshold 0.08   # allow 8%

Accepts every bench artifact shape this repo produces:

- raw ``bench.py`` stdout (one JSON object per line, log lines ignored),
- driver round files ``BENCH_r*.json`` (``{"tail": "...", "parsed":
  ...}`` — metric lines are re-parsed out of ``tail``),
- a bare ``{"metric": ..., "value": ...}`` object or a list of them.

For every metric name shared between the candidate and a reference file,
the gate compares ``value`` (tokens/sec/chip) and **exits 1 if the
candidate is more than ``--threshold`` (default 5%) below the
reference**. Metrics present on only one side are reported but don't
gate (a new bench line must not fail the round that introduces it).
``mfu`` is printed alongside when present. BASELINE.json carries no
absolute numbers (the reference publishes none) — it is accepted and
skipped with a note, so ``--against BASELINE.json BENCH_rNN.json`` works
as a documented CI line.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys


def _records_from_obj(obj):
    if isinstance(obj, list):
        out = []
        for o in obj:
            out.extend(_records_from_obj(o))
        return out
    if not isinstance(obj, dict):
        return []
    recs = []
    if "tail" in obj and isinstance(obj["tail"], str):
        recs.extend(_records_from_text(obj["tail"]))
    if not recs and isinstance(obj.get("parsed"), dict):
        recs.extend(_records_from_obj(obj["parsed"]))
    if "metric" in obj and "value" in obj:
        recs.append(obj)
    return recs


def _records_from_text(text):
    recs = []
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict) and "metric" in obj and "value" in obj:
            recs.append(obj)
    return recs


def load_metrics(path):
    """path -> {metric: record} (last line per metric wins, like the
    driver's parse). Records without a numeric value are dropped."""
    with open(path) as f:
        text = f.read()
    try:
        recs = _records_from_obj(json.loads(text))
    except ValueError:
        recs = _records_from_text(text)
    out = {}
    for r in recs:
        try:
            float(r["value"])
        except (TypeError, ValueError):
            continue
        out[str(r["metric"])] = r
    return out


def _round_files(root):
    files = glob.glob(os.path.join(root, "BENCH_r*.json"))

    def key(p):
        m = re.search(r"BENCH_r(\d+)\.json$", p)
        return int(m.group(1)) if m else -1

    return sorted((p for p in files if key(p) >= 0), key=key)


def resilience_violations(rec):
    """Violation strings from one bench record's "resilience" block and
    its telemetry guard counters. A CLEAN bench run (no chaos injection)
    must report zero anomalies and zero rollbacks — any other value
    means the hardware/numerics misbehaved during the measurement or the
    guard false-positived; either way the round must not land silently
    (docs/RESILIENCE.md)."""
    out = []
    res = rec.get("resilience")
    if isinstance(res, dict) and res.get("enabled"):
        anomalies = res.get("anomalies") or {}
        total = res.get("anomalies_total")
        if total is None:
            total = sum(int(v) for v in anomalies.values())
        if int(total) > 0:
            out.append(f"guard_anomalies_total={total} ({anomalies})")
        if int(res.get("rollbacks") or 0) > 0:
            out.append(f"guard rollbacks={res['rollbacks']}")
        if res.get("aborted"):
            out.append("guard ABORTED the run")
        # the per-guard block is authoritative for this record; the
        # process-global telemetry counters describe the SAME events
        # (shared across every metric line) — reporting both would
        # print one anomaly up to once per source per line
        return out
    counters = (rec.get("telemetry") or {}).get("counters") or {}
    for name in ("guard_anomalies_total", "guard_rollbacks_total"):
        series = counters.get(name) or {}
        total = (sum(series.values()) if isinstance(series, dict)
                 else int(series))
        if total:
            out.append(f"telemetry {name}={total}")
    return out


def comms_violations(rec):
    """Violation strings from one bench record's "comms" block: a
    quantized run whose loss-parity probe drifted past its threshold
    must not land silently — either the quantizer regressed or the
    gradients stopped being block-quantizable (docs/COMMS.md)."""
    out = []
    comms = rec.get("comms")
    if not isinstance(comms, dict):
        return out
    parity = comms.get("parity")
    if isinstance(parity, dict) and parity.get("enabled"):
        err = parity.get("max_rel_err")
        thr = parity.get("threshold")
        if err is not None and thr is not None and float(err) > float(thr):
            out.append(
                f"quantized-collective parity drift {float(err):.4f} > "
                f"threshold {float(thr):.4f}")
        elif parity.get("ok") is False:
            out.append("quantized-collective parity probe reported ok=false")
    return out


def ring_violations(rec):
    """Violation strings from one bench record's "ring" block
    (docs/ATTENTION.md; the ``*_seq32k`` long-context lines): a run
    whose ring-vs-dense parity probe drifted past its embedded
    threshold must not land silently — a hop mask or merge regression
    is a numerics bug, not noise. Reference-free, like the comms parity
    gate; a sep-mesh run whose plan unexpectedly declined (enabled but
    never engaged) also fails — the line would silently measure the
    batch-axis fallback instead of the ring."""
    block = rec.get("ring") if isinstance(rec, dict) else None
    if not isinstance(block, dict) or not block.get("enabled"):
        return []
    out = []
    parity = block.get("parity")
    if isinstance(parity, dict) and parity.get("enabled"):
        err = parity.get("max_rel_err")
        thr = parity.get("threshold")
        if err is not None and thr is not None and float(err) > float(thr):
            out.append(f"ring-attention parity drift {float(err):.2e} > "
                       f"threshold {float(thr):.2e}")
        elif parity.get("ok") is False:
            out.append("ring-attention parity probe reported ok=false")
    if block.get("engaged") is False:
        out.append("ring plan built but never engaged — the long-context "
                   "line measured the batch-axis fallback")
    return out


#: decline reasons that describe the CONFIG's shape, not a silent
#: downgrade — documented fallbacks (docs/PIPELINE.md) a user may run on
#: purpose, so the never-engaged check reports them in the JSON but does
#: not hard-fail the round. Everything else (checkify, frozen shards,
#: optimizer stats, missing reason...) still fails: the line claims a
#: pipeline it silently is not measuring.
PIPE_CONFIG_DECLINES = frozenset({
    "no_stage_placements",          # pp axis live, decoder not staged
    "interleave_not_composed",      # VPP layout (Reason.INTERLEAVE)
    "layers_indivisible_by_pp",     # Reason.LAYERS_INDIVISIBLE
})


def pipe_violations(rec):
    """Reference-free violation strings from one record's "pipe" block
    (docs/PIPELINE.md): the engaged schedule's measured-cost bubble
    fraction must stay within the plain-1F1B budget
    (pp−1)/(n_micro+pp−1) — a fraction past it means the schedule
    arithmetic or the per-phase cost split regressed, not noise (5%
    relative + 0.02 absolute headroom for timing jitter). A pp-live
    mesh whose composition never engaged also fails — the line would
    silently measure the GSPMD fallback while claiming a pipeline —
    unless the recorded decline reason is one of the documented
    config-shape fallbacks (:data:`PIPE_CONFIG_DECLINES`) or an
    explicit escape-hatch knob."""
    block = rec.get("pipe") if isinstance(rec, dict) else None
    if not isinstance(block, dict) or "bubble_fraction" not in block:
        return []
    out = []
    frac = block.get("bubble_fraction")
    budget = block.get("bubble_budget_1f1b")
    if frac is not None and budget is not None:
        if float(frac) > float(budget) * 1.05 + 0.02:
            out.append(
                f"pipeline bubble fraction {float(frac):.3f} over the "
                f"1F1B budget {float(budget):.3f} "
                f"(schedule={block.get('schedule')}, "
                f"pp={block.get('pp')}, n_micro={block.get('n_micro')})")
    if (block.get("pp_axis_live") and not block.get("engaged")
            and not block.get("disabled_by_knob")
            and block.get("decline_reason") not in PIPE_CONFIG_DECLINES):
        # an explicit escape-hatch knob (disabled_by_knob) is an
        # intended A/B baseline, and a config-shape decline is a
        # documented fallback — only a silent decline fails
        out.append("pp axis live but the composed pipeline never "
                   "engaged — the line measured the GSPMD fallback "
                   f"(decline_reason={block.get('decline_reason')!r}; "
                   "see the plan_engagement telemetry)")
    if (block.get("schedule") == "zb"
            and block.get("zb_beats_1f1b") is False):
        out.append("zero-bubble schedule engaged but its measured-cost "
                   "bubble fraction does not beat plain 1F1B")
    return out


def layout_violations(rec):
    """Reference-free violation strings from one record's "layout" block
    (docs/AUTOTUNE.md): an --autotune round must not ship a layout whose
    PREDICTED score loses to the hand-picked baseline's predicted score
    at equal chips — the baseline is searched through the same cost
    model, so by construction the winner can only lose to it when the
    search silently misranked or fell back. A fallback (no searched
    candidate fit) is a legitimate outcome ONLY when it carries its
    structured reason; a silent one would measure the hand config while
    claiming a search."""
    block = rec.get("layout") if isinstance(rec, dict) else None
    if not isinstance(block, dict) or not block.get("label"):
        return []  # {"enabled": false} or absent: not an autotuned line
    out = []
    base = block.get("baseline")
    score = block.get("predicted_score")
    if (isinstance(base, dict) and base.get("fits")
            and score is not None
            and base.get("predicted_tokens_per_sec") is not None
            and float(score)
            < float(base["predicted_tokens_per_sec"]) * (1 - 1e-9)):
        out.append(
            f"autotuned layout {block.get('label')!r} predicted "
            f"{float(score):.1f} tokens/sec loses to the hand-picked "
            f"baseline {base.get('label')!r} at "
            f"{float(base['predicted_tokens_per_sec']):.1f} on the same "
            f"{block.get('device_count')} chips — the searched winner "
            "must beat (or be) every scored candidate")
    if block.get("source") == "fallback" and not block.get(
            "fallback_reason"):
        out.append(
            "layout search fell back to the hand-picked config without "
            "a structured fallback_reason — silent fallbacks would "
            "measure the baseline while claiming a search")
    return out


#: quant decline reasons that describe a DOCUMENTED fallback
#: (docs/QUANT.md): the parity gate / CPU default-off (loud, warned), or
#: a precedence rule ceding the GEMM to an owner kernel/region. A
#: requested run declining for any other (or no) recorded reason fails —
#: the line would silently measure wide GEMMs while claiming quant.
QUANT_CONFIG_DECLINES = frozenset({
    "quant_parity_gate",        # gate red / CPU default-off
    "tp_seam_owns_gemm",        # fused tp seams own the projections
    "fused_kernel_owns_gemm",   # swiglu_down megakernel owns wd
    "pipeline_stage_fn",        # pipeline stage fns: no amax threading
    "composed_region",          # manual composed region owns the math
})


def quant_violations(rec):
    """Reference-free violation strings from one record's "quant" block
    (docs/QUANT.md): the numeric parity-gate report must be green (a red
    gate that still ENGAGED means someone forced past drifted numerics),
    the embedded exact-vs-scaled loss-drift A/B must stay inside its
    0.5% budget, and a requested mode that never engaged must carry a
    documented decline reason — the int8-head gate discipline applied to
    the scaled-GEMM compute mode."""
    block = rec.get("quant") if isinstance(rec, dict) else None
    if not isinstance(block, dict):
        return []
    out = []
    gate = block.get("gate")
    if isinstance(gate, dict) and gate.get("ok") is False:
        out.append(
            "quant parity gate red (loss_rel_err="
            f"{gate.get('loss_rel_err')}, tol={gate.get('tol')}, "
            f"grad_rel_err={gate.get('grad_rel_err')}, "
            f"grad_tol={gate.get('grad_tol')})"
            + (" yet the run ENGAGED scaled GEMMs — forced past a "
               "failing probe" if block.get("engaged") else ""))
    drift = block.get("loss_drift_rel")
    budget = block.get("loss_drift_budget")
    if drift is not None and budget is not None \
            and float(drift) > float(budget):
        out.append(
            f"quant loss drift {float(drift):.4f} > budget "
            f"{float(budget):.4f} vs the embedded exact A/B "
            "(quant.loss_drift_probe)")
    if (block.get("requested") and not block.get("engaged")
            and block.get("reason") not in QUANT_CONFIG_DECLINES):
        out.append(
            "quant compute requested but never engaged "
            f"(decline_reason={block.get('reason')!r}; see the "
            "plan_engagement telemetry)")
    return out


def host_overhead_violations(rec, threshold=0.25):
    """Violation strings from one bench record's "anatomy" block: a
    traced run whose host gap (measured step wall − cost-analysis
    device estimate) exceeds ``threshold`` as a fraction of step time
    is dispatch-bound, not device-bound — the step got slower for a
    reason no kernel profile will show (docs/TELEMETRY.md Tracing).
    Reference-free, like the comms parity gate. Runs without --trace
    ({"enabled": false}) and runs whose roofline peaks are placeholders
    (host_gap_fraction null, e.g. CPU dev) are not gated."""
    anat = rec.get("anatomy") if isinstance(rec, dict) else None
    if not isinstance(anat, dict) or not anat.get("enabled"):
        return []
    frac = (anat.get("device") or {}).get("host_gap_fraction")
    if frac is None:
        return []
    out = []
    if float(frac) > float(threshold):
        gap = (anat.get("device") or {}).get("host_gap_seconds_per_step")
        out.append(
            f"host gap {float(frac):.1%} of step time > threshold "
            f"{float(threshold):.0%}"
            + (f" ({gap}s/step)" if gap is not None else ""))
    return out


def serving_violations(rec):
    """Reference-free violation strings from one record's "serving"
    block (docs/SERVING.md; emitted by tools/serve_bench.py and
    ``bench.py --serve``): the p99-TTFT bound and the goodput-scaling
    target gate only when the block carries their bound — the soak run
    embeds what it was asked to guarantee, like the comms parity block
    embeds its threshold. A soak that lost requests (completed +
    cancelled < submitted) also fails: silent drops are not goodput."""
    block = rec.get("serving") if isinstance(rec, dict) else None
    if not isinstance(block, dict) or not block.get("enabled"):
        return []
    out = []
    p99 = (block.get("ttft") or {}).get("p99")
    if p99 is None:
        p99 = block.get("p99_ttft_seconds")
    budget = block.get("p99_ttft_budget")
    if p99 is not None and budget is not None and float(p99) > float(budget):
        out.append(f"p99 TTFT {float(p99):.4f}s > budget "
                   f"{float(budget):.4f}s")
    x = block.get("goodput_x_single")
    target = block.get("scaling_target")
    if x is not None and target is not None and float(x) < float(target):
        out.append(
            f"goodput scaling {float(x):.2f}x single < target "
            f"{float(target):.2f}x at {block.get('replicas')} replicas")
    reqs = block.get("requests")
    done = block.get("completed")
    cancelled = block.get("cancelled") or 0
    shed = block.get("shed") or 0
    rejected = block.get("rejected") or 0
    if reqs is not None and done is not None and (
            int(done) + int(cancelled) + int(shed) + int(rejected)
            < int(reqs)):
        out.append(f"soak lost requests: {done} completed + {cancelled} "
                   f"cancelled + {shed} shed + {rejected} rejected "
                   f"< {reqs} submitted")
    return out


def slo_violations(rec):
    """Reference-free SLO gate over a CLEAN soak's embedded ``"slo"``
    block (the "serving" block only — an "overload" block runs past
    capacity by design and its alerts are the scenario working): a
    clean soak whose live SLO engine fired any fast-burn alert fails
    the round, same discipline as the guard/OVERLOAD gates. An alert
    still ACTIVE at the end of the run (it never cleared during the
    cool-down) fails at any severity — the condition outlived its
    cause."""
    block = rec.get("serving") if isinstance(rec, dict) else None
    if not isinstance(block, dict) or not block.get("enabled"):
        return []
    slo = block.get("slo")
    if not isinstance(slo, dict) or not slo.get("enabled"):
        return []
    out = []
    fast = int(slo.get("fast_burn_alerts") or 0)
    if fast > 0:
        names = sorted({e.get("objective") for e in slo.get("events") or []
                        if e.get("severity") == "fast_burn"
                        and e.get("event") == "fire"})
        out.append(f"{fast} fast-burn SLO alert(s) fired during a clean "
                   f"soak ({', '.join(n for n in names if n) or '?'})")
    active = slo.get("active") or []
    if active:
        out.append("SLO alert(s) still active at soak end: "
                   + ", ".join(str(a) for a in active))
    return out


def overload_violations(rec):
    """Reference-free violation strings from one record's "overload"
    block (docs/SERVING.md "Overload & degradation"; emitted by
    ``tools/serve_bench.py --overload``). The block embeds every budget
    it was asked to guarantee, like the serving/comms blocks:

    - ``conserved`` false = a submitted request reached no terminal
      outcome (served | cancelled | shed | rejected) — a lost or hung
      request, the hard floor;
    - p99 TTFT of ADMITTED requests over ``p99_ttft_budget`` — admission
      control exists precisely so admitted requests keep their SLO
      under 2x-capacity pressure;
    - ``shed_fraction`` over ``shed_ceiling`` — refusing a bounded
      slice of overload traffic is the design, refusing most of it is a
      regression;
    - ``breaker_opens`` over ``breaker_flap_bound`` — a flapping
      replica must cost a bounded number of breaker flaps;
    - a brownout ladder that did not restore (``restored`` false) —
      degradation must be reversible once pressure clears."""
    block = rec.get("overload") if isinstance(rec, dict) else None
    if not isinstance(block, dict) or not block.get("enabled"):
        return []
    out = []
    if block.get("conserved") is False:
        n = (int(block.get("submitted") or 0)
             - int(block.get("served") or 0)
             - int(block.get("cancelled") or 0)
             - int(block.get("shed") or 0)
             - int(block.get("rejected") or 0))
        out.append(f"outcome conservation broken: {n} of "
                   f"{block.get('submitted')} requests reached no "
                   "terminal outcome (lost or hung)")
    p99 = block.get("p99_ttft_seconds")
    budget = block.get("p99_ttft_budget")
    if p99 is not None and budget is not None and float(p99) > float(budget):
        out.append(f"admitted p99 TTFT {float(p99):.4f}s > budget "
                   f"{float(budget):.4f}s under overload")
    frac = block.get("shed_fraction")
    ceil = block.get("shed_ceiling")
    if frac is not None and ceil is not None and float(frac) > float(ceil):
        out.append(f"shed+rejected fraction {float(frac):.2f} > ceiling "
                   f"{float(ceil):.2f}")
    opens = block.get("breaker_opens")
    bound = block.get("breaker_flap_bound")
    if opens is not None and bound is not None and int(opens) > int(bound):
        out.append(f"breaker flap count {int(opens)} > bound "
                   f"{int(bound)}")
    brown = block.get("brownout") or {}
    if brown and brown.get("restored") is False:
        out.append(f"brownout ladder not restored after the run "
                   f"(level still {brown.get('level')})")
    return out


def upgrade_violations(rec):
    """Reference-free violation strings from one record's "upgrade"
    block (docs/SERVING.md "Process topology"; emitted by
    ``tools/serve_bench.py --procs N``): the multi-process fleet soak
    with a SIGKILLed replica, chaos-injected link faults, and a rolling
    weight upgrade mid-traffic. The invariants are absolute:

    - ``conserved`` false / ``lost_requests`` > 0 — a request lost or
      hung across kills, migrations, and reloads is the hard floor;
    - ``duplicate_stream_tokens`` / ``lost_stream_tokens`` > 0 — every
      generated token must reach its stream callback exactly once,
      counted at an independent seam from the router's suppression;
    - an upgrade that never completed (``upgrade.complete`` false) —
      the rollout must finish while the fleet keeps serving;
    - inside the upgrade *window* (both gates engage only when their
      budget is embedded in the block): goodput fraction under
      ``goodput_floor_fraction`` while work was actually outstanding,
      or worst recent-p99 TTFT over ``p99_ttft_budget``."""
    block = rec.get("upgrade") if isinstance(rec, dict) else None
    if not isinstance(block, dict) or not block.get("enabled"):
        return []
    out = []
    if block.get("conserved") is False:
        out.append(f"outcome conservation broken across the fleet "
                   f"scenario ({block.get('submitted')} submitted, "
                   f"{block.get('served')} served)")
    lost = int(block.get("lost_requests") or 0)
    if lost > 0:
        out.append(f"{lost} request(s) lost (no terminal outcome) "
                   "through kill/migration/upgrade")
    dup = int(block.get("duplicate_stream_tokens") or 0)
    if dup > 0:
        out.append(f"{dup} stream token(s) delivered more than once "
                   "(exactly-once replay broken)")
    missing = int(block.get("lost_stream_tokens") or 0)
    if missing > 0:
        out.append(f"{missing} generated token(s) never delivered to "
                   "their stream callback")
    up = block.get("upgrade") or {}
    if up and not up.get("complete"):
        out.append(f"rolling upgrade to version {up.get('version')} "
                   f"did not complete (stalled after "
                   f"{up.get('upgraded_replicas')})")
    win = block.get("window") or {}
    frac = win.get("goodput_fraction")
    floor = win.get("goodput_floor_fraction")
    if (frac is not None and floor is not None
            and int(win.get("peak_outstanding") or 0) > 0
            and float(frac) < float(floor)):
        out.append(f"goodput inside the upgrade window fell to "
                   f"{float(frac):.3f}x of the whole run "
                   f"(< floor {float(floor):.3f}x) with "
                   f"{win.get('peak_outstanding')} requests outstanding")
    p99 = win.get("p99_ttft_seconds")
    budget = win.get("p99_ttft_budget")
    if p99 is not None and budget is not None \
            and float(p99) > float(budget):
        out.append(f"p99 TTFT {float(p99):.4f}s inside the upgrade "
                   f"window > budget {float(budget):.4f}s")
    return out


def partition_violations(rec):
    """Reference-free violation strings from one record's "partition"
    block (docs/SERVING.md "Cross-host topology"; emitted by
    ``tools/serve_bench.py --hosts N``): the cross-host fleet soak with
    a whole host partitioned away mid-traffic, its replicas fenced and
    their work replayed, then the partition healed. The invariants are
    absolute:

    - ``conserved`` false / ``lost_requests`` > 0 — a severed host must
      not lose or hang a single request;
    - ``duplicate_stream_tokens`` > 0 — the fencing epochs guarantee no
      rid is ever served by two replicas, so the independent callback
      seam must count zero duplicate deliveries (a duplicate here means
      a stale lease's tokens leaked past the fence);
    - ``lost_stream_tokens`` > 0 — exactly-once is not at-most-once;
    - ``fleet_live_at_drain`` false — replay + respawn must reconverge
      the fleet to target size;
    - ``partition.healed`` false with a surviving agent — a healed
      network must return the host to service (with ``agent_killed``
      the host legitimately stays severed and is not gated);
    - an overlapped rolling upgrade that never completed."""
    block = rec.get("partition") if isinstance(rec, dict) else None
    if not isinstance(block, dict) or not block.get("enabled"):
        return []
    out = []
    if block.get("conserved") is False:
        out.append(f"outcome conservation broken across the host "
                   f"partition ({block.get('submitted')} submitted, "
                   f"{block.get('served')} served)")
    lost = int(block.get("lost_requests") or 0)
    if lost > 0:
        out.append(f"{lost} request(s) lost (no terminal outcome) "
                   "through the host partition")
    dup = int(block.get("duplicate_stream_tokens") or 0)
    if dup > 0:
        out.append(f"{dup} stream token(s) delivered more than once "
                   "(a stale lease leaked past the fencing epoch)")
    missing = int(block.get("lost_stream_tokens") or 0)
    if missing > 0:
        out.append(f"{missing} generated token(s) never delivered to "
                   "their stream callback")
    if block.get("fleet_live_at_drain") is False:
        out.append("fleet below target size after the run settled "
                   "(replay/respawn did not reconverge)")
    part = block.get("partition") or {}
    if part.get("healed") is False and not part.get("agent_killed"):
        out.append(f"host {part.get('host')} never returned to service "
                   "after the partition healed")
    up = block.get("upgrade") or {}
    if up and not up.get("complete"):
        out.append(f"rolling upgrade to version {up.get('version')} "
                   "overlapping the partition did not complete")
    return out


def cold_start_violations(rec, ref_rec, threshold=0.25):
    """Referenced gate on the serving block's replica cold start
    (engine construction + program compile, ``warmup()``): must not
    regress more than ``threshold`` vs the reference round at the SAME
    scan-over-layers mode — the depth-flat serving compile guarantee
    (docs/SERVING.md). Sub-second references are noise and skipped."""
    new_b = rec.get("serving") if isinstance(rec, dict) else None
    old_b = ref_rec.get("serving") if isinstance(ref_rec, dict) else None
    if not isinstance(new_b, dict) or not isinstance(old_b, dict):
        return []
    if bool(new_b.get("scan_layers")) != bool(old_b.get("scan_layers")):
        return []
    try:
        old = float(old_b.get("cold_start_seconds"))
        new = float(new_b.get("cold_start_seconds"))
    except (TypeError, ValueError):
        return []
    if old < 1.0:
        return []
    out = []
    if new > old * (1.0 + threshold):
        out.append(
            f"replica cold start {new:.1f}s > {1.0 + threshold:.2f}x "
            f"reference {old:.1f}s "
            f"(scan_layers={bool(new_b.get('scan_layers'))})")
    return out


def mfu_violations(rec, ref_rec, threshold):
    """Violation strings comparing one metric's ``mfu`` field against the
    reference round's (docs/ZERO.md satellite: the stage-3 config-5 line
    is gated on MFU, not only tokens/sec — a sharding regression that
    trades tokens/sec for a quietly shrunken effective batch shows up
    here). Gated for every metric that carries mfu on both sides."""
    new = rec.get("mfu") if isinstance(rec, dict) else None
    old = ref_rec.get("mfu") if isinstance(ref_rec, dict) else None
    try:
        new, old = float(new), float(old)
    except (TypeError, ValueError):
        return []
    if old <= 0:
        return []
    out = []
    if new < old * (1.0 - threshold):
        out.append(f"mfu {new} < {1.0 - threshold:.2f}x reference {old} "
                   f"({(new / old - 1) * 100:+.1f}%)")
    return out


def compile_violations(rec, ref_rec, threshold=0.25):
    """Violation strings comparing one metric's "compile" block against
    the reference round's (docs/SCAN.md): total build wall time
    (trace + lower + compile) must not regress more than ``threshold``
    at the SAME depth and scan mode — the scan-over-layers flat-compile
    guarantee, gated instead of eyeballed. Blocks are only comparable
    when depth/mode match (a depth change legitimately changes compile
    cost); sub-second references are noise-dominated and skipped."""
    new_c = rec.get("compile") if isinstance(rec, dict) else None
    old_c = ref_rec.get("compile") if isinstance(ref_rec, dict) else None
    if not isinstance(new_c, dict) or not isinstance(old_c, dict):
        return []
    if new_c.get("num_layers") != old_c.get("num_layers"):
        return []
    if bool(new_c.get("scan_layers")) != bool(old_c.get("scan_layers")):
        return []

    def total(c):
        return sum(float(c.get(k) or 0.0)
                   for k in ("trace_seconds", "lower_seconds",
                             "compile_seconds"))

    old_t, new_t = total(old_c), total(new_c)
    if old_t < 1.0:
        return []
    out = []
    if new_t > old_t * (1.0 + threshold):
        out.append(
            f"compile time {new_t:.1f}s > {1.0 + threshold:.2f}x reference "
            f"{old_t:.1f}s at depth {new_c.get('num_layers')} "
            f"(scan_layers={bool(new_c.get('scan_layers'))})")
    return out


def compare(new_metrics, ref_metrics, threshold):
    """-> (rows, regressions). Each row: (metric, old, new, ratio|None)."""
    rows, regressions = [], []
    for metric, rec in sorted(new_metrics.items()):
        ref = ref_metrics.get(metric)
        if ref is None:
            rows.append((metric, None, float(rec["value"]), None))
            continue
        old, new = float(ref["value"]), float(rec["value"])
        ratio = new / old if old else float("inf")
        rows.append((metric, old, new, ratio))
        if old > 0 and ratio < 1.0 - threshold:
            regressions.append((metric, old, new, ratio))
    for metric in sorted(set(ref_metrics) - set(new_metrics)):
        rows.append((metric, float(ref_metrics[metric]["value"]), None,
                     None))
    return rows, regressions


def _fmt(metric, old, new, ratio, rec):
    mfu = rec.get("mfu") if rec else None
    mfu_s = f"  mfu={mfu}" if mfu is not None else ""
    if old is None:
        return f"  NEW   {metric}: {new}{mfu_s} (no reference — not gated)"
    if new is None:
        return f"  GONE  {metric}: was {old} (missing from candidate)"
    arrow = f"{old} -> {new} ({(ratio - 1) * 100:+.1f}%)"
    return f"  {'OK  ' if ratio >= 1.0 else 'DOWN'}  {metric}: {arrow}{mfu_s}"


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="exit non-zero on a >threshold tokens/sec regression "
                    "between bench JSON artifacts (docs/PERF.md)")
    ap.add_argument("candidate", nargs="?", default=None,
                    help="bench JSON to gate (default: newest BENCH_r*.json)")
    ap.add_argument("--against", nargs="+", default=None,
                    help="reference artifacts (default: the previous "
                    "BENCH_r*.json round)")
    ap.add_argument("--threshold", type=float, default=0.05,
                    help="allowed fractional drop (default 0.05)")
    ap.add_argument("--compile-threshold", type=float, default=0.25,
                    help="allowed fractional compile-time increase at "
                    "the same depth/scan mode (default 0.25; docs/SCAN.md)")
    ap.add_argument("--host-threshold", type=float, default=0.25,
                    help="allowed host-gap fraction of step time for "
                    "traced runs carrying an 'anatomy' block (default "
                    "0.25; docs/TELEMETRY.md Tracing)")
    ap.add_argument("--root", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        help="repo root for BENCH_r*.json discovery")
    args = ap.parse_args(argv)

    candidate = args.candidate
    refs = args.against
    if candidate is None or refs is None:
        rounds = _round_files(args.root)
        if candidate is None:
            if not rounds:
                print("bench_gate: no BENCH_r*.json rounds found", flush=True)
                return 2
            candidate = rounds[-1]
            rounds = rounds[:-1]
        else:
            rounds = [r for r in rounds
                      if os.path.abspath(r) != os.path.abspath(candidate)]
        if refs is None:
            if not rounds:
                print(f"bench_gate: {candidate}: no earlier round to gate "
                      "against — tokens/sec not gated", flush=True)
                refs = []  # the resilience gate below still applies
            else:
                refs = [rounds[-1]]
                # metric continuity: a gap round recorded on different
                # hardware (e.g. a CPU-only container, BENCH_r06) lacks
                # the tracked metrics — walking back to the NEWEST
                # earlier round carrying each candidate metric keeps
                # the next real round gated instead of every tracked
                # metric reporting "NEW (not gated)" across the gap
                covered = set(load_metrics(rounds[-1]))
                want = set(load_metrics(candidate))
                for r in reversed(rounds[:-1]):
                    missing = want - covered
                    if not missing:
                        break
                    have = set(load_metrics(r))
                    if have & missing:
                        refs.append(r)
                        covered |= have

    new_metrics = load_metrics(candidate)
    if not new_metrics:
        print(f"bench_gate: no metric lines in {candidate}", flush=True)
        return 2

    failed = False
    # resilience gate: independent of any reference round — a clean bench
    # run reporting guard anomalies or rollbacks fails outright
    for metric, rec in sorted(new_metrics.items()):
        for v in resilience_violations(rec):
            print(f"  GUARD {metric}: {v} — clean bench runs must report "
                  "zero anomalies/rollbacks", flush=True)
            failed = True
        # comms gate: also reference-free — parity is a property of the
        # candidate run alone
        for v in comms_violations(rec):
            print(f"  COMMS {metric}: {v}", flush=True)
            failed = True
        # ring gate (docs/ATTENTION.md): the *_seq32k long-context
        # lines embed a ring-vs-dense parity probe — reference-free
        for v in ring_violations(rec):
            print(f"  RING  {metric}: {v}", flush=True)
            failed = True
        # quant gate (docs/QUANT.md): parity-gate report + embedded
        # loss-drift A/B + no silent request-without-engagement
        for v in quant_violations(rec):
            print(f"  QUANT {metric}: {v}", flush=True)
            failed = True
        # host-overhead gate (reference-free): a traced round must stay
        # device-bound at the same metric
        for v in host_overhead_violations(rec, args.host_threshold):
            print(f"  HOST  {metric}: {v}", flush=True)
            failed = True
        # serving gate (reference-free): p99-TTFT bound + goodput
        # scaling target + no lost requests (docs/SERVING.md)
        for v in serving_violations(rec):
            print(f"  SERVE {metric}: {v}", flush=True)
            failed = True
        # SLO gate (reference-free): a clean soak's embedded slo block
        # reporting any fast-burn alert fails the round
        # (docs/TELEMETRY.md "Time series, SLOs...")
        for v in slo_violations(rec):
            print(f"  SLO   {metric}: {v}", flush=True)
            failed = True
        # overload gate (reference-free): outcome conservation at 2x
        # capacity, admitted-p99 budget, shed ceiling, breaker flap
        # bound, brownout restoration (docs/SERVING.md)
        for v in overload_violations(rec):
            print(f"  OVERLOAD {metric}: {v}", flush=True)
            failed = True
        # upgrade gate (reference-free): zero lost / duplicated requests
        # and tokens through SIGKILL + chaos + rolling weight upgrade,
        # plus embedded window budgets (docs/SERVING.md)
        for v in upgrade_violations(rec):
            print(f"  UPGRADE {metric}: {v}", flush=True)
            failed = True
        # partition gate (reference-free): zero lost / duplicated
        # requests and tokens through a whole-host partition — fencing
        # epochs, fleet-wide replay, heal + adoption (docs/SERVING.md)
        for v in partition_violations(rec):
            print(f"  PARTITION {metric}: {v}", flush=True)
            failed = True
        # pipeline gate (docs/PIPELINE.md): measured-cost bubble over
        # budget, or a pp-live mesh whose composition never engaged
        for v in pipe_violations(rec):
            print(f"  PIPE  {metric}: {v}", flush=True)
            failed = True
        # layout gate (docs/AUTOTUNE.md): the autotuned winner's
        # predicted score must not lose to the hand-picked baseline,
        # and a fallback must carry its structured reason
        for v in layout_violations(rec):
            print(f"  LAYOUT {metric}: {v}", flush=True)
            failed = True
    for ref_path in refs:
        ref_metrics = load_metrics(ref_path)
        print(f"bench_gate: {os.path.basename(candidate)} vs "
              f"{os.path.basename(ref_path)} "
              f"(threshold {args.threshold:.0%})")
        if not ref_metrics:
            print("  (no metric lines — reference skipped; BASELINE.json "
                  "publishes no absolute numbers)")
            continue
        rows, regressions = compare(new_metrics, ref_metrics,
                                    args.threshold)
        for metric, old, new, ratio in rows:
            print(_fmt(metric, old, new, ratio, new_metrics.get(metric)))
        for metric, old, new, ratio in regressions:
            print(f"  REGRESSION {metric}: {old} -> {new} "
                  f"({(ratio - 1) * 100:+.1f}% < -{args.threshold:.0%})")
            failed = True
        # compile gate (docs/SCAN.md): same-depth build time must not
        # regress past --compile-threshold vs this reference round
        for metric, rec in sorted(new_metrics.items()):
            for v in compile_violations(rec, ref_metrics.get(metric),
                                        args.compile_threshold):
                print(f"  COMPILE {metric}: {v}", flush=True)
                failed = True
            # mfu gate (docs/ZERO.md): hardware-normalised throughput
            # must hold alongside raw tokens/sec
            for v in mfu_violations(rec, ref_metrics.get(metric),
                                    args.threshold):
                print(f"  MFU {metric}: {v}", flush=True)
                failed = True
            # serving cold-start gate (docs/SERVING.md): replica
            # spin-up compile must stay depth-flat round over round
            for v in cold_start_violations(rec, ref_metrics.get(metric),
                                           args.compile_threshold):
                print(f"  COLD  {metric}: {v}", flush=True)
                failed = True
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
