#!/bin/bash
cd /root/repo
SNAP=/tmp/snap_r5
run() {
  label="$1"; shift
  echo "=== ARM $label: $* ==="
  env "$@" PYTHONPATH=$SNAP:/root/.axon_site timeout 1500 python $SNAP/bench.py 2>&1 | tail -4
  echo "=== END $label ==="
}
run P_llama_b4_gu PTPU_BENCH_MODEL=llama PTPU_BENCH_BATCH=4
run P_llama_b2_gu PTPU_BENCH_MODEL=llama PTPU_BENCH_BATCH=2
