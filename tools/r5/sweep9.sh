#!/bin/bash
cd /root/repo
SNAP=/tmp/snap_r5
run() {
  label="$1"; shift
  echo "=== ARM $label: $* ==="
  env "$@" PYTHONPATH=$SNAP:/root/.axon_site timeout 1500 python $SNAP/bench.py 2>&1 | tail -4
  echo "=== END $label ==="
}
run M_gpt_bwd512 PTPU_BENCH_MODEL=gpt PTPU_FA_BWD_BLOCK=512
run M_llama_bwd512 PTPU_BENCH_MODEL=llama PTPU_FA_BWD_BLOCK=512
run M_gpt_kb512 PTPU_BENCH_MODEL=gpt PTPU_FA_BWD_KBLOCK=512
