#!/bin/bash
cd /root/repo
SNAP=/tmp/snap_r5
run() {
  label="$1"; shift
  echo "=== ARM $label: $* ==="
  env "$@" PYTHONPATH=$SNAP:/root/.axon_site timeout 1500 python $SNAP/bench.py 2>&1 | tail -4
  echo "=== END $label ==="
}
run K_gpt_fusedbwd PTPU_BENCH_MODEL=gpt PTPU_FA_FUSED_BWD=1
run K_llama_fusedbwd PTPU_BENCH_MODEL=llama PTPU_FA_FUSED_BWD=1
