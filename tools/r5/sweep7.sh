#!/bin/bash
cd /root/repo
SNAP=/tmp/snap_r5
echo "=== FINAL DEFAULTS (fused bwd auto) ==="
env PYTHONPATH=$SNAP:/root/.axon_site timeout 1800 python $SNAP/bench.py 2>&1 | tail -4
echo "=== END ==="
