#!/bin/bash
cd /root/repo
SNAP=/tmp/snap_r5
NAMES_NOQKV="names:attn_res,attn_lse,resid_mid,rms_rstd,ffn_gate,ffn_up"
run() {
  label="$1"; shift
  echo "=== ARM $label: $* ==="
  env "$@" PYTHONPATH=$SNAP:/root/.axon_site timeout 1500 python $SNAP/bench.py 2>&1 | tail -4
  echo "=== END $label ==="
}
run O1_gpt_b4_noqkv PTPU_BENCH_MODEL=gpt PTPU_BENCH_BATCH=4 PTPU_BENCH_REMAT="$NAMES_NOQKV"
run O2_llama_b4_noqkv PTPU_BENCH_MODEL=llama PTPU_BENCH_BATCH=4 PTPU_BENCH_REMAT="$NAMES_NOQKV"
run O3_gpt_b3_noqkv PTPU_BENCH_MODEL=gpt PTPU_BENCH_REMAT="$NAMES_NOQKV"
