#!/bin/bash
# r5 follow-up: bwd-block probe on winning GPT config + llama factored arms.
# Runs from a frozen snapshot so repo edits can't race arm transitions.
cd /root/repo
SNAP=/tmp/snap_r5
NAMES_BASE="names:attn_res,attn_lse,attn_q,attn_k,attn_v,resid_mid,rms_rstd"
NAMES_GATE="${NAMES_BASE},ffn_gate"
NAMES_GU="${NAMES_BASE},ffn_gate,ffn_up"
run() {
  label="$1"; shift
  echo "=== ARM $label: $* ==="
  env "$@" PYTHONPATH=$SNAP:/root/.axon_site timeout 1200 python $SNAP/bench.py 2>&1 | tail -12
  echo "=== END $label ==="
}
run F_gpt_gate_bwd2048 PTPU_BENCH_MODEL=gpt PTPU_ADAM_FACTORED=1 PTPU_BENCH_REMAT="$NAMES_GATE" PTPU_FA_BWD_BLOCK=2048
run L_llama_fact PTPU_BENCH_MODEL=llama PTPU_ADAM_FACTORED=1
run L_llama_fact_gate PTPU_BENCH_MODEL=llama PTPU_ADAM_FACTORED=1 PTPU_BENCH_REMAT="$NAMES_GATE"
run L_llama_fact_b4 PTPU_BENCH_MODEL=llama PTPU_ADAM_FACTORED=1 PTPU_BENCH_BATCH=4
run L_llama_fact_gate_b4 PTPU_BENCH_MODEL=llama PTPU_ADAM_FACTORED=1 PTPU_BENCH_REMAT="$NAMES_GATE" PTPU_BENCH_BATCH=4
