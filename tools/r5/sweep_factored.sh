#!/bin/bash
# r5 factored-AdamW A/B sweep — GPT headline arms, sequential.
cd /root/repo
NAMES_BASE="names:attn_res,attn_lse,attn_q,attn_k,attn_v,resid_mid,rms_rstd"
NAMES_GATE="${NAMES_BASE},ffn_gate"
NAMES_GU="${NAMES_BASE},ffn_gate,ffn_up"
run() {
  label="$1"; shift
  echo "=== ARM $label: $* ==="
  env "$@" PTPU_BENCH_MODEL=gpt timeout 900 python bench.py 2>&1 | tail -4
  echo "=== END $label ==="
}
run base_ctrl
run A_fact PTPU_ADAM_FACTORED=1
run B_fact_gate PTPU_ADAM_FACTORED=1 PTPU_BENCH_REMAT="$NAMES_GATE"
run C_fact_b5 PTPU_ADAM_FACTORED=1 PTPU_BENCH_BATCH=5
run D_fact_gu PTPU_ADAM_FACTORED=1 PTPU_BENCH_REMAT="$NAMES_GU"
run E_fact_gate_b5 PTPU_ADAM_FACTORED=1 PTPU_BENCH_BATCH=5 PTPU_BENCH_REMAT="$NAMES_GATE"
