#!/bin/bash
# r5 sweep 3: confirm new defaults (full driver-style run) + gate+up@b3 probes
cd /root/repo
SNAP=/tmp/snap_r5
NAMES_GU="names:attn_res,attn_lse,attn_q,attn_k,attn_v,resid_mid,rms_rstd,ffn_gate,ffn_up"
run() {
  label="$1"; shift
  echo "=== ARM $label: $* ==="
  env "$@" PYTHONPATH=$SNAP:/root/.axon_site timeout 1800 python $SNAP/bench.py 2>&1 | tail -6
  echo "=== END $label ==="
}
run DEFAULTS_CONFIRM
run G2_gpt_gu_b3 PTPU_BENCH_MODEL=gpt PTPU_BENCH_REMAT="$NAMES_GU" PTPU_BENCH_BATCH=3
run L5_llama_gu_b3 PTPU_BENCH_MODEL=llama PTPU_BENCH_REMAT="$NAMES_GU" PTPU_BENCH_BATCH=3
