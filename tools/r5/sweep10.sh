#!/bin/bash
cd /root/repo
SNAP=/tmp/snap_r5
run() {
  label="$1"; shift
  echo "=== ARM $label: $* ==="
  env "$@" PYTHONPATH=$SNAP:/root/.axon_site timeout 1500 python $SNAP/bench.py 2>&1 | tail -4
  echo "=== END $label ==="
}
run N_gpt_default PTPU_BENCH_MODEL=gpt
run N_gpt_kb512_b PTPU_BENCH_MODEL=gpt PTPU_FA_BWD_KBLOCK=512
run N_llama_kb512 PTPU_BENCH_MODEL=llama PTPU_FA_BWD_KBLOCK=512
