#!/bin/bash
# r5 sweep 4: confirm gate+up b3 defaults + attn_out save probe
cd /root/repo
SNAP=/tmp/snap_r5
NAMES_AO="names:attn_res,attn_lse,attn_q,attn_k,attn_v,resid_mid,rms_rstd,ffn_gate,ffn_up,attn_out"
run() {
  label="$1"; shift
  echo "=== ARM $label: $* ==="
  env "$@" PYTHONPATH=$SNAP:/root/.axon_site timeout 1800 python $SNAP/bench.py 2>&1 | tail -6
  echo "=== END $label ==="
}
run DEFAULTS_CONFIRM2
run GA_gpt_attnout PTPU_BENCH_MODEL=gpt PTPU_BENCH_REMAT="$NAMES_AO"
run LA_llama_attnout PTPU_BENCH_MODEL=llama PTPU_BENCH_REMAT="$NAMES_AO"
run GB_gpt_b4_gu PTPU_BENCH_MODEL=gpt PTPU_BENCH_BATCH=4
