"""On-chip split-vs-fused flash-bwd parity: same 3 train steps, loss
values must agree to bf16 tolerance (Mosaic lowering check)."""
import os
import sys

sys.path.insert(0, os.getcwd())
import numpy as np


def run(fused):
    os.environ["PTPU_FA_FUSED_BWD"] = "1" if fused else "0"
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLMPipe

    os.environ.setdefault("PTPU_PALLAS_RMS", "1")
    cfg = GPTConfig(vocab_size=8192, hidden_size=1024, num_layers=4,
                    num_heads=8, max_seq_len=2048, dropout=0.0,
                    dtype="bfloat16", recompute=True,
                    recompute_policy="names:attn_res,attn_lse,attn_q,"
                    "attn_k,attn_v,resid_mid,rms_rstd,ffn_gate,ffn_up")
    paddle.seed(0)
    m = GPTForCausalLMPipe(cfg)
    for _, p in m.named_parameters():
        p._data = p._data.astype(jax.numpy.bfloat16)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=m.parameters(), factored=True)
    step = TrainStep(m, lambda a, b: m.loss(a, b), opt)
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(0, 8192, (2, 2048)).astype(np.int32))
    lab = paddle.to_tensor(rng.integers(0, 8192, (2, 2048)).astype(np.int64))
    return [float(step(ids, lab).numpy()) for _ in range(3)]


if __name__ == "__main__":
    import subprocess

    if len(sys.argv) > 1:
        print(run(sys.argv[1] == "fused"))
        sys.exit(0)
    outs = {}
    for mode in ("split", "fused"):
        r = subprocess.run([sys.executable, __file__, mode],
                           capture_output=True, text=True, timeout=1200)
        line = r.stdout.strip().splitlines()[-1]
        outs[mode] = eval(line)
        print(mode, outs[mode], flush=True)
    a, b = np.asarray(outs["split"]), np.asarray(outs["fused"])
    assert np.allclose(a, b, rtol=2e-2), (a, b)
    print("ON-CHIP PARITY OK, max rel",
          float(np.abs(a - b).max() / np.abs(a).max()))
