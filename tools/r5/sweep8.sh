#!/bin/bash
cd /root/repo
SNAP=/tmp/snap_r5
NAMES_GATE="names:attn_res,attn_lse,attn_q,attn_k,attn_v,resid_mid,rms_rstd,ffn_gate"
run() {
  label="$1"; shift
  echo "=== ARM $label: $* ==="
  env "$@" PYTHONPATH=$SNAP:/root/.axon_site timeout 1500 python $SNAP/bench.py 2>&1 | tail -4
  echo "=== END $label ==="
}
run L1_gpt_b4_gate_fused PTPU_BENCH_MODEL=gpt PTPU_BENCH_BATCH=4 PTPU_BENCH_REMAT="$NAMES_GATE"
run L2_gpt_bwd2048_fused PTPU_BENCH_MODEL=gpt PTPU_FA_BWD_BLOCK=2048
run L3_llama_b4_gate_fused PTPU_BENCH_MODEL=llama PTPU_BENCH_BATCH=4 PTPU_BENCH_REMAT="$NAMES_GATE"
