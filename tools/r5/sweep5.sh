#!/bin/bash
cd /root/repo
SNAP=/tmp/snap_r5
NAMES_ALL="names:attn_res,attn_lse,attn_q,attn_k,attn_v,resid_mid,rms_rstd,ffn_gate,ffn_up,ffn_out,attn_out"
NAMES_GUF="names:attn_res,attn_lse,attn_q,attn_k,attn_v,resid_mid,rms_rstd,ffn_gate,ffn_up,ffn_out"
run() {
  label="$1"; shift
  echo "=== ARM $label: $* ==="
  env "$@" PYTHONPATH=$SNAP:/root/.axon_site timeout 1200 python $SNAP/bench.py 2>&1 | tail -4
  echo "=== END $label ==="
}
run H_gpt_b2_all PTPU_BENCH_MODEL=gpt PTPU_BENCH_BATCH=2 PTPU_BENCH_REMAT="$NAMES_ALL"
run I_gpt_b3_ffnout PTPU_BENCH_MODEL=gpt PTPU_BENCH_REMAT="$NAMES_GUF"
run J_llama_b3_ffnout PTPU_BENCH_MODEL=llama PTPU_BENCH_REMAT="$NAMES_GUF"
