"""Wall-clock A/B of the compiled pipeline schedules on the 8-CPU mesh.

VERDICT r3 item 6: the zero-bubble advantage was cost-model-validated only
(`zero_bubble_cost()` tick arithmetic). This measures the actual schedules
— plain AD 1F1B ring, interleaved, ZB, ZB-interleaved — at pp=4 with
cb-heavy stages, and prints measured ratios next to the model's
predictions. Results are recorded in docs/ZB_WALLCLOCK.md.

Run:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/measure_zb.py
"""
from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P


def measure(fn, *args, iters=8, warmup=2):
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def main():
    from paddle_tpu.distributed.pipeline import (
        interleaved_cost, microbatch, plain_cost, spmd_pipeline,
        spmd_pipeline_interleaved, spmd_pipeline_zero_bubble,
        spmd_pipeline_zero_bubble_interleaved, unmicrobatch,
        zero_bubble_cost)

    pp, v, n_micro = 4, 2, 4
    # cb-heavy stages: deep matmul chains make backward ~2x forward and
    # keep per-tick compute >> ppermute/threading overhead on CPU
    L, H, rows = 16, 384, 512
    layers_per_stage = L // pp

    devs = np.array(jax.devices()[:pp]).reshape(pp)
    mesh = Mesh(devs, ("pp",))

    rng = np.random.RandomState(0)
    w = jnp.asarray(rng.randn(L, H, H) * (1.0 / np.sqrt(H)), jnp.float32)
    x = jnp.asarray(rng.randn(rows, H), jnp.float32)
    xm = microbatch(x, n_micro)

    def stage_fn(w_local, xx):
        def step(xx, w1):
            return jnp.tanh(xx @ w1), None
        out, _ = jax.lax.scan(step, xx, w_local)
        return out

    builders = {
        "1f1b (AD ring)": lambda: spmd_pipeline(
            stage_fn, mesh, pp, params_spec=P("pp")),
        "interleaved v2": lambda: spmd_pipeline_interleaved(
            stage_fn, mesh, pp, v),
        "zero-bubble": lambda: spmd_pipeline_zero_bubble(
            stage_fn, mesh, pp, params_spec=P("pp")),
        "zb-interleaved v2": lambda: spmd_pipeline_zero_bubble_interleaved(
            stage_fn, mesh, pp, v),
    }
    predictions = {
        "1f1b (AD ring)": plain_cost(n_micro, pp),
        "interleaved v2": interleaved_cost(n_micro, pp, v),
        "zero-bubble": zero_bubble_cost(n_micro, pp),
        "zb-interleaved v2": zero_bubble_cost(n_micro, pp, v=v),
    }

    results = {}
    for name, mk in builders.items():
        pipe = mk()

        def loss(w, xm, _pipe=pipe):
            return jnp.sum(unmicrobatch(_pipe(w, xm)) ** 2)

        g = jax.jit(jax.grad(loss))
        ws = jax.device_put(w, NamedSharding(mesh, P("pp")))
        dt = measure(g, ws, xm)
        results[name] = dt
        print(f"{name:20s}  {dt * 1e3:8.2f} ms/step "
              f"(predicted {predictions[name]:.2f} ticks)")

    base = results["1f1b (AD ring)"]
    pbase = predictions["1f1b (AD ring)"]
    print(f"\n{'schedule':20s} {'measured ratio':>15s} {'predicted ratio':>16s}")
    for name in builders:
        print(f"{name:20s} {results[name] / base:15.3f} "
              f"{predictions[name] / pbase:16.3f}")

    # config note: grad-step wall clock includes the post-ring batched
    # wgrad (ZB) vs in-ring wgrad (AD) — exactly the tradeoff the cost
    # model arbitrates
    print(f"\nconfig: pp={pp} v={v} n_micro={n_micro} "
          f"L={L} H={H} rows={rows} ({layers_per_stage} layers/stage)")


if __name__ == "__main__":
    main()
