"""Quickstart 1: train LeNet on MNIST-shaped data with Model.fit
(BASELINE.md config 1). Runs anywhere:
    JAX_PLATFORMS=cpu python examples/01_train_mnist.py
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.io import DataLoader, TensorDataset
from paddle_tpu.vision.models import LeNet


def main():
    paddle.seed(0)
    rng = np.random.default_rng(0)
    # synthetic MNIST-shaped data (swap in paddle.vision.datasets.MNIST)
    x = rng.standard_normal((256, 1, 28, 28)).astype(np.float32)
    y = rng.integers(0, 10, (256,)).astype(np.int64)
    loader = DataLoader(TensorDataset([x, y]), batch_size=64, shuffle=True)

    model = paddle.Model(LeNet())
    model.prepare(
        paddle.optimizer.Adam(learning_rate=1e-3,
                              parameters=model.parameters()),
        nn.CrossEntropyLoss(),
        paddle.metric.Accuracy())
    model.fit(loader, epochs=2, verbose=1)
    print("final eval:", model.evaluate(loader, verbose=0))


if __name__ == "__main__":
    main()
