"""Quickstart 2: decoder-only pretraining on a hybrid-parallel mesh
(fleet dp x mp, BASELINE.md config 4 shape), then the FULL 3-axis
pp x mp x dp composition as one compiled step. On one host:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/02_pretrain_gpt_hybrid.py
On a pod, launch one process per host with
`python -m paddle_tpu.distributed.launch` and the same body.

Crash safety: pass ``--ckpt-dir DIR`` to save every step as a committed
CheckpointManager checkpoint and auto-resume from the newest committed
step after a kill/preemption (``--resume auto``, the default) — SIGTERM
mid-run triggers one final synchronous save and a clean exit
(docs/CHECKPOINT.md).
"""
import argparse

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.checkpoint.manager import (CheckpointManager,
                                                       PreemptionGuard)
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLMPipe


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ckpt-dir", default=None,
                    help="crash-safe checkpoint root (off when unset)")
    ap.add_argument("--resume", choices=("auto", "none"), default="auto")
    ap.add_argument("--guard", action="store_true",
                    help="resilience StepGuard around the 3-axis compiled "
                    "step: nonfinite/spike updates are discarded in-graph "
                    "(skip-only here — attach a per-model CheckpointManager "
                    "as bench.py does to get the rewind rung; the eager "
                    "train_batch loop is not guarded, docs/RESILIENCE.md)")
    args = ap.parse_args()

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 4, "mp_degree": 2,
                               "pp_degree": 1, "sharding_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=4,
                    num_heads=4, max_seq_len=256, dropout=0.0)
    model = GPTForCausalLMPipe(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=3e-4,
                                 parameters=model.parameters())

    dmodel = fleet.distributed_model(model)
    dopt = fleet.distributed_optimizer(opt)

    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (8, 128)).astype(np.int32))
    labels = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (8, 128)).astype(np.int64))

    def lm_loss(logits, y):
        return F.cross_entropy(
            logits.reshape([-1, cfg.vocab_size]), y.reshape([-1]))

    # crash-safe training state: committed per-step saves + auto-resume
    manager = None
    start = 0
    if args.ckpt_dir:
        manager = CheckpointManager(args.ckpt_dir, keep=3)
        # newest GOOD step: restore only walks good steps, so a
        # BAD-inclusive latest_step() gate could crash post-abort
        if args.resume == "auto" and manager.last_good_step() is not None:
            start = manager.restore_training_state(model, opt)
            print(f"resumed from committed step {start}")

    with PreemptionGuard(manager) as guard:
        for step in range(start, 5):
            loss = dmodel.train_batch([ids, labels], dopt, loss_fn=lm_loss)
            print(f"step {step}: loss {float(loss):.4f}")
            if manager is not None:
                # train_step= syncs the compiled step's optimizer slots
                # back into `opt` before the state is snapshotted
                manager.save_training_state(
                    step + 1, model, opt, train_step=dmodel._train_step,
                    async_save=True)
            if guard.preempted:
                if manager is not None:
                    manager.wait()
                    manager.save_training_state(
                        step + 1, model, opt,
                        train_step=dmodel._train_step)
                    print(f"preempted: committed final step {step + 1}")
                return
    if manager is not None:
        manager.wait()

    # -- full 3-axis hybrid: pipeline stages x Megatron TP x data -------
    # parallel, ONE compiled program. Stage sharding comes from the
    # 'pp' placements; tp_axis="mp" adds column/row TP placements on
    # the stacked weights; the batch shards over dp. (Swap the dp axis
    # for sharding_degree=2 + shard_opt_states=True to get ZeRO-1 on
    # top — the 4-axis composition.)
    from paddle_tpu.distributed.parallel_step import ShardedTrainStep

    strategy3 = fleet.DistributedStrategy()
    strategy3.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                                "pp_degree": 2, "sharding_degree": 1}
    fleet.init(is_collective=True, strategy=strategy3)
    paddle.seed(0)
    model3 = GPTForCausalLMPipe(cfg)
    model3.decoder.apply_pipeline_placements(tp_axis="mp")
    opt3 = paddle.optimizer.AdamW(learning_rate=3e-4,
                                  parameters=model3.parameters())
    step3 = ShardedTrainStep(model3, lambda a, b: model3.loss(a, b),
                             opt3, fleet.get_fleet_mesh())
    if args.guard:
        # StepGuard over the hybrid compiled step: a nonfinite or
        # loss-spike update is discarded IN-GRAPH (pre-step state kept,
        # the loop retries), escalating to a committed-checkpoint rewind
        # when a manager is attached (docs/RESILIENCE.md)
        from paddle_tpu.resilience import StepGuard

        # skip-only policy here: `manager` holds the FIRST model's steps,
        # which must not be restored into model3 — attach a per-model
        # CheckpointManager (like bench.py's per-model subroot) to get
        # the rollback rung of the escalation ladder
        guard3 = StepGuard(step3, manager=None)
        gstep = 1
        while gstep <= 3:
            out = guard3(gstep, ids, labels)
            if out.accepted:
                print(f"3-axis step {gstep - 1}: "
                      f"loss {float(out.loss.numpy()):.4f}")
            else:
                print(f"3-axis step {gstep - 1}: {out.action} "
                      f"({out.health.kind})")
            gstep = out.next_step
    else:
        for step in range(3):
            loss = step3(ids, labels)
            print(f"3-axis step {step}: loss {float(loss.numpy()):.4f}")


if __name__ == "__main__":
    main()
