"""Quickstart 3: continuous-batching LLM serving — paged KV cache,
batched chunked prefill, per-request sampling.
    JAX_PLATFORMS=cpu python examples/03_serve_llm.py
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.inference.serving import ContinuousBatchingEngine
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM


def main():
    paddle.seed(0)
    cfg = LlamaConfig(vocab_size=256, hidden_size=64, num_layers=2,
                      num_heads=4, max_seq_len=128, dropout=0.0)
    model = LlamaForCausalLM(cfg)   # load real weights with paddle.load

    engine = ContinuousBatchingEngine(
        model, max_slots=4, page_size=16, max_new_tokens=12,
        prefill_chunk=8)
    rng = np.random.default_rng(0)
    rids = [engine.submit(list(rng.integers(1, 250, n)),
                          temperature=t, top_p=0.9)
            for n, t in ((20, 0.0), (9, 0.8), (33, 1.0))]
    done = engine.run_until_complete()
    for rid in rids:
        print(f"request {rid}: {len(done[rid])} tokens ->",
              done[rid][-12:])


if __name__ == "__main__":
    main()
