"""Quickstart 3: continuous-batching LLM serving — paged KV cache,
batched chunked prefill, per-request sampling, automatic prefix caching.
    JAX_PLATFORMS=cpu python examples/03_serve_llm.py
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.inference.serving import ContinuousBatchingEngine
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM


def main():
    paddle.seed(0)
    cfg = LlamaConfig(vocab_size=256, hidden_size=64, num_layers=2,
                      num_heads=4, max_seq_len=128, dropout=0.0)
    model = LlamaForCausalLM(cfg)   # load real weights with paddle.load

    # enable_prefix_cache: requests sharing a system prompt reuse its
    # KV pages instead of re-prefilling (~2x TTFT on long shared
    # prefixes, measured on-chip). Pool pressure is survivable too:
    # pages grow as sequences do, and on exhaustion the youngest
    # request is preempted and recomputed on re-admission. (Without the
    # prefix cache, preempt_policy="swap" is an alternative that
    # round-trips the victim's KV through host memory instead.)
    engine = ContinuousBatchingEngine(
        model, max_slots=4, page_size=16, max_new_tokens=12,
        prefill_chunk=8, enable_prefix_cache=True)
    rng = np.random.default_rng(0)
    tok = lambda n: list(rng.integers(1, 250, n))
    system = tok(16)                # a shared "system prompt"
    rids = [engine.submit(system + tok(n), temperature=t, top_p=0.9)
            for n, t in ((20, 0.0), (9, 0.8), (33, 1.0))]
    done = engine.run_until_complete()
    for rid in rids:
        print(f"request {rid}: {len(done[rid])} tokens ->",
              done[rid][-12:])

    # a follow-up request with the same system prompt: its prefix pages
    # are already cached, so only the tail prefills (fast first token)
    rid = engine.submit(system + tok(7))
    done = engine.run_until_complete()
    print(f"follow-up {rid}: {len(done[rid])} tokens; prefix cache "
          f"reused {engine.prefix_cache_hits} pages "
          f"({engine.prefix_tokens_skipped} prompt tokens not re-prefilled)")


if __name__ == "__main__":
    main()
