"""Headline benchmark: decoder-only (GPT/LLaMA-style) pretrain throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline",
"memory", "telemetry"}. The reference publishes no absolute numbers
(BASELINE.md), so vs_baseline reports achieved model FLOPs utilisation
(MFU) against the chip peak — a hardware-normalised stand-in the driver
can track across rounds. "memory" is the batch/remat planner decision +
XLA peak bytes (docs/MEMORY.md); "telemetry" the runtime metric snapshot
(docs/TELEMETRY.md).
"""
from __future__ import annotations

import json
import os
import time

import numpy as np


def _serving_smoke_block():
    """Compact fleet-serving soak for the bench JSON (--serve): replica
    cold start (warmup compile, gated vs the previous round by
    bench_gate's COLD gate at the same scan mode) plus a 1-vs-2 replica
    goodput ratio and p99 TTFT vs a 10x-p50 budget (SERVE gate). The
    heavy 1..N sweep lives in tools/serve_bench.py (docs/SERVING.md);
    this block keeps the serving numbers tracked round over round next
    to the training metrics."""
    import paddle_tpu as paddle
    from paddle_tpu.inference.fleet import build_workload, soak_block
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(vocab_size=256, hidden_size=64, num_layers=2,
                      num_heads=4, num_kv_heads=2, max_seq_len=128,
                      dropout=0.0)
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    workload = build_workload(48, 200.0, (6, 10, 14), cfg.vocab_size,
                              seed=1)
    engine_kw = dict(max_slots=4, page_size=8, max_seq_len=64,
                     max_new_tokens=8, prefill_chunk=8)
    base = soak_block(model, replicas=1, workload=workload,
                      engine_kw=engine_kw)
    p50 = (base.get("ttft") or {}).get("p50")
    block = soak_block(model, replicas=2, workload=workload,
                       engine_kw=engine_kw, baseline=base,
                       ttft_budget=(10.0 * p50 if p50 else None))
    block["single"] = {"goodput_tokens_per_sec":
                       base.get("goodput_tokens_per_sec"),
                       "cold_start_seconds":
                       base.get("cold_start_seconds")}
    return block


def run_long_context(ckpt=None):
    """Long-context bench line (``*_seq32k``, docs/ATTENTION.md): the
    train step over a ``sep`` mesh with the ring-attention plan engaged
    — 32k tokens per sequence on TPU, a reduced-length CPU smoke
    otherwise (the honest-smoke discipline of BENCH_r06). Emits ONE
    JSON metric line whose ``"ring"`` block carries the plan summary
    and the ring-vs-dense parity probe ``tools/bench_gate.py`` gates
    reference-free; tokens/sec gates against earlier rounds like every
    metric line."""
    import time as _time

    import jax

    on_tpu = jax.default_backend() not in ("cpu",)
    import paddle_tpu as paddle
    import paddle_tpu.telemetry as telemetry
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLMPipe

    telemetry.enable()
    telemetry.reset()
    n_dev = len(jax.devices())
    seq_env = os.environ.get("PTPU_BENCH_LONG_SEQ")
    if on_tpu:
        # GPT-1.3B arch at 32k context, batch 1: flash keeps attention
        # O(S) so the activation budget is the residual stream, not a
        # [32k, 32k] score matrix (asserted to not exist by the tests)
        cfg = GPTConfig(vocab_size=32000, hidden_size=2048, num_layers=24,
                        num_heads=16, max_seq_len=32768, dropout=0.0,
                        dtype="bfloat16", recompute=True,
                        recompute_policy="names:attn_res,attn_lse,attn_q,"
                        "attn_k,attn_v,resid_mid")
        seq, steps, batch = int(seq_env or 32768), 5, 1
        os.environ.setdefault("PTPU_PALLAS_RMS", "1")
    else:
        cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=2,
                        num_heads=4, max_seq_len=512, dropout=0.0)
        seq, steps, batch = int(seq_env or 512), 3, 2
    # sep = the largest device count that zigzag-divides the sequence
    sep = n_dev
    while sep > 1 and seq % (2 * sep):
        sep -= 1
    mesh = None
    if sep >= 2:
        from paddle_tpu.distributed import fleet as _fleet

        strategy = _fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": n_dev // sep,
                                   "mp_degree": 1, "pp_degree": 1,
                                   "sharding_degree": 1, "sep_degree": sep}
        _fleet.init(is_collective=True, strategy=strategy)
        mesh = _fleet.get_fleet_mesh()

    with paddle.amp.auto_cast(enable=on_tpu, dtype="bfloat16", level="O2"):
        model = GPTForCausalLMPipe(cfg)
    if on_tpu:
        for _, p in model.named_parameters():
            p._data = p._data.astype(jax.numpy.bfloat16)
    opt = paddle.optimizer.AdamW(learning_rate=3e-4,
                                 parameters=model.parameters())

    def train_fn(ids, labels):
        return model.loss(ids, labels)

    if mesh is not None:
        from paddle_tpu.distributed.parallel_step import ShardedTrainStep

        step = ShardedTrainStep(model, train_fn, opt, mesh)
    else:
        step = TrainStep(model, train_fn, opt)

    rng = np.random.default_rng(0)
    dp = (n_dev // sep) if mesh is not None else 1
    rows = max(batch, dp)
    ids = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (rows, seq)).astype(np.int32))
    labels = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (rows, seq)).astype(np.int64))
    loss = step(ids, labels)                   # compile + warmup
    _ = float(loss.numpy())
    t0 = _time.perf_counter()
    for _i in range(steps):
        loss = step(ids, labels)
    _ = float(loss.numpy())
    dt = _time.perf_counter() - t0
    tokens_per_sec = rows * seq * steps / dt

    from paddle_tpu.distributed import collectives as _coll

    plan = step.ring_plan() if hasattr(step, "ring_plan") else None
    engaged = bool(getattr(step, "_ring_last_active", False))
    ring_block = {
        "enabled": plan is not None,
        "engaged": engaged,
        "seq": seq,
        "parity": _coll.ring_parity_probe(mesh),
    }
    if plan is not None:
        ring_block.update(plan.summary())

    n_params = sum(int(np.prod(p.shape))
                   for _, p in model.named_parameters())
    peak = 197e12 if on_tpu else 1e12
    mfu = 6.0 * n_params * tokens_per_sec / peak
    print(json.dumps({
        "metric": "gpt_long_context_tokens_per_sec_seq32k",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec/chip",
        "seq": seq,
        "note": (None if on_tpu and seq >= 32768 else
                 f"reduced-length smoke (seq {seq}, {jax.default_backend()}"
                 ") — the 32k TPU number needs a TPU round"),
        "mfu": round(mfu, 4),
        "vs_baseline": round(mfu, 4),
        # ring plan + reference-free parity probe (docs/ATTENTION.md;
        # gated by bench_gate's RING gate)
        "ring": ring_block,
        "telemetry": telemetry.snapshot(),
    }), flush=True)


def run_model(model_kind, ckpt=None):
    import jax

    backend = jax.default_backend()
    on_tpu = backend not in ("cpu",)

    import paddle_tpu as paddle
    import paddle_tpu.telemetry as telemetry
    from paddle_tpu.telemetry import trace as ptrace
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLMPipe
    import paddle_tpu.nn.functional as F
    from paddle_tpu import quant as _pquant

    # full-run telemetry: op dispatch, collectives, compile events, and
    # step timing all land in the snapshot attached to the bench JSON, so
    # a BENCH_r*.json regression explains itself (docs/TELEMETRY.md)
    telemetry.enable()
    telemetry.reset()

    # --trace / PTPU_TRACE=1: span tracer ON for the whole run — jit
    # build phases, per-step dispatch with cost_analysis attrs, plan
    # collectives, checkpoint phases — exported as Perfetto JSON + JSONL
    # next to the run, summarized in the JSON line's "anatomy" block
    # (docs/TELEMETRY.md Tracing section)
    trace_on = (bool(ckpt is not None and getattr(ckpt, "trace", False))
                or os.environ.get("PTPU_TRACE", "") not in ("", "0"))
    trace_dir = (getattr(ckpt, "trace_dir", None) or ".") if ckpt else "."
    if trace_on:
        ptrace.enable()
        ptrace.reset()

    # --record / PTPU_RECORD=1: background time-series recorder for the
    # whole run — registry samples every --record-interval seconds into
    # a JSONL timeline next to the bench output, summarized in the JSON
    # line's "timeline" block and readable by tools/telemetry_report.py
    # --timeline (docs/TELEMETRY.md "Time series, SLOs...")
    record_on = (bool(ckpt is not None and getattr(ckpt, "record", False))
                 or os.environ.get("PTPU_RECORD", "") not in ("", "0"))
    record_interval = float(
        (getattr(ckpt, "record_interval", None) if ckpt else None)
        or os.environ.get("PTPU_RECORD_INTERVAL", "") or 0.5)
    ts_recorder = None
    if record_on:
        os.makedirs(trace_dir, exist_ok=True)
        ts_recorder = telemetry.recorder(jsonl_path=os.path.join(
            trace_dir, f"timeline_{model_kind}.jsonl"))
        ts_recorder.start(record_interval)

    if on_tpu:
        # Tuned defaults (measured on v5e; r3 sweep + r4 sweep):
        # - Pallas rms kernel with saved rstd residual (+3.1% MFU, r3)
        # - int8 weight-only LM head: no longer force-set here — the
        #   chunked-CE head turns it on by default WHEN the numeric
        #   parity gate passes (fused_cross_entropy.int8_head_enabled;
        #   PTPU_INT8_HEAD still forces either way)
        # - flash fwd block 2048 (+0.6%, r4; bwd stays 1024 — uniform
        #   2048 bwd compile-OOMs, decoupled q/k blocks measured worse)
        os.environ.setdefault("PTPU_PALLAS_RMS", "1")
        os.environ.setdefault("PTPU_FA_BLOCK", "2048")
        # r5: factored second-moment AdamW frees the m2 state (~2.6GB at
        # 1.3B); the headroom buys BOTH ffn saves at batch 3 — the
        # backward re-runs no FFN matmuls at all. Measured (tools/r5
        # sweeps): GPT 0.5468 -> 0.5629, LLaMA 0.5806 -> 0.638.
        # bwd-block-2048 stays dead (scoped-VMEM OOM, not HBM).
        os.environ.setdefault("PTPU_ADAM_FACTORED", "1")
        # r6+: norm->ffn seam megakernel — (silu(gate)*up) @ wd streamed
        # through VMEM, the [tokens, intermediate] product never touches
        # HBM (ops/pallas/swiglu_down, docs/SCAN.md). PTPU_FUSED_FFN=0
        # restores the unfused seam; PTPU_FUSED_SEAMS=1 additionally
        # engages the addrms attn->norm seam.
        os.environ.setdefault("PTPU_FUSED_FFN", "1")
        if model_kind == "llama":
            # BASELINE.md config-5 variant: LLaMA-7B architecture
            # (h=4096, GQA, swiglu, rope) depth-scaled to 8 layers so
            # params+Adam state fit one v5e chip. This line runs REAL
            # sharding_stage=3 (group_sharded_parallel + the ZeRO
            # execution mode below, docs/ZERO.md) over every
            # addressable chip — degree = device count.
            cfg = GPTConfig(vocab_size=32000, hidden_size=4096,
                            num_layers=8, num_heads=32, num_kv_heads=8,
                            intermediate_size=11008, max_seq_len=2048,
                            dropout=0.0, dtype="bfloat16", recompute=True)
        else:
            # GPT-3 1.3B (BASELINE.md config 4) — the headline metric
            cfg = GPTConfig(vocab_size=32000, hidden_size=2048,
                            num_layers=24, num_heads=16, max_seq_len=2048,
                            dropout=0.0, dtype="bfloat16", recompute=True)
        seq, steps = 2048, 10
        batch_grid = (3, 4, 5)
    else:  # smoke path for CPU dev runs
        cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=2,
                        num_heads=4, max_seq_len=256, dropout=0.0)
        seq, steps = 128, 3
        batch_grid = (2,)

    # batch/remat chosen by the memory planner (paddle_tpu.memory): each
    # candidate is lowered+compiled unexecuted and priced by XLA's
    # memory_analysis against the chip HBM budget — no more hand-set
    # "b5 OOMs" caps. The grid pairs the r5 bf16 save list with int8
    # activation-checkpointing variants (int8:<name> saves the residual
    # blockwise-int8 at ~half the bytes, docs/MEMORY.md). Decisions are
    # cached per (config, chip); PTPU_BENCH_BATCH / PTPU_BENCH_REMAT
    # remain as overrides for perf sweeps (both set = planning skipped,
    # the override is still priced + recorded in the JSON).
    base_saves = "attn_res,attn_lse,attn_q,attn_k,attn_v,rms_rstd"
    if on_tpu:
        policy_grid = (
            f"names:{base_saves},resid_mid,ffn_gate,ffn_up",      # r5 default
            f"names:{base_saves},resid_mid,int8:ffn_gate,int8:ffn_up",
            f"names:{base_saves},int8:resid_mid,int8:ffn_gate,int8:ffn_up",
        )
    else:
        # CPU smoke pins the all-int8 policy so one tier-1 bench run
        # exercises planner + quantized save/restore end to end
        policy_grid = (
            f"names:{base_saves},int8:resid_mid,int8:ffn_gate,int8:ffn_up",
        )
    env_batch = os.environ.get("PTPU_BENCH_BATCH")
    env_remat = os.environ.get("PTPU_BENCH_REMAT")
    env_hchunk = os.environ.get("PTPU_BENCH_HEAD_CHUNK")
    # --autotune / PTPU_AUTOTUNE=1 (docs/AUTOTUNE.md): route this line
    # through the layout autotuner — the mesh/schedule lattice is
    # searched lowering-only and the headline runs the winning layout's
    # built ShardedTrainStep instead of the hand-picked config
    autotune_on = (bool(ckpt is not None and getattr(ckpt, "autotune",
                                                     False))
                   or os.environ.get("PTPU_AUTOTUNE", "")
                   not in ("", "0"))
    # fused-CE head chunk: a third plan dimension. Bigger chunks = fewer
    # serialized LSE scan steps; the resident [tokens, chunk] fp32 block
    # is what memory_analysis prices against batch/remat headroom.
    if env_hchunk:
        hchunk_grid = (int(env_hchunk),)
    elif on_tpu:
        hchunk_grid = (16384, 8192)
    else:
        hchunk_grid = (256,)  # CPU smoke: multiple chunks over vocab 512

    # stacked-decoder flagship: lax.scan over layers keeps compile time
    # constant in depth; recompute = jax.checkpoint per block
    with paddle.amp.auto_cast(enable=on_tpu, dtype="bfloat16", level="O2"):
        model = GPTForCausalLMPipe(cfg)
    if on_tpu:
        for _, p in model.named_parameters():
            p._data = p._data.astype(jax.numpy.bfloat16)

    # config-5 (BASELINE.md): the LLaMA-arch line runs sharding_stage=3
    # END TO END (docs/ZERO.md) — params resident as dp shards, grads
    # reduce-scattered, the update on 1/degree slots, scan-body
    # just-in-time weight gathers — over every addressable chip. One
    # chip is the degree-1 degenerate of the SAME code path (the zero
    # plan disengages, GSPMD placements are no-ops), not a separate
    # single-chip approximation.
    zero_stage, zero_degree, zero_mesh = 0, 1, None
    if model_kind == "llama":
        from paddle_tpu.distributed import fleet as _fleet

        zero_stage = 3
        zero_degree = len(jax.devices())
        strategy = _fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                                   "pp_degree": 1,
                                   "sharding_degree": zero_degree}
        _fleet.init(is_collective=True, strategy=strategy)
        zero_mesh = _fleet.get_fleet_mesh()

    # PTPU_ADAM8=1: blockwise-int8 moments (8-bit Adam) — frees ~4GB of
    # optimizer HBM at 1.3B, buying remat headroom (r4; measured LOSING
    # on this chip, defaults off — docs/ROUND4_RESPONSE.md)
    # PTPU_ADAM_FACTORED=1: Adafactor-style factored second moment —
    # frees ~2.6GB (m2) with fp32 math, no quant round-trips (r5)
    # The multi-chip stage-3 line uses PLAIN fp32 moments instead:
    # factored/int8 moments compute cross-element statistics that can't
    # run on a 1/degree shard (the zero plan would decline), and full
    # moments divided by the shard degree beat factored's ~half saving
    # from degree 2 up (docs/ZERO.md).
    sharded_update = zero_stage >= 2 and zero_degree > 1
    opt = paddle.optimizer.AdamW(
        learning_rate=3e-4, parameters=model.parameters(),
        moment_dtype=(None if sharded_update else
                      ("int8" if os.environ.get("PTPU_ADAM8", "")
                       not in ("", "0") else None)),
        factored=(not sharded_update
                  and os.environ.get("PTPU_ADAM_FACTORED", "")
                  not in ("", "0")))
    if zero_stage:
        from paddle_tpu.distributed import group_sharded_parallel

        model, opt, _ = group_sharded_parallel(model, opt, "p_g_os")

    def train_fn(ids, labels):
        # fused chunked head+CE: full logits never materialize (models/gpt.py)
        return model.loss(ids, labels)

    def make_step():
        if zero_mesh is not None:
            from paddle_tpu.distributed.parallel_step import ShardedTrainStep

            return ShardedTrainStep(model, train_fn, opt, zero_mesh)
        return TrainStep(model, train_fn, opt)

    from paddle_tpu import memory as pmem

    # quant-compute axis (docs/QUANT.md): every grid candidate also
    # REQUESTS the scaled fp8/int8 GEMM mode (`quant:all` entries appended
    # to its names: policy). The request creates the amax buffer and rides
    # the plan-cache key; trace-time ENGAGEMENT still resolves behind the
    # parity gate / CPU default-off / PTPU_QUANT_COMPUTE, so a red gate
    # prices and runs the same wide programs with a passthrough buffer.
    # PTPU_BENCH_QUANT=0 drops the request (no buffer — the structural
    # escape hatch, hex-identical to the pre-quant programs).
    env_bquant = os.environ.get("PTPU_BENCH_QUANT", "").strip().lower()
    quant_grid = (None,) if env_bquant in ("0", "off") else ("all",)

    def _quant_policy(policy, q):
        # the request rides the names: policy (models/gpt.py
        # _resolve_remat strips + resolves it); other policies can't
        # carry quant entries
        return (f"{policy},quant:{q}"
                if q and str(policy).startswith("names:") else policy)

    if env_batch and env_remat:
        # reproduce path: only pin the head chunk when the sweep pinned it
        # too — otherwise keep the kernel default the recorded round used.
        # The explicit policy is taken verbatim (carry your own quant:
        # entries to reproduce a quantized round).
        candidates = [pmem.Candidate(
            int(env_batch), env_remat,
            head_chunk=int(env_hchunk) if env_hchunk else None)]
        require_fit = False  # trust the sweep; still price + record it
    else:
        candidates = [
            pmem.Candidate(b, p, head_chunk=hc, quant=q)
            for b in ((int(env_batch),) if env_batch else batch_grid)
            for p in ((env_remat,) if env_remat else policy_grid)
            for hc in hchunk_grid
            for q in quant_grid
        ]
        require_fit = True

    def step_factory(cand):
        pol = _quant_policy(cand.policy, getattr(cand, "quant", None))
        cfg.recompute = pol != "none"
        cfg.recompute_policy = pol
        cfg.head_chunk = cand.head_chunk
        s = make_step()
        return s, (jax.ShapeDtypeStruct((cand.batch, seq), jax.numpy.int32),
                   jax.ShapeDtypeStruct((cand.batch, seq), jax.numpy.int64))

    def act_bytes(cand):
        return pmem.estimate_stacked_activation_bytes(
            cand.policy, num_layers=cfg.num_layers, batch=cand.batch,
            seq=seq, hidden=cfg.hidden_size, num_heads=cfg.num_heads,
            num_kv_heads=cfg.num_kv_heads,
            intermediate=cfg.intermediate_size,
            act_bytes=2 if on_tpu else 4)

    # cache key must carry every knob that changes the lowered program's
    # memory profile — a decision priced under factored Adam reused for a
    # full-moment sweep would hand back a config that OOMs (the exact
    # failure class the planner exists to prevent)
    mem_envs = tuple(
        (k, os.environ.get(k, ""))
        for k in ("PTPU_ADAM_FACTORED", "PTPU_ADAM8", "PTPU_INT8_HEAD",
                  "PTPU_PALLAS_RMS", "PTPU_FUSED_ADDRMS", "PTPU_INT8_FFN",
                  "PTPU_FA_BLOCK", "PTPU_FA_BWD_BLOCK",
                  "PTPU_UNROLL_LAYERS", "PTPU_CE_CHUNK", "PTPU_CE_VCHUNK",
                  "PTPU_LOSS_HEAD", "PTPU_ROPE_HOIST",
                  # scan/seam knobs change the lowered program wholesale
                  # (scan body vs unrolled layers, fused vs plain seams);
                  # the planner key also carries the scan mode itself
                  # (memory/planner.py), this is belt + suspenders
                  "PTPU_SCAN_LAYERS", "PTPU_FUSED_FFN", "PTPU_FUSED_SEAMS",
                  # comms knobs change the lowered program (manual-region
                  # grad reduce, bucket layout, fused tp seams) — a plan
                  # priced under one comm regime must not be reused under
                  # another (docs/COMMS.md)
                  "PTPU_QUANT_COLLECTIVES", "PTPU_QUANT_GRADS",
                  "PTPU_COMM_BUCKET_MB", "PTPU_QUANT_MIN_NUMEL",
                  "PTPU_QUANT_EXCLUDE", "PTPU_TP_SEAM", "PTPU_COMM_SLAB",
                  # zero knobs change the whole step program (manual
                  # region layout, slot shapes, gather seams) —
                  # docs/ZERO.md
                  "PTPU_ZERO_MODE", "PTPU_ZERO_JIT_GATHER",
                  "PTPU_QUANT_PARAM_GATHER",
                  # quant-compute knobs: a plan priced with wide GEMMs
                  # must not replay across a PTPU_QUANT_COMPUTE flip
                  # (planner.py also keys on quant.cache_key_knobs() —
                  # belt + suspenders, docs/QUANT.md)
                  "PTPU_QUANT_COMPUTE", "PTPU_QUANT_DTYPE",
                  "PTPU_QUANT_AMAX_HIST", "PTPU_QUANT_GATE_TOL",
                  "PTPU_INT8_WEIGHTS", "PTPU_BENCH_QUANT",
                  # layout knobs (docs/AUTOTUNE.md): an autotuned
                  # decision priced under one engagement regime must
                  # not replay across a knob flip — nor may a
                  # hand-picked plan replay into an --autotune run
                  "PTPU_AUTOTUNE", "PTPU_PIPELINE_SCHEDULE",
                  "PTPU_RING_ATTN", "PTPU_SHARDED_HEAD", "PTPU_COMPOSED",
                  "PTPU_LINK_GBPS", "PTPU_LAYOUT_CACHE")
    ) + (("int8_head", F.int8_head_enabled()),  # gate outcome, not just env
         ("quant_gate", _pquant.quant_gate()))
    # ZeRO pricing record (docs/ZERO.md): the candidate programs compile
    # ON the sharded mesh, so their memory_analysis peak is already
    # per-device — analytic pools stay 0 and only stage/degree ride the
    # record + plan-cache key (a stage-3 decision never replays for a
    # stage-0 build). The analytic pools are for planning a SHARDED
    # config from an UNSHARDED compile (memory.zero_hbm_savings).
    zero_info = ({"stage": zero_stage, "degree": zero_degree,
                  "param_bytes": 0, "slot_bytes": 0, "grad_bytes": 0}
                 if zero_stage else None)
    cache_extra = (model_kind, cfg.vocab_size, cfg.hidden_size,
                   cfg.num_layers, cfg.num_heads, cfg.num_kv_heads,
                   cfg.intermediate_size, seq,
                   "bf16" if on_tpu else "f32", mem_envs)
    layout_block = {"enabled": False}
    if autotune_on:
        # the layout autotuner (docs/AUTOTUNE.md) owns mesh + model +
        # step: it searches every (dp, sharding, mp, pp, sep) x zero x
        # schedule point the compose lattice accepts (pruning the rest
        # with structured Reasons, lowering-only pricing for survivors)
        # and hands back the BUILT ShardedTrainStep for the winner. The
        # hand-picked config rides along as the baseline — it is scored
        # through the same cost model, may legitimately win, and is
        # what the bench_gate LAYOUT gate compares against. batch in a
        # LayoutCandidate is rows PER DATA SHARD (global = batch x
        # dp*sharding*sep).
        import copy as _copy

        ndev = len(jax.devices())
        factory = pmem.flagship_gpt_factory(
            lambda: _copy.deepcopy(cfg), amp_bf16=on_tpu,
            optimizer_factory=lambda m: paddle.optimizer.AdamW(
                learning_rate=3e-4, parameters=m.parameters()))
        layouts = pmem.enumerate_layouts(
            ndev,
            batches=((int(env_batch),) if env_batch else batch_grid),
            policies=((env_remat,) if env_remat else policy_grid),
            head_chunks=hchunk_grid, quants=quant_grid)
        if model_kind == "llama":
            # the hand-picked config-5 layout: stage-3 over every chip
            base_layout = pmem.LayoutCandidate(
                sharding=ndev, zero_stage=3, batch=batch_grid[0],
                policy=policy_grid[0], head_chunk=hchunk_grid[0],
                quant=quant_grid[0])
        else:
            base_layout = pmem.LayoutCandidate(
                dp=ndev, batch=batch_grid[0], policy=policy_grid[0],
                head_chunk=hchunk_grid[0], quant=quant_grid[0])
        step, layout_decision = pmem.autotune_train_step(
            factory, seq_len=seq, layouts=layouts, baseline=base_layout,
            require_fit=require_fit, cache_extra=cache_extra)
        layout_block = layout_decision.as_json()
        # the winner's PlanDecision-shaped record keeps the "memory"
        # block (and everything downstream of `decision`) unchanged
        decision = pmem.PlanDecision(**layout_decision.memory)
        model, opt = step.model, step.optimizer
        batch = decision.batch
        cfg.recompute = decision.policy != "none"
        cfg.recompute_policy = _quant_policy(
            decision.policy, getattr(decision, "quant", None))
        cfg.head_chunk = decision.head_chunk
    else:
        from paddle_tpu.nn.functional.fused_cross_entropy import (
            resolve_vocab_chunk)

        def _program_key(c):
            # head_chunk reaches the traced program only through the
            # RESOLVED CE vocab chunk — candidates whose chunks clamp
            # to the same effective value share one lowering (the
            # planner memoizes on this key, docs/MEMORY.md)
            return (c.batch,
                    _quant_policy(c.policy, getattr(c, "quant", None)),
                    resolve_vocab_chunk(cfg.vocab_size, c.head_chunk),
                    getattr(c, "depth", None))

        decision = pmem.plan_train_step(
            step_factory, candidates, require_fit=require_fit,
            act_bytes_fn=act_bytes, zero=zero_info,
            opt_state_bytes=opt.slot_nbytes(
                {n: p._data for n, p in model.named_parameters()},
                shard_degree=zero_degree if zero_stage else 1),
            program_key_fn=_program_key,
            cache_extra=cache_extra)
        batch = decision.batch
        cfg.recompute = decision.policy != "none"
        cfg.recompute_policy = _quant_policy(decision.policy,
                                             getattr(decision, "quant",
                                                     None))
        cfg.head_chunk = decision.head_chunk

        # NOTE: on a plan-cache miss the winning program compiles twice
        # (once AOT in the planner, once here at warmup — jit's dispatch
        # cache is not fed by the AOT path). The disk cache makes every
        # later run of the same config skip planning entirely, so the
        # cost is first-run-per-config only.
        step = make_step()

    # Crash-safe checkpointing (--ckpt-dir): per-step committed saves via
    # CheckpointManager, --resume auto restore of the newest committed
    # step BEFORE warmup (the compiled step seeds its optimizer state
    # from the restored slots), and a PreemptionGuard that turns
    # SIGTERM/SIGINT into one final synchronous save + clean exit
    # (docs/CHECKPOINT.md). Default driver runs pass no flags: inactive.
    manager = guard = None
    start_step = 0
    if ckpt is not None and ckpt.ckpt_dir:
        from paddle_tpu.distributed.checkpoint.manager import (
            CheckpointManager, PreemptionGuard)

        # per-model subroot: the default TPU driver run trains BOTH
        # tracked configs, whose state dicts must not share a step dir
        manager = CheckpointManager(
            os.path.join(ckpt.ckpt_dir, model_kind), keep=ckpt.ckpt_keep)
        # gate on the newest GOOD step, not latest_step(): after a
        # guard-aborted run every committed step can carry a BAD marker,
        # and restore only walks good steps — gating on a BAD latest
        # would crash with NoCheckpointError instead of measuring fresh
        latest = manager.last_good_step()
        if ckpt.resume == "auto" and latest is not None:
            if latest < steps:
                start_step = manager.restore_training_state(model, opt)
            else:
                # a finished run's checkpoint would leave ZERO timed
                # steps and fabricate an absurd tokens/sec headline —
                # measure fresh instead (the committed steps remain)
                import sys

                print(f"# ckpt: latest committed step {latest} >= bench "
                      f"steps {steps}; measuring fresh (not resuming)",
                      file=sys.stderr)
        guard = PreemptionGuard(manager).install()

    # Resilience (--guard, docs/RESILIENCE.md): StepGuard wraps the
    # compiled step with the skip/rewind anomaly policy (the rewind is
    # CheckpointManager-backed when --ckpt-dir is set) and a HangWatchdog
    # heartbeats the timed loop, dumping debris under the checkpoint
    # root on a wedged step. The guard decision totals land in the
    # "resilience" block of the JSON line; tools/bench_gate.py fails a
    # clean run that reports any anomaly or rollback.
    step_guard = watchdog = None
    if ckpt is not None and getattr(ckpt, "guard", False):
        from paddle_tpu.resilience import HangWatchdog, StepGuard

        step_guard = StepGuard(step, manager=manager)
        # the watchdog always runs with --guard (the flag promises hang
        # protection): debris lands under the checkpoint root when one
        # exists, else in a temp dir named on stderr
        if manager is not None:
            debris_dir = os.path.join(manager.root, "debris")
        else:
            import sys
            import tempfile

            debris_dir = tempfile.mkdtemp(prefix="ptpu_bench_debris_")
            print(f"# --guard without --ckpt-dir: hang debris -> "
                  f"{debris_dir}", file=sys.stderr)
        watchdog = HangWatchdog(
            debris_dir,
            min_hang_seconds=float(
                os.environ.get("PTPU_HANG_SECONDS", "120"))).start()

    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32))
    labels = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int64))

    loss = step(ids, labels)  # compile + warmup
    _ = float(loss.numpy())
    loss = step(ids, labels)
    _ = float(loss.numpy())

    bench_step = telemetry.histogram(
        "bench_step_seconds", "bench timed-loop per-step dispatch wall "
        "time (async: the device sync runs after the loop, so trailing "
        "device work shows up only in the tokens/sec line)")
    n_ran = 0
    t0 = time.perf_counter()
    t_prev = t0
    gstep = start_step + 1
    while gstep <= steps:
        # the "step" span is the anatomy root: everything recorded
        # inside (train_step/dispatch, ckpt phases, guard fetches)
        # decomposes it in trace.step_anatomy(). A no-op when tracing
        # is off (shared noop singleton).
        with ptrace.span("step", attrs={"step": gstep}, cat="step"):
            if watchdog is not None:
                watchdog.step_started(gstep)
            if step_guard is not None:
                out = step_guard(gstep, ids, labels)
                accepted, next_step = out.accepted, out.next_step
                if accepted:
                    loss = out.loss
            else:
                loss = step(ids, labels)
                accepted, next_step = True, gstep + 1
            if watchdog is not None:
                watchdog.step_finished()
            if accepted and manager is not None \
                    and gstep % ckpt.ckpt_every == 0:
                manager.save_training_state(gstep, model, opt,
                                            train_step=step,
                                            async_save=True)
        t_now = time.perf_counter()
        bench_step.observe(t_now - t_prev)
        t_prev = t_now
        if accepted:
            n_ran += 1
        # poll preemption on EVERY iteration, not only accepted ones: a
        # SIGTERM landing mid anomaly-retry storm must still commit the
        # (pre-anomaly, still-good) live state before the ladder can
        # abort. next_step-1 names the step the live trees correspond
        # to on every path (accept: gstep; skip: the last accepted
        # step; rollback: the restored step).
        if guard is not None and guard.should_stop():
            save_at = next_step - 1
            manager.wait()
            if save_at > start_step:
                manager.save_training_state(save_at, model, opt,
                                            train_step=step)
            break
        gstep = next_step
    _ = float(loss.numpy())  # sync
    dt = time.perf_counter() - t0
    if watchdog is not None:
        watchdog.stop()
    if manager is not None:
        manager.wait()  # surface any async writer failure before reporting
    if guard is not None:
        guard.uninstall()

    # dp-style loss sync over the default group: single-chip it degrades
    # to a no-op copy, but the collective call/byte counters it ticks are
    # exactly what a multi-chip run reports — the telemetry block always
    # carries the comms dimension
    import paddle_tpu.distributed as dist

    dist.all_reduce(loss, op=dist.ReduceOp.AVG)

    # "comms" block (docs/COMMS.md): bytes/calls/seconds per op+axis from
    # the telemetry counters, the exact-vs-int8 traffic split, and the
    # quantized-reduce parity probe tools/bench_gate.py gates on. On a
    # single chip the probe is skipped ({"enabled": false}) but the
    # per-op accounting still lands — the knob state is always visible.
    from paddle_tpu.distributed import collectives as _coll
    from paddle_tpu.distributed.fleet import active_mesh as _active_mesh

    comms = _coll.comms_summary(
        telemetry.snapshot(),
        parity=_coll.parity_probe(_active_mesh()))

    # "quant" block (docs/QUANT.md): the scaled fp8/int8 GEMM state of
    # THIS run — the request (candidate quant axis -> policy quant:
    # entries), the trace-time engagement verdict (compose's quant_gemm
    # plan row: engaged, or the structured decline reason), the numeric
    # parity-gate report, and an embedded reference-free loss-drift A/B
    # (exact vs scaled training on a fixed tiny problem, quant.gemm
    # loss_drift_probe) that tools/bench_gate.py's QUANT gate checks
    # against the 0.5% budget — no baseline file needed, like the comms
    # parity probe above.
    from paddle_tpu.distributed.collectives import compose as _compose_q

    _qv = _compose_q.last_verdicts().get("quant_gemm")
    _q_requested = bool(getattr(decision, "quant", None))
    quant_block = {
        "requested": _q_requested,
        "dtype": _pquant.quant_dtype(),
        "engaged": bool(_qv and _qv[0] == "engaged"),
        "verdict": _qv[0] if _qv else None,
        "reason": _qv[1] if _qv else None,
        "gate": _pquant.quant_gate_report(),
        "loss_drift_rel": round(float(_pquant.loss_drift_probe()), 6),
        "loss_drift_budget": 0.005,
        "amax_hist_len": _pquant.amax_hist_len(),
    }

    # "zero" block (docs/ZERO.md): the ZeRO execution state of THIS run —
    # stage/degree always recorded; when the plan engaged, the per-step
    # gathered-bytes / reduce-scattered-bytes accounting and param-kind
    # counts land next to "comms"/"memory". A degree-1 run records
    # engaged=false (the honest single-chip degenerate).
    zplan = step.zero_plan() if hasattr(step, "zero_plan") else None
    zero_block = (zplan.zero_summary() if zplan is not None
                  else {"engaged": False, "stage": zero_stage,
                        "shard_degree": zero_degree})

    # "pipe" block (docs/PIPELINE.md): pipeline-schedule state + bubble
    # accounting. Engagement comes from the composed plan
    # (collectives/compose); the bubble fractions are priced from
    # MEASURED per-phase stage costs on this host (pipeline.bubble_report
    # — wall-clocking the ring on a core-shared CPU mesh measures
    # contention, not idleness, docs/ZB_WALLCLOCK.md). Without a live pp
    # axis the reference pp=2 x n_micro=4 shape keeps the schedule
    # arithmetic tracked round over round; bench_gate's PIPE gate fails
    # a bubble fraction over the 1F1B budget or a pp-live mesh whose
    # composition never engaged.
    from paddle_tpu.distributed import pipeline as _pl

    cplan = (step.composed_plan()
             if hasattr(step, "composed_plan") else None)
    pp_engaged = bool(cplan is not None and cplan.pp_axis)
    _mesh_b = _active_mesh()
    pp_live = bool(_mesh_b is not None and "pp" in _mesh_b.dim_names
                   and _mesh_b.get_dim_size("pp") > 1)
    from paddle_tpu.distributed.collectives import compose as _compose_b

    # an escape-hatch knob explicitly disabling composition is an
    # intended A/B baseline, not a silent decline — recorded so the
    # PIPE gate only fails the "enabled-but-never-engaged" case.
    # composed_enabled() folds the PTPU_QUANT_COLLECTIVES master knob
    disabled_by_knob = bool(
        not _compose_b.composed_enabled()
        or _compose_b.pipeline_schedule_disabled())
    # the structured why-not for a pp-live mesh without a schedule: a
    # pp-replicated decoder (no stage placements) engages composition
    # without a pipeline row; otherwise the composed plan's own decline
    # reason carries the story. The PIPE gate passes the documented
    # config-shape declines and fails everything silent.
    decline_reason = None
    if pp_live and not pp_engaged:
        if cplan is not None:
            decline_reason = "no_stage_placements"
        else:
            _v = _compose_b.last_verdicts().get("composed")
            decline_reason = _v[1] if _v else None
    pipe_block = dict(
        _pl.bubble_report(
            cplan.pp if pp_engaged else 2,
            cplan.n_micro if pp_engaged else 4,
            schedule=(cplan.pp_schedule if pp_engaged
                      else getattr(cfg, "pp_schedule", "1f1b") or "1f1b")),
        engaged=pp_engaged, pp_axis_live=pp_live,
        disabled_by_knob=disabled_by_knob,
        decline_reason=decline_reason)

    # "compile" block (docs/SCAN.md): trace/lower/compile wall seconds +
    # serialized HLO bytes of THIS run's warmup TrainStep build, with the
    # depth and scan mode that produced them — the measurement behind the
    # scan-over-layers flat-compile claim. tools/bench_gate.py fails a
    # round whose compile time regresses >25% at the same depth/mode.
    from paddle_tpu import jit as pjit
    from paddle_tpu.models.gpt import scan_layers_enabled

    step_label = f"TrainStep[{type(model).__name__}]"
    compile_block = dict(pjit.compile_summary(step_label) or {},
                         function=step_label,
                         num_layers=cfg.num_layers,
                         scan_layers=bool(scan_layers_enabled()))

    tokens_per_sec = batch * seq * max(n_ran, 1) / dt

    # "anatomy" block (docs/TELEMETRY.md Tracing): the traced run's
    # per-phase decomposition of the timed loop, the cost-analysis
    # device estimate vs measured wall (host gap), and where the full
    # trace files landed. {"enabled": false} without --trace.
    anatomy = {"enabled": False}
    if trace_on:
        measured = dt / max(n_ran, 1)
        anat = ptrace.step_anatomy() or {}
        cost = (step.last_dispatch_cost()
                if hasattr(step, "last_dispatch_cost") else None)
        device = None
        if cost:
            dev = cost["device_seconds_est"]
            host_gap = max(0.0, measured - dev)
            placeholder = bool(cost["peak_model_placeholder"])
            device = {
                "flops_per_step": cost["flops"],
                "bytes_accessed_per_step": cost["bytes_accessed"],
                "device_seconds_est_per_step": round(dev, 6),
                "host_gap_seconds_per_step": round(host_gap, 6),
                # the host-overhead bench_gate input: None (not gated)
                # when the roofline peaks are placeholders (CPU dev)
                "host_gap_fraction": (round(host_gap / measured, 4)
                                      if measured > 0 and not placeholder
                                      else None),
                # cost-analysis MFU, alongside the measured "mfu" field:
                # program FLOPs over measured step wall over chip peak
                # (null on placeholder peaks — a CPU number would read
                # as a real attribution)
                "cost_mfu": (round(cost["flops"]
                                   / (measured * cost["peak_flops"]), 4)
                             if measured > 0 and not placeholder
                             else None),
                "peak_model_placeholder": placeholder,
            }
        os.makedirs(trace_dir, exist_ok=True)
        perfetto_path = os.path.join(
            trace_dir, f"trace_{model_kind}.perfetto.json")
        jsonl_path = os.path.join(trace_dir, f"trace_{model_kind}.jsonl")
        ptrace.to_perfetto(perfetto_path)
        ptrace.dump_jsonl(jsonl_path)
        anatomy = {
            "enabled": True,
            "steps_timed": max(n_ran, 1),
            "measured_step_seconds": round(measured, 6),
            "span_step_seconds_mean": anat.get("step_seconds_mean"),
            "phases": anat.get("phases") or {},
            "coverage": anat.get("coverage"),
            "device": device,
            "trace_files": {"perfetto": perfetto_path,
                            "jsonl": jsonl_path},
        }

    # fleet-serving smoke soak (--serve / PTPU_BENCH_SERVE=1): only on
    # the headline (non-llama) line so the driver pays one soak per run
    serving = {"enabled": False}
    serve_on = (bool(ckpt is not None and getattr(ckpt, "serve", False))
                or os.environ.get("PTPU_BENCH_SERVE", "") not in ("", "0"))
    if serve_on and model_kind != "llama":
        serving = _serving_smoke_block()

    # MFU: 6 * params * tokens/sec / peak_flops
    n_params = sum(int(np.prod(p.shape)) for _, p in model.named_parameters())
    model_flops = 6.0 * n_params * tokens_per_sec
    kind = jax.devices()[0].device_kind.lower()
    peak = (459e12 if "v5p" in kind or "v5" == kind else
            197e12 if "v5e" in kind or "v5 lite" in kind else
            275e12 if "v4" in kind else
            918e12 if "v6" in kind or "trillium" in kind else
            197e12) if on_tpu else 1e12  # bf16 peak per chip
    mfu = model_flops / peak

    if on_tpu:
        metric = ("llama7b_arch_8L_pretrain_tokens_per_sec"
                  if model_kind == "llama"
                  else "gpt3_1.3b_pretrain_tokens_per_sec")
    else:
        metric = "gpt_pretrain_tokens_per_sec"

    timeline_block = {"enabled": False}
    if ts_recorder is not None:
        ts_recorder.sample()        # the final totals land in the file
        ts_recorder.close()
        timeline_block = {
            "enabled": True,
            "path": ts_recorder.jsonl_path,
            "samples": ts_recorder.seq,
            "dropped": ts_recorder.dropped,
            "interval_seconds": record_interval,
        }
    print(json.dumps({
        "metric": metric,
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(mfu, 4),
        # explicit MFU field (same value as vs_baseline, which predates
        # it): model FLOPs 6*params*tokens/sec over the chip's bf16 peak
        # from the small chip table above — the driver-tracked headline
        "mfu": round(mfu, 4),
        # planner decision + XLA memory_analysis peak: a BENCH_r*.json
        # regression explains its memory state the same way the
        # "telemetry" key explains its time (tools/hbm_report.py diffs
        # two rounds' blocks; contract in docs/MEMORY.md)
        "memory": decision.as_json(),
        # layout autotuner outcome (--autotune / PTPU_AUTOTUNE=1,
        # docs/AUTOTUNE.md): winner + top-3 scored candidates, pruned
        # counts by compose Reason, search seconds — bench_gate's
        # LAYOUT gate fails a winner whose predicted score loses to
        # the hand-picked baseline or a silent fallback.
        # {"enabled": false} without the flag.
        "layout": layout_block,
        # guard decision totals (docs/RESILIENCE.md): a CLEAN bench run
        # must report zero anomalies and zero rollbacks — bench_gate
        # exits 1 otherwise. {"enabled": false} when --guard is off.
        # comms traffic split + parity probe (mirrors "telemetry"/
        # "memory"; contract in docs/COMMS.md, gated by bench_gate)
        "comms": comms,
        # low-precision compute state: request/engagement/decline, the
        # parity-gate report, and the embedded loss-drift A/B vs the
        # 0.5% budget (docs/QUANT.md; bench_gate QUANT gate)
        "quant": quant_block,
        # ZeRO execution state: stage, shard degree, gathered/rs bytes
        # per step (docs/ZERO.md contract)
        "zero": zero_block,
        # pipeline schedule + measured-cost bubble accounting
        # (docs/PIPELINE.md; bench_gate PIPE gate)
        "pipe": pipe_block,
        # warmup-build compile phases + HLO program size (docs/SCAN.md)
        "compile": compile_block,
        # fleet-serving smoke soak (--serve; docs/SERVING.md): replica
        # cold start + goodput scaling + p99 TTFT vs budget, gated by
        # bench_gate's SERVE/COLD gates
        "serving": serving,
        # background time-series recording (--record; docs/TELEMETRY.md
        # "Time series, SLOs..."): cadence samples of the registry in a
        # JSONL timeline next to the bench output, inspected by
        # tools/telemetry_report.py --timeline
        "timeline": timeline_block,
        # step anatomy from the span tracer (--trace / PTPU_TRACE=1):
        # per-phase seconds, device-vs-host split from cost_analysis,
        # cost-analysis MFU next to the measured "mfu" field, and the
        # exported trace file paths (docs/TELEMETRY.md Tracing;
        # tools/bench_gate.py gates host_gap_fraction)
        "anatomy": anatomy,
        "resilience": (dict(step_guard.summary(),
                            watchdog_fires=(len(watchdog.debris_files)
                                            if watchdog is not None else 0))
                       if step_guard is not None else {"enabled": False}),
        "telemetry": telemetry.snapshot(),
    }), flush=True)


def main():
    import argparse
    import gc
    import logging

    import jax

    ap = argparse.ArgumentParser(
        description="paddle_tpu headline pretrain benchmark")
    ap.add_argument("--ckpt-dir", default=os.environ.get("PTPU_BENCH_CKPT")
                    or None, help="enable crash-safe checkpointing under "
                    "this root (docs/CHECKPOINT.md)")
    ap.add_argument("--ckpt-every", type=int, default=5,
                    help="async committed save every N steps")
    ap.add_argument("--ckpt-keep", type=int, default=3,
                    help="retention: newest N committed steps")
    ap.add_argument("--resume", choices=("auto", "none"), default="auto",
                    help="auto = restore the newest committed step")
    ap.add_argument("--trace", action="store_true",
                    default=os.environ.get("PTPU_TRACE", "")
                    not in ("", "0"),
                    help="span tracer ON for the run: Perfetto + JSONL "
                    "trace files and an 'anatomy' block in the JSON "
                    "line (docs/TELEMETRY.md Tracing)")
    ap.add_argument("--trace-dir", default=".",
                    help="where trace_<model>.perfetto.json / .jsonl "
                    "land (default: cwd)")
    ap.add_argument("--serve", action="store_true",
                    default=os.environ.get("PTPU_BENCH_SERVE", "")
                    not in ("", "0"),
                    help="attach a fleet-serving smoke soak block "
                    "(replica cold start, goodput scaling, p99 TTFT) "
                    "to the headline JSON line (docs/SERVING.md)")
    ap.add_argument("--guard", action="store_true",
                    default=os.environ.get("PTPU_BENCH_GUARD", "")
                    not in ("", "0"),
                    help="StepGuard anomaly policy + hang watchdog around "
                    "the timed loop (docs/RESILIENCE.md); decision totals "
                    "land in the JSON 'resilience' block")
    ap.add_argument("--record", action="store_true",
                    default=os.environ.get("PTPU_RECORD", "")
                    not in ("", "0"),
                    help="record a background time-series timeline "
                    "(registry samples every --record-interval seconds) "
                    "into timeline_<model>.jsonl next to the bench "
                    "output; adds the 'timeline' block to the JSON line "
                    "(docs/TELEMETRY.md)")
    ap.add_argument("--record-interval", type=float, default=None,
                    help="seconds between --record samples "
                    "(default 0.5, or PTPU_RECORD_INTERVAL)")
    ap.add_argument("--autotune", action="store_true",
                    default=os.environ.get("PTPU_AUTOTUNE", "")
                    not in ("", "0"),
                    help="route the headline lines through the layout "
                    "autotuner (mesh/schedule search over the compose "
                    "lattice, docs/AUTOTUNE.md); adds the 'layout' "
                    "block to the JSON line")
    ap.add_argument("--long-context", action="store_true",
                    default=os.environ.get("PTPU_BENCH_LONG", "")
                    not in ("", "0"),
                    help="additionally emit the *_seq32k long-context "
                    "metric line: ring attention over a sep mesh "
                    "(32k tokens on TPU; reduced-length CPU smoke) — "
                    "docs/ATTENTION.md")
    args = ap.parse_args()

    # surface which attention path ran (proof the Pallas kernel engaged)
    logging.basicConfig()
    logging.getLogger("paddle_tpu.pallas").setLevel(logging.INFO)

    on_tpu = jax.default_backend() not in ("cpu",)
    kind = os.environ.get("PTPU_BENCH_MODEL")
    if kind is not None or not on_tpu:
        if args.long_context:
            run_long_context(ckpt=args)
            gc.collect()
        run_model(kind or "gpt", ckpt=args)
        return
    # default driver run: BOTH tracked lines — config-5 (LLaMA-arch)
    # FIRST, the headline GPT line LAST so the parsed metric stays stable
    run_model("llama", ckpt=args)
    gc.collect()
    if args.long_context:
        run_long_context(ckpt=args)
        gc.collect()
    run_model("gpt", ckpt=args)


if __name__ == "__main__":
    main()
