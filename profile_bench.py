"""Profile the headline bench step and print the per-op device-time table.

Dev tool (not part of the driver contract): runs a few train steps under
jax.profiler.trace and aggregates the device plane via
paddle_tpu.profiler.xplane — the guessing-free way to see where the step
time goes on the real chip.
"""
import json
import os
import shutil
import sys
import time

import numpy as np


def main():
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLMPipe

    on_tpu = jax.default_backend() not in ("cpu",)
    policy = os.environ.get("PTPU_BENCH_REMAT", "attn")
    if on_tpu:
        cfg = GPTConfig(vocab_size=32000, hidden_size=2048, num_layers=24,
                        num_heads=16, max_seq_len=2048, dropout=0.0,
                        dtype="bfloat16", recompute=policy != "none",
                        recompute_policy=policy)
        batch, seq = int(os.environ.get("PTPU_BENCH_BATCH", "6")), 2048
    else:
        cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=2,
                        num_heads=4, max_seq_len=256, dropout=0.0,
                        recompute=True, recompute_policy=policy)
        batch, seq = 2, 128

    with paddle.amp.auto_cast(enable=on_tpu, dtype="bfloat16", level="O2"):
        model = GPTForCausalLMPipe(cfg)
    if on_tpu:
        for _, p in model.named_parameters():
            p._data = p._data.astype(jax.numpy.bfloat16)
    opt = paddle.optimizer.AdamW(
        learning_rate=3e-4, parameters=model.parameters(),
        factored=os.environ.get("PTPU_ADAM_FACTORED", "1") not in ("", "0"))
    step = TrainStep(model, lambda i, l: model.loss(i, l), opt)
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32))
    labels = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int64))

    for _ in range(2):  # compile + warm
        _ = float(step(ids, labels).numpy())

    logdir = os.environ.get("PTPU_PROFILE_DIR", "/tmp/ptpu_profile")
    shutil.rmtree(logdir, ignore_errors=True)
    with jax.profiler.trace(logdir):
        for _ in range(3):
            loss = step(ids, labels)
        _ = float(loss.numpy())

    from paddle_tpu.profiler.xplane import (device_op_stats, format_table,
                                            summarize_families)

    rows = device_op_stats(logdir)
    if not rows:
        print("no device events found under", logdir)
        sys.exit(1)
    print(format_table(rows, limit=40))
    print()
    fams = summarize_families(rows)
    print(json.dumps(fams, indent=1))
    total_us = sum(r["total_us"] for r in rows)
    print(f"total device time: {total_us/1e6:.3f} s over 3 steps "
          f"=> {total_us/3e6:.3f} s/step")


if __name__ == "__main__":
    main()
