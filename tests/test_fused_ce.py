"""Chunked / vocab-sharded cross-entropy LM head (ISSUE 4).

Covers:
- loss + grad parity of the vocab-chunked kernel against the dense-logits
  reference at several (tokens, vocab, chunk) shapes; EXACT match when
  chunk >= vocab (single chunk = the dense formula);
- ignore_index masking;
- the vocab-sharded variant matching the unsharded kernel on a 1xN mesh
  (loss and both grads);
- the int8-head parity gate and its default-on criterion / env override;
- the headline memory guarantee: the lowered train-step jaxpr carries NO
  [tokens, vocab] logits or grad-logits array (and the dense oracle
  does — the assertion is two-sided);
- the memory planner's head-chunk plan dimension.
"""
import os
import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.nn.functional import fused_cross_entropy as FCE


def _dense_ref(h, w2, y, ignore_index=-100):
    """Dense-logits oracle, written with the same max-subtracted LSE the
    kernel uses so a single-chunk run can match it bit for bit."""
    logits = jnp.einsum("nh,vh->nv", h, w2,
                        preferred_element_type=jnp.float32)
    m = jnp.max(logits, -1)
    lse = m + jnp.log(jnp.sum(jnp.exp(logits - m[:, None]), -1))
    valid = y != ignore_index
    gold = jnp.take_along_axis(
        logits, jnp.where(valid, y, 0)[:, None], 1)[:, 0]
    n = jnp.maximum(valid.sum().astype(jnp.float32), 1.0)
    return jnp.sum(jnp.where(valid, lse - gold, 0.0)) / n


def _probe(tokens, vocab, hidden=24, seed=0, masked=0):
    rng = np.random.default_rng(seed)
    h = jnp.asarray(rng.standard_normal((tokens, hidden)).astype(np.float32))
    w = jnp.asarray(rng.standard_normal((vocab, hidden)).astype(np.float32))
    y = rng.integers(0, vocab, (tokens,))
    if masked:
        y[rng.choice(tokens, masked, replace=False)] = -100
    return h, w, jnp.asarray(y.astype(np.int32))


class TestChunkedParity:
    @pytest.mark.parametrize("tokens,vocab,chunk", [
        (37, 103, 7),      # ragged: vocab % chunk != 0, pad path
        (64, 256, 64),     # even split
        (48, 96, 96),      # chunk == vocab
        (16, 50, 1024),    # chunk > vocab (clamped to one chunk)
        (33, 129, 128),    # one full + one 1-wide chunk
    ])
    def test_loss_and_grads_match_dense(self, tokens, vocab, chunk):
        h, w, y = _probe(tokens, vocab, masked=3)

        def f(h, w):
            return FCE.chunked_lm_loss_arrays(h, w, y, vocab_chunk=chunk)

        l, (gh, gw) = jax.value_and_grad(f, argnums=(0, 1))(h, w)
        ld, (ghd, gwd) = jax.value_and_grad(
            lambda h, w: _dense_ref(h, w, y), argnums=(0, 1))(h, w)
        np.testing.assert_allclose(float(l), float(ld), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(gh), np.asarray(ghd),
                                   atol=3e-5)
        np.testing.assert_allclose(np.asarray(gw), np.asarray(gwd),
                                   atol=3e-5)

    def test_exact_when_chunk_covers_vocab(self):
        """chunk >= vocab degenerates to ONE chunk whose online-LSE update
        is literally the dense max-subtracted formula — bitwise equal."""
        h, w, y = _probe(29, 61)
        l = FCE.chunked_lm_loss_arrays(h, w, y, vocab_chunk=61)
        assert float(l) == float(_dense_ref(h, w, y))
        l2 = FCE.chunked_lm_loss_arrays(h, w, y, vocab_chunk=4096)
        assert float(l2) == float(_dense_ref(h, w, y))

    def test_all_masked_rows_do_not_nan(self):
        h, w, _ = _probe(8, 32)
        y = jnp.full((8,), -100, jnp.int32)
        l = FCE.chunked_lm_loss_arrays(h, w, y, vocab_chunk=8)
        assert float(l) == 0.0
        g = jax.grad(lambda h: FCE.chunked_lm_loss_arrays(
            h, w, y, vocab_chunk=8))(h)
        assert np.all(np.asarray(g) == 0.0)

    def test_transpose_y_false_layout(self):
        h, w, y = _probe(20, 40)
        l1 = FCE.chunked_lm_loss_arrays(h, w, y, vocab_chunk=16)
        l2 = FCE.chunked_lm_loss_arrays(h, w.T, y, transpose_y=False,
                                        vocab_chunk=16)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)

    def test_eager_tensor_entry_backward(self):
        """The paddle-level op records on the eager tape and its
        custom_vjp backward produces dense-reference grads."""
        h, w, y = _probe(12, 48)
        ht = paddle.to_tensor(np.asarray(h))
        wt = paddle.to_tensor(np.asarray(w))
        yt = paddle.to_tensor(np.asarray(y).astype(np.int64))
        ht.stop_gradient = False
        wt.stop_gradient = False
        loss = FCE.fused_chunked_cross_entropy(ht, wt, yt, vocab_chunk=16,
                                               int8=False)
        loss.backward()
        _, (ghd, gwd) = jax.value_and_grad(
            lambda h, w: _dense_ref(h, w, y), argnums=(0, 1))(h, w)
        np.testing.assert_allclose(ht.grad.numpy(), np.asarray(ghd),
                                   atol=3e-5)
        np.testing.assert_allclose(wt.grad.numpy(), np.asarray(gwd),
                                   atol=3e-5)


class TestShardedCE:
    def _mesh(self):
        from jax.sharding import Mesh

        return Mesh(np.array(jax.devices()[:4]).reshape(1, 4),
                    ("dp", "mp"))

    def test_matches_unsharded_on_1xN_mesh(self):
        mesh = self._mesh()
        h, w, y = _probe(37, 128, masked=4)

        ls = jax.jit(lambda h, w: FCE.sharded_lm_loss_arrays(
            h, w, y, mesh, "mp", vocab_chunk=16))(h, w)
        lu = FCE.chunked_lm_loss_arrays(h, w, y, vocab_chunk=16)
        np.testing.assert_allclose(float(ls), float(lu), rtol=1e-6)

        gs = jax.jit(jax.grad(lambda h, w: FCE.sharded_lm_loss_arrays(
            h, w, y, mesh, "mp", vocab_chunk=16), argnums=(0, 1)))(h, w)
        gu = jax.grad(lambda h, w: FCE.chunked_lm_loss_arrays(
            h, w, y, vocab_chunk=16), argnums=(0, 1))(h, w)
        np.testing.assert_allclose(np.asarray(gs[0]), np.asarray(gu[0]),
                                   atol=3e-5)
        np.testing.assert_allclose(np.asarray(gs[1]), np.asarray(gu[1]),
                                   atol=3e-5)

    def test_vocab_must_divide_axis(self):
        mesh = self._mesh()
        h, w, y = _probe(8, 30)
        with pytest.raises(ValueError, match="divide"):
            FCE.sharded_lm_loss_arrays(h, w, y, mesh, "mp")

    def test_shard_lm_head_marks_and_dispatches(self, monkeypatch):
        """GPTForCausalLMPipe.shard_lm_head + compute_loss: the marker
        routes the loss through the sharded kernel and the result matches
        the unsharded chunked loss."""
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.auto_parallel import set_mesh
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLMPipe

        # 1xN: the satellite contract. A >1 auto axis alongside the manual
        # mp axis trips this XLA's partial-manual SPMD partitioner (the
        # same pre-existing PartitionId failure class as the pipeline
        # suite, CHANGES.md PR-3) — the kernel itself is axis-agnostic.
        mesh = dist.ProcessMesh(shape=(1, 4), dim_names=["dp", "mp"])
        set_mesh(mesh)
        try:
            paddle.seed(3)
            cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=1,
                            num_heads=2, max_seq_len=32, dropout=0.0,
                            head_chunk=16)
            model = GPTForCausalLMPipe(cfg)
            rng = np.random.default_rng(0)
            ids = paddle.to_tensor(
                rng.integers(0, 128, (2, 16)).astype(np.int32))
            labels = paddle.to_tensor(
                rng.integers(0, 128, (2, 16)).astype(np.int64))
            base = float(model.loss(ids, labels).numpy())

            model.shard_lm_head(mesh, axis="mp")
            assert model.embed_tokens.weight._vocab_shard_axis == "mp"

            def f(i, l):
                return model.loss(paddle.Tensor(i), paddle.Tensor(l))._data

            sharded = float(jax.jit(f)(ids._data, labels._data))
            np.testing.assert_allclose(sharded, base, rtol=1e-5)
        finally:
            set_mesh(None)


class TestInt8HeadGate:
    def test_gate_passes_on_probe(self):
        """The default-on criterion: the deterministic parity probe keeps
        the loss shift under tolerance, so the gate passes."""
        FCE._GATE_CACHE.clear()
        assert FCE.int8_head_gate() is True

    def test_env_forces_both_ways(self, monkeypatch):
        monkeypatch.setenv("PTPU_INT8_HEAD", "0")
        assert FCE.int8_head_enabled() is False
        monkeypatch.setenv("PTPU_INT8_HEAD", "1")
        assert FCE.int8_head_enabled() is True

    def test_default_is_gate_outcome_on_accelerators(self, monkeypatch):
        """Unset env: CPU keeps the fp head (no int8 MXU rate to win);
        on an accelerator backend the gate's pass IS the default-on."""
        monkeypatch.delenv("PTPU_INT8_HEAD", raising=False)
        assert FCE.int8_head_enabled() is False  # cpu backend
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        FCE._GATE_CACHE.clear()
        assert FCE.int8_head_enabled() is True   # gate passed -> on

    def test_gate_fails_when_probe_drifts(self, monkeypatch):
        """A broken int8 path must fail the gate, not ship by default."""
        real = FCE.chunked_lm_loss_arrays

        def drifty(h, w, y, **kw):
            loss = real(h, w, y, **kw)
            return loss * (1.5 if kw.get("int8") else 1.0)

        monkeypatch.setattr(FCE, "chunked_lm_loss_arrays", drifty)
        FCE._GATE_CACHE.clear()
        try:
            assert FCE.int8_head_gate() is False
        finally:
            FCE._GATE_CACHE.clear()

    def test_int8_parity_through_chunked_kernel(self):
        h, w, y = _probe(32, 128, seed=5)
        lf = float(FCE.chunked_lm_loss_arrays(h, w, y, vocab_chunk=32))
        l8 = float(FCE.chunked_lm_loss_arrays(h, w, y, vocab_chunk=32,
                                              int8=True))
        assert abs(l8 - lf) / lf < 0.02


class TestNoFullLogits:
    """Acceptance: the lowered train-step-shaped program never holds a
    [tokens, vocab] logits or grad-logits array."""

    B, S, V = 2, 16, 512

    def _grad_jaxpr(self, monkeypatch, mode):
        from paddle_tpu import framework
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLMPipe

        if mode:
            monkeypatch.setenv("PTPU_LOSS_HEAD", mode)
        else:
            monkeypatch.delenv("PTPU_LOSS_HEAD", raising=False)
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=self.V, hidden_size=32, num_layers=1,
                        num_heads=2, max_seq_len=32, dropout=0.0,
                        head_chunk=128)
        model = GPTForCausalLMPipe(cfg)
        entries = model.state_dict()
        params = {n: t._data for n, t in entries.items()}
        rng = np.random.default_rng(0)
        ids = jnp.asarray(rng.integers(0, self.V, (self.B, self.S)),
                          jnp.int32)
        labels = jnp.asarray(rng.integers(0, self.V, (self.B, self.S)),
                             jnp.int64)

        def pure_loss(params):
            with model._swap_state(dict(params)):
                with framework.no_grad():
                    return model.loss(paddle.Tensor(ids),
                                      paddle.Tensor(labels))._data

        return str(jax.make_jaxpr(jax.grad(pure_loss))(params))

    def _full_logits_avals(self, jaxpr):
        n = self.B * self.S
        pats = [rf"\b{n},{self.V}\]", rf"\b{self.B},{self.S},{self.V}\]"]
        return [p for p in pats if re.search(p, jaxpr)]

    def test_chunked_step_has_no_tokens_by_vocab_array(self, monkeypatch):
        assert self._full_logits_avals(
            self._grad_jaxpr(monkeypatch, None)) == []

    def test_dense_oracle_does(self, monkeypatch):
        """Two-sided: the dense path DOES carry the array the pattern
        hunts, so the assertion above can't pass vacuously."""
        assert self._full_logits_avals(
            self._grad_jaxpr(monkeypatch, "dense")) != []

    def test_dense_and_chunked_losses_agree(self, monkeypatch):
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

        paddle.seed(1)
        cfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=1,
                        num_heads=2, max_seq_len=32, dropout=0.0,
                        head_chunk=32)
        model = GPTForCausalLM(cfg)
        rng = np.random.default_rng(2)
        ids = paddle.to_tensor(rng.integers(0, 96, (2, 8)).astype(np.int32))
        labels = paddle.to_tensor(
            rng.integers(0, 96, (2, 8)).astype(np.int64))
        monkeypatch.setenv("PTPU_LOSS_HEAD", "dense")
        ld = float(model.loss(ids, labels).numpy())
        monkeypatch.delenv("PTPU_LOSS_HEAD")
        lc = float(model.loss(ids, labels).numpy())
        np.testing.assert_allclose(lc, ld, rtol=1e-5)


class TestPlannerHeadChunk:
    def test_score_prefers_bigger_chunks(self):
        from paddle_tpu import memory as pmem

        s_small = pmem.throughput_score(2, "full", head_chunk=1024)
        s_big = pmem.throughput_score(2, "full", head_chunk=16384)
        s_none = pmem.throughput_score(2, "full")
        assert s_big > s_small
        assert s_none == pmem.throughput_score(2, "full", head_chunk=None)

    def test_decision_records_head_chunk(self, tmp_path):
        """plan_train_step carries the chosen candidate's head_chunk into
        the decision (and the bench JSON/cache round-trips it)."""
        from paddle_tpu import memory as pmem
        from paddle_tpu.jit import TrainStep
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLMPipe

        paddle.seed(0)
        cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=1,
                        num_heads=2, max_seq_len=64, dropout=0.0)
        model = GPTForCausalLMPipe(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters())

        def factory(cand):
            cfg.recompute = cand.policy != "none"
            cfg.recompute_policy = cand.policy
            cfg.head_chunk = cand.head_chunk
            step = TrainStep(model, lambda i, l: model.loss(i, l), opt)
            return step, (jax.ShapeDtypeStruct((cand.batch, 32), jnp.int32),
                          jax.ShapeDtypeStruct((cand.batch, 32), jnp.int64))

        cache = str(tmp_path / "plan.json")
        decision = pmem.plan_train_step(
            factory, [pmem.Candidate(1, "full", head_chunk=32)],
            cache_path=cache)
        assert decision.head_chunk == 32
        assert decision.as_json()["head_chunk"] == 32
        # cache hit round-trips the field
        again = pmem.plan_train_step(
            factory, [pmem.Candidate(1, "full", head_chunk=32)],
            cache_path=cache)
        assert again.source == "cache" and again.head_chunk == 32


class TestTelemetryGauges:
    def test_head_mode_and_chunk_bytes_gauges(self):
        import paddle_tpu.telemetry as telemetry

        telemetry.enable()
        try:
            telemetry.reset()
            h, w, y = _probe(16, 64)
            FCE.fused_chunked_cross_entropy(
                paddle.to_tensor(np.asarray(h)),
                paddle.to_tensor(np.asarray(w)),
                paddle.to_tensor(np.asarray(y).astype(np.int64)),
                vocab_chunk=32, int8=False)
            snap = telemetry.snapshot()
            assert snap["gauges"]["loss_head_mode"][
                "mode=chunked,int8=off"] == 1
            assert snap["gauges"]["loss_head_chunk_bytes"][""] == 16 * 32 * 4
        finally:
            telemetry.disable()
