"""Tensor creation / metadata / host-interop tests (reference model:
test/legacy_test tensor tests)."""
import numpy as np
import pytest

import paddle_tpu as paddle


class TestCreation:
    def test_to_tensor_from_list(self):
        t = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
        assert t.shape == [2, 2]
        assert t.dtype == paddle.float32
        np.testing.assert_allclose(t.numpy(), [[1, 2], [3, 4]])

    def test_to_tensor_int_defaults_int64(self):
        t = paddle.to_tensor([1, 2, 3])
        assert t.dtype == paddle.int64

    def test_to_tensor_dtype(self):
        t = paddle.to_tensor([1, 2], dtype="float16")
        assert t.dtype == paddle.float16

    def test_zeros_ones_full(self):
        assert paddle.zeros([2, 3]).numpy().sum() == 0
        assert paddle.ones([2, 3]).numpy().sum() == 6
        f = paddle.full([2, 2], 7, dtype="int32")
        assert f.dtype == paddle.int32
        assert f.numpy().sum() == 28

    def test_arange_linspace_eye(self):
        np.testing.assert_array_equal(paddle.arange(5).numpy(), np.arange(5))
        assert paddle.arange(5).dtype == paddle.int64
        np.testing.assert_allclose(
            paddle.linspace(0, 1, 5).numpy(), np.linspace(0, 1, 5), rtol=1e-6
        )
        np.testing.assert_array_equal(paddle.eye(3).numpy(), np.eye(3, dtype=np.float32))

    def test_like_variants(self):
        x = paddle.ones([2, 3], dtype="float32")
        assert paddle.zeros_like(x).shape == [2, 3]
        assert paddle.full_like(x, 5).numpy()[0, 0] == 5

    def test_scalar_item(self):
        t = paddle.to_tensor(3.5)
        assert t.item() == pytest.approx(3.5)
        assert float(t) == pytest.approx(3.5)

    def test_repr(self):
        t = paddle.ones([2])
        assert "Tensor" in repr(t)


class TestMeta:
    def test_shape_ndim_size(self):
        t = paddle.ones([2, 3, 4])
        assert t.shape == [2, 3, 4]
        assert t.ndim == 3
        assert t.size == 24
        assert t.numel() == 24

    def test_astype(self):
        t = paddle.ones([2]).astype("int64")
        assert t.dtype == paddle.int64

    def test_dtype_eq_string(self):
        assert paddle.float32 == "float32"
        assert paddle.float32 == np.float32
        assert paddle.float32 != "int32"

    def test_stop_gradient_default_true(self):
        assert paddle.ones([1]).stop_gradient is True


class TestIndexing:
    def test_basic_slice(self):
        x = paddle.arange(12).reshape([3, 4])
        np.testing.assert_array_equal(x[1].numpy(), [4, 5, 6, 7])
        np.testing.assert_array_equal(x[:, 1].numpy(), [1, 5, 9])
        np.testing.assert_array_equal(x[1:, 2:].numpy(), [[6, 7], [10, 11]])

    def test_tensor_index(self):
        x = paddle.arange(10)
        idx = paddle.to_tensor([1, 3, 5])
        np.testing.assert_array_equal(x[idx].numpy(), [1, 3, 5])

    def test_bool_mask(self):
        x = paddle.arange(6)
        mask = x > 3
        np.testing.assert_array_equal(x[mask].numpy(), [4, 5])

    def test_setitem(self):
        x = paddle.zeros([3, 3])
        x[1] = 5.0
        assert x.numpy()[1].sum() == 15
        x[0, 0] = paddle.to_tensor(2.0)
        assert x.numpy()[0, 0] == 2

    def test_iter(self):
        rows = list(paddle.arange(6).reshape([2, 3]))
        assert len(rows) == 2
        np.testing.assert_array_equal(rows[1].numpy(), [3, 4, 5])


class TestInplace:
    def test_add_(self):
        x = paddle.ones([2])
        x.add_(paddle.ones([2]))
        np.testing.assert_array_equal(x.numpy(), [2, 2])

    def test_fill_zero_(self):
        x = paddle.ones([2, 2])
        x.fill_(3.0)
        assert x.numpy().sum() == 12
        x.zero_()
        assert x.numpy().sum() == 0

    def test_set_value(self):
        x = paddle.ones([2, 2])
        x.set_value(np.full((2, 2), 9, np.float32))
        assert x.numpy().sum() == 36


class TestSaveLoad:
    def test_save_load_state(self, tmp_path):
        obj = {"w": paddle.ones([2, 2]), "step": 3, "nested": [paddle.zeros([1])]}
        p = str(tmp_path / "ckpt.pdparams")
        paddle.save(obj, p)
        loaded = paddle.load(p)
        assert loaded["step"] == 3
        np.testing.assert_array_equal(loaded["w"].numpy(), np.ones((2, 2)))
