"""Distributed stack tests on the virtual 8-device CPU mesh.

Mirrors the reference's fake-backend strategy (SURVEY §4: process_group_xccl
runs the ProcessGroup suite on custom_cpu devices) and its SPMD-rule unit
tests (test/auto_parallel/spmd_rules/*): assert placements/shardings and
numeric parity between sharded and single-device execution.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import nn


# ---------------------------------------------------------------------------
# auto_parallel: shard_tensor / reshard
# ---------------------------------------------------------------------------
class TestShardTensor:
    def test_shard_and_spec(self):
        mesh = dist.ProcessMesh(shape=(2, 4), dim_names=["dp", "mp"])
        x = paddle.ones([8, 16])
        d = dist.shard_tensor(x, mesh, [dist.Shard(0), dist.Shard(1)])
        assert d._dist_attr.placements[0].is_shard(0)
        # each device holds an [4, 4] shard
        shard_shape = d._data.sharding.shard_shape(d._data.shape)
        assert shard_shape == (4, 4)
        np.testing.assert_array_equal(d.numpy(), np.ones((8, 16)))

    def test_reshard_roundtrip(self):
        mesh = dist.ProcessMesh(shape=(8,), dim_names=["x"])
        src = np.arange(64, dtype=np.float32).reshape(8, 8)
        d = dist.shard_tensor(paddle.to_tensor(src), mesh, [dist.Shard(0)])
        r = dist.reshard(d, mesh, [dist.Shard(1)])
        assert r._data.sharding.shard_shape(r._data.shape) == (8, 1)
        np.testing.assert_array_equal(r.numpy(), src)
        rep = dist.reshard(r, mesh, [dist.Replicate()])
        np.testing.assert_array_equal(rep.numpy(), src)

    def test_partial_resolution(self):
        mesh = dist.ProcessMesh(shape=(8,), dim_names=["x"])
        x = paddle.ones([4])
        d = dist.shard_tensor(x, mesh, [dist.Partial()])
        out = dist.reshard(d, mesh, [dist.Replicate()])
        # 8 replicas each holding ones -> partial-sum resolves to 8
        np.testing.assert_array_equal(out.numpy(), np.full((4,), 8.0, np.float32))

    def test_dtensor_from_fn(self):
        mesh = dist.ProcessMesh(shape=(8,), dim_names=["x"])
        d = dist.dtensor_from_fn(paddle.zeros, mesh, [dist.Replicate()], [4, 4])
        assert d.shape == [4, 4]


# ---------------------------------------------------------------------------
# collective API (degenerate single-controller SPMD semantics)
# ---------------------------------------------------------------------------
class TestCollectives:
    def test_all_reduce_sum(self):
        g = dist.new_group(list(range(8)))
        t = paddle.to_tensor(np.ones((2, 2), np.float32))
        dist.all_reduce(t, group=g)
        np.testing.assert_array_equal(t.numpy(), np.full((2, 2), 8.0))

    def test_all_reduce_max(self):
        g = dist.new_group(list(range(8)))
        t = paddle.to_tensor(np.full((2,), 3.0, np.float32))
        dist.all_reduce(t, op=dist.ReduceOp.MAX, group=g)
        np.testing.assert_array_equal(t.numpy(), np.full((2,), 3.0))

    def test_all_gather(self):
        g = dist.new_group(list(range(4)))
        out = []
        dist.all_gather(out, paddle.to_tensor(np.arange(3, dtype=np.float32)), group=g)
        assert len(out) == 4
        np.testing.assert_array_equal(out[2].numpy(), np.arange(3, dtype=np.float32))

    def test_reduce_scatter(self):
        g = dist.new_group(list(range(4)))
        inputs = [paddle.to_tensor(np.full((2,), float(i), np.float32)) for i in range(4)]
        out = paddle.zeros([2])
        dist.reduce_scatter(out, inputs, group=g)
        # degenerate semantics: every rank holds the same inputs -> slot r sums to 4*r
        np.testing.assert_array_equal(out.numpy(), np.full((2,), 0.0))

    def test_world_size_one_noop(self):
        g = dist.new_group([0])
        t = paddle.to_tensor(np.ones((2,), np.float32))
        dist.all_reduce(t, group=g)
        np.testing.assert_array_equal(t.numpy(), np.ones((2,)))


# ---------------------------------------------------------------------------
# fleet hybrid: TP layers + sharded train step parity
# ---------------------------------------------------------------------------
def _make_fleet(dp=2, mp=2):
    from paddle_tpu.distributed import fleet

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": dp, "mp_degree": mp, "pp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    return fleet


class _TinyTPModel(nn.Layer):
    def __init__(self, fleet):
        super().__init__()
        self.embed = fleet.VocabParallelEmbedding(32, 16)
        self.col = fleet.ColumnParallelLinear(16, 32, gather_output=False)
        self.row = fleet.RowParallelLinear(32, 16, input_is_parallel=True)

    def forward(self, x):
        h = self.embed(x)
        h = self.col(h)
        h = paddle.nn.functional.relu(h)
        return self.row(h)


class TestFleetHybrid:
    def test_topology(self):
        from paddle_tpu.distributed.fleet.topology import build_hybrid_mesh

        topo, hcg, mesh = build_hybrid_mesh(dp=2, mp=2, pp=2)
        assert hcg.get_model_parallel_world_size() == 2
        assert hcg.get_pipe_parallel_world_size() == 2
        assert mesh.shape == [2, 2, 1, 1, 2]
        assert hcg.get_stage_id() == 0
        assert hcg.is_first_stage()

    def test_tp_layer_annotations(self):
        fleet = _make_fleet(dp=2, mp=2)
        m = _TinyTPModel(fleet)
        mesh = fleet.get_fleet_mesh()
        mp_idx = mesh.dim_names.index("mp")
        assert m.embed.weight._dist_attr.placements[mp_idx] == dist.Shard(0)
        assert m.col.weight._dist_attr.placements[mp_idx] == dist.Shard(1)
        assert m.row.weight._dist_attr.placements[mp_idx] == dist.Shard(0)

    def test_sharded_train_step_matches_single_device(self):
        import paddle_tpu.optimizer as opt
        from paddle_tpu.distributed import ShardedTrainStep
        from paddle_tpu.jit import TrainStep

        fleet = _make_fleet(dp=2, mp=2)
        mesh = fleet.get_fleet_mesh()

        paddle.seed(7)
        m1 = _TinyTPModel(fleet)
        paddle.seed(7)
        m2 = _TinyTPModel(fleet)
        # strip dist annotations from m2 -> plain single-device model
        for _, p in m2.named_parameters():
            p._dist_attr = None

        x = paddle.to_tensor(np.random.randint(0, 32, (8, 4)))
        y = paddle.to_tensor(np.random.randn(8, 4, 16).astype(np.float32))

        def loss_fn(model):
            def fn(xb, yb):
                out = model(xb)
                return ((out - yb) ** 2).mean()
            return fn

        s1 = ShardedTrainStep(m1, loss_fn(m1), opt.AdamW(learning_rate=1e-2, parameters=m1.parameters()), mesh=mesh)
        s2 = TrainStep(m2, loss_fn(m2), opt.AdamW(learning_rate=1e-2, parameters=m2.parameters()))

        for _ in range(3):
            l1 = s1(x, y)
            l2 = s2(x, y)
            np.testing.assert_allclose(l1.numpy(), l2.numpy(), rtol=2e-5, atol=1e-6)
        # params stayed sharded and numerically aligned
        w1 = m1.col.weight
        assert w1._data.sharding.shard_shape(w1._data.shape)[1] == 16
        np.testing.assert_allclose(w1.numpy(), m2.col.weight.numpy(), rtol=2e-5, atol=1e-6)

    def test_all_reduce_prod_negative(self):
        g = dist.new_group(list(range(4)))
        t = paddle.to_tensor(np.array([-2.0, 3.0], np.float32))
        dist.all_reduce(t, op=dist.ReduceOp.PROD, group=g)
        np.testing.assert_allclose(t.numpy(), np.array([16.0, 81.0]), rtol=1e-6)

    def test_shard_tensor_explicit_stop_gradient(self):
        mesh = dist.ProcessMesh(shape=(8,), dim_names=["x"])
        p = paddle.ones([8])
        p.stop_gradient = False
        d = dist.shard_tensor(p, mesh, [dist.Shard(0)], stop_gradient=True)
        assert d.stop_gradient is True

    def test_zero12_shards_opt_states(self):
        import paddle_tpu.optimizer as opt
        from paddle_tpu.distributed import group_sharded_parallel
        from paddle_tpu.distributed import fleet as fleet_mod

        strategy = fleet_mod.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "sharding_degree": 4}
        fleet_mod.init(is_collective=True, strategy=strategy)
        m = nn.Linear(8, 8)
        o = opt.AdamW(learning_rate=1e-3, parameters=m.parameters())
        m2, o, _ = group_sharded_parallel(m, o, "os_g")
        wrapped = fleet_mod.distributed_model(m2)
        x = paddle.to_tensor(np.random.randn(8, 8).astype(np.float32))
        y = paddle.to_tensor(np.random.randn(8, 8).astype(np.float32))
        wrapped.train_batch([x, y], o, loss_fn=lambda out, t: ((out - t) ** 2).mean())
        step = wrapped._train_step
        # the moment slot of the weight must be sharded over the "sharding" axis
        slot = next(
            v for k, v in step._opt_state.items() if "w" in k.lower() or True
        )
        specs = {str(arr.sharding.spec) for arr in slot.values() if arr.ndim > 0}
        assert any("sharding" in s for s in specs), specs

    def test_zero3_marks(self):
        fleet = _make_fleet(dp=4, mp=1)
        from paddle_tpu.distributed import group_sharded_parallel
        import paddle_tpu.optimizer as opt

        # use the sharding axis: rebuild with sharding degree
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1, "sharding_degree": 4}
        fleet.init(is_collective=True, strategy=strategy)
        m = nn.Linear(8, 8)
        o = opt.AdamW(learning_rate=1e-3, parameters=m.parameters())
        m, o, _ = group_sharded_parallel(m, o, "p_g_os")
        mesh = fleet.get_fleet_mesh()
        sh_idx = mesh.dim_names.index("sharding")
        assert m.weight._dist_attr.placements[sh_idx].is_shard()


class TestEagerP2P:
    """Compiled eager send/recv: ppermute over the {src, dst} device pair —
    no TCP store involved (VERDICT r2 item 10; parity slot:
    process_group_nccl.cc point-to-point on the comm stream)."""

    def test_send_recv_compiled_no_store(self, monkeypatch):
        from paddle_tpu.distributed import communication as comm

        g = dist.new_group(list(range(8)))
        payload = np.arange(6, dtype=np.float32).reshape(2, 3)
        dist.send(paddle.to_tensor(payload), dst=3, group=g)

        # the receiving "rank" runs the same program with its own rank id
        monkeypatch.setenv("PADDLE_TRAINER_ID", "3")
        buf = paddle.zeros([2, 3])
        dist.recv(buf, src=0, group=g)
        np.testing.assert_array_equal(buf.numpy(), payload)

        # data moved via the compiled path onto rank 3's device; the TCP
        # store mailbox was never created
        assert comm._p2p_store[0] is None
        import jax

        assert buf._data.device == jax.devices()[3]

    def test_send_recv_dtype_cast_and_seq(self, monkeypatch):
        g = dist.new_group(list(range(8)))
        dist.send(paddle.to_tensor(np.ones(4, np.float32)), dst=1, group=g)
        dist.send(paddle.to_tensor(np.full(4, 2.0, np.float32)), dst=1, group=g)
        monkeypatch.setenv("PADDLE_TRAINER_ID", "1")
        a = paddle.zeros([4], dtype="float64")
        b = paddle.zeros([4], dtype="float64")
        dist.recv(a, src=0, group=g)  # seq order: first send first
        dist.recv(b, src=0, group=g)
        np.testing.assert_array_equal(a.numpy(), np.ones(4))
        np.testing.assert_array_equal(b.numpy(), np.full(4, 2.0))
        assert str(a.dtype).endswith("float64")

    def test_recv_without_send_raises(self, monkeypatch):
        import pytest as _pytest

        monkeypatch.setenv("PADDLE_TRAINER_ID", "5")
        with _pytest.raises(RuntimeError, match="no matching send"):
            dist.recv(paddle.zeros([2]), src=4)
