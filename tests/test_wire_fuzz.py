"""PTF1 frame fuzz/property test (docs/SERVING.md wire format).

The transport retries on FrameError and the chaos harness injects
drops/duplicates/corruption, so the single load-bearing property of the
codec layer is: a mutated byte stream NEVER decodes to garbage — every
mutation either raises :class:`~paddle_tpu.inference.fleet.wire.
FrameError` or decodes cleanly back to the original object.  Checked
for both payload codecs under seed-deterministic truncation, single-bit
flips, frame duplication, and junk prefixes; fast enough for tier-1.
"""
import numpy as np
import pytest

from paddle_tpu.inference.fleet import wire

SEED = 0xC0DEC


def _gen_obj(rng, depth=0):
    """One representative wire object: the RPC data model (frames are
    dicts of scalars/lists/bytes, arbitrarily nested)."""
    kinds = ["none", "bool", "int", "bigint", "float", "str", "bytes"]
    if depth < 3:
        kinds += ["list", "dict", "dict"]
    k = kinds[int(rng.integers(len(kinds)))]
    if k == "none":
        return None
    if k == "bool":
        return bool(rng.integers(2))
    if k == "int":
        return int(rng.integers(-(2 ** 31), 2 ** 31))
    if k == "bigint":
        return int(rng.integers(-(2 ** 62), 2 ** 62))
    if k == "float":
        return float(rng.normal()) * 10 ** int(rng.integers(-8, 9))
    if k == "str":
        n = int(rng.integers(0, 64))
        return "".join(chr(int(c)) for c in rng.integers(32, 0x2FF, n))
    if k == "bytes":
        return rng.integers(0, 256, int(rng.integers(0, 128)),
                            dtype=np.uint8).tobytes()
    if k == "list":
        return [_gen_obj(rng, depth + 1)
                for _ in range(int(rng.integers(0, 6)))]
    return {f"k{i}_{int(rng.integers(1000))}": _gen_obj(rng, depth + 1)
            for i in range(int(rng.integers(0, 6)))}


def _decodes_clean_or_raises(buf, original):
    """The fuzz property: FrameError, or a bitwise-faithful decode."""
    try:
        out = wire.decode_frame(buf)
    except wire.FrameError:
        return True
    assert out == original, (
        "mutated frame decoded to a DIFFERENT object — corruption "
        "slipped past magic/length/CRC validation")
    return True


@pytest.mark.parametrize("codec", wire.available_codecs())
def test_fuzz_mutations_never_decode_to_garbage(codec):
    rng = np.random.default_rng(SEED + codec)
    for _ in range(30):
        obj = {"id": int(rng.integers(1 << 30)),
               "m": "fuzz", "a": _gen_obj(rng), "ep": int(rng.integers(8))}
        frame = wire.encode_frame(obj, codec)
        assert wire.decode_frame(frame) == obj      # clean roundtrip

        # truncation at arbitrary cut points (header and payload)
        for _ in range(8):
            cut = int(rng.integers(0, len(frame)))
            with pytest.raises(wire.FrameError):
                wire.decode_frame(frame[:cut])

        # single-bit flips anywhere in the frame
        for _ in range(16):
            pos = int(rng.integers(len(frame)))
            bit = 1 << int(rng.integers(8))
            mutated = bytearray(frame)
            mutated[pos] ^= bit
            _decodes_clean_or_raises(bytes(mutated), obj)

        # duplication: a doubled frame is NOT one frame
        with pytest.raises(wire.FrameError):
            wire.decode_frame(frame + frame)
        # junk prefix: the magic check rejects mid-stream resync
        with pytest.raises(wire.FrameError):
            wire.decode_frame(b"\x00" * 4 + frame)


@pytest.mark.parametrize("codec", wire.available_codecs())
def test_fuzz_is_seed_deterministic(codec):
    """Two runs from the same seed generate byte-identical frames — a
    fuzz failure is always reproducible from the seed in the test."""
    frames = []
    for _ in range(2):
        rng = np.random.default_rng(SEED + codec)
        frames.append([wire.encode_frame(_gen_obj(rng), codec)
                       for _ in range(10)])
    assert frames[0] == frames[1]


def test_crosscodec_header_says_which_codec():
    """The codec byte travels in the header: a frame encoded by either
    codec decodes without the receiver being configured."""
    obj = {"id": 1, "m": "x", "a": {"t": [1, 2, 3], "b": b"\x00\xff"}}
    for codec in wire.available_codecs():
        frame = wire.encode_frame(obj, codec)
        got_codec, length, _ = wire.parse_header(frame[:wire.HEADER_SIZE])
        assert got_codec == codec
        assert length == len(frame) - wire.HEADER_SIZE
        assert wire.decode_frame(frame) == obj
