"""The examples/ quickstarts must stay runnable (they are the first
thing a reference user tries)."""
import os
import subprocess
import sys

import pytest

_EX = os.path.join(os.path.dirname(__file__), "..", "examples")


@pytest.mark.parametrize("script", [
    # 01/03 are slow-marked subprocess runs (tier-1 time budget, ISSUE 4);
    # 02 stays tier-1 so the driver keeps eyes on its known 3-axis failure
    pytest.param("01_train_mnist.py", marks=pytest.mark.slow),
    "02_pretrain_gpt_hybrid.py",
    pytest.param("03_serve_llm.py", marks=pytest.mark.slow),
])
def test_example_runs(script):
    env = dict(os.environ)
    # prepend (don't clobber) so machines relying on PYTHONPATH keep it;
    # JAX_PLATFORMS/XLA_FLAGS are inherited from conftest.py's setup
    env["PYTHONPATH"] = os.pathsep.join(filter(None, [
        os.path.abspath(os.path.join(_EX, "..")),
        os.environ.get("PYTHONPATH", "")]))
    r = subprocess.run([sys.executable, os.path.join(_EX, script)],
                       capture_output=True, text=True, timeout=280,
                       env=env)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
